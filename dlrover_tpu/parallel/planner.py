"""Cost-model mesh planner: pick the parallelism layout analytically.

Role parity: ``atorch/auto/opt_lib/shard_planners/`` —
``mip_tp_planner.py:29`` (mixed-integer-programming TP planner over an op
DAG with a comm/compute cost model), ``base_stage_planner.py:125``
(pipeline stage split), ``topology.py`` (device topology). The TPU search
space is small enough (factorizations of the device count over five mesh
axes) that exhaustive scoring under an analytic cost model replaces the
MIP solver; the cost model mirrors the roofline terms of the public
scaling playbook: MXU FLOPs, HBM bytes, ICI collective bytes.

The dryrun search (``parallel.search``) measures; this planner *predicts*
— useful before any compile (initial plan, elasticity re-planning) and as
the candidate-ordering prior for the measured search.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.mesh import MeshPlan, candidate_plans

logger = get_logger("parallel.planner")


@dataclass(frozen=True)
class DeviceSpec:
    """Per-chip capability (reference: topology.py DeviceTopology).
    Defaults are TPU v5e; override per generation."""

    flops_per_s: float = 197e12  # bf16
    hbm_bytes: float = 16e9
    hbm_bw: float = 8.2e11  # bytes/s
    ici_bw: float = 4.5e10  # bytes/s per link, one direction
    dcn_bw: float = 2.5e9  # bytes/s per host


TPU_SPECS = {
    "v4": DeviceSpec(275e12, 32e9, 1.2e12, 4.5e10),
    "v5e": DeviceSpec(197e12, 16e9, 8.2e11, 4.5e10),
    "v5p": DeviceSpec(459e12, 95e9, 2.8e12, 9.0e10),
    "v6e": DeviceSpec(918e12, 32e9, 1.6e12, 9.0e10),
}


@dataclass
class ModelSpec:
    """What the planner needs to know about the workload (derivable from
    a model config or ``utils.meta_init.param_stats``)."""

    param_count: int
    num_layers: int
    hidden_size: int
    seq_len: int
    global_batch: int  # rows per step
    vocab_size: int = 32000
    param_bytes: int = 2  # bf16 storage
    optim_bytes_per_param: int = 8  # adam moments in f32... adafactor ~1
    dtype_bytes: int = 2
    ffn_mult: float = 2.7  # intermediate/hidden ratio (llama ~2.69)
    # GQA shape: kv_heads/num_heads sets the ring-attention ICI bytes
    # (0 = MHA, kv bytes == activation bytes)
    num_heads: int = 0
    kv_heads: int = 0
    # switch-MoE shape (0 experts = dense). The dispatch choice changes
    # the cost STRUCTURE, not just a constant: see _moe_dispatch_terms.
    num_experts: int = 0
    moe_top_k: int = 1
    moe_capacity_factor: float = 1.25
    # "gather" | "einsum" | "grouped" | "grouped_ep" (ops.moe dispatches)
    moe_dispatch: str = "gather"
    # grouped_ep chunked double-buffered dispatch (ops.moe
    # dispatch_chunks): C > 1 splits the row exchange into C
    # ppermute-ring chunks so the grouped GEMM overlaps the in-flight
    # exchange. The BYTES on the wire are invariant in C (the audit
    # contract); what changes is how much of them is EXPOSED — see
    # ``estimate``'s overlap-aware moe_disp_comm_s.
    moe_dispatch_chunks: int = 1
    # FSDP layer prefetch (models/llama.py fsdp_prefetch): gather layer
    # l+1's params under layer l's compute, exposing only the
    # non-overlappable remainder of the fsdp gather bytes.
    fsdp_prefetch: bool = False
    # grouped_ep wire precision (ops.moe precision / ops.quantize):
    # "fp8" ships the row exchanges as block-scaled e4m3 values plus
    # f32 per-block scales — the BYTES change (wire_bytes_per_elem
    # below), which is exactly what the G106 audit must see; the
    # schedule does not. "fp8_qdq" (the reference oracle) prices as
    # bf16: its wire IS full precision.
    moe_precision: str = "bf16"
    # dense FSDP wire precision (models/llama.py fsdp_precision):
    # "fp8" ships the per-layer param GATHERS of the scan-over-layers
    # as block-scaled e4m3 + f32 scales (``fsdp_wire_bytes_per_elem``
    # — ~1/4 of an f32 gather); the gradient reduce-scatter direction
    # stays at the param dtype — under GSPMD the cotangent reduction
    # ships the compute dtype regardless of the gradient-path
    # quantization (``grad_precision``), whose error-feedback qdq is a
    # numerics contract, not a transport change. "fp8_qdq" (the
    # dequant-exact oracle) prices at the full-precision wire it
    # actually ships.
    fsdp_precision: str = "bf16"

    def moe_wire_bytes_per_elem(self) -> float:
        """Wire bytes per exchanged row element, scale side-band
        INCLUDED: the quantized wire ships 1-byte e4m3 values plus one
        f32 scale per quantization block (ops.quantize layout), so a
        bf16 exchange drops to 1 + 4/32 = 1.125 bytes/elem (~0.56x).
        The ONE formula the pricing, the audit, and the bench's
        wire-bytes ratio all read. The "fp8_qdq" reference oracle
        prices at the f32 wire its implementation actually ships
        (``dequantize_block_scaled`` decodes to f32 before the
        exchange) — never at the bytes it does not save."""
        if self.moe_precision == "fp8":
            from dlrover_tpu.ops.quantize import resolve_quant_block

            block = resolve_quant_block(max(1, int(self.hidden_size)))
            return 1.0 + 4.0 / block
        if self.moe_precision == "fp8_qdq":
            return 4.0
        return float(self.dtype_bytes)

    def fsdp_wire_bytes_per_elem(self) -> float:
        """Wire bytes per gathered PARAM element on the dense FSDP
        gather legs, scale side-band included — the fsdp analog of
        ``moe_wire_bytes_per_elem`` and likewise the ONE formula the
        pricing, the G106 audit comparison and the bench wire-bytes
        ratio read. "fp8" ships e4m3 values + one f32 scale per
        quantization block (blocks along each kernel's last dim;
        hidden_size is the representative channel count). "fp8_qdq"
        decodes BEFORE the wire, so it prices at the param bytes it
        actually ships (never winning on bytes it does not save)."""
        if self.fsdp_precision == "fp8":
            from dlrover_tpu.ops.quantize import resolve_quant_block

            block = resolve_quant_block(max(1, int(self.hidden_size)))
            return 1.0 + 4.0 / block
        return float(self.param_bytes)

    def fsdp_byte_split(self, fsdp: int, tensor: int = 1,
                        pipe: int = 1) -> Tuple[float, float]:
        """(gather_bytes, scatter_bytes) of the per-step dense FSDP
        traffic for one chip — the two DIRECTIONS of the wire, split
        so each can be priced at the dtype it actually ships:

          gather  : 2 traversals of the sharded params (the forward
                    per-layer all-gather + the backward re-gather the
                    remat replay pays) at ``fsdp_wire_bytes_per_elem``
                    — the legs the fsdp_precision knob compresses;
          scatter : 1 traversal (the gradient reduce-scatter) at the
                    param dtype — under GSPMD the cotangent reduction
                    ships the compute dtype regardless of
                    ``grad_precision`` (see docs/parallelism.md).

        At precision "bf16" the sum reproduces the historical
        ``3 * shard_bytes * (fsdp-1)/fsdp`` exactly."""
        if fsdp <= 1:
            return 0.0, 0.0
        shard_elems = self.param_count / (tensor * pipe)
        frac = (fsdp - 1) / fsdp
        gather = 2.0 * shard_elems * self.fsdp_wire_bytes_per_elem() * frac
        scatter = shard_elems * self.param_bytes * frac
        return gather, scatter


# Recompute multiplier on executed FLOPs per remat policy: "full" re-runs
# the forward in the backward (8N vs 6N per token), dots_saveable saves
# the matmul outputs and re-runs roughly half of the forward.
REMAT_RECOMPUTE = {
    "": 1.0,
    "none": 1.0,
    "dots_saveable": 7.0 / 6.0,
    "dots_and_attn_saveable": 7.0 / 6.0,
    "attn_saveable": 7.5 / 6.0,  # full minus the attention-fwd re-run
    "full": 8.0 / 6.0,
    "nothing_saveable": 8.0 / 6.0,  # jax alias for save-nothing
}

# No predicted step may claim better than this fraction of peak: keeps
# every prediction physical (MFU < 1) even with zero modeled comm.
MAX_EFFICIENCY = 0.9

# Host-side overhead per compiled-step dispatch (the Python step loop,
# runtime enqueue, rng split, lagged-ring bookkeeping) — order of
# magnitude from the CPU dispatch wedge (bench.py --mode dispatch).
# ``steps_per_call`` amortizes it (one dispatch per K optimizer steps)
# and the executor's in-flight window overlaps it with device work, so
# it enters the step time as a FLOOR (max), not an additive term: big
# models never see it, while tiny/fast steps are host-dispatch-bound
# exactly as measured.
HOST_DISPATCH_OVERHEAD_S = 350e-6


@dataclass(frozen=True)
class CalibrationAnchor:
    """One measured (model, chip) -> step-time point used to fit the
    compute-efficiency term (reference: the MIP planner's cost model is
    likewise fitted to profiled kernels, ``mip_tp_planner.py:29``)."""

    name: str
    model: ModelSpec
    device_gen: str
    remat_policy: str
    measured_step_s: float
    measured_mfu: float


# Measured single-chip anchors from the committed bench artifacts
# (BENCH_r01.json / BENCH_r02.json: llama_pretrain_mfu on one v5e).
MEASURED_ANCHORS = (
    CalibrationAnchor(
        name="bench_r01_940m",  # bench.py "1b" preset
        model=ModelSpec(
            param_count=940_640_256, num_layers=16, hidden_size=2048,
            seq_len=2048, global_batch=4, vocab_size=32000,
            optim_bytes_per_param=1, ffn_mult=5504 / 2048,
            num_heads=16, kv_heads=16,
        ),
        device_gen="v5e",
        remat_policy="dots_saveable",
        measured_step_s=0.443,
        measured_mfu=0.5676,
    ),
    CalibrationAnchor(
        name="bench_r02_2p7b",  # bench.py default (2.7B) preset
        model=ModelSpec(
            param_count=2_701_560_320, num_layers=32, hidden_size=2560,
            seq_len=2048, global_batch=2, vocab_size=32000,
            optim_bytes_per_param=1, ffn_mult=6912 / 2560,
            num_heads=20, kv_heads=20,
        ),
        device_gen="v5e",
        remat_policy="full",
        measured_step_s=0.701,
        measured_mfu=0.5106,
    ),
    CalibrationAnchor(
        name="bench_r03_2p7b_tuned",  # round-3 sweep winner (BENCH_r03)
        model=ModelSpec(
            param_count=2_701_560_320, num_layers=32, hidden_size=2560,
            seq_len=1024, global_batch=16, vocab_size=32000,
            optim_bytes_per_param=1, ffn_mult=6912 / 2560,
            num_heads=20, kv_heads=20,
        ),
        device_gen="v5e",
        remat_policy="full",
        measured_step_s=2.4624,
        measured_mfu=0.5645,
    ),
)


_DEFAULT_EFFICIENCY: Optional[float] = None


def calibrated_efficiency(anchors: Tuple = MEASURED_ANCHORS) -> float:
    """Executed-FLOP throughput / peak, geomean-fitted to the measured
    anchors (~0.67 on v5e), clamped to MAX_EFFICIENCY."""
    global _DEFAULT_EFFICIENCY
    if anchors is MEASURED_ANCHORS and _DEFAULT_EFFICIENCY is not None:
        return _DEFAULT_EFFICIENCY
    effs = []
    for a in anchors:
        exec_flops = _flops_per_step(a.model) * REMAT_RECOMPUTE.get(
            a.remat_policy, 1.0
        )
        dev = TPU_SPECS[a.device_gen]
        effs.append(exec_flops / (dev.flops_per_s * a.measured_step_s))
    out = float(min(math.exp(
        sum(math.log(e) for e in effs) / len(effs)
    ), MAX_EFFICIENCY))
    if anchors is MEASURED_ANCHORS:
        _DEFAULT_EFFICIENCY = out
    return out


@dataclass
class PlanScore:
    plan: MeshPlan
    step_time_s: float
    memory_bytes: float
    fits: bool
    breakdown: Dict[str, float]
    predicted_mfu: float = 0.0


# breakdown keys that are ICI/DCN collective seconds — the "comm" term
# of combine_step_time (and the term the runtime calibrator scales as
# one family; see master/optimizer/calibration.py)
COMM_BREAKDOWN_KEYS = (
    "tp_comm_s", "fsdp_comm_s", "dp_comm_s", "seq_comm_s",
    "pipe_comm_s", "moe_disp_comm_s",
)


def overlap_exposed_comm(comm_s: float, overlappable_compute_s: float,
                         chunks: int) -> float:
    """EXPOSED seconds of a chunked, double-buffered exchange — the
    overlap-aware pricing both overlapped paths share (chunked expert
    dispatch, FSDP layer prefetch), so the planner stops summing comm
    and compute serially where the program actually interleaves them.

    The C-chunk schedule is: exchange chunk 0; then for each next chunk
    its exchange runs UNDER the previous chunk's compute; the last
    chunk's compute runs alone. With per-chunk exchange e = comm/C and
    per-chunk compute g = overlappable/C the exposed comm is
    e + (C-1)*max(e - g, 0), which simplifies to

        max(comm_s / C,  comm_s - (C-1)/C * overlappable_compute_s)

    — at C=1 this is the serial comm_s; it is non-increasing in C for
    fixed bytes (both tests pin both directions), and it can never go
    below comm_s/C (the un-overlappable head of the pipeline)."""
    c = max(1, int(chunks))
    if comm_s <= 0:
        return 0.0
    if c <= 1:
        return comm_s
    return max(comm_s / c,
               comm_s - overlappable_compute_s * (c - 1) / c)


def combine_step_time(compute_s: float, comm_s: float,
                      dispatch_s: float,
                      overlapped: bool = True) -> float:
    """The ONE formula turning cost terms into a predicted step time —
    used by ``estimate`` and by the runtime optimizer's calibrated
    re-pricing (``master/optimizer/calibration.py``), so the two can
    never drift apart.

    Comm overlaps compute imperfectly: charge the max plus a quarter of
    the smaller (conservative). The host dispatch cost enters as a
    FLOOR when the executor's in-flight window overlaps it with device
    work (``overlapped=True``, the production default); a synchronous
    loop (``train_window=0``) pays it additively. Dispatch-bound plans
    keep a 1% residual of their device time so the ranking still
    prefers the faster compiled program instead of collapsing every
    tiny-model mesh into a tie."""
    step_s = max(compute_s, comm_s) + 0.25 * min(compute_s, comm_s)
    if not overlapped:
        return step_s + dispatch_s
    if dispatch_s > step_s:
        step_s = dispatch_s + 0.01 * step_s
    return step_s


def _flops_per_step(m: ModelSpec) -> float:
    tokens = m.global_batch * m.seq_len
    attn = 12 * m.num_layers * m.hidden_size * m.seq_len * 0.5
    return (6.0 * m.param_count + attn) * tokens


def ring_kv_repeat(kv_heads: int, num_heads: int,
                   tensor: int) -> Optional[int]:
    """The minimal KV-head repeat ``ops.ring_attention`` applies when the
    kv heads don't divide the tensor axis — planner-visible so the seq
    comm term prices the extra ICI bytes instead of hiding them.

    Returns None when NO legal repeat exists — the same inputs make the
    runtime legalizer (``ops.flash_attention.minimal_kv_repeat``) raise,
    so the planner must demote the mesh as infeasible rather than price
    a program that cannot be built."""
    if kv_heads <= 0 or tensor <= 1 or kv_heads % tensor == 0:
        return 1
    num_heads = max(num_heads, kv_heads)
    for rep in range(1, num_heads // kv_heads + 1):
        if (kv_heads * rep) % tensor == 0 and num_heads % (kv_heads * rep) == 0:
            return rep
    return None


def _moe_dispatch_terms(
    model: ModelSpec,
    device: DeviceSpec,
    eff: float,
    tokens_per_chip: float,
    ep: int,
) -> Tuple[float, float]:
    """(extra compute seconds, extra ICI *bytes*) the MoE DISPATCH adds
    per step — the term that ranks ``grouped_ep`` against the capacity
    paths honestly (the expert GEMMs themselves ride the 6N model-FLOPs
    compute term like every other matmul).

    Cost structure per layer (t = tokens/chip, k = top_k, cf =
    capacity_factor, D = hidden, P = expert-parallel degree):

      einsum, and gather when experts shard over the EP submesh (P>1):
        the one-hot [T,E,C] dispatch/combine einsums — the gather
        path's data-dependent scatters are opaque to GSPMD across the
        expert axis, so the EP-sharded lowering falls back to exactly
        this capacity-shaped movement. 2 einsums x 2TECD FLOPs x 3
        (fwd+bwd) with E*C = cf*k*t  =>  12*cf*k*t^2*D — QUADRATIC in
        tokens.
      gather / grouped per-shard (P==1): slot-map gathers, O(t*D) HBM
        bytes — linear and tiny.
      grouped_ep: two all_to_alls fwd + their transposes bwd moving the
        static dropless row buffer [P, t*k, D] => 4*P*t*k*D *
        wire_bytes_per_elem bytes on ICI — LINEAR in tokens, and
        DTYPE-AWARE: the fp8 wire ships 1-byte values + the f32
        per-block scale side-band (``ModelSpec.moe_wire_bytes_per_elem``
        — ~0.56x of bf16), which is what the G106 audit of a quantized
        program must be compared against. (The buffer is the
        static-shape worst case the implementation actually exchanges;
        see ``ops.moe._moe_compute_grouped_ep``.)

    The quadratic-vs-linear structure crosses over: below ~12k
    tokens/chip (v5e numbers) the capacity fallback wins, above it
    ``grouped_ep`` does — ``tests/test_planner.py`` pins the flip.
    """
    if model.num_experts <= 0:
        return 0.0, 0.0
    t = tokens_per_chip
    d = model.hidden_size
    k = max(1, model.moe_top_k)
    cf = model.moe_capacity_factor
    layers = model.num_layers
    dispatch = model.moe_dispatch
    if dispatch == "einsum" or (dispatch == "gather" and ep > 1):
        flops = 12.0 * cf * k * t * t * d * layers
        return flops / (device.flops_per_s * eff), 0.0
    if dispatch == "grouped_ep" and ep > 1:
        ici_bytes = (4.0 * ep * t * k * d * layers
                     * model.moe_wire_bytes_per_elem())
        return 0.0, ici_bytes
    if dispatch == "grouped" and ep > 1:
        # the kernel is opaque to GSPMD: EP-sharded expert weights get
        # all-gathered to every chip each layer (fwd + the grad
        # reduce-scatter bwd) — price that honestly so the planner
        # steers EP meshes to grouped_ep/gather instead
        w_bytes = (2.0 * model.num_experts * d * (model.ffn_mult * d)
                   * model.dtype_bytes)
        ici_bytes = 3.0 * w_bytes * (ep - 1) / ep * layers
        return 0.0, ici_bytes
    # per-shard gather/grouped (and grouped_ep degraded to P==1):
    # slot-gather/sort data movement, a few passes over the token rows
    hbm_bytes = 4.0 * cf * k * t * d * model.dtype_bytes * layers
    return hbm_bytes / device.hbm_bw, 0.0


def predicted_collective_bytes(
    plan: MeshPlan,
    model: ModelSpec,
    device: DeviceSpec = DeviceSpec(),
    efficiency: Optional[float] = None,
    pipe_virtual: int = 1,
) -> Dict[str, float]:
    """Per-step collective traffic (bytes, per link/chip) the cost model
    prices for one mesh — the SAME formulas ``estimate`` divides by link
    bandwidth, exposed so the graph lint (``dlrover_tpu.analysis``) can
    audit the compiled HLO's actual collective bytes against the plan the
    planner scored. If the two drift by more than the audit tolerance,
    either XLA is executing a different program than the one we priced
    (plan/graph divergence) or the cost model has rotted — both must fail
    loudly (ISSUE 2 / ElasWave's silent-divergence failure class).

    Keys: ``tp`` (activation allreduces), ``fsdp`` (param gather + grad
    scatter), ``dp`` (grad allreduce), ``seq`` (ring-attention KV
    rotation), ``pipe`` (stage-boundary activation handoff — DCN, not
    ICI), ``moe_dispatch`` (all-to-all / weight-gather bytes of the MoE
    dispatch; 0 for the capacity paths, whose overhead is compute-shaped).
    """
    pipe = max(getattr(plan, "pipe", 1), 1)
    data = max(getattr(plan, "data", 1), 1)
    fsdp = max(getattr(plan, "fsdp", 1), 1)
    seq = max(getattr(plan, "seq", 1), 1)
    tensor = max(getattr(plan, "tensor", 1), 1)

    rows = model.global_batch / max(data * fsdp, 1)
    act_elems = rows * (model.seq_len / seq) * model.hidden_size

    out = {"tp": 0.0, "fsdp": 0.0, "dp": 0.0, "seq": 0.0, "pipe": 0.0,
           "moe_dispatch": 0.0}
    if tensor > 1:
        bytes_per_ar = 2 * (tensor - 1) / tensor * (
            act_elems * model.dtype_bytes
        )
        out["tp"] = 4 * model.num_layers * bytes_per_ar
    if fsdp > 1:
        # dtype-aware split (ModelSpec.fsdp_byte_split): the 2 gather
        # traversals at the wire precision + the reduce-scatter at the
        # param dtype — at "bf16" this IS the historical
        # 3 * shard_bytes * (fsdp-1)/fsdp
        gather_b, scatter_b = model.fsdp_byte_split(fsdp, tensor, pipe)
        out["fsdp"] = gather_b + scatter_b
    if data > 1:
        grad_bytes = model.param_count * model.param_bytes / (
            tensor * pipe * fsdp
        )
        out["dp"] = 2 * grad_bytes * (data - 1) / data
    if pipe > 1:
        out["pipe"] = (
            2 * max(pipe_virtual, 1) * act_elems * model.dtype_bytes
        )
    if seq > 1:
        kv_frac = 1.0
        if model.kv_heads and model.num_heads:
            rep = ring_kv_repeat(model.kv_heads, model.num_heads, tensor)
            # rep None = infeasible heads (estimate marks the plan
            # unbuildable); keep the rep=1 bytes so the breakdown stays
            # finite and comparable
            kv_frac = model.kv_heads * (rep or 1) / model.num_heads
        kv_bytes = 2 * act_elems * model.dtype_bytes * kv_frac
        out["seq"] = model.num_layers * (seq - 1) * kv_bytes
    eff = min(
        efficiency if efficiency is not None else calibrated_efficiency(),
        MAX_EFFICIENCY,
    )
    _, moe_bytes = _moe_dispatch_terms(
        model, device, eff, rows * (model.seq_len / seq), data * fsdp
    )
    out["moe_dispatch"] = moe_bytes
    return out


def estimate(
    plan: MeshPlan,
    model: ModelSpec,
    device: DeviceSpec = DeviceSpec(),
    remat_policy: str = "",
    efficiency: Optional[float] = None,
    pipe_microbatches: int = 0,
    pipe_virtual: int = 1,
    stage_depths=None,
    stage_remat: Optional[bool] = None,
    steps_per_call: int = 1,
) -> PlanScore:
    """Analytic step-time + memory estimate for one mesh factorization.

    Terms:
      compute  : *executed* FLOPs (model FLOPs x remat recompute) over
                 chips x peak x a compute efficiency **calibrated to the
                 measured BENCH anchors** (``calibrated_efficiency``, ~0.67
                 on v5e). Efficiency is clamped to MAX_EFFICIENCY, so the
                 predicted step time is always >= executed FLOPs /
                 (0.9 * peak) — no prediction can be unphysical (MFU >= 1).
                 Pipeline adds the GPipe bubble factor.
      tp comm  : 2 allreduces of activations per layer over the tensor
                 axis (Megatron fwd+bwd), ICI bandwidth.
      fsdp comm: params all-gathered + grads reduce-scattered per step
                 over the fsdp axis.
      dp comm  : gradient allreduce over the data axis.
      seq comm : ring-attention KV rotation — only the (possibly
                 repeated, ``ring_kv_repeat``) kv heads travel.
      moe disp : MoE dispatch overhead per ``model.moe_dispatch`` —
                 quadratic one-hot einsums for the capacity paths under
                 EP, linear all-to-all bytes for "grouped_ep"
                 (``_moe_dispatch_terms``; ep degree = data x fsdp, the
                 expert submesh of the canonical rule sets). With
                 ``moe_dispatch_chunks`` > 1 (and with
                 ``fsdp_prefetch`` for the fsdp gathers) only the
                 EXPOSED remainder enters the step time
                 (``overlap_exposed_comm``); bytes stay invariant.
      memory   : params+optimizer sharded over (fsdp x tensor x pipe),
                 activations for one microbatch per layer (remat floor).

    ``stage_remat``: whether the model ACTUALLY applies stage-boundary
    remat when pipelined (``apply_pipelined`` derives it from the MODEL
    config's remat_policy, not the strategy's) — pass it from aot/
    callers that know; None falls back to inferring from
    ``remat_policy``.
    """
    pipe = max(getattr(plan, "pipe", 1), 1)
    data = max(getattr(plan, "data", 1), 1)
    fsdp = max(getattr(plan, "fsdp", 1), 1)
    seq = max(getattr(plan, "seq", 1), 1)
    tensor = max(getattr(plan, "tensor", 1), 1)
    n_chips = pipe * data * fsdp * seq * tensor

    # ---- compute (executed flops at calibrated efficiency)
    flops = _flops_per_step(model)
    from dlrover_tpu.ops.remat import remat_enabled

    recompute = REMAT_RECOMPUTE.get(remat_policy or "", 1.0)
    stage_remat_on = (stage_remat if stage_remat is not None
                      else remat_enabled(remat_policy))
    if pipe > 1 and stage_remat_on:
        # pipelined stages run under STAGE-BOUNDARY remat (the tick
        # scan stores only one state per tick; dispatch_pipeline's
        # remat_stage): the backward replays each stage's forward, so
        # executed FLOPs are at least the save-nothing factor (8/6 =
        # fwd + fwd-replay + bwd over fwd + bwd) regardless of how
        # much the inner per-layer policy saves during the replay.
        # The models key remat_stage off the MODEL config's policy, so
        # callers that know it pass stage_remat explicitly — the
        # strategy-level string may be empty while the model remats
        # (examples/train_llama.py), or vice versa.
        recompute = max(recompute, REMAT_RECOMPUTE["full"])
    eff = min(
        efficiency if efficiency is not None else calibrated_efficiency(),
        MAX_EFFICIENCY,
    )
    exec_flops = flops * recompute
    compute_s = exec_flops / (n_chips * device.flops_per_s * eff)
    if pipe > 1:
        # circular interleaved bubble (P-1)/(V*M+P-1); V=1 reduces to
        # the GPipe factor (M+P-1)/M this branch always modeled
        microbatches = pipe_microbatches or max(2 * pipe, 4)
        v = max(pipe_virtual, 1)
        compute_s *= 1.0 + (pipe - 1) / (v * microbatches)
        if stage_depths:
            # uneven split: every tick runs max(depths) padded layer
            # slots per chunk — the slots beyond L/(V*P) are idle-time
            # overhead on the light stages (pipeline.stack_stages_uneven)
            d = tuple(stage_depths)
            compute_s *= (v * pipe * max(d)) / max(1, sum(d))

    # ---- per-chip batch rows (data-ish axes shard the batch)
    rows = model.global_batch / max(data * fsdp, 1)
    act_elems = rows * (model.seq_len / seq) * model.hidden_size

    # ---- collective traffic: all byte quantities come from
    # predicted_collective_bytes — the ONE set of formulas the graph
    # lint's HLO audit also reads, so the seconds priced here and the
    # bytes audited there cannot drift apart.
    #   tp   : 2 allreduces of activations per layer fwd + 2 bwd (ICI)
    #   fsdp : param all-gather + grad reduce-scatter per step (ICI)
    #   dp   : plain gradient allreduce (ICI)
    #   seq  : ring-attention KV rotation, GQA- and repeat-aware (ICI)
    #   pipe : stage-boundary activation handoff, per-link; pipe is the
    #          outermost axis so on multi-slice topologies it rides DCN
    #          (V>1: the circular schedule wraps each microbatch around
    #          the ring V times)
    comm_bytes = predicted_collective_bytes(
        plan, model, device, efficiency=eff, pipe_virtual=pipe_virtual
    )
    tp_comm_s = comm_bytes["tp"] / device.ici_bw
    fsdp_comm_s = comm_bytes["fsdp"] / device.ici_bw
    dp_comm_s = comm_bytes["dp"] / device.ici_bw
    seq_comm_s = comm_bytes["seq"] / device.ici_bw
    pipe_comm_s = comm_bytes["pipe"] / device.dcn_bw

    # feasibility: the runtime head-shard legalizer raises when no legal
    # KV repeat exists for this head/tensor combination; any mesh relying
    # on it must never win the ranking
    heads_shardable = True
    if model.kv_heads and model.num_heads and ring_kv_repeat(
            model.kv_heads, model.num_heads, tensor) is None:
        heads_shardable = False

    # ---- MoE dispatch overhead (quadratic capacity einsums vs linear
    # all-to-all bytes): ep degree = data x fsdp, the expert submesh of
    # the canonical rule sets (mesh.py: "expert" aliases data x fsdp)
    tokens_per_chip = rows * (model.seq_len / seq)
    moe_disp_comp_s, _moe_bytes = _moe_dispatch_terms(
        model, device, eff, tokens_per_chip, data * fsdp
    )
    moe_disp_comm_s = comm_bytes["moe_dispatch"] / device.ici_bw
    compute_s += moe_disp_comp_s

    # ---- overlap-aware exposure: on the overlapped paths the planner
    # must not sum comm and compute serially. The BYTES stay invariant
    # (predicted_collective_bytes — the G106 audit side); what the
    # chunk schedule changes is how many of their seconds are EXPOSED.
    moe_disp_comm_serial_s = moe_disp_comm_s
    # the bf16 TWIN: what the same exchange would cost at the compute
    # dtype's wire — held beside the (possibly quantized) actual
    # pricing so `tpurun plan` shows what the precision knob buys, and
    # so the monotonicity pin (quantized <= bf16, both directions) has
    # an in-breakdown anchor. At precision "bf16" the twins are equal.
    moe_disp_comm_bf16_serial_s = moe_disp_comm_serial_s
    if (model.num_experts > 0 and model.moe_dispatch == "grouped_ep"
            and model.moe_precision != "bf16"):
        import dataclasses as _dc

        _, bf16_bytes = _moe_dispatch_terms(
            _dc.replace(model, moe_precision="bf16"), device, eff,
            tokens_per_chip, data * fsdp,
        )
        moe_disp_comm_bf16_serial_s = bf16_bytes / device.ici_bw
    moe_disp_comm_bf16_s = moe_disp_comm_bf16_serial_s
    chunks = max(1, int(getattr(model, "moe_dispatch_chunks", 1)))
    if (model.num_experts > 0 and model.moe_dispatch == "grouped_ep"
            and moe_disp_comm_s > 0):
        # what the row exchange hides under: the expert FFN's own
        # grouped GEMMs (up+down, fwd+bwd) on this chip's rows —
        # per-chunk exchange c+1 runs beneath chunk c's GEMMs
        f_dim = model.ffn_mult * model.hidden_size
        gemm_flops = (
            12.0 * tokens_per_chip * max(1, model.moe_top_k)
            * model.hidden_size * f_dim * model.num_layers
        )
        moe_gemm_s = gemm_flops / (device.flops_per_s * eff)
        moe_disp_comm_s = overlap_exposed_comm(
            moe_disp_comm_serial_s, moe_gemm_s, chunks)
        moe_disp_comm_bf16_s = overlap_exposed_comm(
            moe_disp_comm_bf16_serial_s, moe_gemm_s, chunks)

    # dense-wire split twins: gather legs (dtype-aware — what the
    # fsdp_precision knob compresses) vs the grad reduce-scatter (the
    # param dtype GSPMD actually ships); the bf16 twins hold the
    # unquantized pricing beside them so `tpurun plan` shows what the
    # precision knob buys and the monotonicity pin (quantized <= bf16,
    # both directions) has an in-breakdown anchor
    gather_b, scatter_b = model.fsdp_byte_split(fsdp, tensor, pipe)
    fsdp_gather_serial_s = gather_b / device.ici_bw
    fsdp_scatter_s = scatter_b / device.ici_bw
    fsdp_gather_s = fsdp_gather_serial_s
    fsdp_comm_serial_s = fsdp_gather_serial_s + fsdp_scatter_s
    bf16_gather_serial_s = fsdp_gather_serial_s
    if fsdp > 1 and model.fsdp_precision != "bf16":
        import dataclasses as _dc

        bf16_gather_b, _ = _dc.replace(
            model, fsdp_precision="bf16"
        ).fsdp_byte_split(fsdp, tensor, pipe)
        bf16_gather_serial_s = bf16_gather_b / device.ici_bw
    fsdp_comm_bf16_serial_s = bf16_gather_serial_s + fsdp_scatter_s
    bf16_gather_s = bf16_gather_serial_s
    if model.fsdp_prefetch and fsdp > 1 and fsdp_comm_s > 0:
        # layer prefetch hides the GATHER legs (forward all-gather +
        # the backward re-gather) under the neighboring layers'
        # compute — a chunk schedule with one chunk per layer; the
        # grad reduce-scatter has nothing later to hide under
        fsdp_gather_s = overlap_exposed_comm(
            fsdp_gather_serial_s, compute_s, max(1, model.num_layers))
        bf16_gather_s = overlap_exposed_comm(
            bf16_gather_serial_s, compute_s, max(1, model.num_layers))
    fsdp_comm_s = fsdp_gather_s + fsdp_scatter_s
    fsdp_comm_bf16_s = bf16_gather_s + fsdp_scatter_s

    # comm + dispatch fold into the step time through the shared
    # combiner (overlap max + dispatch floor; see combine_step_time)
    comm_s = (tp_comm_s + fsdp_comm_s + dp_comm_s + seq_comm_s
              + pipe_comm_s + moe_disp_comm_s)
    dispatch_s = HOST_DISPATCH_OVERHEAD_S / max(1, steps_per_call)
    step_s = combine_step_time(compute_s, comm_s, dispatch_s)

    # ---- memory (modeled on the production path: flash attention, so
    # no S^2 tile; dots_saveable-style per-layer saves). Terms validated
    # against XLA memory_analysis of 7B AOT compiles: 28.87 GB/chip at
    # data=2 x fsdp=4 x tensor=2 (reproduced by tests/test_aot.py's slow
    # cross-check) and 27.39 GB at data=8 x tensor=2 (AOT_7B.json).
    param_shard = model.param_count * (
        model.param_bytes + model.optim_bytes_per_param
    ) / (fsdp * tensor * pipe)
    # gradient AND optimizer-update trees materialize in f32 during the
    # step (donation reuses the state buffers, not these); both are
    # sharded over the model axes only, replicated across data
    grad_temp = 2 * model.param_count * 4 / (fsdp * tensor * pipe)
    # fsdp all-gather working set: at least 2 layers' worth of gathered
    # bf16 params live at once (current + prefetch); XLA sometimes hoists
    # the whole stacked gather out of the layer scan, which the 0.8 fit
    # threshold below leaves headroom for
    gather_buf = 0.0
    if fsdp > 1:
        per_layer = model.param_count * model.param_bytes / max(
            model.num_layers, 1
        ) / (tensor * pipe)
        gather_buf = 2 * per_layer
    # activations: the remat floor persists ~2 residual-stream saves per
    # layer; recomputation additionally holds ONE layer's full working
    # set (attention projections + MLP gate/up, tensor-sharded) at a
    # time during the backward sweep
    # residual stream (unsharded) + attention projections and MLP
    # gate/up, both tensor-sharded
    layer_working = act_elems * model.dtype_bytes * (
        1.0 + (2.0 + 2.0 * model.ffn_mult) / tensor
    )
    act_bytes = (
        model.num_layers / pipe
    ) * act_elems * model.dtype_bytes * 2 + layer_working
    # vocab logits in f32, forward value + backward cotangent
    logits_bytes = (
        rows * (model.seq_len / seq) * model.vocab_size / tensor * 4 * 2
    )
    memory = (
        param_shard + grad_temp + gather_buf + act_bytes + logits_bytes
    )
    # 0.8: headroom for allocator fragmentation, collective buffers, and
    # the hoisted-gather case the model undercounts (measured 28.87 vs
    # modeled ~22.7 GB on the 7B AOT point => ~1.3x, inside the margin)
    fits = memory < device.hbm_bytes * 0.8
    if not heads_shardable:
        # the attention program cannot be built for this head/tensor
        # combination — never feasible, and never the least-bad fallback
        fits = False
        step_s = float("inf")

    # predicted MFU convention: MODEL flops (6N+attn), not recompute
    # flops; bounded < 1 by construction (step_s >= exec/(n*peak*0.9))
    predicted_mfu = (
        flops / (n_chips * device.flops_per_s * step_s)
        if step_s != float("inf") else 0.0
    )

    return PlanScore(
        plan=plan,
        step_time_s=step_s,
        memory_bytes=memory,
        fits=fits,
        predicted_mfu=predicted_mfu,
        breakdown={
            "compute_s": compute_s,
            "dispatch_s": dispatch_s,
            "tp_comm_s": tp_comm_s,
            # the EXPOSED seconds (post-overlap) — what enters the
            # step time; the *_serial_s twins keep the pre-overlap
            # figure visible so `tpurun plan` can show what the chunk
            # schedule bought
            "fsdp_comm_s": fsdp_comm_s,
            "fsdp_comm_serial_s": fsdp_comm_serial_s,
            # the dense-wire split: gather legs (dtype-aware, the
            # fsdp_precision knob's lever, overlappable by
            # fsdp_prefetch) vs the grad reduce-scatter (param-dtype,
            # never hidden) — plus the bf16 twins (equal to the pair
            # above at precision "bf16"), the quantized-vs-bf16 delta
            # `tpurun plan` surfaces
            "fsdp_gather_s": fsdp_gather_s,
            "fsdp_gather_serial_s": fsdp_gather_serial_s,
            "fsdp_scatter_s": fsdp_scatter_s,
            "fsdp_comm_bf16_s": fsdp_comm_bf16_s,
            "fsdp_comm_bf16_serial_s": fsdp_comm_bf16_serial_s,
            "dp_comm_s": dp_comm_s,
            "seq_comm_s": seq_comm_s,
            "pipe_comm_s": pipe_comm_s,
            "moe_disp_comp_s": moe_disp_comp_s,
            "moe_disp_comm_s": moe_disp_comm_s,
            "moe_disp_comm_serial_s": moe_disp_comm_serial_s,
            # the bf16 twins (what the wire would cost unquantized;
            # equal to the pair above at precision "bf16") — the
            # quantized-vs-bf16 delta `tpurun plan` surfaces
            "moe_disp_comm_bf16_s": moe_disp_comm_bf16_s,
            "moe_disp_comm_bf16_serial_s": moe_disp_comm_bf16_serial_s,
            "moe_dispatch_chunks": float(chunks),
            # predicted analog of the attribution plane's measured
            # exposed-comm bound (1 - compute/step): what `tpurun
            # plan`/`attribution` print beside the measured gauge
            "exposed_comm_frac": (
                min(max(1.0 - compute_s / step_s, 0.0), 1.0)
                if step_s not in (0.0, float("inf")) else 0.0
            ),
            "param_shard_bytes": param_shard,
            "grad_temp_bytes": grad_temp,
            "gather_buf_bytes": gather_buf,
            "act_bytes": act_bytes,
            "exec_flops": exec_flops,
            "efficiency": eff,
        },
    )


def plan_mesh(
    model: ModelSpec,
    n_devices: int,
    device: DeviceSpec = DeviceSpec(),
    candidates: Optional[List[MeshPlan]] = None,
    top_k: int = 1,
    remat_policy: str = "",
) -> List[PlanScore]:
    """Score every factorization; return the ``top_k`` feasible plans,
    fastest first (the MIP planner's argmin under constraints)."""
    plans = candidates if candidates is not None else candidate_plans(
        n_devices
    )
    scored = [estimate(p, model, device, remat_policy=remat_policy)
              for p in plans]
    feasible = [s for s in scored if s.fits]
    pool = feasible if feasible else scored  # degrade gracefully
    pool.sort(key=lambda s: s.step_time_s)
    if not feasible:
        logger.warning(
            "no mesh plan fits in HBM for %d devices; returning least-bad",
            n_devices,
        )
    return pool[:top_k]


def plan_stages(
    layer_costs: List[float], num_stages: int
) -> List[Tuple[int, int]]:
    """Split layers into contiguous stages minimizing the max stage cost
    (reference base_stage_planner.py:125). Returns [start, end) spans.

    Dynamic programming over prefix sums — optimal, O(L^2 * P)."""
    layers = len(layer_costs)
    if num_stages <= 0 or layers < num_stages:
        raise ValueError(
            f"cannot split {layers} layers into {num_stages} stages"
        )
    prefix = [0.0]
    for cost in layer_costs:
        prefix.append(prefix[-1] + cost)

    def span_cost(i, j):
        return prefix[j] - prefix[i]

    inf = float("inf")
    # best[p][j]: minimal max-stage-cost splitting first j layers into p
    best = [[inf] * (layers + 1) for _ in range(num_stages + 1)]
    cut = [[0] * (layers + 1) for _ in range(num_stages + 1)]
    best[0][0] = 0.0
    for p in range(1, num_stages + 1):
        for j in range(p, layers + 1):
            for i in range(p - 1, j):
                c = max(best[p - 1][i], span_cost(i, j))
                if c < best[p][j]:
                    best[p][j] = c
                    cut[p][j] = i
    spans = []
    j = layers
    for p in range(num_stages, 0, -1):
        i = cut[p][j]
        spans.append((i, j))
        j = i
    return list(reversed(spans))


def plan_stage_depths(
    layer_costs: List[float], num_stages: int, num_virtual: int = 1
) -> Tuple[int, ...]:
    """Per-stage-chunk layer counts for ``Strategy.stage_depths``.

    Runs the ``plan_stages`` DP over V*P contiguous chunks (visit
    order), minimizing the max chunk cost — the quantity a lockstep
    tick pays. With uniform layer costs this is the balanced
    ceil/floor split of L % (V*P) != 0; with heterogeneous costs
    (e.g. a future mixed dense/MoE stack) it shifts layer counts off
    the expensive chunks. Feed the result to
    ``Strategy(stage_depths=...)`` / ``apply_pipelined``.
    """
    spans = plan_stages(layer_costs, num_stages * num_virtual)
    return tuple(j - i for i, j in spans)


# -- the serving decode term --------------------------------------------------
#
# Decode is the MEMORY-BOUND regime: each step reads every live KV page
# plus (its share of) the weights once, per generated token — so the
# bytes term is KV reads + weight reads over HBM bandwidth, and the
# FLOPs term almost never binds. The slot width multiplies tokens/step
# for nearly-flat step time (the weight read amortizes across slots;
# the KV read scales with slots), which is exactly why continuous
# batching wins and why ``serve_slots`` is an optimizer knob — until
# the pool no longer fits, which is the HBM feasibility gate's job.


def kv_bytes_per_elem(kv_precision: str, channels: int = 0) -> float:
    """Stored bytes per KV element: int8 = values + the f32 per-block
    scale side-band (the ``ops.quantize`` block geometry, resolved
    against the channel/head dim when known); ONE formula for pricing,
    the feasibility gate, ``KVCacheSpec.bytes_per_slot`` and the bench
    wedge — they cannot drift."""
    if kv_precision == "int8":
        from dlrover_tpu.ops.quantize import (
            QUANT_BLOCK,
            resolve_quant_block,
        )

        block = (resolve_quant_block(channels) if channels
                 else QUANT_BLOCK)
        return 1.0 + 4.0 / block
    if kv_precision == "bf16":
        return 2.0
    return 4.0


def serve_cache_bytes(m: ModelSpec, serve_slots: int, max_seq: int,
                      kv_precision: str = "f32") -> float:
    """Whole-pool KV residency (K and V, every slot at full depth —
    preallocated, so this is what must FIT, not an average)."""
    kv_heads = m.kv_heads or m.num_heads or 1
    heads = max(1, m.num_heads or 1)
    head_dim = m.hidden_size // heads
    elems = (m.num_layers * serve_slots * max_seq
             * max(1, kv_heads) * head_dim)
    return 2.0 * elems * kv_bytes_per_elem(kv_precision, head_dim)


def serve_prefix_pool_bytes(m: ModelSpec, pool_pages: int,
                            page_size: int,
                            kv_precision: str = "f32") -> float:
    """Device residency of the shared prefix pool (K and V for every
    layer, ``pool_pages`` pages of ``page_size`` tokens) — the SAME
    byte formula as ``serve_cache_bytes``/``KVCacheSpec``. The pool
    REPLICATES across the data axes (any slot may admit any page), so
    the per-device HBM charge is this number UNDIVIDED."""
    kv_heads = m.kv_heads or m.num_heads or 1
    heads = max(1, m.num_heads or 1)
    head_dim = m.hidden_size // heads
    elems = (m.num_layers * max(0, int(pool_pages))
             * max(1, int(page_size)) * max(1, kv_heads) * head_dim)
    return 2.0 * elems * kv_bytes_per_elem(kv_precision, head_dim)


def decode_kv_read_bytes(m: ModelSpec, serve_slots: int, seq_fill: int,
                         kv_precision: str = "f32") -> float:
    """Bytes of KV pages one decode step reads: every live token's K
    and V, every layer, every slot (``seq_fill`` = the depth actually
    filled — callers price at max_seq/2 as the steady-state average)."""
    kv_heads = m.kv_heads or m.num_heads or 1
    heads = max(1, m.num_heads or 1)
    head_dim = m.hidden_size // heads
    elems = (m.num_layers * serve_slots * seq_fill
             * max(1, kv_heads) * head_dim)
    return 2.0 * elems * kv_bytes_per_elem(kv_precision, head_dim)


def estimate_decode(m: ModelSpec, num_devices: int, serve_slots: int,
                    prefill_chunk: int, max_seq: int,
                    kv_precision: str = "f32",
                    prefix_pool_pages: int = 0,
                    page_size: int = 16,
                    prefix_hit_rate: float = 0.0,
                    spec_draft_len: int = 0,
                    spec_accept_rate: float = -1.0,
                    device: Optional[DeviceSpec] = None) -> Dict:
    """Price one serving config: predicted decode-step seconds and
    tokens/second, with the breakdown the decision trail shows.

    Terms (per device, ``num_devices`` shards the batch and weights):
      kv_read_s      KV pages at half fill over HBM bandwidth
      weight_read_s  2 bytes/param/step over HBM bandwidth (decode
                     re-reads the weights once per step; batch-
                     amortized across slots by construction)
      flops_s        2*params*slots/peak — the check that the regime
                     really is memory-bound
      dispatch_s     the PR 3 host floor, one dispatch per step
      prefill amortization: a bigger chunk admits a prompt in fewer
                     interleaved steps but each chunk stalls one
                     decode step longer — priced as chunk_steps
                     spread over the chunk's tokens. A nonzero prefix
                     pool discounts it by the expected hit rate
                     (matched tokens are page COPIES, priced as one
                     dispatch per page instead of a chunk prefill).
      speculative decode (``spec_draft_len`` K > 0): a verify step
                     emits 1 + rate*K expected tokens but computes
                     K+1 positions — the FLOPs term scales by K+1
                     while the memory terms stay per-step, so the
                     trade is real, not assumed. Priced ONLY from an
                     observed ``spec_accept_rate`` in [0, 1]: with no
                     evidence (rate < 0) the estimate is EXACTLY the
                     K=0 estimate — 1.0x, no speculative speedup
                     assumed (the prefix-discount discipline).

    Returns {"step_s", "tokens_per_s", "cache_bytes",
    "cache_bytes_per_device", "breakdown"}. ``tokens_per_s`` is
    monotone-increasing in ``serve_slots`` until the HBM gate refuses
    the pool — which is the caller's check (``serve_cache_bytes`` plus
    the UNDIVIDED ``serve_prefix_pool_bytes`` against the device
    budget), not this function's.
    """
    dev = device or DeviceSpec()
    n = max(1, int(num_devices))
    slots = max(1, int(serve_slots))
    chunk = max(1, int(prefill_chunk))
    pool_pages = max(0, int(prefix_pool_pages))
    hit_rate = min(1.0, max(0.0, float(prefix_hit_rate))) \
        if pool_pages else 0.0
    cache_bytes = serve_cache_bytes(m, slots, max_seq, kv_precision)
    pool_bytes = serve_prefix_pool_bytes(
        m, pool_pages, page_size, kv_precision)
    kv_read = decode_kv_read_bytes(
        m, slots, max(1, max_seq // 2), kv_precision) / n
    kv_read_s = kv_read / dev.hbm_bw
    weight_read_s = (m.param_count * 2.0 / n) / dev.hbm_bw
    flops_s = (2.0 * m.param_count * slots / n) / (
        dev.flops_per_s * MAX_EFFICIENCY)
    dispatch_s = HOST_DISPATCH_OVERHEAD_S
    # a prompt of L tokens takes ceil(L/chunk) interleaved prefill
    # calls; each call costs ~one dispatch + the chunk's weight read.
    # Amortized per generated token (assuming ~one admission per slot
    # drain), this prefers bigger chunks until the chunk itself
    # dominates a decode step — the trade the optimizer enumerates.
    avg_prompt = max(1.0, max_seq / 4.0)
    prefill_calls = math.ceil(avg_prompt / chunk)
    prefill_s_per_req = prefill_calls * (
        dispatch_s + weight_read_s + chunk * kv_read_s / max(1, max_seq // 2) / slots)
    # prefix reuse: an expected-hit admission replaces its matched
    # prefill with per-page admit copies (one dispatch each; the page
    # bytes move at HBM bandwidth, negligible beside the dispatch).
    # The pool can only ever hold hit tokens it has pages for, so the
    # discount is additionally capped by the pool's token capacity
    # against the average prompt.
    if pool_pages:
        pool_tokens = pool_pages * max(1, int(page_size))
        coverage = min(1.0, pool_tokens / avg_prompt)
        discount = hit_rate * coverage
        copy_pages = avg_prompt / max(1, int(page_size))
        copy_s_per_req = discount * copy_pages * dispatch_s
        prefill_s_per_req = ((1.0 - discount) * prefill_s_per_req
                             + copy_s_per_req)
    avg_new = max(1.0, max_seq / 4.0)
    prefill_amort_s = prefill_s_per_req / avg_new / slots
    # speculative decode: evidence-gated. k stays 0 unless BOTH the
    # knob is on and an acceptance rate was observed, so the no-spec /
    # no-evidence estimate below is byte-identical to today's — the
    # "zero evidence prices at exactly 1.0x" contract the optimizer
    # and its tests pin.
    k = max(0, int(spec_draft_len))
    rate = float(spec_accept_rate)
    if k > 0 and 0.0 <= rate <= 1.0:
        # expected emitted tokens per verify step (greedy acceptance
        # of an i.i.d.-approximated draft stream: 1 + rate*K is the
        # linear lower bound of the geometric sum — conservative)
        expected_tokens = 1.0 + rate * k
        # the verify step runs K+1 positions: FLOPs scale, the KV and
        # weight reads stay one pass per step (slot-major pool reads
        # the same pages; weights are read once per step regardless)
        spec_flops_s = flops_s * (k + 1)
        step_s = max(kv_read_s + weight_read_s + prefill_amort_s,
                     spec_flops_s, dispatch_s)
        tokens_per_s = slots * expected_tokens / step_s
    else:
        expected_tokens = 1.0
        step_s = max(kv_read_s + weight_read_s + prefill_amort_s,
                     flops_s, dispatch_s)
        tokens_per_s = slots / step_s
    return {
        "step_s": step_s,
        "tokens_per_s": tokens_per_s,
        "cache_bytes": cache_bytes,
        "cache_bytes_per_device": cache_bytes / n + pool_bytes,
        "breakdown": {
            "kv_read_s": kv_read_s,
            "weight_read_s": weight_read_s,
            "flops_s": flops_s,
            "dispatch_s": dispatch_s,
            "prefill_amort_s": prefill_amort_s,
            "prefix_pool_bytes": pool_bytes,
            "prefix_hit_rate": hit_rate,
            "spec_draft_len": k,
            "spec_accept_rate": (rate if 0.0 <= rate <= 1.0
                                 else -1.0),
            "spec_expected_tokens_per_step": expected_tokens,
            # channel-resolved, exactly as the terms above priced it —
            # the decision trail must show the number that was USED
            "kv_bytes_per_elem": kv_bytes_per_elem(
                kv_precision,
                m.hidden_size // max(1, m.num_heads or 1)),
        },
    }


def model_spec_from_llama(config, global_batch: int) -> ModelSpec:
    """Convenience: derive a ModelSpec from a LlamaConfig."""
    import numpy as np

    from dlrover_tpu.common.config import get_context
    from dlrover_tpu.models import llama

    return ModelSpec(
        param_count=llama.param_count(config),
        num_layers=config.num_layers,
        hidden_size=config.hidden_size,
        seq_len=config.max_seq_len,
        global_batch=global_batch,
        vocab_size=config.vocab_size,
        param_bytes=np.dtype(config.param_dtype).itemsize,
        ffn_mult=config.intermediate_size / config.hidden_size,
        num_heads=config.num_heads,
        kv_heads=config.num_kv_heads,
        num_experts=config.num_experts,
        moe_top_k=config.moe_top_k,
        moe_capacity_factor=config.moe_capacity_factor,
        moe_dispatch=config.moe_dispatch,
        # 0 = the Context knob, exactly how ops.moe resolves it at
        # trace time — the spec must price the program that will build
        moe_dispatch_chunks=(
            config.moe_dispatch_chunks
            or int(getattr(get_context(), "dispatch_chunks", 1))
        ),
        fsdp_prefetch=(
            bool(config.fsdp_prefetch)
            if config.fsdp_prefetch is not None
            else bool(getattr(get_context(), "fsdp_prefetch", False))
        ),
        # "" = the Context knob, exactly how ops.moe resolves it at
        # trace time — the spec must price the wire the program ships
        moe_precision=(
            config.moe_precision
            or str(getattr(get_context(), "moe_precision", "bf16")
                   or "bf16")
        ),
        # "" = the Context knob, exactly how models/llama resolves the
        # dense wire at trace time (resolve_fsdp_precision)
        fsdp_precision=(
            getattr(config, "fsdp_precision", "")
            or str(getattr(get_context(), "fsdp_precision", "bf16")
                   or "bf16")
        ),
    )
