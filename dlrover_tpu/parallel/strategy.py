"""The acceleration strategy object.

Role parity: atorch's strategy — an ordered list of optimization methods
(``atorch/atorch/auto/strategy.py``, picklable, re-fit to the world size by
``adjust_strategy``). On TPU the whole wrapper catalog (DDP/ZeRO/FSDP/TP/
AMP/checkpointing) collapses into four declarative knobs:

  mesh      : how devices are arranged        (parallel_mode/zero/tp/pp)
  rules     : where tensors live on the mesh  (fsdp wrap policy, tp plan)
  remat     : what activations to save        (checkpoint_optimization)
  dtypes    : what precision to compute in    (amp/half optimization)

plus ``grad_accum_steps`` — the elasticity lever that keeps the global
batch fixed when the world shrinks (``trainer/torch/elastic.py:387-401``).
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Optional, Tuple

from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.sharding_rules import (
    ShardingRules,
    bert_pp_rules,
    bert_rules,
    clip_rules,
    glm_pp_rules,
    glm_rules,
    gpt2_pp_rules,
    llama_pp_rules,
    llama_rules,
    moe_ep_rules,
    moe_rules,
    neox_pp_rules,
    neox_rules,
)

RULE_SETS = {
    "fsdp": lambda: ShardingRules(),
    "llama": llama_rules,
    "llama_pp": llama_pp_rules,
    "moe": moe_rules,
    # dropless expert-parallel ("grouped_ep" dispatch): expert FFN dims
    # unsharded so the grouped Pallas kernel stays per-shard inside its
    # shard_map; experts over (data x fsdp) as in "moe"
    "moe_ep": moe_ep_rules,
    "bert": bert_rules,
    "bert_pp": bert_pp_rules,
    "clip": clip_rules,
    "neox": neox_rules,
    "neox_pp": neox_pp_rules,
    "glm": glm_rules,
    "glm_pp": glm_pp_rules,
    "gpt2_pp": gpt2_pp_rules,
}


@dataclass
class DtypePolicy:
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    output_dtype: str = "float32"


@dataclass
class Strategy:
    mesh: MeshPlan = field(default_factory=MeshPlan)
    rule_set: str = "fsdp"
    remat_policy: str = ""  # "", "full", "dots_saveable", "nothing_saveable"
    dtypes: DtypePolicy = field(default_factory=DtypePolicy)
    grad_accum_steps: int = 1
    # pipeline schedule: virtual stages per physical stage (V>1 = the
    # circular/interleaved schedule, PiPPy StageInterleaver parity —
    # bubble shrinks (P-1)/(M+P-1) -> (P-1)/(V*M+P-1)). Consumed by
    # model forwards via ``apply_pipelined(..., num_virtual=...)``.
    num_virtual: int = 1
    # uneven pipeline stage split: per-stage-chunk layer counts (V*P
    # entries in visit order, summing to the model's layer count). None
    # = even split. Lets the planner place a lighter first/last stage
    # (embed/head-adjacent) or handle L % (V*P) != 0 — reference's
    # uneven stage placement (atorch base_stage_planner.py:125).
    # Consumed by ``apply_pipelined(..., stage_depths=...)``.
    stage_depths: Optional[Tuple[int, ...]] = None
    # global batch row count; accelerate() validates the example batch
    # against it and adjust_to_world keeps accum a divisor of it.
    # 0 = derived from the example batch at accelerate() time.
    global_batch_size: int = 0

    def rules(self) -> ShardingRules:
        factory = RULE_SETS.get(self.rule_set)
        if factory is None:
            raise ValueError(
                f"unknown rule set {self.rule_set!r}; "
                f"have {sorted(RULE_SETS)}"
            )
        return factory()

    # -- elasticity ---------------------------------------------------------

    def adjust_to_world(self, num_devices: int,
                        prev_num_devices: Optional[int] = None) -> "Strategy":
        """Re-fit after a membership change, keeping the global batch fixed.

        The DP degree changes with the world; grad_accum_steps scales
        inversely so batch_per_device * dp * accum stays constant
        (ElasticTrainer semantics, ``elastic.py:387-401``).
        """
        new_mesh = self.mesh.adjust_to_world(num_devices)
        accum = self.grad_accum_steps
        if prev_num_devices and prev_num_devices != num_devices:
            old_dp = max(1, self.mesh.adjust_to_world(prev_num_devices).dp_degree)
            new_dp = max(1, new_mesh.dp_degree)
            accum = max(1, round(self.grad_accum_steps * old_dp / new_dp))
            if self.global_batch_size > 0:
                # accum must divide the per-step batch or the microbatch
                # reshape in accelerate() fails: snap to the nearest
                # divisor of the global batch.
                divisors = [
                    d for d in range(1, self.global_batch_size + 1)
                    if self.global_batch_size % d == 0
                ]
                accum = min(divisors, key=lambda d: abs(d - accum))
        return dataclasses.replace(self, mesh=new_mesh,
                                   grad_accum_steps=accum)

    # -- persistence (reference strategies are picklable; ours are JSON) ----

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "Strategy":
        raw = json.loads(text)
        raw["mesh"] = MeshPlan(**raw.get("mesh", {}))
        raw["dtypes"] = DtypePolicy(**raw.get("dtypes", {}))
        if raw.get("stage_depths") is not None:
            raw["stage_depths"] = tuple(raw["stage_depths"])
        return cls(**raw)

    def save(self, path: str):
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "Strategy":
        with open(path) as f:
            return cls.from_json(f.read())
