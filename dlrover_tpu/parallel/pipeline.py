"""GPipe-style pipeline parallelism over the "pipe" mesh axis.

Role parity: atorch's PiPPy compiler stack (``atorch/atorch/modules/
distributed_modules/compilers/pipe_compiler/distributed_pippy_compiler.py:90-378``
— FX graph split into stages, torch RPC drivers, interleaver). The TPU
formulation needs none of that machinery: stages are a *leading array
dimension* sharded on the "pipe" mesh axis, the whole schedule is a
``lax.scan`` over pipeline ticks, and the per-tick shift of activations to
the next stage (``jnp.roll`` over the stage dim) lowers to an XLA
collective-permute over ICI/DCN. Because this is plain GSPMD (no manual
``shard_map``), it composes freely with the data/fsdp/seq/tensor axes —
tensor-parallel matmuls inside a stage still get their collectives from
the partitioner.

Schedule: GPipe. With M microbatches and P stages the bubble fraction is
(P-1)/(M+P-1); backward runs the reverse schedule automatically because
``jax.grad`` transposes the scan and the collective-permute.

Contract: ``stage_fn(stage_params, state) -> state`` must be
shape/dtype-preserving on ``state`` (homogeneous stages — the transformer
block case); heterogeneous embed/head layers stay *outside* the pipeline
in the surrounding GSPMD program.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def _context_has_axis(axis_name: str) -> bool:
    """Sharding constraints only resolve under a mesh context
    (``jax.sharding.set_mesh``, or the legacy ``with mesh:``
    thread-resources context on old jax — what ``accelerate``
    establishes either way; ``shard_compat.ambient_mesh``); skip them
    when running unsharded."""
    from dlrover_tpu.ops.shard_compat import ambient_mesh

    mesh = ambient_mesh()
    return mesh is not None and axis_name in getattr(
        mesh, "axis_names", ()
    )


def pipe_batch_constraint(
    x: jax.Array,
    axis_name: str = "pipe",
    batch_axes: Tuple = ("data", "fsdp"),
) -> jax.Array:
    """Spread dim 0 of a post-pipeline activation over the pipe axis too.

    The surrounding GSPMD program (embed / final-norm / lm head) has no
    operand sharded on "pipe", so XLA replicates that compute across
    every pipe group — at scale the head is a large fraction of a
    stage's FLOPs. Constraining the batch dim over (batch_axes + pipe)
    is comm-free at this point (replicated -> sharded lowers to a local
    slice) and cuts the outer compute by the pipe degree; the backward
    pays one activation-size all-gather over pipe to re-replicate the
    gradient entering the pipeline. No-op without a pipe mesh axis.
    """
    if not _context_has_axis(axis_name):
        return x
    from jax.sharding import PartitionSpec as P

    return lax.with_sharding_constraint(
        x,
        P((*batch_axes, axis_name),
          *(P.UNCONSTRAINED for _ in range(x.ndim - 1))),
    )


def split_microbatches(tree: PyTree, num_microbatches: int) -> PyTree:
    """[B, ...] leaves -> [M, B/M, ...] microbatch-stacked leaves."""

    def split(x):
        b = x.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch of {b} rows not divisible into "
                f"{num_microbatches} microbatches"
            )
        return x.reshape((num_microbatches, b // num_microbatches)
                         + x.shape[1:])

    return jax.tree.map(split, tree)


def merge_microbatches(tree: PyTree) -> PyTree:
    """[M, mb, ...] -> [M*mb, ...] (inverse of split_microbatches)."""
    return jax.tree.map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]), tree
    )


def _stage_constraint(tree: PyTree, axis_name: str,
                      batch_axes: Optional[Tuple]) -> PyTree:
    """Pin the leading (stage) dim of every leaf on the pipe axis and the
    microbatch dim on the data axes, leaving trailing dims to XLA."""
    from jax.sharding import PartitionSpec as P

    unconstrained = P.UNCONSTRAINED

    def constrain(x):
        spec = [axis_name]
        if x.ndim > 1:
            spec.append(batch_axes)
        spec.extend(unconstrained for _ in range(x.ndim - len(spec)))
        return lax.with_sharding_constraint(x, P(*spec))

    return jax.tree.map(constrain, tree)


def pipeline_apply(
    stage_fn: Callable[[PyTree, PyTree], PyTree],
    stage_params: PyTree,  # leaves [num_stages, ...], pipe-sharded on dim 0
    x_mb: PyTree,  # microbatch-stacked inputs, leaves [M, ...]
    axis_name: str = "pipe",
    batch_axes: Optional[Tuple] = ("data", "fsdp"),
    constrain: bool = True,
    remat_stage: bool = False,
) -> PyTree:
    """Run M microbatches through P homogeneous stages; returns outputs
    with the same [M, ...] layout as ``x_mb``.

    ``stage_fn`` sees one stage's params (dim 0 of ``stage_params``
    stripped by vmap) and one microbatch-shaped ``state``.

    ``remat_stage``: checkpoint each stage application so the tick
    scan's backward stores one stage-boundary state per tick instead
    of every inner layer-scan carry. The stage params here are a scan
    constant, so the checkpoint's saved inputs do not stack per tick.
    """
    stage_leaves = jax.tree.leaves(stage_params)
    if not stage_leaves:
        raise ValueError("stage_params is empty")
    num_stages = stage_leaves[0].shape[0]
    constrain = constrain and _context_has_axis(axis_name)
    if constrain:
        from jax.sharding import PartitionSpec as P

        stage_params = jax.tree.map(
            lambda w: lax.with_sharding_constraint(
                w,
                P(axis_name, *(P.UNCONSTRAINED for _ in range(w.ndim - 1))),
            ),
            stage_params,
        )
    x_leaves = jax.tree.leaves(x_mb)
    num_mb = x_leaves[0].shape[0]
    num_ticks = num_mb + num_stages - 1

    if remat_stage:
        stage_fn = jax.checkpoint(stage_fn)
    vstage = jax.vmap(stage_fn)

    def maybe_constrain(tree):
        if not constrain:
            return tree
        return _stage_constraint(tree, axis_name, batch_axes)

    # state: one in-flight microbatch per stage, [P, mb, ...]
    state0 = jax.tree.map(
        lambda x: jnp.zeros((num_stages,) + x.shape[1:], x.dtype), x_mb
    )
    outs0 = jax.tree.map(jnp.zeros_like, x_mb)

    def tick(carry, t):
        state, outs = carry
        # feed the next microbatch into stage 0 (garbage during drain)
        inp = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, num_mb - 1), 0, keepdims=False
            ),
            x_mb,
        )
        state = jax.tree.map(
            lambda s, i: lax.dynamic_update_index_in_dim(s, i, 0, 0),
            state, inp,
        )
        state = maybe_constrain(state)
        y = vstage(stage_params, state)
        y = maybe_constrain(y)
        # stage P-1 finished microbatch t-(P-1): collect it
        out_idx = t - (num_stages - 1)
        valid = jnp.logical_and(out_idx >= 0, out_idx < num_mb)
        idx = jnp.clip(out_idx, 0, num_mb - 1)
        outs = jax.tree.map(
            lambda o, yy: jnp.where(
                valid,
                lax.dynamic_update_index_in_dim(o, yy[-1], idx, 0),
                o,
            ),
            outs, y,
        )
        # shift every stage's output to its successor: one collective
        # permute around the pipe ring (slot 0 is overwritten next tick)
        state = jax.tree.map(lambda yy: jnp.roll(yy, 1, axis=0), y)
        return (state, outs), None

    (_, outs), _ = lax.scan(
        tick, (state0, outs0), jnp.arange(num_ticks)
    )
    return outs


def stack_stages(layer_params: PyTree, num_stages: int) -> PyTree:
    """[L, ...] scan-stacked layer params -> [P, L/P, ...] stage chunks."""

    def restack(x):
        layers = x.shape[0]
        if layers % num_stages:
            raise ValueError(
                f"{layers} layers not divisible into {num_stages} stages"
            )
        return x.reshape((num_stages, layers // num_stages) + x.shape[1:])

    return jax.tree.map(restack, layer_params)


def stack_stages_uneven(
    layer_params: PyTree, depths
) -> Tuple[PyTree, jax.Array]:
    """[L, ...] scan-stacked layer params -> ([P, Lmax, ...] zero-padded
    stage chunks, [P, Lmax] float validity mask).

    Per-stage layer counts (``depths``, summing to L) express UNEQUAL
    stage splits — a deliberately lighter first/last stage, or a layer
    count that doesn't divide by the stage count. Role parity: the
    reference's uneven stage placement
    (``atorch/atorch/auto/opt_lib/shard_planners/base_stage_planner.py:125``).

    Cost model: any lockstep pipeline ticks at the HEAVIEST stage's
    cost, so running every stage over Lmax = max(depths) padded slots
    costs the same wall-clock as a ragged implementation would — the
    light stages' padded slots burn cycles the tick-barrier would waste
    anyway. The real overheads are (P*Lmax - L)/L extra parameter
    memory and the masked slots' energy. The caller's ``stage_fn`` must
    skip masked slots (carry the state through where mask == 0).
    """
    depths = tuple(int(d) for d in depths)
    if not depths or any(d <= 0 for d in depths):
        raise ValueError(f"stage depths must be positive: {depths}")
    lmax = max(depths)
    offsets = [0]
    for d in depths:
        offsets.append(offsets[-1] + d)
    total = offsets[-1]

    def restack(x):
        if x.shape[0] != total:
            raise ValueError(
                f"{x.shape[0]} layers != sum(depths) = {total}"
            )
        chunks = []
        for p, d in enumerate(depths):
            chunk = lax.slice_in_dim(x, offsets[p], offsets[p] + d, axis=0)
            if d < lmax:
                pad = jnp.zeros((lmax - d,) + x.shape[1:], x.dtype)
                chunk = jnp.concatenate([chunk, pad], axis=0)
            chunks.append(chunk)
        return jnp.stack(chunks)

    mask = jnp.asarray(
        [[1.0 if j < d else 0.0 for j in range(lmax)] for d in depths],
        jnp.float32,
    )
    return jax.tree.map(restack, layer_params), mask


def stack_stages_interleaved_uneven(
    layer_params: PyTree, num_stages: int, num_virtual: int, depths
) -> Tuple[PyTree, jax.Array]:
    """[L, ...] -> ([V, P, Lmax, ...] zero-padded chunks, [V, P, Lmax]
    mask) for the circular schedule with per-chunk layer counts.

    ``depths`` has V*P entries in VISIT order — round 0 stages 0..P-1,
    then round 1 stages 0..P-1, ... — matching the logical layer order
    of ``stack_stages_interleaved``. Physical stage p's total layer load
    is ``sum(depths[r*P + p] for r in range(V))``; a lighter first/last
    stage means making those column sums smaller at the ends.
    """
    depths = tuple(int(d) for d in depths)
    if len(depths) != num_stages * num_virtual:
        raise ValueError(
            f"need {num_virtual}x{num_stages} = "
            f"{num_virtual * num_stages} depths, got {len(depths)}"
        )
    stacked, mask = stack_stages_uneven(layer_params, depths)

    def to_vp(x):
        return x.reshape((num_virtual, num_stages) + x.shape[1:])

    return jax.tree.map(to_vp, stacked), to_vp(mask)


def dispatch_pipeline(
    stage_fn: Callable[[PyTree, PyTree], PyTree],
    layer_params: PyTree,
    state_mb: PyTree,
    num_stages: int,
    num_virtual: int = 1,
    stage_depths=None,
    remat_stage: bool = False,
) -> PyTree:
    """Shared stacking + schedule dispatch for model ``apply_pipelined``
    implementations: picks gpipe vs interleaved vs their uneven-depth
    variants, stacks ``layer_params`` accordingly, and runs the
    schedule. ``stage_fn((layers_chunk, mask), state)`` receives
    ``mask=None`` on the even paths (None is an empty pytree, so vmap
    passes it through untouched); with a mask it must skip masked slots
    (carry the state through where mask == 0, e.g. via
    ``masked_layer_scan``).

    ``remat_stage``: checkpoint each stage application so the tick
    scan's backward saves only STAGE-BOUNDARY activations (one state
    per tick), not every inner layer-scan carry — without it a deep
    stage saves ticks x layers-per-stage residuals, which at 70B scale
    is tens of GB per device and OOMs where plain PP activation math
    (microbatches x stage boundaries) fits comfortably. The checkpoint
    is applied INSIDE the schedules (around the round-selection in the
    interleaved case) so the saved inputs are the loop-INVARIANT
    params plus the per-tick state — wrapping the stage fn itself
    would stack the dynamically-selected param chunk per tick, ~20 GB
    of param copies at 70B. The model's per-layer remat policy still
    shapes the recompute inside the stage."""
    if stage_depths is not None:
        if num_virtual > 1:
            stage_params = stack_stages_interleaved_uneven(
                layer_params, num_stages, num_virtual, stage_depths
            )
            return pipeline_apply_interleaved(
                stage_fn, stage_params, state_mb,
                remat_stage=remat_stage,
            )
        if len(stage_depths) != num_stages:
            raise ValueError(
                f"stage_depths has {len(stage_depths)} entries "
                f"for {num_stages} stages"
            )
        stage_params = stack_stages_uneven(layer_params, stage_depths)
        return pipeline_apply(stage_fn, stage_params, state_mb,
                              remat_stage=remat_stage)
    if num_virtual > 1:
        stage_params = (stack_stages_interleaved(
            layer_params, num_stages, num_virtual
        ), None)
        return pipeline_apply_interleaved(stage_fn, stage_params, state_mb,
                                          remat_stage=remat_stage)
    stage_params = (stack_stages(layer_params, num_stages), None)
    return pipeline_apply(stage_fn, stage_params, state_mb,
                          remat_stage=remat_stage)


def masked_layer_scan(
    block: Callable, x: jax.Array, layers_chunk: PyTree,
    mask: Optional[jax.Array],
) -> jax.Array:
    """Scan ``block(carry, layer) -> (new_carry, _)`` over a stage
    chunk. ``mask=None`` (even split) is a plain scan; with a mask
    (zero-padded uneven chunk) masked slots carry the state through
    untouched (the zero params keep the masked branch finite, so it
    cannot poison the selected branch's gradient). For blocks whose
    carry is the activation alone; models with richer carries write
    their own slot loop."""
    if mask is None:
        x, _ = lax.scan(block, x, layers_chunk)
        return x

    def slot(carry, inp):
        layer, valid = inp
        new_x, _ = block(carry, layer)
        return jnp.where(valid > 0, new_x, carry), None

    x, _ = lax.scan(slot, x, (layers_chunk, mask))
    return x


def stack_stages_interleaved(
    layer_params: PyTree, num_stages: int, num_virtual: int
) -> PyTree:
    """[L, ...] -> [V, P, L/(V*P), ...] chunks for the circular schedule.

    Logical layer order: a microbatch visits device 0..P-1 with round-0
    chunks, wraps, visits 0..P-1 with round-1 chunks, ... — so layer
    ``l`` lands in chunk (round r = l // (P*per), device p = (l // per)
    % P).
    """

    def restack(x):
        layers = x.shape[0]
        total = num_stages * num_virtual
        if layers % total:
            raise ValueError(
                f"{layers} layers not divisible into {num_virtual}x"
                f"{num_stages} virtual stages"
            )
        per = layers // total
        return x.reshape(
            (num_virtual, num_stages, per) + x.shape[1:]
        )

    return jax.tree.map(restack, layer_params)


def pipeline_apply_interleaved(
    stage_fn: Callable[[PyTree, PyTree], PyTree],
    stage_params: PyTree,  # leaves [V, P, ...]; dim 1 pipe-sharded
    x_mb: PyTree,  # microbatch-stacked inputs, leaves [M, ...]
    axis_name: str = "pipe",
    batch_axes: Optional[Tuple] = ("data", "fsdp"),
    constrain: bool = True,
    remat_stage: bool = False,
) -> PyTree:
    """Circular (interleaved virtual stage) schedule.

    Role parity: PiPPy's ``StageInterleaver`` / Megatron interleaved
    virtual stages. Each physical stage holds V parameter chunks; a
    microbatch circles the pipe ring V times, taking chunk r on round r.
    With M microbatches the bubble shrinks from (P-1)/(M+P-1) to
    (P-1)/(V*M+P-1) — the V-fold reduction interleaving buys — at the
    cost of V-1 extra ring wraps of activation traffic.

    Scheduling invariant (device 0 is busy with wrapped microbatches as
    soon as round 1 begins): requires M >= P.
    """
    stage_leaves = jax.tree.leaves(stage_params)
    if not stage_leaves:
        raise ValueError("stage_params is empty")
    num_virtual, num_stages = stage_leaves[0].shape[:2]
    x_leaves = jax.tree.leaves(x_mb)
    num_mb = x_leaves[0].shape[0]
    if num_mb < num_stages:
        raise ValueError(
            f"circular schedule needs microbatches >= stages "
            f"(got M={num_mb} < P={num_stages})"
        )
    constrain = constrain and _context_has_axis(axis_name)

    if constrain:
        from jax.sharding import PartitionSpec as P

        stage_params = jax.tree.map(
            lambda w: lax.with_sharding_constraint(
                w,
                P(None, axis_name,
                  *(P.UNCONSTRAINED for _ in range(w.ndim - 2))),
            ),
            stage_params,
        )

    def maybe_constrain(tree):
        if not constrain:
            return tree
        return _stage_constraint(tree, axis_name, batch_axes)

    # stage p at tick t works on (round (t-p)//M, microbatch (t-p)%M)
    def chunk_select(params_v, round_idx, state):
        chunk = jax.tree.map(
            lambda w: lax.dynamic_index_in_dim(
                w, round_idx, 0, keepdims=False
            ),
            params_v,
        )
        return stage_fn(chunk, state)

    if remat_stage:
        # checkpoint OUTSIDE the round selection: the saved inputs are
        # then the loop-invariant [V, ...] params (a scan constant, not
        # stacked per tick) + the scalar round + the per-tick state —
        # checkpointing stage_fn itself would stack the dynamically
        # selected param chunk for every tick (~20 GB at 70B)
        chunk_select = jax.checkpoint(chunk_select)

    # vmap over stages: params [V, P, ...] -> per-stage [V, ...]
    vstage = jax.vmap(chunk_select, in_axes=(1, 0, 0))

    stage_ids = jnp.arange(num_stages)
    num_ticks = num_virtual * num_mb + num_stages - 1
    # a wrap activation leaves stage P-1 at tick m+P-1 but stage 0 only
    # consumes it at tick M+m (it processes all round-r jobs before any
    # round-r+1 job): a FIFO of M-P+1 slots provides exactly that delay
    fifo_len = num_mb - num_stages + 1

    state0 = jax.tree.map(
        lambda x: jnp.zeros((num_stages,) + x.shape[1:], x.dtype), x_mb
    )
    fifo0 = jax.tree.map(
        lambda x: jnp.zeros((fifo_len,) + x.shape[1:], x.dtype), x_mb
    )
    outs0 = jax.tree.map(jnp.zeros_like, x_mb)

    def tick(carry, t):
        state, fifo, outs = carry
        # stage 0 input: fresh microbatch during round 0, else the FIFO
        # head (the wrap that left stage P-1 exactly M-P+1 ticks ago)
        feed_fresh = t < num_mb
        fresh = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, num_mb - 1), 0, keepdims=False
            ),
            x_mb,
        )
        inp = jax.tree.map(
            lambda f, q: jnp.where(feed_fresh, f, q[0]), fresh, fifo
        )
        state = jax.tree.map(
            lambda s, i: lax.dynamic_update_index_in_dim(s, i, 0, 0),
            state, inp,
        )
        state = maybe_constrain(state)
        rounds = jnp.clip((t - stage_ids) // num_mb, 0, num_virtual - 1)
        y = vstage(stage_params, rounds, state)
        y = maybe_constrain(y)

        # last stage finishes microbatch m of the FINAL round at tick
        # (V-1)*M + m + (P-1)
        fin = t - (num_stages - 1) - (num_virtual - 1) * num_mb
        valid = jnp.logical_and(fin >= 0, fin < num_mb)
        idx = jnp.clip(fin, 0, num_mb - 1)
        outs = jax.tree.map(
            lambda o, yy: jnp.where(
                valid,
                lax.dynamic_update_index_in_dim(o, yy[-1], idx, 0),
                o,
            ),
            outs, y,
        )
        # push this tick's wrap (stage P-1 output) onto the FIFO tail;
        # slot 0 of the ring shift is overwritten next tick anyway
        fifo = jax.tree.map(
            lambda q, yy: lax.dynamic_update_index_in_dim(
                jnp.roll(q, -1, axis=0), yy[-1], fifo_len - 1, 0
            ),
            fifo, y,
        )
        state = jax.tree.map(lambda yy: jnp.roll(yy, 1, axis=0), y)
        return (state, fifo, outs), None

    (_, _, outs), _ = lax.scan(
        tick, (state0, fifo0, outs0), jnp.arange(num_ticks)
    )
    return outs
