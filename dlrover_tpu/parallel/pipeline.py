"""GPipe-style pipeline parallelism over the "pipe" mesh axis.

Role parity: atorch's PiPPy compiler stack (``atorch/atorch/modules/
distributed_modules/compilers/pipe_compiler/distributed_pippy_compiler.py:90-378``
— FX graph split into stages, torch RPC drivers, interleaver). The TPU
formulation needs none of that machinery: stages are a *leading array
dimension* sharded on the "pipe" mesh axis, the whole schedule is a
``lax.scan`` over pipeline ticks, and the per-tick shift of activations to
the next stage (``jnp.roll`` over the stage dim) lowers to an XLA
collective-permute over ICI/DCN. Because this is plain GSPMD (no manual
``shard_map``), it composes freely with the data/fsdp/seq/tensor axes —
tensor-parallel matmuls inside a stage still get their collectives from
the partitioner.

Schedule: GPipe. With M microbatches and P stages the bubble fraction is
(P-1)/(M+P-1); backward runs the reverse schedule automatically because
``jax.grad`` transposes the scan and the collective-permute.

Contract: ``stage_fn(stage_params, state) -> state`` must be
shape/dtype-preserving on ``state`` (homogeneous stages — the transformer
block case); heterogeneous embed/head layers stay *outside* the pipeline
in the surrounding GSPMD program.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

PyTree = Any


def _context_has_axis(axis_name: str) -> bool:
    """Sharding constraints only resolve under a mesh context
    (``jax.sharding.set_mesh``); skip them when running unsharded."""
    try:
        mesh = jax.sharding.get_abstract_mesh()
    except Exception:
        return False
    return axis_name in getattr(mesh, "axis_names", ())


def split_microbatches(tree: PyTree, num_microbatches: int) -> PyTree:
    """[B, ...] leaves -> [M, B/M, ...] microbatch-stacked leaves."""

    def split(x):
        b = x.shape[0]
        if b % num_microbatches:
            raise ValueError(
                f"batch of {b} rows not divisible into "
                f"{num_microbatches} microbatches"
            )
        return x.reshape((num_microbatches, b // num_microbatches)
                         + x.shape[1:])

    return jax.tree.map(split, tree)


def merge_microbatches(tree: PyTree) -> PyTree:
    """[M, mb, ...] -> [M*mb, ...] (inverse of split_microbatches)."""
    return jax.tree.map(
        lambda x: x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:]), tree
    )


def _stage_constraint(tree: PyTree, axis_name: str,
                      batch_axes: Optional[Tuple]) -> PyTree:
    """Pin the leading (stage) dim of every leaf on the pipe axis and the
    microbatch dim on the data axes, leaving trailing dims to XLA."""
    from jax.sharding import PartitionSpec as P

    unconstrained = P.UNCONSTRAINED

    def constrain(x):
        spec = [axis_name]
        if x.ndim > 1:
            spec.append(batch_axes)
        spec.extend(unconstrained for _ in range(x.ndim - len(spec)))
        return lax.with_sharding_constraint(x, P(*spec))

    return jax.tree.map(constrain, tree)


def pipeline_apply(
    stage_fn: Callable[[PyTree, PyTree], PyTree],
    stage_params: PyTree,  # leaves [num_stages, ...], pipe-sharded on dim 0
    x_mb: PyTree,  # microbatch-stacked inputs, leaves [M, ...]
    axis_name: str = "pipe",
    batch_axes: Optional[Tuple] = ("data", "fsdp"),
    constrain: bool = True,
) -> PyTree:
    """Run M microbatches through P homogeneous stages; returns outputs
    with the same [M, ...] layout as ``x_mb``.

    ``stage_fn`` sees one stage's params (dim 0 of ``stage_params``
    stripped by vmap) and one microbatch-shaped ``state``.
    """
    stage_leaves = jax.tree.leaves(stage_params)
    if not stage_leaves:
        raise ValueError("stage_params is empty")
    num_stages = stage_leaves[0].shape[0]
    constrain = constrain and _context_has_axis(axis_name)
    if constrain:
        from jax.sharding import PartitionSpec as P

        stage_params = jax.tree.map(
            lambda w: lax.with_sharding_constraint(
                w,
                P(axis_name, *(P.UNCONSTRAINED for _ in range(w.ndim - 1))),
            ),
            stage_params,
        )
    x_leaves = jax.tree.leaves(x_mb)
    num_mb = x_leaves[0].shape[0]
    num_ticks = num_mb + num_stages - 1

    vstage = jax.vmap(stage_fn)

    def maybe_constrain(tree):
        if not constrain:
            return tree
        return _stage_constraint(tree, axis_name, batch_axes)

    # state: one in-flight microbatch per stage, [P, mb, ...]
    state0 = jax.tree.map(
        lambda x: jnp.zeros((num_stages,) + x.shape[1:], x.dtype), x_mb
    )
    outs0 = jax.tree.map(jnp.zeros_like, x_mb)

    def tick(carry, t):
        state, outs = carry
        # feed the next microbatch into stage 0 (garbage during drain)
        inp = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(
                x, jnp.clip(t, 0, num_mb - 1), 0, keepdims=False
            ),
            x_mb,
        )
        state = jax.tree.map(
            lambda s, i: lax.dynamic_update_index_in_dim(s, i, 0, 0),
            state, inp,
        )
        state = maybe_constrain(state)
        y = vstage(stage_params, state)
        y = maybe_constrain(y)
        # stage P-1 finished microbatch t-(P-1): collect it
        out_idx = t - (num_stages - 1)
        valid = jnp.logical_and(out_idx >= 0, out_idx < num_mb)
        idx = jnp.clip(out_idx, 0, num_mb - 1)
        outs = jax.tree.map(
            lambda o, yy: jnp.where(
                valid,
                lax.dynamic_update_index_in_dim(o, yy[-1], idx, 0),
                o,
            ),
            outs, y,
        )
        # shift every stage's output to its successor: one collective
        # permute around the pipe ring (slot 0 is overwritten next tick)
        state = jax.tree.map(lambda yy: jnp.roll(yy, 1, axis=0), y)
        return (state, outs), None

    (_, outs), _ = lax.scan(
        tick, (state0, outs0), jnp.arange(num_ticks)
    )
    return outs


def stack_stages(layer_params: PyTree, num_stages: int) -> PyTree:
    """[L, ...] scan-stacked layer params -> [P, L/P, ...] stage chunks."""

    def restack(x):
        layers = x.shape[0]
        if layers % num_stages:
            raise ValueError(
                f"{layers} layers not divisible into {num_stages} stages"
            )
        return x.reshape((num_stages, layers // num_stages) + x.shape[1:])

    return jax.tree.map(restack, layer_params)
