"""Strategy search: combination generation + Bayesian optimization.

Role parity: atorch's acceleration engine —
``atorch/atorch/auto/engine/strategy.py:49`` (``StrategyInfoCollection``
of dryrun-scored candidates), ``sg_algo/combination_sg.py:16``
(cartesian candidate generation) and ``sg_algo/bo_sg.py:41`` (Bayesian
optimization via the bundled HEBO). The TPU search space is the
declarative Strategy: mesh factorization x remat policy x grad-accum.
The BO here is a small numpy Gaussian process with expected-improvement
acquisition — no external dependency, same role.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from dlrover_tpu.common.log import get_logger
from dlrover_tpu.parallel.mesh import MeshPlan, candidate_plans
from dlrover_tpu.parallel.strategy import Strategy

logger = get_logger("parallel.search")

REMAT_POLICIES = ["none", "dots_saveable", "dots_and_attn_saveable", "full"]


@dataclass
class StrategyInfo:
    """One scored candidate (reference: StrategyInfoCollection entries)."""

    strategy: Strategy
    step_time_s: float = 0.0
    peak_memory_bytes: int = 0
    compile_time_s: float = 0.0
    error: str = ""

    @property
    def ok(self) -> bool:
        return not self.error and self.step_time_s > 0


class StrategyInfoCollection:
    """History of evaluated strategies, JSON-persistable so later jobs
    warm-start (the reference pickles its strategies)."""

    def __init__(self):
        self._infos: List[StrategyInfo] = []

    def add(self, info: StrategyInfo):
        self._infos.append(info)

    def __len__(self):
        return len(self._infos)

    def __iter__(self):
        return iter(self._infos)

    @property
    def best(self) -> Optional[StrategyInfo]:
        ok = [i for i in self._infos if i.ok]
        return min(ok, key=lambda i: i.step_time_s) if ok else None

    def to_json(self) -> str:
        return json.dumps([
            {
                "strategy": json.loads(i.strategy.to_json()),
                "step_time_s": i.step_time_s,
                "peak_memory_bytes": i.peak_memory_bytes,
                "compile_time_s": i.compile_time_s,
                "error": i.error,
            }
            for i in self._infos
        ])

    @classmethod
    def from_json(cls, text: str) -> "StrategyInfoCollection":
        out = cls()
        for row in json.loads(text):
            out.add(StrategyInfo(
                strategy=Strategy.from_json(json.dumps(row["strategy"])),
                step_time_s=row["step_time_s"],
                peak_memory_bytes=row["peak_memory_bytes"],
                compile_time_s=row["compile_time_s"],
                error=row["error"],
            ))
        return out


def combination_candidates(
    n_devices: int,
    base: Optional[Strategy] = None,
    remat_policies: Optional[Sequence[str]] = None,
    accum_options: Sequence[int] = (1, 2, 4),
    max_candidates: int = 64,
) -> List[Strategy]:
    """Cartesian product over (mesh plan, remat policy, grad accum)
    (reference combination_sg)."""
    base = base or Strategy()
    remats = list(remat_policies) if remat_policies is not None else (
        REMAT_POLICIES
    )
    out = []
    for plan, remat, accum in itertools.product(
        candidate_plans(n_devices), remats, accum_options
    ):
        if base.global_batch_size and base.global_batch_size % accum:
            continue
        out.append(dataclasses.replace(
            base, mesh=plan, remat_policy="" if remat == "none" else remat,
            grad_accum_steps=accum,
        ))
        if len(out) >= max_candidates:
            break
    return out


class ProposalCooldown:
    """Re-plan cooldown/dedup guard: an IDENTICAL candidate proposed
    twice within the cooldown window is suppressed, so a flapping
    trigger (a straggler verdict re-confirmed every report window, a
    rendezvous that oscillates) cannot thrash the job through the same
    plan over and over. Keys are caller-chosen strings (the runtime
    optimizer uses the serialized knob tuple); a DIFFERENT candidate is
    never suppressed — only the exact repeat is.

    ``check(key, now)`` returns True when the proposal may proceed (and
    records it); False when it is inside the cooldown of an identical
    earlier proposal. The clock is injected for testability."""

    def __init__(self, cooldown_secs: float = 60.0):
        self.cooldown_secs = float(cooldown_secs)
        self._last: Dict[str, float] = {}

    def check(self, key: str, now: Optional[float] = None) -> bool:
        import time

        now = float(now if now is not None else time.monotonic())
        last = self._last.get(key)
        if last is not None and now - last < self.cooldown_secs:
            return False
        self._last[key] = now
        return True

    def seconds_remaining(self, key: str,
                         now: Optional[float] = None) -> float:
        import time

        now = float(now if now is not None else time.monotonic())
        last = self._last.get(key)
        if last is None:
            return 0.0
        return max(0.0, self.cooldown_secs - (now - last))


# -- encoding ----------------------------------------------------------------


def encode_strategy(s: Strategy) -> np.ndarray:
    """Knob vector for the GP: log2 mesh axis sizes + remat index +
    log2 accum."""
    mesh = s.mesh
    axes = [mesh.pipe, mesh.data, mesh.fsdp, mesh.seq, mesh.tensor]
    remat = s.remat_policy or "none"
    remat_idx = REMAT_POLICIES.index(remat) if remat in REMAT_POLICIES else 0
    return np.array(
        [math.log2(max(a, 1)) for a in axes]
        + [float(remat_idx), math.log2(max(s.grad_accum_steps, 1))],
        dtype=np.float64,
    )


# -- gaussian process --------------------------------------------------------


class _GP:
    """Tiny RBF-kernel GP regression (zero mean, observation noise)."""

    def __init__(self, length_scale: float = 1.0, noise: float = 1e-4):
        self._ls = length_scale
        self._noise = noise
        self._x: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._l_chol: Optional[np.ndarray] = None
        self._y_mean = 0.0
        self._y_std = 1.0

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return np.exp(-0.5 * d2 / self._ls ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray):
        self._x = x
        self._y_mean = float(y.mean())
        self._y_std = float(y.std()) or 1.0
        yn = (y - self._y_mean) / self._y_std
        k = self._kernel(x, x) + self._noise * np.eye(len(x))
        self._l_chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._l_chol.T, np.linalg.solve(self._l_chol, yn)
        )

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        ks = self._kernel(x, self._x)  # [n, m]
        mean = ks @ self._alpha
        v = np.linalg.solve(self._l_chol, ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return (
            mean * self._y_std + self._y_mean,
            np.sqrt(var) * self._y_std,
        )


def _expected_improvement(
    mean: np.ndarray, std: np.ndarray, best: float
) -> np.ndarray:
    """EI for minimization."""
    z = (best - mean) / std
    # standard normal pdf/cdf without scipy
    pdf = np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)
    cdf = 0.5 * (1.0 + np.vectorize(math.erf)(z / math.sqrt(2)))
    return (best - mean) * cdf + std * pdf


class BayesianSearch:
    """Sequential candidate proposal (reference bo_sg/HEBO role):
    ``propose`` returns the unevaluated candidate with the highest
    expected improvement under a GP fit to the observations so far."""

    def __init__(self, candidates: Sequence[Strategy],
                 init_random: int = 3, seed: int = 0):
        self._pool: List[Strategy] = list(candidates)
        self._encoded = [encode_strategy(s) for s in self._pool]
        self._observed: List[Tuple[int, float]] = []  # (pool idx, y)
        self._failed: set = set()
        self._init_random = init_random
        self._rng = np.random.RandomState(seed)

    def _remaining(self) -> List[int]:
        done = {i for i, _ in self._observed} | self._failed
        return [i for i in range(len(self._pool)) if i not in done]

    def propose(self) -> Optional[Tuple[int, Strategy]]:
        remaining = self._remaining()
        if not remaining:
            return None
        if len(self._observed) < self._init_random:
            idx = int(self._rng.choice(remaining))
            return idx, self._pool[idx]
        x = np.stack([self._encoded[i] for i, _ in self._observed])
        y = np.array([v for _, v in self._observed])
        gp = _GP(length_scale=1.5)
        gp.fit(x, y)
        cand = np.stack([self._encoded[i] for i in remaining])
        mean, std = gp.predict(cand)
        ei = _expected_improvement(mean, std, float(y.min()))
        idx = remaining[int(np.argmax(ei))]
        return idx, self._pool[idx]

    def observe(self, idx: int, step_time_s: float, failed: bool = False):
        if failed:
            self._failed.add(idx)
        else:
            self._observed.append((idx, step_time_s))

    @property
    def best(self) -> Optional[Tuple[Strategy, float]]:
        if not self._observed:
            return None
        idx, y = min(self._observed, key=lambda t: t[1])
        return self._pool[idx], y


def bayesian_search_strategy(
    evaluate: Callable[[Strategy], StrategyInfo],
    n_devices: int,
    base: Optional[Strategy] = None,
    budget: int = 12,
    candidates: Optional[Sequence[Strategy]] = None,
    collection: Optional[StrategyInfoCollection] = None,
) -> Tuple[Strategy, StrategyInfoCollection]:
    """BO loop: generate combinations, evaluate ``budget`` of them guided
    by EI, return (best strategy, full history).

    ``evaluate`` is typically ``lambda s: dryrun-of(accelerate(..., s))``
    (see ``parallel.auto_tune``); it must return a StrategyInfo.
    """
    pool = list(candidates) if candidates is not None else (
        combination_candidates(n_devices, base)
    )
    collection = collection or StrategyInfoCollection()
    search = BayesianSearch(pool)
    for _ in range(min(budget, len(pool))):
        proposal = search.propose()
        if proposal is None:
            break
        idx, strategy = proposal
        info = evaluate(strategy)
        collection.add(info)
        search.observe(idx, info.step_time_s, failed=not info.ok)
        logger.info(
            "search: %s remat=%s accum=%d -> %s",
            strategy.mesh, strategy.remat_policy or "none",
            strategy.grad_accum_steps,
            f"{info.step_time_s:.4f}s" if info.ok else f"FAIL {info.error[:60]}",
        )
    best = collection.best
    if best is None:
        raise RuntimeError("no viable strategy found in search budget")
    return best.strategy, collection
