"""Typed client for every master RPC.

Role parity: ``dlrover/python/elastic_agent/master_client.py:51-487`` — the
one object agents/trainers use to talk to the master, with retries, plus the
process-wide singleton built from the ``DLROVER_TPU_MASTER_ADDR`` env var.
"""

from __future__ import annotations

import os
import time
from typing import Dict, List, Optional, Tuple

from dlrover_tpu.common import comm
from dlrover_tpu.common.constants import NodeEnv, RendezvousName
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.rpc.client import RpcChannel

logger = get_logger("agent.client")


class MasterClient:
    def __init__(self, addr: str, node_id: int = 0,
                 node_type: str = "worker", timeout: float = 30.0):
        self.addr = addr
        self.node_id = node_id
        self.node_type = node_type
        self._channel = RpcChannel(addr, timeout=timeout)

    # -- data sharding ------------------------------------------------------

    def report_dataset_shard_params(self, **kwargs) -> comm.Response:
        return self._channel.report(comm.DatasetShardParams(**kwargs))

    def get_task(self, dataset_name: str) -> comm.Task:
        return self._channel.get(
            comm.TaskRequest(dataset_name=dataset_name, node_id=self.node_id)
        )

    def report_task_result(self, dataset_name: str, task_id: int,
                           err_message: str = "") -> comm.Response:
        return self._channel.report(comm.TaskResult(
            dataset_name=dataset_name, task_id=task_id,
            err_message=err_message, node_id=self.node_id,
        ))

    def report_batch_done(self, dataset_name: str,
                          record_count: int) -> comm.Response:
        return self._channel.report(comm.BatchDoneReport(
            dataset_name=dataset_name, node_id=self.node_id,
            record_count=record_count,
        ))

    def get_shard_checkpoint(self, dataset_name: str) -> str:
        resp = self._channel.get(
            comm.ShardCheckpointRequest(dataset_name=dataset_name)
        )
        return resp.content

    def report_shard_checkpoint(self, dataset_name: str,
                                content: str) -> comm.Response:
        return self._channel.report(comm.ShardCheckpoint(
            dataset_name=dataset_name, content=content
        ))

    def get_data_report(self, dataset_name: str = "") -> dict:
        """The master's shard-dispatch ledger: per-dataset queue/epoch
        accounting + per-node consumption (``tpurun data --addr``)."""
        import json

        resp = self._channel.get(comm.DataShardRequest(
            dataset_name=dataset_name))
        try:
            return json.loads(resp.report_json or "{}")
        except ValueError:
            return {}

    # -- serving request plane ----------------------------------------------

    def submit_serve_request(self, prompt, max_new_tokens: int = 16,
                             request_id: str = "",
                             eos_id: int = -1) -> str:
        """Enqueue one inference request; returns the router-assigned
        request id."""
        resp = self._channel.report(comm.ServeSubmit(
            request_id=request_id, prompt=[int(t) for t in prompt],
            max_new_tokens=max_new_tokens, eos_id=eos_id,
        ))
        return str(resp.data or request_id)

    def serve_lease(self, max_requests: int = 1) -> list:
        """Lease up to ``max_requests`` queued requests (wire dicts)."""
        resp = self._channel.get(comm.ServeLeaseRequest(
            node_id=self.node_id, max_requests=max_requests))
        return list(resp.requests or [])

    def serve_complete(self, request_id: str, tokens,
                       ttft_s=None, e2e_s=None,
                       error_code: str = "",
                       prefix_hit_tokens: int = 0,
                       spec_drafted_tokens: int = 0,
                       spec_accepted_tokens: int = 0) -> comm.Response:
        return self._channel.report(comm.ServeResult(
            node_id=self.node_id, request_id=request_id,
            tokens=[int(t) for t in tokens or []],
            ttft_s=ttft_s, e2e_s=e2e_s, error_code=error_code,
            prefix_hit_tokens=int(prefix_hit_tokens or 0),
            spec_drafted_tokens=int(spec_drafted_tokens or 0),
            spec_accepted_tokens=int(spec_accepted_tokens or 0),
        ))

    def serve_touch(self) -> comm.Response:
        return self._channel.report(comm.ServeTouch(
            node_id=self.node_id))

    def report_serve_config(self, **kwargs) -> comm.Response:
        """Report the serving config this worker actually runs (the
        optimizer's serve-knob input; a non-empty plan_id acks)."""
        kwargs.setdefault("node_id", self.node_id)
        return self._channel.report(comm.ServeConfigReport(**kwargs))

    def get_serve_report(self) -> dict:
        """The router ledger (``tpurun requests --addr``)."""
        import json

        resp = self._channel.get(comm.ServeReportRequest())
        try:
            return json.loads(resp.report_json or "{}")
        except ValueError:
            return {}

    def get_serve_slo(self) -> dict:
        """The serving SLO plane: targets, burn rates, active
        violation verdicts, scale proposals (``tpurun serve slo
        --addr``)."""
        import json

        resp = self._channel.get(comm.ServeSLORequest())
        try:
            return json.loads(resp.report_json or "{}")
        except ValueError:
            return {}

    # -- rendezvous ---------------------------------------------------------

    def report_rdzv_params(self, min_nodes: int, max_nodes: int,
                           waiting_timeout: float, node_unit: int,
                           rdzv_name: str = "") -> comm.Response:
        return self._channel.report(comm.RendezvousParams(
            min_nodes=min_nodes, max_nodes=max_nodes,
            waiting_timeout=waiting_timeout, node_unit=node_unit,
            rdzv_name=rdzv_name,
        ))

    def join_rendezvous(self, node_rank: int, local_world_size: int,
                        rdzv_name: str = RendezvousName.TRAINING,
                        addr: str = "", slice_index: int = 0) -> int:
        resp = self._channel.report(comm.JoinRendezvousRequest(
            node_rank=node_rank, local_world_size=local_world_size,
            rdzv_name=rdzv_name, node_id=self.node_id, addr=addr,
            slice_index=slice_index,
        ))
        if resp.data is not None:
            return resp.data.round
        return 0

    def get_comm_world(
        self, rdzv_name: str = RendezvousName.TRAINING, node_rank: int = -1
    ) -> comm.CommWorld:
        return self._channel.get(comm.CommWorldRequest(
            rdzv_name=rdzv_name, node_rank=node_rank
        ))

    def num_nodes_waiting(
        self, rdzv_name: str = RendezvousName.TRAINING
    ) -> int:
        state = self._channel.get(
            comm.WaitingNodeNumRequest(rdzv_name=rdzv_name)
        )
        return state.waiting_num

    def network_ready(self) -> Tuple[bool, str]:
        resp = self._channel.get(comm.NetworkReadyRequest())
        return resp.success, resp.reason

    def report_network_check_result(self, node_rank: int, normal: bool,
                                    elapsed: float = 0.0) -> comm.Response:
        return self._channel.report(comm.NetworkCheckResult(
            node_rank=node_rank, normal=normal, elapsed_time=elapsed
        ))

    def abnormal_ranks(self) -> List[int]:
        resp = self._channel.get(comm.AbnormalNodesRequest())
        return list(resp.ranks or [])

    def straggler_ranks(self) -> List[int]:
        resp = self._channel.get(comm.StragglerExistRequest())
        if not resp.reason:
            return []
        return [int(r) for r in resp.reason.split(",")]

    # -- kv / sync ----------------------------------------------------------

    def kv_store_set(self, key: str, value: str) -> comm.Response:
        return self._channel.report(
            comm.KVStoreSetRequest(key=key, value=value)
        )

    def kv_store_get(self, key: str) -> Optional[str]:
        val = self._channel.get(comm.KVStoreGetRequest(key=key))
        return val.value if val.found else None

    def kv_store_add(self, key: str, amount: int) -> int:
        val = self._channel.get(
            comm.KVStoreAddRequest(key=key, amount=amount)
        )
        return int(val.value)

    def join_sync(self, sync_name: str, node_rank: int) -> bool:
        return self._channel.report(comm.SyncJoinRequest(
            sync_name=sync_name, node_rank=node_rank
        )).success

    def sync_finished(self, sync_name: str) -> bool:
        return self._channel.get(
            comm.SyncJoinRequest(sync_name=sync_name)
        ).success

    def barrier(self, barrier_name: str, notify: bool = False) -> bool:
        if notify:
            return self._channel.report(comm.BarrierRequest(
                barrier_name=barrier_name, notify=True
            )).success
        return self._channel.get(
            comm.BarrierRequest(barrier_name=barrier_name)
        ).success

    # -- monitoring / failures ---------------------------------------------

    def failed_nodes(self, since_timestamp: float = 0.0) -> list:
        """Node ids with hard failures since ``since_timestamp``."""
        return self.failed_nodes_since(since_timestamp)[0]

    def failed_nodes_since(self, since_timestamp: float = 0.0) -> tuple:
        """(failed node ids, master-clock response time). Pollers pass
        the returned server time back as the next window start — both
        ends of the comparison stay on the master's clock."""
        resp = self._channel.get(
            comm.FailedNodesRequest(since_timestamp=since_timestamp)
        )
        return (
            list(getattr(resp, "ranks", None) or []),
            float(getattr(resp, "server_time", 0.0)),
        )

    def report_failure(self, node_rank: int, restart_count: int,
                       error_data: str, level: str) -> comm.Response:
        return self._channel.report(comm.NodeFailure(
            node_id=self.node_id, node_rank=node_rank,
            restart_count=restart_count, error_data=error_data, level=level,
        ))

    def report_resource(self, cpu_percent: float, memory_mb: int,
                        chips: int = 0, duty_cycle: float = 0.0):
        return self._channel.report(comm.ResourceStats(
            node_id=self.node_id, node_type=self.node_type,
            cpu_percent=cpu_percent, memory_mb=memory_mb, chips=chips,
            duty_cycle=duty_cycle,
        ))

    def report_global_step(self, step: int,
                           elapsed_per_step: float = 0.0,
                           reset: bool = False) -> comm.Response:
        return self._channel.report(comm.GlobalStep(
            step=step, timestamp=time.time(),
            elapsed_time_per_step=elapsed_per_step, reset=reset,
        ))

    def report_node_runtime(self, **kwargs) -> comm.Response:
        """Push a node-tagged runtime snapshot (the cluster diagnosis
        plane's input; see NodeRuntimeReportHook in trainer/executor)."""
        kwargs.setdefault("node_id", self.node_id)
        kwargs.setdefault("node_type", self.node_type)
        kwargs.setdefault("timestamp", time.time())
        return self._channel.report(comm.NodeRuntimeReport(**kwargs))

    def get_diagnosis(self, node_id: int = -1) -> dict:
        """The master's cluster diagnosis: per-node latest samples plus
        straggler/hang verdicts (``tpurun diagnose --addr`` view)."""
        import json

        resp = self._channel.get(comm.DiagnosisRequest(node_id=node_id))
        try:
            return json.loads(resp.report_json or "{}")
        except ValueError:
            return {}

    def report_trainer_config(self, **kwargs) -> comm.Response:
        """Report the config the trainer actually runs (the runtime
        optimizer's input; a non-empty plan_id acks an applied plan)."""
        kwargs.setdefault("node_id", self.node_id)
        return self._channel.report(comm.TrainerConfigReport(**kwargs))

    def get_plan(self, limit: int = 0) -> dict:
        """The master's runtime-optimizer report: running config,
        calibration factors, decision trail (``tpurun plan --addr``)."""
        import json

        resp = self._channel.get(comm.PlanRequest(limit=limit))
        try:
            return json.loads(resp.report_json or "{}")
        except ValueError:
            return {}

    def get_attribution(self, node_id: int = -1, limit: int = 0) -> dict:
        """The master's performance-attribution view: per-node derived
        MFU / exposed-comm / HBM gauges + the optimizer's memory-gate
        rejections (``tpurun attribution --addr``)."""
        import json

        resp = self._channel.get(comm.AttributionRequest(
            node_id=node_id, limit=limit))
        try:
            return json.loads(resp.report_json or "{}")
        except ValueError:
            return {}

    # -- peer-redundant host snapshots ---------------------------------------

    def report_replica_endpoint(self, **kwargs) -> comm.Response:
        """Register/refresh this node's replica-store endpoint (the
        ReplicaDirectory's liveness + budget + freshness input)."""
        kwargs.setdefault("node_id", self.node_id)
        kwargs.setdefault("timestamp", time.time())
        return self._channel.report(comm.ReplicaEndpointReport(**kwargs))

    def get_replica_plan(self) -> comm.ReplicaPlan:
        """This node's master-assigned replica peers (rendezvous-stable,
        budget-admitted; ``degraded`` marks a plan priced below k)."""
        return self._channel.get(comm.ReplicaPlanRequest(
            node_id=self.node_id))

    def get_recovery_plan(self) -> dict:
        """Owner -> ordered live replica holders: the peer-rebuild map a
        recovering worker streams its state from (plus the master's
        ``predicted_mttr`` rung prices for this node)."""
        import json

        resp = self._channel.get(comm.RecoveryPlanRequest(
            node_id=self.node_id))
        try:
            return json.loads(resp.report_json or "{}")
        except ValueError:
            return {}

    def get_readiness(self, node_id: int = -1) -> dict:
        """The recovery-readiness report: durability posture, per-node
        blast-radius verdicts, and the predicted-MTTR-per-rung table
        (``tpurun readiness --addr``'s live view)."""
        import json

        resp = self._channel.get(comm.ReadinessRequest(node_id=node_id))
        try:
            return json.loads(resp.report_json or "{}")
        except ValueError:
            return {}

    def report_heartbeat(self) -> comm.Response:
        return self._channel.report(comm.NodeHeartbeat(
            node_id=self.node_id, timestamp=time.time()
        ))

    def report_node_status(self, status: str) -> comm.Response:
        return self._channel.report(comm.NodeStatusReport(
            node_id=self.node_id, node_type=self.node_type, status=status
        ))

    def report_model_info(self, info: comm.ModelInfo) -> comm.Response:
        return self._channel.report(info)

    # -- PS parity ----------------------------------------------------------

    def get_cluster_version(self, version_type: str, task_type: str,
                            task_id: int) -> int:
        resp = self._channel.get(comm.ClusterVersionRequest(
            task_type=task_type, task_id=task_id, version_type=version_type
        ))
        return resp.version

    def update_cluster_version(self, version_type: str, version: int,
                               task_type: str, task_id: int,
                               expected: int = -1):
        return self._channel.report(comm.ClusterVersionUpdate(
            task_type=task_type, task_id=task_id,
            version_type=version_type, version=version,
            expected=expected,
        ))

    def query_ps_nodes(self) -> comm.PsNodes:
        return self._channel.get(comm.QueryPsNodesRequest())

    # -- parallel config / job control --------------------------------------

    def get_parallel_config(self) -> comm.ParallelConfig:
        return self._channel.get(
            comm.ParallelConfigRequest(node_id=self.node_id)
        )

    def report_parallel_config(self, cfg: comm.ParallelConfig):
        return self._channel.report(cfg)

    def report_job_exit(self, success: bool, reason: str = "") -> comm.Response:
        return self._channel.report(comm.JobExitRequest(
            node_id=self.node_id, success=success, reason=reason
        ))

    def close(self):
        self._channel.close()


_GLOBAL_CLIENT: Optional[MasterClient] = None


def build_master_client(addr: Optional[str] = None, node_id: int = 0,
                        node_type: str = "worker") -> Optional[MasterClient]:
    """Build (and cache) the process-wide client from env if addr omitted."""
    global _GLOBAL_CLIENT
    addr = addr or os.environ.get(NodeEnv.MASTER_ADDR, "")
    if not addr:
        return None
    _GLOBAL_CLIENT = MasterClient(
        addr,
        node_id=int(os.environ.get(NodeEnv.NODE_ID, node_id)),
        node_type=node_type,
    )
    return _GLOBAL_CLIENT


def global_master_client() -> Optional[MasterClient]:
    return _GLOBAL_CLIENT
