"""Agent-side rendezvous against the master.

Role parity: ``MasterRendezvousHandler`` in
``dlrover/python/elastic_agent/torch/training.py:75-212``, retargeted at
JAX: instead of building a torch c10d store, the completed world is turned
into ``jax.distributed.initialize`` coordinates — (coordinator_addr,
num_processes, process_id_base) — that the agent injects into its worker
processes' environment.
"""

from __future__ import annotations

import socket
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import (
    EventKind,
    SpanName,
    emit_event,
    get_registry,
    names as tm,
    span,
)

logger = get_logger("agent.rdzv")


class RendezvousTimeoutError(Exception):
    pass


@dataclass
class RendezvousInfo:
    """Everything a host needs to start its slice of the SPMD world."""

    round: int = 0
    world: Dict[int, int] = field(default_factory=dict)
    group_rank: int = 0  # this node's index in the sorted world
    group_world_size: int = 0  # number of nodes in the world
    process_id_base: int = 0  # first global process id on this host
    local_world_size: int = 0
    num_processes: int = 0  # total jax processes across the world
    coordinator_addr: str = ""  # host:port for jax.distributed


def free_port(host: str = "") -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind((host or "", 0))
        return s.getsockname()[1]


def reserve_port(host: str = "") -> socket.socket:
    """Bind (and keep) a socket on a free port; the caller closes it just
    before the real user of the port binds, shrinking the reuse race from
    the whole rendezvous wait down to milliseconds."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    s.bind((host or "", 0))
    return s


def local_host_ip() -> str:
    """Best-effort routable IP of this host (falls back to loopback)."""
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect(("8.8.8.8", 53))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


class MasterRendezvousHandler:
    def __init__(
        self,
        master_client: MasterClient,
        node_rank: int,
        rdzv_name: str = RendezvousName.TRAINING,
        local_world_size: int = 1,
        min_nodes: int = 1,
        max_nodes: int = 1,
        waiting_timeout: float = 30.0,
        node_unit: int = 1,
        host_ip: Optional[str] = None,
        poll_interval: float = 0.5,
    ):
        self._client = master_client
        self.node_rank = node_rank
        self.rdzv_name = rdzv_name
        self.local_world_size = local_world_size
        self._min_nodes = min_nodes
        self._max_nodes = max_nodes
        self._waiting_timeout = waiting_timeout
        self._node_unit = node_unit
        self._host_ip = host_ip if host_ip is not None else local_host_ip()
        self._poll_interval = poll_interval
        self._reserved_sock: Optional[socket.socket] = None
        # True while a renegotiate() round is in flight: tags the
        # round's timeline events as live-reshard traffic
        self._live_round = False

    def release_coordinator_port(self):
        """Free the reserved port right before the coordinator binds it."""
        if self._reserved_sock is not None:
            try:
                self._reserved_sock.close()
            finally:
                self._reserved_sock = None

    def _push_params_once(self):
        # rank 0 owns the rendezvous parameters (reference :99-105)
        if self.node_rank == 0:
            self._client.report_rdzv_params(
                self._min_nodes, self._max_nodes, self._waiting_timeout,
                self._node_unit, self.rdzv_name,
            )

    def next_rendezvous(self, timeout: Optional[float] = None) -> RendezvousInfo:
        """Join and block-poll until this node is in a completed world."""
        ctx = get_context()
        timeout = timeout or ctx.rdzv_timeout_secs
        self._push_params_once()
        # a fresh coordination port per round avoids bind clashes with the
        # previous round's (possibly lingering) coordinator service; it is
        # held open until the workers spawn (release_coordinator_port).
        self.release_coordinator_port()
        self._reserved_sock = reserve_port()
        coord_port = self._reserved_sock.getsockname()[1]
        addr = f"{self._host_ip}:{coord_port}"
        t0 = time.monotonic()
        emit_event(EventKind.RDZV_JOIN, rdzv=self.rdzv_name,
                   node_rank=self.node_rank,
                   live=self._live_round or None)
        with span(SpanName.RENDEZVOUS, category="rdzv",
                  rdzv=self.rdzv_name):
            self._client.join_rendezvous(
                self.node_rank, self.local_world_size,
                rdzv_name=self.rdzv_name, addr=addr,
            )
            deadline = time.time() + timeout
            while True:
                world_msg = self._client.get_comm_world(
                    self.rdzv_name, self.node_rank
                )
                world = world_msg.world or {}
                if self.node_rank in world:
                    elapsed = time.monotonic() - t0
                    reg = get_registry()
                    reg.counter(
                        tm.RDZV_ROUNDS,
                        help="completed rendezvous rounds").inc()
                    reg.histogram(
                        tm.RDZV_TIME,
                        help="join -> completed-world wall time",
                    ).observe(elapsed)
                    emit_event(EventKind.RDZV_COMPLETE,
                               rdzv=self.rdzv_name,
                               round=world_msg.round,
                               world_size=len(world),
                               wait_seconds=round(elapsed, 3),
                               live=self._live_round or None)
                    return self._build_info(world_msg.round, world,
                                            world_msg.coordinator_addr)
                if time.time() > deadline:
                    emit_event(EventKind.RDZV_TIMEOUT,
                               error_code="RDZV_TIMEOUT",
                               rdzv=self.rdzv_name,
                               node_rank=self.node_rank,
                               timeout_seconds=timeout)
                    raise RendezvousTimeoutError(
                        f"{self.rdzv_name}: rank {self.node_rank} not "
                        f"admitted within {timeout}s (world={world})"
                    )
                time.sleep(self._poll_interval)

    def _build_info(self, rdzv_round: int, world: Dict[int, int],
                    coordinator_addr: str) -> RendezvousInfo:
        ranks = sorted(world)
        group_rank = ranks.index(self.node_rank)
        process_id_base = sum(world[r] for r in ranks[:group_rank])
        info = RendezvousInfo(
            round=rdzv_round,
            world=world,
            group_rank=group_rank,
            group_world_size=len(ranks),
            process_id_base=process_id_base,
            local_world_size=world[self.node_rank],
            num_processes=sum(world.values()),
            coordinator_addr=coordinator_addr,
        )
        logger.info(
            "%s round %d: node %d -> group_rank=%d procs [%d, %d) of %d, "
            "coordinator=%s", self.rdzv_name, rdzv_round, self.node_rank,
            group_rank, process_id_base,
            process_id_base + info.local_world_size, info.num_processes,
            coordinator_addr,
        )
        return info

    def num_nodes_waiting(self) -> int:
        return self._client.num_nodes_waiting(self.rdzv_name)

    def renegotiate(self, timeout: Optional[float] = None) -> RendezvousInfo:
        """Re-join the rendezvous from a SURVIVING process — the live
        elastic recovery path.

        A classic restart tears the worker down and lets a fresh
        process call ``next_rendezvous``; a live reshard keeps the
        process (and its host-DRAM snapshot + compiled programs) and
        only needs the new world's coordinates: re-join, wait for the
        master to complete the round at the new size, and hand the
        coordinates to the in-process rebuild
        (``jax.distributed.shutdown()`` + ``initialize()`` with the new
        coordinator, then ``ElasticTrainer.live_reshard``). Identical
        wire protocol to ``next_rendezvous`` — the master cannot tell a
        renegotiating survivor from a restarted worker — but tagged in
        the event timeline so MTTR derivation can attribute the round
        to a live reshard instead of a restart."""
        self._live_round = True
        try:
            return self.next_rendezvous(timeout=timeout)
        finally:
            self._live_round = False
