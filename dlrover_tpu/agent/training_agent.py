"""The per-host elastic agent.

Role parity: ``ElasticTrainingAgent`` + ``NetworkCheckElasticAgent`` in
``dlrover/python/elastic_agent/torch/training.py:215-767``: rendezvous
through the master, spawn the host's training processes, monitor them,
report failures, restart on failure or membership change, and (optionally)
run the paired network check before training starts.

TPU retarget: a "worker restart" hands new ``jax.distributed`` coordinates
to fresh processes — XLA recompiles for the new topology (compile caches
make this fast); the master's ``node_unit`` keeps every world a whole
number of slices.
"""

from __future__ import annotations

import shutil
import subprocess
import sys
import tempfile
import time
import dataclasses
from dataclasses import dataclass
from typing import List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.rendezvous import (
    MasterRendezvousHandler,
    RendezvousInfo,
    RendezvousTimeoutError,
)
from dlrover_tpu.agent.worker_group import (
    WorkerGroup,
    WorkerGroupState,
    WorkerSpec,
)
from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import (
    NodeStatus,
    RendezvousName,
    TrainingExceptionLevel,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    get_registry,
    names as tm,
)
from dlrover_tpu.telemetry.trace_context import (
    TRACE_ID_ENV,
    clear_trace_id,
    new_trace_id,
    set_trace_id,
)

logger = get_logger("agent.training")


@dataclass
class AgentConfig:
    node_rank: int = 0
    node_id: int = 0
    nproc_per_node: int = 1
    min_nodes: int = 1
    max_nodes: int = 1
    node_unit: int = 1
    max_restarts: int = 3
    monitor_interval: float = 2.0
    rdzv_waiting_timeout: float = 30.0
    network_check: bool = False
    probe_platform: str = ""  # '' = process default (tpu in prod, cpu tests)
    # > 0 enables hang-relaunch (reference --relaunch_on_hanging): when no
    # worker heartbeat lands for this many seconds while processes are
    # still alive (a collective blocked on a dead peer), restart workers
    hang_timeout: float = 0.0
    # extra allowance before the FIRST beat of a round: the initial XLA
    # compile (+ checkpoint restore) happens inside the first step, where
    # the worker has no opportunity to beat — without this grace a slow
    # compile looks like a hang and restarts burn the budget on a
    # healthy job (each round recompiling into the same false flag)
    hang_first_beat_grace: float = 600.0
    # live elastic recovery: when a membership change arrives while this
    # host's workers are HEALTHY, delegate to their in-process reshard
    # (TrainExecutor.request_live_reshard via the failover monitor)
    # instead of stopping and respawning them — the agent only falls
    # back to a worker restart if the change is still unabsorbed after
    # live_reshard_grace seconds. Off (default) = classic restart-on-
    # change (tpurun --live_recovery turns it on).
    live_recovery: bool = False
    live_reshard_grace: float = 120.0


class ElasticTrainingAgent:
    def __init__(self, config: AgentConfig, spec: WorkerSpec,
                 master_client: MasterClient,
                 host_ip: Optional[str] = None):
        self._config = config
        self._client = master_client
        self._owned_hb_dir = ""
        if config.hang_timeout > 0 and not spec.heartbeat_dir:
            # copy, don't mutate the caller's spec; the dir is ours to
            # remove on exit
            self._owned_hb_dir = tempfile.mkdtemp(prefix="dlrover_hb_")
            spec = dataclasses.replace(
                spec, heartbeat_dir=self._owned_hb_dir)
        self._worker_group = WorkerGroup(spec)
        self._rdzv_handler = MasterRendezvousHandler(
            master_client,
            config.node_rank,
            RendezvousName.TRAINING,
            local_world_size=config.nproc_per_node,
            min_nodes=config.min_nodes,
            max_nodes=config.max_nodes,
            waiting_timeout=config.rdzv_waiting_timeout,
            node_unit=config.node_unit,
            host_ip=host_ip,
        )
        self._remaining_restarts = config.max_restarts
        self._host_ip = host_ip
        self.last_rdzv: Optional[RendezvousInfo] = None
        # the open incident's trace id (minted at failure detection;
        # closed when the recovery edge lands): ambient for every event
        # this agent emits, attached to master RPCs as metadata, and
        # handed to relaunched workers via their environment so the
        # whole recovery round correlates to ONE incident
        self._incident_trace: Optional[str] = None
        # deadline for a delegated in-process reshard to absorb the
        # current membership change; None = nothing delegated
        self._reshard_deadline: Optional[float] = None
        reg = get_registry()
        self._c_restarts = reg.counter(
            tm.AGENT_WORKER_RESTARTS, help="worker-group restarts")
        self._c_hangs = reg.counter(
            tm.AGENT_HANG_DETECTIONS, help="heartbeat-gap hangs detected")
        self._c_failures = reg.counter(
            tm.AGENT_WORKER_FAILURES, help="worker process failures seen")

    # -- lifecycle ----------------------------------------------------------

    def run(self) -> int:
        self._client.report_node_status(NodeStatus.RUNNING)
        try:
            if self._config.network_check:
                ok = NetworkCheckAgent(
                    self._config, self._client, self._host_ip
                ).run()
                if not ok:
                    logger.error("network check failed on this node")
                    self._client.report_node_status(NodeStatus.BREAKDOWN)
                    return 1
            self._initialize_workers()
            return self._invoke_run()
        finally:
            self._worker_group.stop()
            if self._owned_hb_dir:
                shutil.rmtree(self._owned_hb_dir, ignore_errors=True)

    def _open_incident(self):
        """Mint the incident trace id at FAILURE DETECTION (once per
        incident — a burst of failures is one incident, like the MTTR
        pairing): every later event in this thread, every master RPC's
        ingress events, and the relaunched workers' startup all carry
        it."""
        if self._incident_trace is None:
            self._incident_trace = new_trace_id()
            set_trace_id(self._incident_trace)

    def _close_incident(self):
        if self._incident_trace is not None:
            self._incident_trace = None
            clear_trace_id()

    def _initialize_workers(self):
        rdzv = self._rdzv_handler.next_rendezvous()
        self.last_rdzv = rdzv
        self._rdzv_handler.release_coordinator_port()
        # workers relaunched as part of an incident inherit its trace
        # id: their startup events land in the same correlated view
        extra_env = (
            {TRACE_ID_ENV: self._incident_trace}
            if self._incident_trace else None
        )
        self._worker_group.start(
            rdzv, self._client.addr, self._config.node_id,
            extra_env=extra_env,
        )
        # the MTTR recovery edge: for every failure-class event before
        # it (worker death, hang), this marks workers running again
        emit_event(EventKind.WORKERS_STARTED,
                   round=rdzv.round,
                   restart_round=self._worker_group.restart_round,
                   world_size=rdzv.group_world_size)
        # the recovery edge closes the incident: later events (and the
        # NEXT incident) must not inherit this id
        self._close_incident()

    def _restart_workers(self):
        logger.info("restarting workers into a new rendezvous round")
        self._c_restarts.inc()
        emit_event(EventKind.AGENT_RESTART,
                   restart_round=self._worker_group.restart_round,
                   remaining_restarts=self._remaining_restarts)
        self._worker_group.stop()
        self._worker_group.restart_count_up()
        self._initialize_workers()

    def _invoke_run(self) -> int:
        """The agent monitor loop (reference ``_invoke_run:365``)."""
        while True:
            time.sleep(self._config.monitor_interval)
            self._client.report_heartbeat()
            state = self._worker_group.monitor()
            if state == WorkerGroupState.SUCCEEDED:
                logger.info("all workers finished successfully")
                self._client.report_node_status(NodeStatus.SUCCEEDED)
                return 0
            if state == WorkerGroupState.FAILED:
                self._report_failure()
                if self._remaining_restarts > 0:
                    self._remaining_restarts -= 1
                    self._restart_workers()
                    continue
                logger.error("restart budget exhausted; giving up")
                self._client.report_node_status(NodeStatus.FAILED)
                return 1
            # healthy processes can still be HUNG (the TPU failure mode: a
            # collective waiting forever on a dead peer keeps every
            # process alive while the step loop is frozen)
            hang_gap = self._hang_gap()
            if hang_gap is not None:
                self._report_hang(hang_gap)
                if self._remaining_restarts > 0:
                    self._remaining_restarts -= 1
                    self._restart_workers()
                    continue
                logger.error("hang detected and restart budget exhausted")
                self._client.report_node_status(NodeStatus.FAILED)
                return 1
            # healthy: check whether membership changed (new/rejoined nodes
            # waiting) and restart into a bigger/smaller world if so —
            # unless live recovery delegates the change to the workers'
            # in-process reshard first (docs/operations.md ladder).
            if self._membership_changed():
                if not self._maybe_delegate_reshard():
                    self._restart_workers()
            else:
                # the change was absorbed (or none pending): clear any
                # delegation window so the next event gets a fresh grace
                self._reshard_deadline = None

    def _hang_gap(self) -> Optional[float]:
        """Stale-heartbeat gap in seconds, or None if healthy/disabled.
        Measured once so the report matches what triggered the restart."""
        if self._config.hang_timeout <= 0:
            return None
        latest, beaten = self._worker_group.latest_heartbeat()
        allowed = self._config.hang_timeout
        if not beaten:
            # first window of the round: compile/restore runs inside the
            # first step, so the worker cannot beat yet
            allowed += self._config.hang_first_beat_grace
        gap = time.time() - latest
        return gap if gap > allowed else None

    def _report_hang(self, gap: float):
        logger.error(
            "no worker heartbeat for %.1f s (timeout %.1f s): treating "
            "as hang", gap, self._config.hang_timeout,
        )
        self._open_incident()
        self._c_hangs.inc()
        emit_event(EventKind.HANG_DETECTED, error_code="HANG",
                   gap_seconds=round(gap, 1),
                   timeout_seconds=self._config.hang_timeout)
        self._client.report_failure(
            node_rank=self._config.node_rank,
            restart_count=self._worker_group.restart_round,
            error_data=f"hang: no heartbeat for {gap:.1f}s",
            level=TrainingExceptionLevel.NODE_ERROR,
        )

    def _maybe_delegate_reshard(self) -> bool:
        """Live recovery at the agent: a membership change while this
        host's workers are healthy is SURVIVABLE (failover.py
        classify_recovery) — the workers' failover monitor will reshard
        in place, so stopping them here would throw away live state and
        compiled programs for nothing. Returns True when the restart
        should be SKIPPED this poll (delegation active), False when the
        agent must restart (knob off, classification says restart, or
        the grace window expired without the change being absorbed)."""
        if not self._config.live_recovery:
            return False
        from dlrover_tpu.trainer.failover import (
            RecoveryDecision,
            classify_recovery,
        )

        decision = classify_recovery(EventKind.RDZV_JOIN,
                                     self_affected=False)
        if decision != RecoveryDecision.LIVE_RESHARD:
            return False
        now = time.time()
        if self._reshard_deadline is None:
            self._reshard_deadline = (
                now + self._config.live_reshard_grace
            )
            logger.info(
                "membership change delegated to in-process reshard "
                "(%.0fs grace before falling back to a worker restart)",
                self._config.live_reshard_grace,
            )
            emit_event(EventKind.LIVE_RESHARD_DELEGATED,
                       grace_seconds=self._config.live_reshard_grace,
                       restart_round=self._worker_group.restart_round)
            return True
        if now < self._reshard_deadline:
            return True  # still inside the grace window
        logger.warning(
            "delegated reshard did not absorb the membership change "
            "within %.0fs; falling back to a worker restart",
            self._config.live_reshard_grace,
        )
        self._reshard_deadline = None
        return False

    def _membership_changed(self) -> bool:
        try:
            return self._rdzv_handler.num_nodes_waiting() > 0
        except Exception as e:  # noqa: BLE001 — master briefly unreachable
            # "no change" is the safe answer for one poll, but say so: a
            # master that stays unreachable makes the agent blind to
            # scale-ups, which reads as "elasticity silently off" (DLR002)
            logger.warning(
                "num_nodes_waiting failed, assuming no membership change "
                "this poll (%s: %s)", type(e).__name__, e,
            )
            return False

    def _report_failure(self):
        self._open_incident()
        for failure in self._worker_group.failures():
            logger.error(
                "worker local_rank=%d exited with code %d",
                failure.local_rank, failure.exit_code,
            )
            self._c_failures.inc()
            emit_event(EventKind.WORKER_FAILED,
                       error_code=f"EXIT_{failure.exit_code}",
                       local_rank=failure.local_rank,
                       restart_round=self._worker_group.restart_round)
            self._client.report_failure(
                node_rank=self._config.node_rank,
                restart_count=self._worker_group.restart_round,
                error_data=(
                    f"local_rank={failure.local_rank} "
                    f"exit_code={failure.exit_code}"
                ),
                level=TrainingExceptionLevel.PROCESS_ERROR,
            )


class NetworkCheckAgent:
    """Runs the 2-round paired probe before training starts.

    Role parity: ``NetworkCheckElasticAgent.run`` (reference ``:618-654``).
    Each round: join the NETWORK_CHECK rendezvous, receive a probe group,
    run the probe subprocess over that group, report (normal, elapsed).
    After both rounds the master's diagnosis decides.
    """

    CHECK_ROUNDS = 2

    def __init__(self, config: AgentConfig, master_client: MasterClient,
                 host_ip: Optional[str] = None):
        self._config = config
        self._client = master_client
        self._handler = MasterRendezvousHandler(
            master_client,
            config.node_rank,
            RendezvousName.NETWORK_CHECK,
            local_world_size=1,  # one probe process per host
            min_nodes=config.min_nodes,
            max_nodes=config.max_nodes,
            waiting_timeout=config.rdzv_waiting_timeout,
            node_unit=1,
            host_ip=host_ip,
        )

    def run(self) -> bool:
        ctx = get_context()
        for _ in range(self.CHECK_ROUNDS):
            try:
                group = self._handler.next_rendezvous(
                    timeout=ctx.network_check_timeout_secs
                )
            except RendezvousTimeoutError:
                # not admitted to this check round: we are outside the
                # world, so do NOT report a result (it would corrupt the
                # master's per-round accounting); the node stays suspect.
                logger.warning("not admitted to network-check round")
                return False
            self._handler.release_coordinator_port()
            normal, elapsed = self._run_probe(group)
            self._client.report_network_check_result(
                self._config.node_rank, normal, elapsed
            )
            self._wait_round_reported(group)
        deadline = time.time() + ctx.network_check_timeout_secs
        while time.time() < deadline:
            success, reason = self._client.network_ready()
            if success:
                return self._config.node_rank not in set(
                    self._abnormal_ranks()
                )
            if reason != "waiting":
                break
            time.sleep(1.0)
        return self._config.node_rank not in set(self._abnormal_ranks())

    def _abnormal_ranks(self) -> List[int]:
        """Ranks the master's 2-round diagnosis marks as failed."""
        try:
            return self._client.abnormal_ranks()
        except Exception as e:  # noqa: BLE001 — master briefly unreachable
            # an empty answer admits this node to training; log it so a
            # flaky master can be distinguished from a clean bill (DLR002)
            logger.warning(
                "abnormal_ranks query failed, treating diagnosis as clean "
                "(%s: %s)", type(e).__name__, e,
            )
            return []

    def _run_probe(self, group: RendezvousInfo) -> tuple:
        cmd = [
            sys.executable, "-m", "dlrover_tpu.agent.network_probe",
            "--coordinator", group.coordinator_addr,
            "--process_id", str(group.group_rank),
            "--num_processes", str(group.group_world_size),
        ]
        if self._config.probe_platform:
            cmd += ["--platform", self._config.probe_platform]
        t0 = time.time()
        try:
            proc = subprocess.run(
                cmd, capture_output=True, timeout=120, text=True
            )
            elapsed = time.time() - t0
            if proc.returncode != 0:
                logger.warning("probe failed: %s", proc.stderr[-2000:])
                return False, elapsed
            return True, elapsed
        except subprocess.TimeoutExpired:
            return False, time.time() - t0

    def _wait_round_reported(self, group: RendezvousInfo,
                             timeout: float = 60.0):
        """Block until every node in the group reported, so rounds don't
        overlap (cheap poll against the master)."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            success, reason = self._client.network_ready()
            if reason != "waiting":
                return
            time.sleep(0.5)
