"""Per-host resource usage reporter.

Role parity: ``dlrover/python/elastic_agent/monitor/resource.py:86-184`` —
a daemon thread sampling host CPU/memory (and accelerator duty where
available) and pushing it to the master, feeding hang detection and the
resource optimizer.
"""

from __future__ import annotations

import threading
from typing import Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.log import get_logger

logger = get_logger("agent.resource")

try:
    import psutil
except ImportError:  # pragma: no cover - psutil ships in the image
    psutil = None


def current_process_usage() -> tuple:
    """(cpu_percent, memory_mb) of this host."""
    if psutil is None:
        return 0.0, 0
    cpu = psutil.cpu_percent(interval=None) / 100.0
    mem_mb = int(psutil.virtual_memory().used / (1024 * 1024))
    return cpu, mem_mb


class ResourceMonitor:
    def __init__(self, master_client: Optional[MasterClient],
                 chips: int = 0):
        self._client = master_client
        self._chips = chips
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self):
        if self._client is None or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._run, name="resource-monitor", daemon=True
        )
        self._thread.start()

    def stop(self):
        self._stop.set()

    def _run(self):
        ctx = get_context()
        while not self._stop.wait(ctx.seconds_interval_to_report):
            try:
                cpu, mem_mb = current_process_usage()
                self._client.report_resource(
                    cpu_percent=cpu, memory_mb=mem_mb, chips=self._chips
                )
            except Exception as e:
                logger.debug("resource report failed: %s", e)
