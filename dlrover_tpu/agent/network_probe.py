"""Standalone network/accelerator health probe.

Role parity: ``dlrover/trainer/torch/run_network_check.py`` (10x timed
allgather). TPU retarget: the probe validates the two fabrics a host
depends on --
  1. **chip health / ICI**: a jitted matmul + psum over the host's local
     chips (exercises the MXU and intra-host links);
  2. **host fabric (DCN/NIC)**: a gloo-backed CPU allgather across the probe
     group handed out by the NetworkCheckRendezvousManager.

Run as ``python -m dlrover_tpu.agent.network_probe`` with the coordinates in
argv; exits 0 when healthy, 1 otherwise, and prints the elapsed time so the
agent can report straggler timings.
"""

from __future__ import annotations

import argparse
import sys
import time


def probe_local_chips(platform: str) -> float:
    """Matmul+reduce on the local backend; returns elapsed seconds."""
    import jax
    import jax.numpy as jnp

    if platform:
        jax.config.update("jax_platforms", platform)
    t0 = time.time()
    n = jax.local_device_count()
    x = jnp.ones((256, 256), dtype=jnp.bfloat16)

    @jax.jit
    def _work(a):
        return (a @ a).astype(jnp.float32).sum()

    results = [jax.device_put(x, d) for d in jax.local_devices()]
    outs = [_work(r) for r in results]
    for o in outs:
        o.block_until_ready()
    elapsed = time.time() - t0
    print(f"probe: {n} local devices ok in {elapsed:.3f}s", flush=True)
    return elapsed


def probe_group_fabric(coordinator: str, process_id: int,
                      num_processes: int, rounds: int = 10) -> float:
    """Timed cross-host allgather over the probe group (CPU/gloo — checks
    the host NIC/DCN path without claiming TPU slices)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # knob name varies across jax versions
        pass
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    t0 = time.time()
    for _ in range(rounds):
        local = jnp.arange(1024, dtype=jnp.float32) + process_id
        gathered = multihost_utils.process_allgather(local)
        assert gathered.shape[0] == num_processes
    elapsed = time.time() - t0
    print(f"probe: {rounds} allgathers over {num_processes} procs "
          f"in {elapsed:.3f}s", flush=True)
    return elapsed


def main(argv=None) -> int:
    parser = argparse.ArgumentParser()
    parser.add_argument("--coordinator", default="")
    parser.add_argument("--process_id", type=int, default=0)
    parser.add_argument("--num_processes", type=int, default=1)
    parser.add_argument("--platform", default="",
                        help="backend for the chip probe ('' = default)")
    parser.add_argument("--skip_chip_probe", action="store_true")
    parser.add_argument("--rounds", type=int, default=10)
    args = parser.parse_args(argv)

    elapsed = 0.0
    try:
        if not args.skip_chip_probe:
            elapsed += probe_local_chips(args.platform)
        if args.num_processes > 1 and args.coordinator:
            elapsed += probe_group_fabric(
                args.coordinator, args.process_id, args.num_processes,
                args.rounds,
            )
    except Exception as e:  # any probe failure marks this host suspect
        print(f"probe failed: {type(e).__name__}: {e}", file=sys.stderr,
              flush=True)
        return 1
    print(f"PROBE_ELAPSED={elapsed:.3f}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
