"""Worker-side dynamic shard consumption.

Role parity: ``dlrover/python/elastic_agent/sharding/client.py:31-337``
(ShardingClient / IndexShardingClient): fetch shards from the master, credit
consumed batches back so tasks complete by record count, and surface shard
checkpoints for mid-epoch resume.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Deque, Iterator, List, Optional

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import comm
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.telemetry import get_registry, names as tm

logger = get_logger("agent.sharding")


class ShardingClient:
    """One per (worker, dataset): the worker's window into the master's
    todo/doing queues."""

    def __init__(
        self,
        master_client: MasterClient,
        dataset_name: str,
        batch_size: int,
        dataset_size: int,
        num_epochs: int = 1,
        shuffle: bool = False,
        num_minibatches_per_shard: int = 2,
        storage_type: str = "table",
        task_type: str = "training",
    ):
        self._client = master_client
        self.dataset_name = dataset_name
        self.batch_size = batch_size
        self._lock = threading.Lock()
        self._pending_batch_count = 0
        self._current_task: Optional[comm.Task] = None
        # data-plane instruments (null handles when telemetry is off):
        # fetch latency is the worker's view of the master's dispatch
        # queue — a starved pipeline shows up here before anywhere else
        reg = get_registry()
        self._h_fetch = reg.histogram(
            tm.DATA_SHARD_FETCH_TIME,
            help="get_task RPC latency fetching the next shard")
        self._c_fetched = reg.counter(
            tm.DATA_SHARDS_FETCHED, help="shards fetched from the master")
        self._c_completed = reg.counter(
            tm.DATA_SHARDS_COMPLETED,
            help="shards this worker reported complete")
        self._c_report_retries = reg.counter(
            tm.DATA_BATCH_REPORT_RETRIES,
            help="batch-done credits re-queued after a failed report "
                 "RPC (restored, not dropped)")
        self._client.report_dataset_shard_params(
            dataset_name=dataset_name,
            dataset_size=dataset_size,
            batch_size=batch_size,
            num_epochs=num_epochs,
            shuffle=shuffle,
            num_minibatches_per_shard=num_minibatches_per_shard,
            storage_type=storage_type,
            task_type=task_type,
        )

    def fetch_shard(self) -> Optional[comm.Shard]:
        """Next shard, or None when the dataset is exhausted."""
        t0 = time.monotonic()
        task = self._client.get_task(self.dataset_name)
        self._h_fetch.observe(time.monotonic() - t0)
        if task is None or task.task_id < 0:
            return None
        self._c_fetched.inc()
        self._current_task = task
        return task.shard

    def report_batch_done(self, batch_count: int = 1):
        """Credit consumed batches; flushed to the master per batch group
        (cheap: one rpc per batch, still shard-granular on the master).

        A failed report RPC restores the pending count instead of
        dropping it: a silently lost credit would leave the shard to
        complete only via the master's timeout re-dispatch — re-reading
        data the job already consumed. The retry is counted and the
        next report carries the accumulated credit."""
        with self._lock:
            self._pending_batch_count += batch_count
            pending = self._pending_batch_count
            self._pending_batch_count = 0
        records = pending * self.batch_size
        if not records:
            return
        try:
            self._client.report_batch_done(self.dataset_name, records)
        except Exception:
            with self._lock:
                self._pending_batch_count += pending
            self._c_report_retries.inc()
            raise

    def report_task_done(self, err_message: str = ""):
        if self._current_task is not None:
            self._client.report_task_result(
                self.dataset_name, self._current_task.task_id, err_message
            )
            if not err_message:
                self._c_completed.inc()
            self._current_task = None

    @property
    def current_task_id(self) -> Optional[int]:
        return (self._current_task.task_id
                if self._current_task is not None else None)

    def report_task_done_by_id(self, task_id: int, err_message: str = ""):
        """Complete a specific task — for consumers that buffer records
        across fetches (packing) and must defer completion until the
        buffered data has actually been emitted."""
        self._client.report_task_result(
            self.dataset_name, task_id, err_message
        )
        if not err_message:
            self._c_completed.inc()
        if self._current_task is not None and \
                self._current_task.task_id == task_id:
            self._current_task = None

    def get_shard_checkpoint(self) -> str:
        return self._client.get_shard_checkpoint(self.dataset_name)

    def restore_shard_checkpoint(self, content: str):
        self._client.report_shard_checkpoint(self.dataset_name, content)


class IndexShardingClient(ShardingClient):
    """Streams record indices out of fetched shards — the piece an
    index-based sampler/dataloader plugs into (the reference's
    ``IndexShardingClient:249``)."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        self._indices: Deque[int] = deque()

    def fetch_record_index(self) -> Optional[int]:
        # the get_task RPC must happen OUTSIDE the lock: a slow/dead
        # master would otherwise hold the index queue hostage for the
        # full rpc timeout while every other consumer thread stalls
        # behind the lock. Two threads refilling concurrently is fine —
        # both shards land in the deque and each index is popped once.
        while True:
            with self._lock:
                if self._indices:
                    return self._indices.popleft()
            shard = self.fetch_shard()
            if shard is None:
                return None
            with self._lock:
                if shard.record_indices:
                    self._indices.extend(shard.record_indices)
                else:
                    self._indices.extend(range(shard.start, shard.end))

    def record_indices(self) -> Iterator[int]:
        while True:
            idx = self.fetch_record_index()
            if idx is None:
                return
            yield idx
