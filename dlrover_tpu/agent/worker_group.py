"""Local worker process group: spawn/monitor/kill the per-host JAX
training processes.

Role parity: the subprocess-management half of torch's LocalElasticAgent as
used in ``dlrover/python/elastic_agent/torch/training.py`` (PContext spawn +
``_monitor_workers``). One process per local chip-group; each gets the
jax.distributed coordinates in its environment.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional, Sequence

from dlrover_tpu.agent.rendezvous import RendezvousInfo
from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.common.log import get_logger

logger = get_logger("agent.workers")


class WorkerGroupState(str, Enum):
    INIT = "INIT"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"


@dataclass
class WorkerSpec:
    """What to run on this host."""

    entrypoint: str  # a python script path or executable
    args: Sequence[str] = field(default_factory=tuple)
    nproc_per_node: int = 1
    env: Dict[str, str] = field(default_factory=dict)
    redirect_output: Optional[str] = None  # directory for per-rank logs
    heartbeat_dir: str = ""  # exported for hang-relaunch (agent sets it)


@dataclass
class WorkerFailure:
    local_rank: int
    exit_code: int
    log_tail: str = ""


class WorkerGroup:
    def __init__(self, spec: WorkerSpec):
        self.spec = spec
        self._procs: List[subprocess.Popen] = []
        self._log_files: List = []
        self.state = WorkerGroupState.INIT
        self.restart_round = 0
        self.started_at = time.time()

    def latest_heartbeat(self) -> "Tuple[float, bool]":
        """(newest beat unix time, whether any beat landed this round).
        The spawn time floors the value so a fresh round isn't judged by
        the previous round's stale files; the flag lets the agent allow a
        longer first window (XLA compile happens inside the first step,
        with no Python-side opportunity to beat)."""
        latest = self.started_at
        beaten = False
        now = time.time()
        d = self.spec.heartbeat_dir
        if d and os.path.isdir(d):
            for name in os.listdir(d):
                path = os.path.join(d, name)
                if name.startswith("hb_"):
                    try:
                        mtime = os.path.getmtime(path)
                    except OSError:
                        continue
                    if mtime > self.started_at:
                        beaten = True
                        latest = max(latest, mtime)
                elif name.startswith("lease_"):
                    # a declared bounded no-beat window (recompile,
                    # restore): counts as liveness until its deadline.
                    # Only leases WRITTEN this round count — a stale one
                    # from before a restart must not extend the fresh
                    # round's clock
                    try:
                        if os.path.getmtime(path) <= self.started_at:
                            continue
                        with open(path) as f:
                            deadline = float(f.read().strip() or 0)
                    except (OSError, ValueError):
                        continue
                    latest = max(latest, min(deadline, now))
        return latest, beaten

    def start(self, rdzv: RendezvousInfo, master_addr: str, node_id: int,
              extra_env=None):
        """Spawn ``nproc_per_node`` processes with SPMD coordinates.
        ``extra_env``: per-round additions (e.g. the open incident's
        trace id) layered over the spec's static env."""
        if self.spec.nproc_per_node < 1:
            raise ValueError(
                f"nproc_per_node must be >= 1, got {self.spec.nproc_per_node}"
            )
        self.stop()
        self._procs = []
        self._log_files = []
        self.started_at = time.time()
        if self.spec.heartbeat_dir:
            os.makedirs(self.spec.heartbeat_dir, exist_ok=True)
        for local_rank in range(self.spec.nproc_per_node):
            env = dict(os.environ)
            env.update(self.spec.env)
            if extra_env:
                env.update(extra_env)
            if self.spec.heartbeat_dir:
                env[NodeEnv.HEARTBEAT_DIR] = self.spec.heartbeat_dir
            env.update({
                NodeEnv.MASTER_ADDR: master_addr,
                NodeEnv.NODE_ID: str(node_id),
                NodeEnv.NODE_RANK: str(rdzv.group_rank),
                NodeEnv.NODE_NUM: str(rdzv.group_world_size),
                NodeEnv.COORDINATOR_ADDR: rdzv.coordinator_addr,
                NodeEnv.PROCESS_ID: str(rdzv.process_id_base + local_rank),
                NodeEnv.NUM_PROCESSES: str(rdzv.num_processes),
                NodeEnv.RESTART_ROUND: str(self.restart_round),
                "LOCAL_RANK": str(local_rank),
                "LOCAL_WORLD_SIZE": str(self.spec.nproc_per_node),
            })
            cmd = self._build_cmd()
            stdout = stderr = None
            if self.spec.redirect_output:
                os.makedirs(self.spec.redirect_output, exist_ok=True)
                path = os.path.join(
                    self.spec.redirect_output,
                    f"worker_{rdzv.process_id_base + local_rank}"
                    f"_r{self.restart_round}.log",
                )
                f = open(path, "ab")
                self._log_files.append(f)
                stdout = stderr = f
            proc = subprocess.Popen(
                cmd, env=env, stdout=stdout, stderr=stderr,
                start_new_session=True,
            )
            self._procs.append(proc)
        self.state = WorkerGroupState.RUNNING
        logger.info(
            "spawned %d workers (restart round %d): %s",
            len(self._procs), self.restart_round, self._build_cmd(),
        )

    def _build_cmd(self) -> List[str]:
        entry = self.spec.entrypoint
        if entry.endswith(".py"):
            return [sys.executable, "-u", entry, *self.spec.args]
        return [entry, *self.spec.args]

    def monitor(self) -> WorkerGroupState:
        """Poll subprocess states; FAILED wins over SUCCEEDED."""
        if self.state not in (WorkerGroupState.RUNNING,):
            return self.state
        if not self._procs:  # never started: nothing ran, nothing succeeded
            self.state = WorkerGroupState.FAILED
            return self.state
        codes = [p.poll() for p in self._procs]
        if any(c is not None and c != 0 for c in codes):
            self.state = WorkerGroupState.FAILED
        elif all(c == 0 for c in codes):
            self.state = WorkerGroupState.SUCCEEDED
        return self.state

    def failures(self) -> List[WorkerFailure]:
        out = []
        for i, p in enumerate(self._procs):
            code = p.poll()
            if code is not None and code != 0:
                out.append(WorkerFailure(local_rank=i, exit_code=code))
        return out

    def stop(self, grace_secs: float = 30.0):
        """Terminate the whole process group of every worker.

        The grace default budgets for the executor's preemption-grace
        path (``trainer/executor.py``): SIGTERM makes a worker finish
        its in-flight step and flush an emergency host-staged
        checkpoint before exiting — escalating to SIGKILL sooner would
        tear exactly the save the notice exists to enable."""
        for p in self._procs:
            if p.poll() is None:
                try:
                    os.killpg(p.pid, signal.SIGTERM)
                except (ProcessLookupError, PermissionError):
                    pass
        deadline = time.time() + grace_secs
        for p in self._procs:
            remaining = max(0.1, deadline - time.time())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                try:
                    os.killpg(p.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
                p.wait()
        for f in self._log_files:
            try:
                f.close()
            except OSError:
                pass
        self._log_files = []
        if self._procs:
            self.state = WorkerGroupState.STOPPED

    def restart_count_up(self):
        self.restart_round += 1
