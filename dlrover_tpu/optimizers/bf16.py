"""BF16 training with fp32 master weights.

Role parity: ``atorch/atorch/optimizers/bf16_optimizer.py:46``
(``BF16Optimizer`` — wraps a torch optimizer, keeps fp32 master copies of
every half-precision parameter, steps the masters, copies back). The TPU
version is an optax wrapper: the optimizer state holds the fp32 masters,
the update returned to ``optax.apply_updates`` is the bf16 delta that
moves the stored params onto the freshly-stepped masters — so tiny
updates accumulate in fp32 even when each one underflows bf16.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

_HALF_DTYPES = (jnp.bfloat16, jnp.float16)


class MasterWeightsState(NamedTuple):
    master: Any  # fp32 copies of half-precision params (others aliased)
    base_state: Any


def bf16_master_weights(
    base_optimizer: optax.GradientTransformation,
) -> optax.GradientTransformation:
    """Wrap ``base_optimizer`` so half-precision params are stepped
    through fp32 masters. Full-precision params pass through unchanged."""

    def _to_master(p):
        return p.astype(jnp.float32) if p.dtype in _HALF_DTYPES else p

    def init(params):
        master = jax.tree.map(_to_master, params)
        return MasterWeightsState(
            master=master, base_state=base_optimizer.init(master)
        )

    def update(grads, state: MasterWeightsState, params=None):
        if params is None:
            raise ValueError("bf16_master_weights requires params")
        grads32 = jax.tree.map(
            lambda g: g.astype(jnp.float32)
            if g.dtype in _HALF_DTYPES else g,
            grads,
        )
        master_updates, base_state = base_optimizer.update(
            grads32, state.base_state, state.master
        )
        new_master = optax.apply_updates(state.master, master_updates)
        # the emitted update lands params exactly on cast(new_master)
        updates = jax.tree.map(
            lambda m, p: m.astype(p.dtype) - p, new_master, params
        )
        return updates, MasterWeightsState(new_master, base_state)

    return optax.GradientTransformation(init, update)
