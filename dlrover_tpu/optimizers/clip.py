"""Parallelism-aware gradient clipping.

Role parity: ``atorch/atorch/auto/clip_grad_norm.py`` — the reference
must sum squared norms across tensor-parallel process groups by hand.
Under GSPMD the gradient pytree is logically global, so the plain global
norm is already parallelism-correct; the ``axis_names`` path covers
``shard_map`` contexts where collectives are manual.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import optax


def global_norm(
    tree: Any, axis_names: Optional[Sequence[str]] = None
) -> jnp.ndarray:
    """L2 norm over every leaf; with ``axis_names``, the squared sum is
    ``lax.psum``-ed over those mesh axes first (for use inside
    ``shard_map`` where each shard only sees its local slice)."""
    sq = sum(
        jnp.sum(jnp.square(leaf.astype(jnp.float32)))
        for leaf in jax.tree.leaves(tree)
    )
    if axis_names:
        sq = jax.lax.psum(sq, tuple(axis_names))
    return jnp.sqrt(sq)


def clip_by_global_norm(
    max_norm: float, axis_names: Optional[Sequence[str]] = None
) -> optax.GradientTransformation:
    """optax transformation clipping to ``max_norm``; shard_map-safe when
    ``axis_names`` is given."""

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        norm = global_norm(updates, axis_names)
        factor = jnp.minimum(1.0, max_norm / (norm + 1e-12))
        return jax.tree.map(
            lambda u: (u.astype(jnp.float32) * factor).astype(u.dtype),
            updates,
        ), state

    return optax.GradientTransformation(init, update)
