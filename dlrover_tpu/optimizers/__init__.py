from dlrover_tpu.optimizers.bf16 import bf16_master_weights
from dlrover_tpu.optimizers.clip import clip_by_global_norm, global_norm
from dlrover_tpu.optimizers.grad_scaler import (
    DynamicGradScaler,
    GradScalerState,
    all_finite,
)
from dlrover_tpu.optimizers.wsam import WsamOptimizer, wsam

__all__ = [
    "bf16_master_weights",
    "clip_by_global_norm",
    "global_norm",
    "DynamicGradScaler",
    "GradScalerState",
    "all_finite",
    "WsamOptimizer",
    "wsam",
]
