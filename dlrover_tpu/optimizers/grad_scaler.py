"""Dynamic loss scaling.

Role parity: ``atorch/atorch/utils/grad_scaler.py`` /
``amp/pipe_amp.py:51`` (``PipeGradScaler``) — torch ``GradScaler``
variants. On TPU the default dtype is bf16 (no scaling needed), but the
fp16 path and the reference's AMP surface need the same contract:
scale the loss up, check grads for inf/nan, skip the step and back off
on overflow, grow after a stable streak. Implemented as pure functions
over an explicit state so the whole thing lives inside ``jit``.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Tuple

import jax
import jax.numpy as jnp


class GradScalerState(NamedTuple):
    scale: jnp.ndarray  # f32 scalar
    growth_tracker: jnp.ndarray  # consecutive finite steps, int32


def all_finite(tree: Any) -> jnp.ndarray:
    """Scalar bool: every leaf of the pytree is finite."""
    leaves = [
        jnp.all(jnp.isfinite(leaf)) for leaf in jax.tree.leaves(tree)
    ]
    if not leaves:
        return jnp.asarray(True)
    return jnp.stack(leaves).all()


class DynamicGradScaler:
    """torch.cuda.amp.GradScaler semantics, functionally.

    Usage inside a train step::

        state = scaler.init()
        loss = scaler.scale(loss, state)          # before grad
        grads = ...                                # grads of scaled loss
        grads, finite = scaler.unscale(grads, state)
        state = scaler.update(state, finite)
        # apply the optimizer step only where `finite` (lax.cond / where)
    """

    def __init__(
        self,
        init_scale: float = 2.0 ** 16,
        growth_factor: float = 2.0,
        backoff_factor: float = 0.5,
        growth_interval: int = 2000,
        max_scale: float = 2.0 ** 24,
    ):
        self.init_scale = init_scale
        self.growth_factor = growth_factor
        self.backoff_factor = backoff_factor
        self.growth_interval = growth_interval
        self.max_scale = max_scale

    def init(self) -> GradScalerState:
        return GradScalerState(
            scale=jnp.asarray(self.init_scale, jnp.float32),
            growth_tracker=jnp.zeros((), jnp.int32),
        )

    def scale(self, loss: jnp.ndarray, state: GradScalerState):
        return loss * state.scale.astype(loss.dtype)

    def unscale(
        self, grads: Any, state: GradScalerState
    ) -> Tuple[Any, jnp.ndarray]:
        inv = (1.0 / state.scale).astype(jnp.float32)
        unscaled = jax.tree.map(
            lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype), grads
        )
        return unscaled, all_finite(unscaled)

    def update(
        self, state: GradScalerState, grads_finite: jnp.ndarray
    ) -> GradScalerState:
        grew = state.growth_tracker + 1 >= self.growth_interval
        new_scale = jnp.where(
            grads_finite,
            jnp.where(
                grew,
                jnp.minimum(
                    state.scale * self.growth_factor, self.max_scale
                ),
                state.scale,
            ),
            state.scale * self.backoff_factor,
        )
        new_tracker = jnp.where(
            grads_finite,
            jnp.where(grew, 0, state.growth_tracker + 1),
            0,
        )
        return GradScalerState(
            scale=new_scale, growth_tracker=new_tracker.astype(jnp.int32)
        )
