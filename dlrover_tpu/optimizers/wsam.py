"""WSAM — Weighted Sharpness-Aware Minimization (KDD'23).

Role parity: ``atorch/atorch/optimizers/wsam.py:11-123`` (``WeightedSAM``).
The reference is a torch optimizer driven by a closure that re-runs
forward/backward at the perturbed point; the TPU version is a functional
two-gradient optimizer: the train step hands it ``grad_fn`` and both
gradient evaluations happen inside one jitted XLA program (no eager
closure, no ``no_sync`` bookkeeping — under GSPMD the gradients are
already global, which matches the reference's post-allreduce semantics).

Update rule (alpha = gamma / (1 - gamma)):

  e_w    = rho * g / (||g|| + eps)            (adaptive: |p|^2-scaled)
  g_sam  = grad(loss)(w + e_w)
  coupled:   w <- base_update(w, (1-alpha) g + alpha g_sam)
  decoupled: w <- base_update(w, g) - lr * alpha * (g_sam - g)
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Callable, NamedTuple, Optional, Union

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.optimizers.clip import global_norm


class WsamState(NamedTuple):
    base_state: Any
    count: jnp.ndarray  # step counter (drives lr schedules in decouple mode)


@dataclass(frozen=True)
class WsamOptimizer:
    """Two-gradient optimizer. ``parallel.accelerate`` detects the
    ``update_with_grad_fn`` method and supplies ``grad_fn`` (a full
    forward/backward at given params on the current batch)."""

    init: Callable[[Any], WsamState]
    update_with_grad_fn: Callable  # (grads, state, params, grad_fn)


def wsam(
    base_optimizer: optax.GradientTransformation,
    rho: float = 0.05,
    gamma: float = 0.9,
    sam_eps: float = 1e-12,
    adaptive: bool = False,
    decouple: bool = True,
    max_norm: Optional[float] = None,
    learning_rate: Union[float, Callable, None] = None,
) -> WsamOptimizer:
    """Wrap ``base_optimizer`` with WSAM.

    ``learning_rate`` is only needed in ``decouple`` mode (the sharpness
    term is applied directly to the weights, scaled by the current lr,
    mirroring ``wsam.py:98-104``); pass the same value/schedule as the
    base optimizer's.
    """
    if rho < 0.0:
        raise ValueError(f"Invalid rho, should be non-negative: {rho}")
    if decouple and learning_rate is None:
        raise ValueError(
            "decouple=True applies the sharpness term with the current "
            "learning rate; pass learning_rate= (value or schedule)"
        )
    alpha = gamma / (1.0 - gamma)

    def init(params):
        return WsamState(
            base_state=base_optimizer.init(params),
            count=jnp.zeros((), jnp.int32),
        )

    def _clip(grads):
        if max_norm is None:
            return grads
        norm = global_norm(grads)
        factor = jnp.minimum(1.0, max_norm / (norm + 1e-12))
        return jax.tree.map(lambda g: g * factor, grads)

    def update_with_grad_fn(grads, state: WsamState, params, grad_fn):
        # ASAM semantics: when adaptive, the perturbation radius is
        # measured in the weight-adaptive metric, so the norm is taken
        # over |p|*g (matching the reference's _grad_norm) while the
        # numerator carries |p|^2*g.
        if adaptive:
            norm = global_norm(
                jax.tree.map(lambda p, g: jnp.abs(p) * g, params, grads)
            )
        else:
            norm = global_norm(grads)
        scale = rho / (norm + sam_eps)
        e_w = jax.tree.map(
            lambda p, g: (jnp.square(p) if adaptive else 1.0) * g * (
                scale.astype(g.dtype)
            ),
            params, grads,
        )
        g_sam = grad_fn(jax.tree.map(jnp.add, params, e_w))
        grads_c = _clip(grads)
        g_sam_c = _clip(g_sam)

        if not decouple:
            g_final = jax.tree.map(
                lambda g, gs: (1.0 - alpha) * g + alpha * gs,
                grads_c, g_sam_c,
            )
            updates, base_state = base_optimizer.update(
                g_final, state.base_state, params
            )
            return updates, WsamState(base_state, state.count + 1)

        # decoupled: base step on the plain gradient, sharpness term
        # applied as a direct weight delta scaled by the current lr
        updates, base_state = base_optimizer.update(
            grads_c, state.base_state, params
        )
        lr = learning_rate(state.count) if callable(learning_rate) else (
            learning_rate
        )
        sharp = jax.tree.map(jnp.subtract, g_sam_c, grads_c)
        updates = jax.tree.map(
            lambda u, s: u - (lr * alpha) * s, updates, sharp
        )
        return updates, WsamState(base_state, state.count + 1)

    return WsamOptimizer(init=init, update_with_grad_fn=update_with_grad_fn)
