"""Kubernetes platform client and spec builders.

Role parity: ``dlrover/python/scheduler/kubernetes.py`` (``k8sClient``
singleton with retries + pod/service/CR CRUD). The real ``kubernetes``
package is optional: the client is a thin injectable seam, and tests drive
the scaler/watcher logic against a ``FakeK8sClient`` exactly like the
reference monkey-patches its ``k8sClient`` (reference ``tests/test_utils.py``).

TPU-first: pod specs request ``google.com/tpu`` chips and carry the
topology selector a GKE TPU node pool expects.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from dlrover_tpu.common.log import get_logger

logger = get_logger("scheduler.k8s")

ELASTICJOB_GROUP = "elastic.dlrover-tpu.org"
ELASTICJOB_VERSION = "v1alpha1"
SCALEPLAN_PLURAL = "scaleplans"
ELASTICJOB_PLURAL = "elasticjobs"
TPU_RESOURCE_KEY = "google.com/tpu"
TPU_TOPOLOGY_SELECTOR = "cloud.google.com/gke-tpu-topology"
TPU_ACCELERATOR_SELECTOR = "cloud.google.com/gke-tpu-accelerator"


def retry_k8s_request(func: Callable) -> Callable:
    """Retry transient API failures (reference: k8sClient retry wrappers)."""

    def wrapped(*args, **kwargs):
        for attempt in range(3):
            try:
                return func(*args, **kwargs)
            except Exception as exc:  # noqa: BLE001 - API errors are opaque
                if attempt == 2:
                    logger.error("%s failed: %s", func.__name__, exc)
                    return None
                time.sleep(0.5 * (attempt + 1))

    return wrapped


class K8sClient:
    """Thin wrapper over the kubernetes python client.

    Only constructed when the ``kubernetes`` package is importable; all
    control-plane logic depends on this interface, not the package, so the
    whole master runs (and is tested) without a cluster.
    """

    _instance: Optional["K8sClient"] = None
    _lock = threading.Lock()

    def __init__(self, namespace: str = "default"):
        import kubernetes  # deferred: optional dependency

        kubernetes.config.load_incluster_config()
        self._core = kubernetes.client.CoreV1Api()
        self._custom = kubernetes.client.CustomObjectsApi()
        self._watch = kubernetes.watch
        self.namespace = namespace

    @classmethod
    def singleton_instance(cls, namespace: str = "default") -> "K8sClient":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(namespace)
            return cls._instance

    @retry_k8s_request
    def create_pod(self, pod: Dict[str, Any]):
        return self._core.create_namespaced_pod(self.namespace, pod)

    @retry_k8s_request
    def delete_pod(self, name: str):
        return self._core.delete_namespaced_pod(name, self.namespace)

    @retry_k8s_request
    def list_pods(self, label_selector: str = "") -> List[Dict[str, Any]]:
        pods = self._core.list_namespaced_pod(
            self.namespace, label_selector=label_selector
        )
        return [p.to_dict() for p in pods.items]

    @retry_k8s_request
    def create_service(self, service: Dict[str, Any]):
        return self._core.create_namespaced_service(self.namespace, service)

    @retry_k8s_request
    def create_custom_resource(self, plural: str, body: Dict[str, Any]):
        return self._custom.create_namespaced_custom_object(
            ELASTICJOB_GROUP, ELASTICJOB_VERSION, self.namespace, plural, body
        )

    @retry_k8s_request
    def get_custom_resource(self, plural: str, name: str):
        return self._custom.get_namespaced_custom_object(
            ELASTICJOB_GROUP, ELASTICJOB_VERSION, self.namespace, plural, name
        )

    @retry_k8s_request
    def list_custom_resources(self, plural: str) -> List[Dict[str, Any]]:
        out = self._custom.list_namespaced_custom_object(
            ELASTICJOB_GROUP, ELASTICJOB_VERSION, self.namespace, plural
        )
        return list(out.get("items", []))

    @retry_k8s_request
    def update_custom_resource_status(
        self, plural: str, name: str, body: Dict[str, Any]
    ):
        return self._custom.patch_namespaced_custom_object_status(
            ELASTICJOB_GROUP, ELASTICJOB_VERSION, self.namespace, plural,
            name, body,
        )


def build_pod_labels(job_name: str, node_type: str, rank_index: int) -> Dict[str, str]:
    return {
        "app": "dlrover-tpu",
        "elasticjob-name": job_name,
        "replica-type": node_type,
        "rank-index": str(rank_index),
    }


def parse_cpu_cores(quantity) -> float:
    """K8s cpu quantity -> cores: '500m' -> 0.5, '4' -> 4.0, 2 -> 2.0.

    The ONE cpu-quantity parser (master watcher + brain watcher) — two
    divergent copies would let the same pod spec ingest differently."""
    if isinstance(quantity, (int, float)):
        return float(quantity)
    s = str(quantity).strip()
    try:
        if s.endswith("m"):
            return float(s[:-1]) / 1000.0
        return float(s)
    except ValueError:
        return 0.0


_MEM_SUFFIX_BYTES = {
    "Ki": 1 << 10, "Mi": 1 << 20, "Gi": 1 << 30, "Ti": 1 << 40,
    "K": 10 ** 3, "M": 10 ** 6, "G": 10 ** 9, "T": 10 ** 12,
}


def parse_memory_mib(quantity) -> int:
    """K8s memory quantity -> MiB, per the real quantity grammar:
    binary suffixes ('8Gi', '512Mi'), decimal suffixes ('8G' = 8e9
    bytes), and a PLAIN number is BYTES — '8589934592' and 8589934592
    are both 8192 MiB. The ONE memory-quantity parser (see
    ``parse_cpu_cores``)."""
    if isinstance(quantity, (int, float)):
        return int(quantity / (1 << 20))
    s = str(quantity).strip()
    try:
        # two-char binary suffixes first: 'Mi' must not match 'M'
        for suffix in ("Ki", "Mi", "Gi", "Ti", "K", "M", "G", "T"):
            if s.endswith(suffix):
                return int(
                    float(s[: -len(suffix)])
                    * _MEM_SUFFIX_BYTES[suffix] / (1 << 20)
                )
        return int(float(s) / (1 << 20))
    except ValueError:
        return 0


def build_pod_spec(
    job_name: str,
    pod_name: str,
    node_type: str,
    node_id: int,
    rank_index: int,
    image: str,
    command: List[str],
    cpu: float,
    memory_mb: int,
    tpu_chips: int = 0,
    tpu_topology: str = "",
    tpu_accelerator: str = "",
    env: Optional[Dict[str, str]] = None,
) -> Dict[str, Any]:
    """Build the pod dict the scaler submits (reference: PodScaler._create_pod_obj).

    TPU pods pin to a GKE TPU node pool via topology/accelerator selectors
    and request whole hosts' worth of chips — fractional TPU requests are
    not a thing.
    """
    resources: Dict[str, Any] = {
        "requests": {"cpu": str(cpu), "memory": f"{memory_mb}Mi"},
        "limits": {"memory": f"{memory_mb}Mi"},
    }
    node_selector: Dict[str, str] = {}
    if tpu_chips > 0:
        resources["requests"][TPU_RESOURCE_KEY] = str(tpu_chips)
        resources["limits"][TPU_RESOURCE_KEY] = str(tpu_chips)
        if tpu_topology:
            node_selector[TPU_TOPOLOGY_SELECTOR] = tpu_topology
        if tpu_accelerator:
            node_selector[TPU_ACCELERATOR_SELECTOR] = tpu_accelerator
    env_list = [{"name": k, "value": v} for k, v in (env or {}).items()]
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": pod_name,
            "labels": build_pod_labels(job_name, node_type, rank_index),
            "annotations": {"node-id": str(node_id)},
        },
        "spec": {
            "restartPolicy": "Never",
            "nodeSelector": node_selector,
            "containers": [
                {
                    "name": "main",
                    "image": image,
                    "command": command,
                    "resources": resources,
                    "env": env_list,
                }
            ],
        },
    }


def build_scale_plan_cr(
    job_name: str,
    node_group_resources: Dict[str, Dict[str, Any]],
    create_pods: Optional[List[Dict[str, Any]]] = None,
    remove_pods: Optional[List[str]] = None,
    ps_hosts: Optional[List[str]] = None,
) -> Dict[str, Any]:
    """ScalePlan CR body (reference: ElasticJobScaler + scaleplan_types.go)."""
    return {
        "apiVersion": f"{ELASTICJOB_GROUP}/{ELASTICJOB_VERSION}",
        "kind": "ScalePlan",
        "metadata": {
            "name": f"{job_name}-scaleplan-{int(time.time())}",
            "labels": {"elasticjob-name": job_name},
        },
        "spec": {
            "ownerJob": job_name,
            "replicaResourceSpecs": node_group_resources,
            "createPods": create_pods or [],
            "removePods": remove_pods or [],
            "psHosts": ps_hosts or [],
        },
    }
