"""Platform-independent job description.

Role parity: ``dlrover/python/scheduler/job.py`` (``JobArgs``, ``NodeArgs``,
``ResourceLimits``) — the master's view of what the user asked for, filled in
from CLI args (local platform) or an ElasticJob custom resource (k8s).

TPU-first: a node group carries slice topology (``node_unit`` = hosts per
slice) so rendezvous and scaling keep worlds whole-slice.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from dlrover_tpu.common.constants import (
    DistributionStrategy,
    NodeType,
    PlatformType,
)
from dlrover_tpu.common.node import NodeGroupResource, NodeResource


@dataclass
class ResourceLimits:
    """Upper bounds the auto-scaler must respect (reference: ResourceLimits)."""

    cpu: float = 0.0
    memory: int = 0
    chips: int = 0


@dataclass
class NodeArgs:
    """Per-node-type request (reference: NodeArgs)."""

    group_resource: NodeGroupResource = field(default_factory=NodeGroupResource)
    auto_scale: bool = True
    restart_count: int = 3
    critical_nodes: str = ""


@dataclass
class JobArgs:
    """Everything the master needs to know about one job.

    ``initialize()`` on subclasses fills this from the platform source of
    truth (CLI flags / ElasticJob CR).
    """

    platform: str = PlatformType.LOCAL
    namespace: str = "default"
    job_name: str = "job"
    job_uuid: str = ""
    user: str = ""
    distribution_strategy: str = DistributionStrategy.SPMD
    optimize_mode: str = "single-job"
    node_args: Dict[str, NodeArgs] = field(default_factory=dict)
    resource_limits: ResourceLimits = field(default_factory=ResourceLimits)
    enable_dynamic_sharding: bool = True
    enable_elastic_scheduling: bool = True
    relaunch_always: bool = False
    remove_exited_node: bool = True
    cordon_fault_node: bool = True
    # TPU: how many hosts form one slice — worlds must be multiples of this.
    node_unit: int = 1

    def worker_args(self) -> Optional[NodeArgs]:
        return self.node_args.get(NodeType.WORKER)


def local_job_args(
    job_name: str = "local",
    node_num: int = 1,
    node_unit: int = 1,
    distribution_strategy: str = DistributionStrategy.SPMD,
) -> JobArgs:
    """JobArgs for the local/standalone platform (reference: LocalJobArgs)."""
    args = JobArgs(
        platform=PlatformType.LOCAL,
        job_name=job_name,
        distribution_strategy=distribution_strategy,
        node_unit=node_unit,
    )
    args.node_args[NodeType.WORKER] = NodeArgs(
        group_resource=NodeGroupResource(
            count=node_num, node_resource=NodeResource(cpu=1, memory=1024)
        ),
        restart_count=3,
    )
    return args
