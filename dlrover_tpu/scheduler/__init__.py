"""Platform abstraction: job description + node scheduling backends.

Role parity: ``dlrover/python/scheduler/`` in the reference — a
platform-independent ``JobArgs`` description plus per-platform clients
(local subprocesses for development/tests, Kubernetes for production TPU
node pools).
"""
