"""Ray platform client and job description.

Role parity: ``dlrover/python/scheduler/ray.py:51-209`` (``RayClient``
singleton, ``RayElasticJob``, ``RayJobArgs``). Like the k8s client, the
``ray`` package is an optional deferred import behind a thin injectable
seam — the scaler/watcher logic is tested against a fake, and the master
runs without a Ray cluster present.

Actor naming convention (shared with the watcher): ``{type}-{id}``, the
same scheme ``common.node.Node`` uses, so names round-trip.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from dlrover_tpu.common.constants import (
    DistributionStrategy,
    NodeType,
    PlatformType,
)
from dlrover_tpu.common.log import get_logger
from dlrover_tpu.common.node import NodeGroupResource, NodeResource
from dlrover_tpu.scheduler.job import JobArgs, NodeArgs

logger = get_logger("scheduler.ray")


@dataclass
class ActorArgs:
    """What it takes to start one worker actor (reference: ActorArgs)."""

    actor_name: str
    executor: str = ""  # module:callable the actor runs
    num_cpus: float = 1.0
    memory_mb: int = 1024
    resources: Dict[str, float] = field(default_factory=dict)  # e.g. {"TPU": 4}
    env: Dict[str, str] = field(default_factory=dict)
    args: List[Any] = field(default_factory=list)
    kwargs: Dict[str, Any] = field(default_factory=dict)


class RayWorker:
    """The actor class every training node runs as (reference
    ``scheduler/ray.py:40`` ``RayWorker`` — exec_module + health probe).
    Instantiated remotely by ``RayClient.create_actor``; the env dict
    carries the master address / rank contract (``NodeEnv``)."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        import os

        for key, value in (env or {}).items():
            os.environ[key] = str(value)

    def ping(self) -> str:
        return "pong"

    def run_module(self, module: str, args: Optional[List[str]] = None) -> int:
        """Run ``python -m module args...`` in-process (the agent
        entrypoint)."""
        import runpy
        import sys

        argv = [module] + list(args or [])
        old = sys.argv
        sys.argv = argv
        try:
            runpy.run_module(module, run_name="__main__")
            return 0
        except SystemExit as e:
            if e.code is None:
                return 0
            # sys.exit("message") means failure with the message printed
            return e.code if isinstance(e.code, int) else 1
        finally:
            sys.argv = old

    def exec_func(self, target: str, *args, **kwargs):
        """Run ``module:callable`` and return its result."""
        import importlib

        module_name, _, attr = target.partition(":")
        fn = getattr(importlib.import_module(module_name), attr)
        return fn(*args, **kwargs)


def parse_type_id_from_actor_name(name: str):
    """"worker-3" -> ("worker", 3) (reference ray_watcher.py:63)."""
    node_type, _, node_id = name.rpartition("-")
    try:
        return node_type, int(node_id)
    except ValueError:
        return name, 0


class RayClient:
    """Deferred-import wrapper over the ray actor API (reference
    ``RayClient.singleton_instance``).

    Actors of one job are scoped by name prefix (``{job}__``): the state
    API lists actors cluster-wide, so scaler/watcher logic would otherwise
    see other jobs' actors. Node names stay prefix-free — the prefix is
    added on create and stripped on list.
    """

    _instance: Optional["RayClient"] = None
    _lock = threading.Lock()

    def __init__(self, namespace: str = "dlrover-tpu", job_name: str = ""):
        import ray  # deferred: optional dependency

        self._ray = ray
        self.namespace = namespace
        self._prefix = f"{job_name}__" if job_name else ""
        if not ray.is_initialized():
            ray.init(namespace=namespace, ignore_reinit_error=True)

    @classmethod
    def singleton_instance(
        cls, namespace: str = "dlrover-tpu", job_name: str = ""
    ) -> "RayClient":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(namespace, job_name)
            return cls._instance

    def create_actor(self, actor_args: ActorArgs):
        import importlib

        module_name, _, attr = actor_args.executor.partition(":")
        executor = getattr(importlib.import_module(module_name), attr)
        if not isinstance(executor, type):
            raise TypeError(
                f"executor {actor_args.executor!r} must resolve to a class "
                "(ray actors are classes; see scheduler.ray.RayWorker)"
            )
        remote_cls = self._ray.remote(executor)
        kwargs = dict(actor_args.kwargs)
        if actor_args.env and "env" not in kwargs:
            kwargs["env"] = actor_args.env
        return remote_cls.options(
            num_cpus=actor_args.num_cpus,
            memory=actor_args.memory_mb * 1024 * 1024,
            resources=actor_args.resources or None,
            name=self._prefix + actor_args.actor_name,
            lifetime="detached",
        ).remote(*actor_args.args, **kwargs)

    def delete_actor(self, actor_name: str) -> bool:
        try:
            handle = self._ray.get_actor(
                self._prefix + actor_name, namespace=self.namespace
            )
        except ValueError:
            return False
        self._ray.kill(handle)
        return True

    def list_actors(self) -> Dict[str, str]:
        """{actor_name: state} for this job (prefix-filtered; the state
        API itself is cluster-wide)."""
        from ray.util.state import list_actors

        out = {}
        for actor in list_actors():
            name = getattr(actor, "name", "") or actor.get("name", "")
            state = getattr(actor, "state", "") or actor.get("state", "")
            if not name or not name.startswith(self._prefix):
                continue
            out[name[len(self._prefix):]] = state
        return out

    def get_actor_status(self, actor_name: str) -> str:
        return self.list_actors().get(actor_name, "DEAD")

    def remote_call_actor(self, actor_name: str, func: str,
                          args=(), kwargs=None, timeout: float = 30.0):
        handle = self._ray.get_actor(
            self._prefix + actor_name, namespace=self.namespace
        )
        ref = getattr(handle, func).remote(*args, **(kwargs or {}))
        return self._ray.get(ref, timeout=timeout)

    def check_health(self, actor_name: str) -> bool:
        try:
            return self.remote_call_actor(actor_name, "ping", timeout=5.0) is not None
        except Exception:  # noqa: BLE001
            return False


def ray_job_args(
    conf: Dict[str, Any],
    job_name: str = "ray-job",
    namespace: str = "dlrover-tpu",
) -> JobArgs:
    """Build JobArgs from a Ray job conf dict (reference: ``RayJobArgs.
    initilize`` reading the python conf module). Expected shape::

        {"worker": {"count": 4, "cpu": 8, "memory": 16384, "chips": 4},
         "ps": {...},  # optional
         "distribution_strategy": "spmd" | "ps", "node_unit": 1}
    """
    args = JobArgs(
        platform=PlatformType.RAY,
        namespace=namespace,
        job_name=job_name,
        distribution_strategy=conf.get(
            "distribution_strategy", DistributionStrategy.SPMD
        ),
        node_unit=int(conf.get("node_unit", 1)),
    )
    for node_type in (NodeType.WORKER, NodeType.PS, NodeType.CHIEF,
                      NodeType.EVALUATOR):
        spec = conf.get(node_type)
        if not spec:
            continue
        resource = NodeResource(
            cpu=float(spec.get("cpu", 1)),
            memory=int(spec.get("memory", 1024)),
        )
        resource.accelerator.chips = int(spec.get("chips", 0))
        args.node_args[node_type] = NodeArgs(
            group_resource=NodeGroupResource(
                count=int(spec.get("count", 0)), node_resource=resource
            ),
            restart_count=int(spec.get("restart_count", 3)),
        )
    return args
