"""Local platform backend: nodes are subprocesses on this host.

Role parity: the reference's ``--platform local`` path plus the process
machinery its tests mock out. Here it is a real, working backend: the
``LocalProcessBackend`` keeps the scaler (creates processes) and the watcher
(polls them into ``NodeEvent``s) coherent, which is also how multi-node
behavior is exercised single-machine in tests — N agent processes against a
real master, per SURVEY §4.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from dlrover_tpu.common.constants import NodeExitReason, NodeStatus
from dlrover_tpu.common.log import get_logger

logger = get_logger("scheduler.local")


@dataclass
class LocalProcess:
    """One scheduled 'node' backed by a subprocess."""

    name: str
    node_type: str
    node_id: int
    rank_index: int
    popen: Optional[subprocess.Popen] = None
    create_time: float = field(default_factory=time.time)
    # Filled by the watcher when the process exits.
    exit_reason: str = ""

    def status(self) -> str:
        if self.popen is None:
            return NodeStatus.PENDING
        rc = self.popen.poll()
        if rc is None:
            return NodeStatus.RUNNING
        return NodeStatus.SUCCEEDED if rc == 0 else NodeStatus.FAILED

    def exit_code(self) -> Optional[int]:
        return None if self.popen is None else self.popen.poll()


class LocalProcessBackend:
    """Process table shared by LocalProcessScaler and LocalProcessWatcher."""

    def __init__(self):
        self._lock = threading.Lock()
        self._procs: Dict[str, LocalProcess] = {}

    def start_process(
        self,
        name: str,
        node_type: str,
        node_id: int,
        rank_index: int,
        command: List[str],
        env: Optional[Dict[str, str]] = None,
    ) -> LocalProcess:
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        popen = subprocess.Popen(
            command, env=full_env, start_new_session=True,
            stdout=sys.stdout if sys.stdout.isatty() else subprocess.DEVNULL,
            stderr=subprocess.STDOUT if sys.stdout.isatty() else subprocess.DEVNULL,
        )
        proc = LocalProcess(
            name=name, node_type=node_type, node_id=node_id,
            rank_index=rank_index, popen=popen,
        )
        with self._lock:
            self._procs[name] = proc
        logger.info("started %s pid=%d: %s", name, popen.pid, " ".join(command))
        return proc

    def kill_process(self, name: str, grace_secs: float = 3.0) -> bool:
        with self._lock:
            proc = self._procs.get(name)
        if proc is None or proc.popen is None:
            return False
        if proc.popen.poll() is None:
            try:
                os.killpg(proc.popen.pid, signal.SIGTERM)
                try:
                    proc.popen.wait(timeout=grace_secs)
                except subprocess.TimeoutExpired:
                    os.killpg(proc.popen.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        proc.exit_reason = NodeExitReason.KILLED
        return True

    def remove(self, name: str):
        with self._lock:
            self._procs.pop(name, None)

    def list_processes(self) -> List[LocalProcess]:
        with self._lock:
            return list(self._procs.values())

    def get(self, name: str) -> Optional[LocalProcess]:
        with self._lock:
            return self._procs.get(name)

    def stop_all(self):
        for proc in self.list_processes():
            self.kill_process(proc.name)
