"""Checkpoint subsystem + ElasticTrainer + elastic data input.

The headline behavior under test is the reference's hardest trick made
native: save at one world size, restore at another
(``fsdp_save_util.py``'s reshard-on-load), via GSPMD + Orbax.
"""

import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.checkpoint import (
    CheckpointInterval,
    ElasticCheckpointManager,
    abstract_like,
)
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.data import (
    ElasticDataLoader,
    ElasticDistributedSampler,
)
from dlrover_tpu.trainer.elastic import ElasticTrainer


def _mlp_init(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "w1": jax.random.normal(k1, (16, 32)) * 0.1,
        "w2": jax.random.normal(k2, (32, 8)) * 0.1,
    }


def _mlp_loss(params, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"])
    logits = h @ params["w2"]
    loss = jnp.mean((logits - batch["y"]) ** 2)
    return loss, {}


def _batch(n=32, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.normal(size=(n, 16)).astype(np.float32),
        "y": rng.normal(size=(n, 8)).astype(np.float32),
    }


def _build(strategy, devices=None):
    return accelerate(
        _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
        strategy=strategy, devices=devices,
    )


class TestCheckpointInterval:
    def test_step_cadence(self):
        iv = CheckpointInterval(steps=10)
        assert not iv.should_save(5)
        assert iv.should_save(10)
        iv.mark_saved(10)
        assert not iv.should_save(15)
        assert iv.should_save(20)


class TestElasticCheckpoint:
    def test_save_restore_roundtrip(self, tmp_path):
        res = _build(Strategy(mesh=MeshPlan(data=-1)))
        state = res.init_fn(jax.random.PRNGKey(0))
        mgr = ElasticCheckpointManager(str(tmp_path), async_save=False)
        assert mgr.save(0, state, metadata={"k": 1}, force=True)
        mgr.wait()

        target = abstract_like(state, res.state_sharding)
        out = mgr.restore(target)
        assert out is not None
        assert out["meta"]["k"] == 1
        np.testing.assert_allclose(
            np.asarray(out["state"].params["w1"]),
            np.asarray(state.params["w1"]),
        )
        mgr.close()

    def test_host_dram_staging_mirror_and_restore(self, tmp_path):
        """Flash-checkpoint parity: after the async save commits, the
        step is mirrored to the staging dir, and restore prefers it even
        when the primary directory is gone (the remote-storage-outage /
        fast-restart case)."""
        import os
        import shutil

        res = _build(Strategy(mesh=MeshPlan(data=-1)))
        state = res.init_fn(jax.random.PRNGKey(0))
        primary = tmp_path / "primary"
        staging = tmp_path / "shm_staging"
        mgr = ElasticCheckpointManager(
            str(primary), staging_dir=str(staging)
        )
        assert mgr.save(3, state, metadata={"k": 7}, force=True)
        mgr.wait()
        assert mgr.staged_step() == 3
        # only the newest step is kept staged
        state2, _ = res.train_step(
            state, res.shard_batch(_batch()), jax.random.PRNGKey(1)
        )
        assert mgr.save(5, state2, force=True)
        mgr.wait()
        assert mgr.staged_step() == 5
        assert not os.path.isdir(str(staging / "3"))

        # nuke the primary step dir: restore must come from staging
        shutil.rmtree(str(primary / "5"))
        target = abstract_like(state, res.state_sharding)
        out = mgr.restore(target, step=5)
        assert out is not None and out["step"] == 5
        np.testing.assert_allclose(
            np.asarray(out["state"].params["w1"]),
            np.asarray(state2.params["w1"]),
        )
        mgr.close()

    def test_stale_staging_from_previous_job_is_ignored(self, tmp_path):
        """A mirror left in tmpfs by a PREVIOUS job at the same
        checkpoint path must never be restored as the new job's weights:
        the staged digest is validated against the primary step dir."""
        import shutil

        res = _build(Strategy(mesh=MeshPlan(data=-1)))
        primary = tmp_path / "primary"
        staging = tmp_path / "shm_staging"

        old_state = res.init_fn(jax.random.PRNGKey(0))
        m1 = ElasticCheckpointManager(str(primary),
                                      staging_dir=str(staging))
        assert m1.save(5, old_state, force=True)
        m1.wait()
        assert m1.staged_step() == 5
        m1.close()

        # operator wipes the checkpoint dir and starts a fresh run at
        # the same path; the stale tmpfs mirror survives the restart
        shutil.rmtree(str(primary))
        new_state = res.init_fn(jax.random.PRNGKey(42))
        m2 = ElasticCheckpointManager(str(primary),
                                      staging_dir=str(staging))
        assert m2.save(5, new_state, force=True)
        m2.wait()

        out = m2.restore(
            abstract_like(new_state, res.state_sharding), step=5
        )
        np.testing.assert_allclose(
            np.asarray(out["state"].params["w1"]),
            np.asarray(new_state.params["w1"]),
        )
        assert not np.allclose(
            np.asarray(out["state"].params["w1"]),
            np.asarray(old_state.params["w1"]),
        )
        m2.close()

    def test_fresh_job_with_only_stale_staging_restores_nothing(
        self, tmp_path
    ):
        """A fresh job whose empty primary coexists with a stale staging
        mirror must get 'no checkpoint' (None), not a crash and not the
        old job's weights."""
        import shutil

        res = _build(Strategy(mesh=MeshPlan(data=-1)))
        primary = tmp_path / "primary"
        staging = tmp_path / "shm_staging"
        state = res.init_fn(jax.random.PRNGKey(0))
        m1 = ElasticCheckpointManager(str(primary),
                                      staging_dir=str(staging))
        assert m1.save(7, state, force=True)
        m1.wait()
        m1.close()

        # fresh job: wiped primary, stale mirror survives in tmpfs
        shutil.rmtree(str(primary))
        m2 = ElasticCheckpointManager(str(primary),
                                      staging_dir=str(staging))
        target = abstract_like(state, res.state_sharding)
        assert m2.restore(target) is None  # from scratch, no crash
        m2.close()

    def test_reshard_on_load_across_world_sizes(self, tmp_path):
        """Save on an 8-device fsdp mesh, restore onto a 4-device mesh."""
        res8 = _build(Strategy(mesh=MeshPlan(data=2, fsdp=4)))
        state = res8.init_fn(jax.random.PRNGKey(0))
        state, _ = res8.train_step(
            state, res8.shard_batch(_batch()), jax.random.PRNGKey(1)
        )
        mgr = ElasticCheckpointManager(str(tmp_path), async_save=False)
        mgr.save(int(state.step), state, force=True)
        mgr.wait()

        devices4 = jax.devices()[:4]
        res4 = _build(
            Strategy(mesh=MeshPlan(data=2, fsdp=2)),
            devices=devices4,
        )
        abstract = jax.eval_shape(res4.init_fn, jax.random.PRNGKey(0))
        target = abstract_like(abstract, res4.state_sharding)
        out = mgr.restore(target)
        assert out is not None
        restored = out["state"]
        # Values identical to the 8-device state, now on the 4-device mesh.
        np.testing.assert_allclose(
            np.asarray(restored.params["w1"]),
            np.asarray(state.params["w1"]),
            rtol=1e-6,
        )
        assert restored.params["w1"].sharding.mesh.devices.size == 4
        # And the restored state trains.
        restored, metrics = res4.train_step(
            restored, res4.shard_batch(_batch()), jax.random.PRNGKey(2)
        )
        assert np.isfinite(float(metrics["loss"]))
        mgr.close()

    def test_shard_checkpoint_rides_along(self, tmp_path):
        res = _build(Strategy(mesh=MeshPlan(data=-1)))
        state = res.init_fn(jax.random.PRNGKey(0))
        mgr = ElasticCheckpointManager(str(tmp_path), async_save=False)
        mgr.save(0, state, shard_checkpoint='{"todo": [[0, 64]]}', force=True)
        mgr.wait()
        out = mgr.restore(abstract_like(state, res.state_sharding))
        assert out["shard_checkpoint"] == '{"todo": [[0, 64]]}'
        mgr.close()

    def test_wait_surfaces_mirror_timeout(self, tmp_path):
        """A staging mirror that never commits must not be silently
        forgotten: wait() returns timed_out=True, logs the
        CKPT_MIRROR_TIMEOUT error code, and keeps the thread joinable
        for a later wait (ISSUE 3 satellite — the preemption drain
        needs to TELL that the mirror never committed)."""
        import threading

        mgr = ElasticCheckpointManager(str(tmp_path), async_save=False)
        release = threading.Event()
        stuck = threading.Thread(target=release.wait, daemon=True,
                                 name="stuck-mirror")
        stuck.start()
        mgr._mirror_threads = [stuck]
        assert mgr.wait(mirror_timeout=0.05) is True
        assert mgr._mirror_threads == [stuck]  # observable, not dropped
        # an already-flagged thread is only POLLED: back-to-back waits
        # (the preemption drain) must not re-pay the join timeout
        t0 = time.monotonic()
        assert mgr.wait(mirror_timeout=60.0) is True
        assert time.monotonic() - t0 < 5.0
        release.set()
        stuck.join(timeout=5.0)
        assert mgr.wait(mirror_timeout=5.0) is False
        assert mgr._mirror_threads == []
        mgr.close()

    def test_superseded_step_mirror_stops_polling(self, tmp_path):
        """max_to_keep can delete a step dir before its mirror thread
        ever sees it; the poll must bail when a NEWER step committed
        instead of spinning to the 600 s deadline (and stalling wait()
        for the full join timeout on every exit path)."""
        import time as _time

        mgr = ElasticCheckpointManager(
            str(tmp_path / "ckpt"), async_save=False,
            staging_dir=str(tmp_path / "shm"),
        )
        # a newer committed step exists; step 1 never will
        (tmp_path / "ckpt" / "5").mkdir()
        t0 = _time.monotonic()
        mgr._wait_and_mirror(1, deadline_s=30.0)
        assert _time.monotonic() - t0 < 5.0
        assert mgr.staged_step() != 1
        mgr.close()


class TestElasticTrainer:
    def test_train_and_resume(self, tmp_path):
        trainer = ElasticTrainer(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            strategy=Strategy(mesh=MeshPlan(data=-1)),
            ckpt_dir=str(tmp_path),
        )
        state = trainer.prepare()
        losses = []
        batch = _batch()
        for _ in range(5):
            state, metrics = trainer.step(state, batch)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]
        trainer.save(state)
        trainer.finalize()

        # A fresh trainer resumes from the checkpoint.
        trainer2 = ElasticTrainer(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            strategy=Strategy(mesh=MeshPlan(data=-1)),
            ckpt_dir=str(tmp_path),
        )
        state2 = trainer2.prepare()
        assert int(state2.step) == 5
        np.testing.assert_allclose(
            np.asarray(state2.params["w1"]), np.asarray(state.params["w1"])
        )
        trainer2.finalize()

    def test_on_world_change_reshards_state(self):
        trainer = ElasticTrainer(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=4)),
        )
        state = trainer.prepare()
        state, _ = trainer.step(state, _batch())
        w1_before = np.asarray(state.params["w1"])
        state = trainer.on_world_change(state)
        np.testing.assert_allclose(
            np.asarray(state.params["w1"]), w1_before
        )
        state, metrics = trainer.step(state, _batch(seed=3))
        assert np.isfinite(float(metrics["loss"]))


class TestElasticSampler:
    def test_partition_covers_all_indices(self):
        samplers = [
            ElasticDistributedSampler(100, num_shards=4, shard_rank=r,
                                      shuffle=False, drop_last=True)
            for r in range(4)
        ]
        seen = sorted(i for s in samplers for i in s)
        assert seen == list(range(100))

    def test_resume_skips_consumed(self):
        s = ElasticDistributedSampler(100, num_shards=2, shard_rank=0,
                                      shuffle=False)
        s.record_batch(40)
        remaining = list(s)
        assert min(remaining) >= 40
        assert len(remaining) == 30

    def test_reshard_after_world_change(self):
        s = ElasticDistributedSampler(96, num_shards=4, shard_rank=0,
                                      shuffle=False, drop_last=True)
        s.record_batch(32)
        s.reshard(num_shards=2, shard_rank=0)
        part0 = list(s)
        s.reshard(num_shards=2, shard_rank=1)
        part1 = list(s)
        assert sorted(part0 + part1) == list(range(32, 96))

    def test_pad_larger_than_remainder(self):
        # 1 remaining index, 4 shards: every shard must still yield one
        # sample (tiled padding) or SPMD hosts desync at the epoch tail.
        counts = []
        for r in range(4):
            s = ElasticDistributedSampler(97, num_shards=4, shard_rank=r,
                                          shuffle=False)
            s.record_batch(96)
            counts.append(len(list(s)))
        assert counts == [1, 1, 1, 1]

    def test_state_dict_roundtrip(self):
        s = ElasticDistributedSampler(50, shuffle=True, seed=7)
        s.set_epoch(2)
        s.record_batch(10)
        s2 = ElasticDistributedSampler(50, shuffle=True, seed=7)
        s2.load_state_dict(s.state_dict())
        assert list(s2) == list(s)


class TestElasticDataLoader:
    def test_batches_and_runtime_resize(self):
        data = [{"x": np.full((4,), i, np.float32)} for i in range(32)]
        loader = ElasticDataLoader(data, batch_size=8)
        batches = list(loader)
        assert len(batches) == 4
        assert batches[0]["x"].shape == (8, 4)
        loader.set_batch_size(16)
        assert len(list(loader)) == 2
