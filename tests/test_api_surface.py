"""The public API index (docs/api.md) stays truthful: every documented
entry point imports and exists. Catches silent breakage of the surface
users program against — and doc drift when something is renamed."""

import importlib

import pytest

SURFACE = {
    "dlrover_tpu.parallel.accelerate": ["accelerate"],
    "dlrover_tpu.parallel.strategy": ["Strategy", "RULE_SETS"],
    "dlrover_tpu.parallel.mesh": ["MeshPlan"],
    "dlrover_tpu.parallel.planner": ["plan_mesh", "estimate",
                                     "plan_stages", "plan_stage_depths",
                                     "ModelSpec", "estimate_decode",
                                     "serve_cache_bytes"],
    "dlrover_tpu.parallel.aot": ["aot_compile_train_step"],
    "dlrover_tpu.parallel.auto_tune": ["dryrun", "search_strategy"],
    "dlrover_tpu.trainer.run": ["main"],
    "dlrover_tpu.trainer.elastic": ["ElasticTrainer"],
    "dlrover_tpu.trainer.executor": ["TrainExecutor"],
    "dlrover_tpu.trainer.conf": ["build_configuration"],
    "dlrover_tpu.trainer.data": ["ElasticDataLoader",
                                 "ElasticDistributedSampler",
                                 "DevicePreloader"],
    "dlrover_tpu.trainer.text_reader": ["LineIndexedFile",
                                        "ByteTokenizer",
                                        "ShardedTextBatches",
                                        "HFTokenizerAdapter"],
    "dlrover_tpu.checkpoint.manager": ["ElasticCheckpointManager",
                                       "abstract_like"],
    "dlrover_tpu.agent.master_client": ["MasterClient"],
    "dlrover_tpu.agent.sharding_client": ["ShardingClient",
                                          "IndexShardingClient"],
    "dlrover_tpu.agent.training_agent": ["ElasticTrainingAgent",
                                         "AgentConfig"],
    "dlrover_tpu.master.local_master": ["start_local_master"],
    "dlrover_tpu.serving.kv_cache": ["KVCacheSpec", "init_kv_cache",
                                     "kv_cache_rules",
                                     "resolve_kv_precision"],
    "dlrover_tpu.serving.engine": ["ServeEngine", "ServeExecutor"],
    "dlrover_tpu.serving.router": ["RequestRouter"],
    "dlrover_tpu.serving.cli": ["main"],
    "dlrover_tpu.master.main": ["main"],
    "dlrover_tpu.ops.flash_attention": [
        "flash_attention", "flash_attention_auto",
        "flash_attention_segmented", "flash_attention_segmented_auto",
        "flash_attention_prefix", "flash_attention_prefix_auto",
        "flash_attention_prefix_lse",
        "segmented_attention", "flash_attention_lse",
    ],
    "dlrover_tpu.ops.ring_attention": ["ring_attention",
                                       "ring_attention_local",
                                       "impl_from_flags"],
    "dlrover_tpu.ops.moe": ["moe_ffn"],
    "dlrover_tpu.optimizers.wsam": ["wsam"],
    "dlrover_tpu.ps.server": ["start_ps_shard", "PsShardServer"],
    "dlrover_tpu.ps.client": ["PsClusterClient", "partition_params"],
    "dlrover_tpu.ps.trainer": ["AsyncPsTrainer"],
    "dlrover_tpu.ps.repartition": ["repartition_checkpoint", "main"],
    "dlrover_tpu.diagnosis.hang_detector": ["HangingDetector",
                                            "touch_heartbeat",
                                            "announce_long_phase"],
    "dlrover_tpu.diagnosis.fault_injection": ["kill_workers",
                                              "make_flaky",
                                              "corrupt_checkpoint"],
    "dlrover_tpu.models.llama": ["init", "apply", "apply_pipelined",
                                 "llama2_7b", "llama3_8b",
                                 "llama3_70b", "segment_positions"],
    "dlrover_tpu.models.gpt_neox": ["init", "apply", "neox_tiny"],
    "dlrover_tpu.models.glm": ["init", "apply", "glm_tiny"],
    "dlrover_tpu.models.bert": ["init", "apply"],
    "dlrover_tpu.models.clip": ["init"],
    "dlrover_tpu.models.deepfm": ["init", "apply"],
    "dlrover_tpu.utils.prof": ["analyze_cost", "DryRunner", "AProfiler"],
    "dlrover_tpu.brain.client": ["BrainClient"],
    "dlrover_tpu.brain.watcher": ["ClusterWatcher", "K8sClusterSource"],
    "dlrover_tpu.telemetry": ["get_registry", "emit_event",
                              "read_events", "span",
                              "export_chrome_trace", "mttr_report",
                              "EventKind", "SpanName", "names"],
    "dlrover_tpu.telemetry.exporter": ["MetricsExporter",
                                       "maybe_start_exporter"],
    "dlrover_tpu.telemetry.cli": ["main"],
}


@pytest.mark.parametrize("module_name", sorted(SURFACE))
def test_documented_surface_exists(module_name):
    module = importlib.import_module(module_name)
    missing = [n for n in SURFACE[module_name] if not hasattr(module, n)]
    assert not missing, f"{module_name} lost documented symbols: {missing}"
