"""Ring attention WITH the flash kernel, end-to-end through training.

The production long-context path is ring attention over the "seq" mesh
axis where every ring step runs the in-tree Pallas flash kernel
(``ops/ring_attention.py`` — on TPU, ``impl="pallas"``). Op-level tests
cover the kernel inside the ring; this file closes the remaining seam
(round-3 verdict #4): the FULL training step — llama forward, loss,
grads through the kernel's custom VJP, optimizer update — jitted over a
(data x seq x tensor) mesh with ``use_flash=True``, executed off-TPU via
``flash_interpret=True``, and matched against the blockwise-XLA ring
(``use_flash=False``), the reference implementation.

Reference counterpart: ``atorch/atorch/modules/distributed_transformer/
distributed_attention.py:21-130`` composed with its FlashAttention
adapters (``modules/transformer/layers.py``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy


def _step(cfg, plan, batch):
    """One full train step; returns (loss, updated params tree)."""
    result = accelerate(
        llama.make_init_fn(cfg),
        llama.make_loss_fn(cfg),
        optax.adamw(1e-2),
        batch,
        strategy=Strategy(mesh=plan, rule_set="llama",
                          remat_policy="none"),
    )
    state = result.init_fn(jax.random.PRNGKey(0))
    sharded = result.shard_batch(batch)
    state, metrics = result.train_step(state, sharded,
                                       jax.random.PRNGKey(1))
    loss = float(jax.device_get(metrics["loss"]))
    params = jax.device_get(
        jax.tree.map(np.asarray, state.params if hasattr(state, "params")
                     else state["params"])
    )
    return loss, params


def _configs(plan, **overrides):
    mesh = plan.build()
    common = dict(
        remat_policy="none", seq_axis="seq", mesh=mesh,
        flash_block_q=32, flash_block_k=32, **overrides,
    )
    flash = llama.llama_tiny(use_flash=True, flash_interpret=True,
                             **common)
    xla = llama.llama_tiny(use_flash=False, **common)
    return flash, xla


def _batch(vocab, rows=4, seq=128, packed=False):
    rng = np.random.RandomState(0)
    ids = rng.randint(0, vocab, size=(rows, seq + 1))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }
    if packed:
        seg = np.sort(rng.randint(0, 3, size=(rows, seq)), axis=1)
        same_next = np.concatenate(
            [seg[:, :-1] == seg[:, 1:], np.zeros((rows, 1), bool)],
            axis=1,
        )
        batch["labels"] = jnp.asarray(
            np.where(same_next, ids[:, 1:], -100))
        batch["segment_ids"] = jnp.asarray(seg.astype(np.int32))
    return batch


@pytest.mark.slow
def test_flash_ring_training_step_matches_xla_ring():
    """dp=2 x sp=2 x tp=2: the flash-kernel ring (interpreted Pallas,
    the TPU production path's exact code route) produces the same loss
    and the same post-step weights as the blockwise-XLA ring."""
    plan = MeshPlan(data=2, seq=2, tensor=2)
    cfg_flash, cfg_xla = _configs(plan)
    batch = _batch(cfg_flash.vocab_size)

    loss_flash, p_flash = _step(cfg_flash, plan, batch)
    loss_xla, p_xla = _step(cfg_xla, plan, batch)

    assert np.isfinite(loss_flash)
    # abs=2e-2: the two rings are different fusion/reduction orders of
    # the same math, and on this box's CPU backend the divergence on a
    # ~5.9 loss lands around 1e-2 (a documented numerics flake, rel
    # ~2e-3 — not a drift regression, which shows up orders of
    # magnitude larger); params keep the tight bound
    assert loss_flash == pytest.approx(loss_xla, abs=2e-2)
    flat_f = jax.tree.leaves(p_flash)
    flat_x = jax.tree.leaves(p_xla)
    assert len(flat_f) == len(flat_x) and flat_f
    for a, b in zip(flat_f, flat_x):
        np.testing.assert_allclose(a, b, atol=2e-3, rtol=2e-3)


@pytest.mark.slow
def test_ambient_mesh_ring_survives_world_change():
    """A ring config carrying only seq_axis (no frozen mesh) trains at
    one world size and keeps training after re-accelerate over a
    DIFFERENT device count — the elastic contract a mesh baked into
    the config at startup would break (stale shard_map mesh holding
    departed devices)."""
    cfg = llama.llama_tiny(remat_policy="none", seq_axis="seq")
    batch = _batch(cfg.vocab_size, rows=4, seq=128)

    def one_step(plan, devices):
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.adamw(1e-2), batch,
            strategy=Strategy(mesh=plan, rule_set="llama",
                              remat_policy="none"),
            devices=devices,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        state, m = result.train_step(
            state, result.shard_batch(batch), jax.random.PRNGKey(1))
        return float(jax.device_get(m["loss"]))

    loss8 = one_step(MeshPlan(data=2, fsdp=2, seq=2),
                     jax.devices()[:8])
    # the injected "world change": same config, half the devices
    loss4 = one_step(MeshPlan(data=2, fsdp=1, seq=2),
                     jax.devices()[:4])
    assert np.isfinite(loss8) and np.isfinite(loss4)
    # same math at both world sizes (same global batch and seed) UP TO
    # the reduction-order change the resharded mesh implies: fsdp 2->1
    # re-associates the gather/matmul sums, which on this box lands
    # around 1e-2 on a ~6.0 loss (documented numerics flake; a real
    # survives-world-change regression is NaN/garbage, not 0.2% drift)
    assert loss8 == pytest.approx(loss4, abs=2e-2)


@pytest.mark.slow
def test_flash_ring_packed_training_step_matches_xla_ring():
    """Packed documents spanning ring shards: every ring step runs the
    segmented PAIR flash kernel; the full train step matches the XLA
    ring with the same segment masking."""
    plan = MeshPlan(data=2, seq=2, tensor=2)
    cfg_flash, cfg_xla = _configs(plan)
    batch = _batch(cfg_flash.vocab_size, packed=True)

    loss_flash, p_flash = _step(cfg_flash, plan, batch)
    loss_xla, p_xla = _step(cfg_xla, plan, batch)

    assert np.isfinite(loss_flash)
    assert loss_flash == pytest.approx(loss_xla, abs=1e-4)
    for a, b in zip(jax.tree.leaves(p_flash), jax.tree.leaves(p_xla)):
        np.testing.assert_allclose(a, b, atol=2e-4, rtol=2e-4)
