"""Parallelism library tests on the 8-device virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan, candidate_plans
from dlrover_tpu.parallel.sharding_rules import (
    FSDP_AUTO,
    REPLICATED,
    ShardingRules,
    llama_rules,
)
from dlrover_tpu.parallel.strategy import Strategy
from conftest import mesh_ctx


class TestMeshPlan:
    def test_resolve_infers_axis(self):
        plan = MeshPlan(data=-1, fsdp=2, tensor=2).resolve(8)
        assert plan.data == 2 and plan.fsdp == 2 and plan.tensor == 2

    def test_resolve_rejects_indivisible(self):
        with pytest.raises(ValueError):
            MeshPlan(data=3, tensor=3).resolve(8)

    def test_build_mesh(self):
        mesh = MeshPlan(data=2, fsdp=2, tensor=2).build()
        assert mesh.devices.size == 8
        assert mesh.axis_names == ("pipe", "data", "fsdp", "seq", "tensor")

    def test_adjust_to_world_keeps_model_parallel(self):
        plan = MeshPlan(data=2, fsdp=2, tensor=2)
        smaller = plan.adjust_to_world(4)  # lost half the hosts
        assert smaller.tensor == 2
        assert smaller.dp_degree == 2
        bigger = plan.adjust_to_world(16)
        assert bigger.tensor == 2 and bigger.dp_degree == 8

    def test_candidate_plans_cover_device_count(self):
        plans = candidate_plans(8)
        for p in plans:
            assert p.resolve(8)
        assert any(p.tensor == 8 for p in plans)
        assert any(p.fsdp == 8 for p in plans)


class TestShardingRules:
    AXES = {"data": 2, "fsdp": 2, "tensor": 2}

    def test_explicit_rule(self):
        rules = llama_rules()
        spec = rules.spec_for(
            "model/layers_0/attn/q_proj/kernel", (64, 64), self.AXES
        )
        assert spec == (None, "tensor")

    def test_auto_fsdp_picks_largest_divisible(self):
        rules = ShardingRules()
        assert rules.spec_for("x/kernel", (6, 64), self.AXES) == (None, "fsdp")
        # indivisible dims replicate
        assert rules.spec_for("x/kernel", (3, 7), self.AXES) == (None, None)

    def test_replicated_rule(self):
        rules = llama_rules()
        assert rules.spec_for("model/norm/scale", (64,), self.AXES) == (None,)

    def test_collapsed_axis_replicates(self):
        rules = llama_rules()
        spec = rules.spec_for(
            "a/q_proj/kernel", (64, 64), {"tensor": 1, "fsdp": 2}
        )
        assert spec == (None, None)


def _mlp_init(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "dense1": {"kernel": jax.random.normal(k1, (16, 64)) * 0.1,
                   "bias": jnp.zeros((64,))},
        "dense2": {"kernel": jax.random.normal(k2, (64, 4)) * 0.1,
                   "bias": jnp.zeros((4,))},
    }


def _mlp_loss(params, batch, rng):
    x, y = batch["x"], batch["y"]
    h = jnp.tanh(x @ params["dense1"]["kernel"] + params["dense1"]["bias"])
    logits = h @ params["dense2"]["kernel"] + params["dense2"]["bias"]
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, y).mean()
    return loss, {}


def _batch(n=32, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "x": jnp.asarray(rng.randn(n, 16), jnp.float32),
        "y": jnp.asarray(rng.randint(0, 4, size=(n,))),
    }


class TestAccelerate:
    def _build(self, strategy):
        return accelerate(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            strategy=strategy, rng=jax.random.PRNGKey(0),
        )

    def test_training_decreases_loss_on_3d_mesh(self):
        result = self._build(
            Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2))
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        batch = result.shard_batch(_batch())
        rng = jax.random.PRNGKey(1)
        losses = []
        for _ in range(20):
            state, metrics = result.train_step(state, batch, rng)
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0] * 0.7
        assert int(jax.device_get(state.step)) == 20

    def test_params_actually_sharded(self):
        result = self._build(Strategy(mesh=MeshPlan(data=1, fsdp=8)))
        state = result.init_fn(jax.random.PRNGKey(0))
        kernel = state.params["dense1"]["kernel"]  # (16, 64): 64 % 8 == 0
        # each device holds 1/8 of the kernel
        shard_shape = kernel.addressable_shards[0].data.shape
        assert shard_shape == (16, 8)

    def test_grad_accum_matches_full_batch(self):
        r1 = self._build(Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                                  grad_accum_steps=1))
        r4 = self._build(Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                                  grad_accum_steps=4))
        s1 = r1.init_fn(jax.random.PRNGKey(0))
        s4 = r4.init_fn(jax.random.PRNGKey(0))
        batch = _batch()
        s1, m1 = r1.train_step(s1, r1.shard_batch(batch), jax.random.PRNGKey(1))
        s4, m4 = r4.train_step(s4, r4.shard_batch(batch), jax.random.PRNGKey(1))
        # mean-reduced loss: averaging 4 microbatch grads == full-batch grad
        np.testing.assert_allclose(
            float(m1["loss"]), float(m4["loss"]), rtol=1e-5
        )
        k1 = jax.device_get(s1.params["dense1"]["kernel"])
        k4 = jax.device_get(s4.params["dense1"]["kernel"])
        np.testing.assert_allclose(k1, k4, rtol=1e-4, atol=1e-6)

    def test_eval_step(self):
        result = self._build(Strategy(mesh=MeshPlan(data=4, fsdp=2)))
        state = result.init_fn(jax.random.PRNGKey(0))
        metrics = result.eval_step(state, result.shard_batch(_batch()))
        assert float(metrics["loss"]) > 0


class TestShardedFlashAttention:
    """GSPMD cannot auto-partition a Mosaic custom call: under a
    multi-device mesh the llama forward must route flash through the
    shard_map wrapper (``ops.flash_attention.flash_attention_sharded``)
    and match the unsharded reference exactly."""

    def test_flash_under_mesh_matches_reference_path(self):
        import numpy as np

        from dlrover_tpu.models import llama

        ids = np.random.RandomState(0).randint(0, 256, size=(8, 65))
        batch = {
            "input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:]),
        }
        losses = {}
        for flash in (False, True):
            cfg = llama.llama_tiny(num_layers=2, max_seq_len=64,
                                   use_flash=flash)
            result = accelerate(
                llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
                optax.sgd(1e-2), batch,
                strategy=Strategy(
                    mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                    rule_set="llama",
                ),
            )
            state = result.init_fn(jax.random.PRNGKey(0))
            _, metrics = result.train_step(
                state, result.shard_batch(batch), jax.random.PRNGKey(1)
            )
            losses[flash] = float(jax.device_get(metrics["loss"]))
        assert abs(losses[True] - losses[False]) < 2e-3, losses

    # budget triage (PR 16): segment masking is pinned at the ops level
    # and mesh composition by the unsegmented sharded test; the
    # segmented-under-mesh cross product rides slow
    @pytest.mark.slow
    def test_segmented_flash_under_mesh_matches_reference_path(self):
        """Packed sequences on the production multi-chip path: llama with
        segment_ids + use_flash under a 2x2x2 mesh must route the
        segmented Mosaic kernel through shard_map and match the bias
        (use_flash=False) path."""
        import numpy as np

        from dlrover_tpu.models import llama

        rng = np.random.RandomState(0)
        ids = rng.randint(0, 256, size=(8, 64))
        seg = np.sort(rng.randint(0, 3, size=(8, 64)), axis=1)
        labels = np.where(
            np.concatenate([seg[:, :-1] == seg[:, 1:],
                            np.zeros((8, 1), bool)], axis=1),
            np.concatenate([ids[:, 1:], ids[:, :1]], axis=1), -100)
        batch = {
            "input_ids": jnp.asarray(ids),
            "labels": jnp.asarray(labels),
            "segment_ids": jnp.asarray(seg),
        }
        losses = {}
        for flash in (False, True):
            cfg = llama.llama_tiny(num_layers=2, max_seq_len=64,
                                   use_flash=flash, flash_interpret=True)
            result = accelerate(
                llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
                optax.sgd(1e-2), batch,
                strategy=Strategy(
                    mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                    rule_set="llama",
                ),
            )
            state = result.init_fn(jax.random.PRNGKey(0))
            _, metrics = result.train_step(
                state, result.shard_batch(batch), jax.random.PRNGKey(1)
            )
            losses[flash] = float(jax.device_get(metrics["loss"]))
        assert abs(losses[True] - losses[False]) < 2e-3, losses

    def test_partial_mesh_stays_on_plain_path(self):
        """A user-built mesh missing the data/fsdp/tensor axes must not
        crash the auto-router on an unbound shard_map axis — it stays on
        the plain pallas path (review regression)."""
        import numpy as np
        from jax.sharding import Mesh

        from dlrover_tpu.ops.flash_attention import (
            ambient_shard_mesh,
            flash_attention_auto,
        )

        devices = np.asarray(jax.devices()).reshape(8)
        with mesh_ctx(Mesh(devices, ("data",))):
            assert ambient_shard_mesh() is None
            q = jnp.ones((2, 4, 64, 32), jnp.float32)
            out = flash_attention_auto(q, q, q, True)
        assert out.shape == q.shape

    def test_gqa_indivisible_kv_heads_legalized(self):
        import numpy as np

        from dlrover_tpu.models import llama

        # 8 query heads / 2 kv heads over tensor=4: needs kv repeat x2
        ids = np.random.RandomState(1).randint(0, 256, size=(4, 65))
        batch = {
            "input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:]),
        }
        cfg = llama.llama_tiny(
            num_layers=2, max_seq_len=64, hidden_size=64,
            num_heads=8, num_kv_heads=2, use_flash=True,
        )
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.sgd(1e-2), batch,
            strategy=Strategy(
                mesh=MeshPlan(data=2, fsdp=1, tensor=4),
                rule_set="llama",
            ),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        _, metrics = result.train_step(
            state, result.shard_batch(batch), jax.random.PRNGKey(1)
        )
        assert jnp.isfinite(float(jax.device_get(metrics["loss"])))


class TestStrategy:
    def test_json_roundtrip(self, tmp_path):
        s = Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                     rule_set="llama", remat_policy="dots_saveable",
                     grad_accum_steps=4)
        path = str(tmp_path / "strategy.json")
        s.save(path)
        loaded = Strategy.load(path)
        assert loaded == s

    def test_adjust_to_world_scales_accum(self):
        s = Strategy(mesh=MeshPlan(data=4, fsdp=1, tensor=2),
                     grad_accum_steps=2)
        # 8 devices -> 4: dp halves, accum doubles => global batch fixed
        s2 = s.adjust_to_world(4, prev_num_devices=8)
        assert s2.mesh.dp_degree == 2
        assert s2.grad_accum_steps == 4


class TestAutoTune:
    def test_dryrun_reports_metrics(self):
        from dlrover_tpu.parallel.auto_tune import dryrun

        result = accelerate(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            strategy=Strategy(mesh=MeshPlan(data=4, fsdp=2)),
        )
        report = dryrun(result, _batch(), profile_steps=2)
        assert report.ok
        assert report.step_time_s > 0
        assert report.compile_time_s > 0

    def test_search_picks_a_viable_mesh(self):
        from dlrover_tpu.parallel.auto_tune import search_strategy

        best, reports = search_strategy(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            candidates=[
                MeshPlan(data=8), MeshPlan(data=4, fsdp=2),
                MeshPlan(data=2, fsdp=2, tensor=2),
            ],
            profile_steps=1,
        )
        assert best.mesh.resolve(8)
        assert sum(r.ok for r in reports) >= 1

    def test_planner_prior_orders_the_measured_budget(self):
        """With a ModelSpec, the analytic planner decides WHICH
        candidates get the limited dryrun compiles: the measured pool
        must be the planner's top picks, not enumeration order."""
        from dlrover_tpu.parallel import planner
        from dlrover_tpu.parallel.auto_tune import search_strategy

        spec = planner.ModelSpec(
            param_count=1_000_000, num_layers=2, hidden_size=64,
            seq_len=32, global_batch=32,
        )
        # enumeration puts tensor-heavy plans FIRST: without the prior,
        # max_candidates=1 would measure tensor=8 only
        cands = [MeshPlan(tensor=8), MeshPlan(data=2, tensor=4),
                 MeshPlan(data=8)]
        best, reports = search_strategy(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            candidates=cands,
            profile_steps=1,
            max_candidates=1,
            model_spec=spec,
        )
        # the single measured candidate must be the planner's own top
        # pick (wiring check: ordering applied before the truncation)
        assert len(reports) == 1
        scored = [planner.estimate(p, spec) for p in cands]
        expected = sorted(
            scored, key=lambda s: (not s.fits, s.step_time_s)
        )[0].plan
        assert best.mesh.axis_sizes() == expected.axis_sizes()
        # and it is NOT simply the first enumerated candidate
        assert best.mesh.axis_sizes() != cands[0].axis_sizes()


class TestPutGlobalBatch:
    """put_global_batch: fully-addressable shardings stay on device_put;
    the multi-host assembly path validates its process-local row
    contract loudly."""

    def test_fully_addressable_device_put(self):
        from dlrover_tpu.parallel.accelerate import put_global_batch
        from dlrover_tpu.parallel.sharding_rules import batch_sharding

        mesh = MeshPlan(data=4, fsdp=2).build()
        spec = batch_sharding(mesh)
        out = put_global_batch({"x": jnp.ones((8, 4))}, spec,
                               global_rows=8)
        # pinned to the REQUESTED sharding, not merely any placement
        assert out["x"].sharding == spec
        assert out["x"].shape == (8, 4)

    def test_non_addressable_wrong_rows_raises(self):
        from dlrover_tpu.parallel.accelerate import put_global_batch

        class StubSharding:
            is_fully_addressable = False

        with pytest.raises(ValueError, match="PROCESS-LOCAL rows"):
            put_global_batch(
                {"x": jnp.ones((8, 4))}, StubSharding(), global_rows=4
            )
