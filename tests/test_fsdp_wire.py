"""Quantize the dense wire (ISSUE 12): fp8 FSDP param gathers +
error-feedback gradient reduce-scatters, priced, audited,
optimizer-retunable.

Pins, per the acceptance criteria:

  * the fp8 dense-gather wire is BITWISE equal to the "fsdp_qdq"
    dequant-exact oracle fwd AND bwd (loss + grads), plain scan and
    fsdp_prefetch alike — the transform is pure-forward;
  * the error-feedback gradient path telescopes: the cumulative
    applied-gradient error equals the final residual EXACTLY (bounded),
    while quantize-without-feedback accumulates linearly — and at the
    model level the fp8-EF loss trajectory stays bounded against bf16
    AND strictly tighter than the no-feedback control;
  * the residual rides TrainState: zeros at init, sharded like params,
    surviving checkpoint save→restore and live reshard 8→4;
  * ``planner`` splits the fsdp term into dtype-aware gather legs +
    the param-dtype reduce-scatter with bf16 twins, the fp8/bf16 byte
    ratio pinned to the one formula, and the G106 audit both clean on
    the quantized program and firing on perturbed predictions in both
    directions;
  * the fsdp_precision knob resolves config > Context(env) > default,
    keys the program cache (|fp=), prewarm+retunes with ZERO
    recompiles, the optimizer's candidate key / churn / blacklist
    carry it, and the executor negative-acks a plan the backend's fp8
    probe cannot honor;
  * G109 gains per-family entries (moe vs fsdp vs grad) in
    ``quant_baseline.json``, fire/clean per family.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.models import llama
from dlrover_tpu.ops.quantize import (
    dequantize_block_scaled,
    error_feedback_qdq,
    qdq,
    quantize_block_scaled,
)
from dlrover_tpu.parallel.accelerate import (
    accelerate,
    resolve_grad_precision,
)
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.planner import (
    DeviceSpec,
    ModelSpec,
    estimate,
    model_spec_from_llama,
    predicted_collective_bytes,
)
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.elastic import ElasticTrainer


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


def _dense_cfg(**over):
    over.setdefault("num_layers", 4)
    return llama.llama_tiny(**over)


def _probe_batch(cfg, rows=4, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, size=(rows, cfg.max_seq_len + 1))
    return {"input_ids": jnp.asarray(ids[:, :-1]),
            "labels": jnp.asarray(ids[:, 1:])}


_LG_CACHE = {}


def _loss_and_grads(precision, prefetch=False):
    """Cached per (precision, prefetch): the oracle tests compare the
    same programs from several angles — compile each once."""
    key = (precision, prefetch)
    if key in _LG_CACHE:
        return _LG_CACHE[key]
    _LG_CACHE[key] = _loss_and_grads_uncached(precision, prefetch)
    return _LG_CACHE[key]


def _loss_and_grads_uncached(precision, prefetch):
    cfg = _dense_cfg(fsdp_precision=precision, fsdp_prefetch=prefetch)
    batch = _probe_batch(cfg)
    params = llama.init(jax.random.PRNGKey(0), cfg)
    loss_fn = llama.make_loss_fn(cfg)
    val_grad = jax.jit(jax.value_and_grad(
        lambda p, b, r: loss_fn(p, b, r)[0]))
    loss, grads = val_grad(params, batch, jax.random.PRNGKey(1))
    return jax.device_get(loss), jax.device_get(grads)


def _trees_bitwise(a, b) -> bool:
    return all(
        bool((np.asarray(x) == np.asarray(y)).all())
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b))
    )


# -- the dequant-exact oracle: fp8 == fsdp_qdq, fwd AND bwd -------------------


class TestFsdpWireOracle:
    def test_fp8_matches_qdq_oracle_bitwise_fwd_and_bwd(self):
        """The acceptance pin: the quantized wire changes transport,
        never numbers — quantization commutes with the per-layer slice
        the scan takes, so fp8 (quantized xs, dequant at consumption)
        and fsdp_qdq (decode before the wire) are bitwise equal in
        loss AND in every gradient leaf (both straight-through)."""
        l_q, g_q = _loss_and_grads("fp8")
        l_r, g_r = _loss_and_grads("fp8_qdq")
        assert l_q.tobytes() == l_r.tobytes()
        assert _trees_bitwise(g_q, g_r)

    def test_fp8_drifts_from_bf16_but_boundedly(self):
        """The wire IS a weight-qdq: bf16 and fp8 losses legitimately
        differ (the G109 fsdp family ratchets it), but by rounding
        magnitudes, not structure."""
        l_b, g_b = _loss_and_grads("bf16")
        l_q, _ = _loss_and_grads("fp8")
        assert l_b.tobytes() != l_q.tobytes()
        assert abs(float(l_b) - float(l_q)) / abs(float(l_b)) < 5e-3
        assert all(np.isfinite(np.asarray(g)).all()
                   for g in jax.tree.leaves(g_b))

    def test_prefetch_path_holds_the_oracle_too(self):
        """fsdp_prefetch + fp8: the wire forms ride the double-buffered
        carry (dequant still at consumption) and the oracle contract
        survives the restructure bitwise; prefetch-vs-plain matches to
        float roundoff as always."""
        l_q, g_q = _loss_and_grads("fp8", prefetch=True)
        l_r, g_r = _loss_and_grads("fp8_qdq", prefetch=True)
        assert l_q.tobytes() == l_r.tobytes()
        assert _trees_bitwise(g_q, g_r)
        l_plain, _ = _loss_and_grads("fp8")
        np.testing.assert_allclose(float(l_q), float(l_plain),
                                   rtol=1e-5)

    def test_only_rank3_kernels_ride_the_wire(self):
        """Vector params (norm scales) stay exact and rank-4 expert
        tensors (consumed shard-local, never gathered) are excluded."""
        from dlrover_tpu.models.llama import _quantize_layer_stack

        cfg = _dense_cfg(num_experts=4, moe_dispatch="gather")
        params = llama.init(jax.random.PRNGKey(0), cfg)
        wire = _quantize_layer_stack(params["layers"], "fp8")
        assert wire  # the dense kernels are wired
        assert not any("input_norm" in k or "post_norm" in k
                       for k in wire)
        assert not any("experts" in k for k in wire)
        assert any(k.endswith("router/kernel") for k in wire)


# -- knob resolution ----------------------------------------------------------


class TestFsdpKnobResolution:
    def test_config_wins_then_context_then_default(self, monkeypatch):
        from dlrover_tpu.models.llama import resolve_fsdp_precision

        ctx = get_context()
        monkeypatch.setattr(ctx, "fsdp_precision", "fp8")
        assert resolve_fsdp_precision(_dense_cfg()) == "fp8"
        assert resolve_fsdp_precision(
            _dense_cfg(fsdp_precision="bf16")) == "bf16"
        monkeypatch.setattr(ctx, "fsdp_precision", "bf16")
        assert resolve_fsdp_precision(_dense_cfg()) == "bf16"

    def test_unknown_precision_raises(self):
        from dlrover_tpu.models.llama import resolve_fsdp_precision

        with pytest.raises(ValueError, match="FSDP wire precision"):
            resolve_fsdp_precision(_dense_cfg(fsdp_precision="int4"))

    def test_probe_failure_degrades_to_bf16(self, monkeypatch):
        from dlrover_tpu.models.llama import resolve_fsdp_precision
        from dlrover_tpu.ops import shard_compat

        monkeypatch.setattr(shard_compat, "fp8_wire_supported",
                            lambda: False)
        assert resolve_fsdp_precision(
            _dense_cfg(fsdp_precision="fp8")) == "bf16"

    def test_model_spec_resolves_the_context_knob(self, monkeypatch):
        ctx = get_context()
        monkeypatch.setattr(ctx, "fsdp_precision", "fp8")
        spec = model_spec_from_llama(_dense_cfg(), 8)
        assert spec.fsdp_precision == "fp8"
        spec = model_spec_from_llama(
            _dense_cfg(fsdp_precision="bf16"), 8)
        assert spec.fsdp_precision == "bf16"

    def test_grad_precision_resolution(self, monkeypatch):
        ctx = get_context()
        monkeypatch.setattr(ctx, "grad_precision", "fp8")
        assert resolve_grad_precision() == "fp8"
        assert resolve_grad_precision("bf16") == "bf16"
        with pytest.raises(ValueError, match="grad precision"):
            resolve_grad_precision("int4")
        from dlrover_tpu.ops import shard_compat

        monkeypatch.setattr(shard_compat, "fp8_wire_supported",
                            lambda: False)
        assert resolve_grad_precision("fp8") == "bf16"


# -- planner: dtype-aware gather/scatter split twins --------------------------


def _dense_spec(precision="bf16", **over):
    base = dict(
        param_count=7_000_000_000, num_layers=32, hidden_size=4096,
        seq_len=4096, global_batch=64, num_heads=32, kv_heads=32,
        fsdp_precision=precision,
    )
    base.update(over)
    return ModelSpec(**base)


class TestPlannerFsdpSplit:
    PLAN = MeshPlan(data=2, fsdp=4)

    def test_bf16_reproduces_the_historical_formula(self):
        spec = _dense_spec("bf16")
        fsdp = predicted_collective_bytes(self.PLAN, spec)["fsdp"]
        shard = spec.param_count * spec.param_bytes
        assert fsdp == pytest.approx(3 * shard * 3 / 4)

    def test_fp8_byte_ratio_pinned_to_the_one_formula(self):
        """gather legs at 1 + 4/block bytes/elem, the reduce-scatter
        at param bytes: ratio = (2*wire + param) / (3*param). The
        pricing, the audit comparison and the bench wire-bytes ratio
        all read this formula — they cannot drift apart."""
        b = predicted_collective_bytes(self.PLAN, _dense_spec())["fsdp"]
        q = predicted_collective_bytes(
            self.PLAN, _dense_spec("fp8"))["fsdp"]
        wire = 1.0 + 4.0 / 32
        assert q / b == pytest.approx((2 * wire + 2.0) / (3 * 2.0))

    def test_qdq_prices_at_the_full_precision_wire(self):
        b = predicted_collective_bytes(self.PLAN, _dense_spec())["fsdp"]
        r = predicted_collective_bytes(
            self.PLAN, _dense_spec("fp8_qdq"))["fsdp"]
        assert r == b  # the oracle never wins on bytes it does not save

    def test_breakdown_twins_quantized_leq_bf16_both_directions(self):
        s_b = estimate(self.PLAN, _dense_spec("bf16"))
        s_q = estimate(self.PLAN, _dense_spec("fp8"))
        for s in (s_b, s_q):
            for key in ("fsdp_gather_s", "fsdp_gather_serial_s",
                        "fsdp_scatter_s", "fsdp_comm_bf16_s",
                        "fsdp_comm_bf16_serial_s"):
                assert key in s.breakdown
        # at bf16 the twins collapse
        assert s_b.breakdown["fsdp_comm_s"] == pytest.approx(
            s_b.breakdown["fsdp_comm_bf16_s"])
        # quantized: cheaper than its own bf16 twin, twin equals the
        # bf16 program's actual cost (both directions of the pin)
        assert (s_q.breakdown["fsdp_comm_s"]
                < s_q.breakdown["fsdp_comm_bf16_s"])
        assert s_q.breakdown["fsdp_comm_bf16_s"] == pytest.approx(
            s_b.breakdown["fsdp_comm_s"])
        # the scatter leg is precision-invariant (GSPMD ships the
        # param dtype regardless)
        assert s_q.breakdown["fsdp_scatter_s"] == pytest.approx(
            s_b.breakdown["fsdp_scatter_s"])

    def test_prefetch_overlap_composes_with_the_quantized_gather(self):
        s = estimate(self.PLAN, _dense_spec("fp8", fsdp_prefetch=True))
        b = s.breakdown
        assert b["fsdp_gather_s"] < b["fsdp_gather_serial_s"]
        # the reduce-scatter has nothing later to hide under
        assert b["fsdp_comm_s"] == pytest.approx(
            b["fsdp_gather_s"] + b["fsdp_scatter_s"])

    def test_audit_fires_on_perturbed_predictions_both_directions(self):
        """The PR 2-style regression pin: a cost term drifting 1000x in
        EITHER direction must fail the G106 audit loudly."""
        from dlrover_tpu.analysis.graph_lint import collective_audit

        fsdp = predicted_collective_bytes(
            self.PLAN, _dense_spec("fp8"))["fsdp"]
        assert collective_audit(fsdp, fsdp) == []
        over = collective_audit(fsdp * 1000.0, fsdp)
        under = collective_audit(fsdp / 1000.0, fsdp)
        assert over and over[0].rule_id == "G106"
        assert "does not price" in over[0].message
        assert under and under[0].rule_id == "G106"
        assert "overprices" in under[0].message


# -- compiled wire bytes + G106 clean on the quantized program ----------------


class TestFsdpWireBytesAndLint:
    @pytest.mark.slow  # PR 13 triage: a second copy of a lint-compile
    # test — the G106 audit machinery stays tier-1 via test_lint_clean
    # + test_analysis, and the dtype-aware fsdp byte formula stays
    # tier-1 via the planner perturbation/ratio pins in this file
    def test_quantized_program_audits_clean_with_shrunk_gathers(self):
        """The acceptance pin: G106 audits the fp8 dense program's
        collective bytes against the dtype-aware prediction within the
        existing tolerance AND the compiled all-gather bytes come out
        well under the bf16 twin's — the shrink is verified on the
        COMPILED HLO, not asserted from the formula. (On the CPU
        backend the e4m3 transport legalizes to f16, so the measured
        ratio lands near 0.5x rather than the true-fp8 0.28x — the
        documented PR 11 caveat, docs/parallelism.md.)"""
        from dlrover_tpu.analysis.graph_lint import lint_train_step

        rep_q = lint_train_step(
            _dense_cfg(fsdp_precision="fp8",
                       param_dtype=jnp.bfloat16,
                       compute_dtype=jnp.bfloat16),
            label="llama_tiny[fsdp,fp8]",
        )
        assert rep_q.findings == [], [
            f.render() for f in rep_q.findings]
        rep_b = lint_train_step(
            _dense_cfg(fsdp_precision="bf16",
                       param_dtype=jnp.bfloat16,
                       compute_dtype=jnp.bfloat16),
            label="llama_tiny[fsdp,bf16]",
        )
        assert rep_b.findings == [], [
            f.render() for f in rep_b.findings]
        ag_q = rep_q.measured_bytes.get("all-gather", 0)
        ag_b = rep_b.measured_bytes.get("all-gather", 0)
        assert ag_q > 0 and ag_b > 0
        assert ag_q < ag_b, (ag_q, ag_b)
        # and the prediction the audit compared against used the
        # dtype-aware split
        assert rep_q.predicted_bytes["fsdp"] \
            < rep_b.predicted_bytes["fsdp"]


# -- error feedback: the telescoping contract ---------------------------------


class TestErrorFeedbackTelescoping:
    def test_residual_is_exactly_the_quantization_error(self):
        g = jnp.asarray(
            np.random.RandomState(0).randn(8, 64).astype(np.float32))
        r = jnp.zeros_like(g)
        gq, nr = error_feedback_qdq(g, r)
        np.testing.assert_array_equal(
            np.asarray(gq) + np.asarray(nr), np.asarray(g))

    def test_cumulative_error_telescopes_vs_accumulating(self):
        """The EF identity: sum(applied) = sum(raw) - final_residual,
        so the cumulative applied-gradient error stays bounded by ONE
        quantization error — while quantize-without-feedback applies
        the same biased rounding every step and its cumulative error
        grows LINEARLY. Pinned on a constant gradient whose qdq error
        is nonzero by construction."""
        rng = np.random.RandomState(1)
        g = jnp.asarray(rng.randn(4, 64).astype(np.float32) * 1e-2)
        per_step_err = float(jnp.max(jnp.abs(qdq(g).astype(g.dtype) - g)))
        assert per_step_err > 0  # the constant g must actually round
        steps = 64
        r = jnp.zeros_like(g)
        applied_fb = jnp.zeros_like(g)
        applied_nofb = jnp.zeros_like(g)
        for _ in range(steps):
            gq, r = error_feedback_qdq(g, r)
            applied_fb = applied_fb + gq
            gq_n, _ = error_feedback_qdq(g, jnp.zeros_like(g),
                                         feedback=False)
            applied_nofb = applied_nofb + gq_n
        raw_sum = np.asarray(g) * steps
        err_fb = np.abs(np.asarray(applied_fb) - raw_sum).max()
        err_nofb = np.abs(np.asarray(applied_nofb) - raw_sum).max()
        # telescoped: the cumulative error IS the final residual (up
        # to f32 summation order across the 64 accumulated steps)
        np.testing.assert_allclose(
            err_fb, np.abs(np.asarray(r)).max(), rtol=1e-2)
        # bounded by ~one step's error vs ~steps * error
        assert err_fb <= 4 * per_step_err
        assert err_nofb > 8 * err_fb

    def test_no_feedback_mode_drops_the_error(self):
        g = jnp.asarray(
            np.random.RandomState(2).randn(2, 32).astype(np.float32))
        r = jnp.full_like(g, 0.5)
        gq, nr = error_feedback_qdq(g, r, feedback=False)
        assert float(jnp.abs(nr).max()) == 0.0
        # and the raw g (not g + r) was quantized
        gq_ref, _ = error_feedback_qdq(g, jnp.zeros_like(g))
        np.testing.assert_array_equal(np.asarray(gq), np.asarray(gq_ref))


class TestGradWireModelLevel:
    def _run(self, gp, steps=24, lr=1e-3):
        cfg = llama.llama_tiny(num_layers=2)
        batch = _probe_batch(cfg, rows=4)
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.sgd(lr), batch,
            strategy=Strategy(mesh=MeshPlan(data=1), rule_set="llama"),
            devices=jax.devices()[:1],
            grad_precision=gp,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for _ in range(steps):
            state, m = result.train_step(state, sharded,
                                         jax.random.PRNGKey(7))
            losses.append(float(m["loss"]))
        return np.array(losses), state

    # budget triage (PR 16): the error-feedback contract stays pinned
    # tier-1 by the residual-telescoping units and the G109 grad-family
    # ratchet; the model-level trajectory comparison rides slow
    @pytest.mark.slow
    def test_loss_trajectory_bounded_and_tighter_than_no_feedback(self):
        """The acceptance pin: over N repeated-batch SGD steps in the
        linear regime, the fp8-EF loss trajectory stays bounded
        against bf16 AND strictly tighter than quantize-without-
        feedback (whose biased rounding compounds step over step)."""
        l_bf, state_b = self._run("bf16")
        l_fp8, state = self._run("fp8")
        l_nofb, _ = self._run("fp8_nofb")
        dev_fb = np.abs(l_fp8 - l_bf).max()
        dev_nofb = np.abs(l_nofb - l_bf).max()
        assert dev_fb < 1e-3, (dev_fb, dev_nofb)
        assert dev_fb < dev_nofb, (dev_fb, dev_nofb)
        # the residual is live state by the end of the run — and only
        # when the quantized path carries it (bf16 stays structurally
        # unchanged), mirroring the param tree leaf-for-leaf
        assert state_b.wire_residual is None
        assert state.wire_residual is not None
        assert float(optax.global_norm(state.wire_residual)) > 0
        assert (jax.tree_util.tree_structure(state.wire_residual)
                == jax.tree_util.tree_structure(state.params))


# -- the residual rides the state machinery -----------------------------------


def _dense_trainer(grad_precision="bf16", fsdp_precision="bf16",
                   n_layers=2, mesh=None, **kwargs):
    cfg = llama.llama_tiny(num_layers=n_layers)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(8, 17))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }
    trainer = ElasticTrainer(
        llama.make_init_fn(cfg),
        llama.make_loss_fn(cfg),
        optax.adafactor(1e-3),
        batch,
        strategy=Strategy(mesh=mesh or MeshPlan(data=2, fsdp=2,
                                                tensor=2),
                          rule_set="llama"),
        fsdp_precision=fsdp_precision,
        grad_precision=grad_precision,
        model_spec=model_spec_from_llama(
            llama.llama_tiny(num_layers=n_layers,
                             fsdp_precision=fsdp_precision or "bf16"),
            8),
        **kwargs,
    )
    return trainer, batch


class TestResidualRidesStateMachinery:
    def test_checkpoint_save_restore_preserves_the_residual(
            self, tmp_path):
        """The residual is training state proper: a save→restore round
        trip through the elastic checkpoint manager reproduces it
        bit-for-bit (losing it would re-apply the compressed error the
        feedback already accounted for)."""
        trainer, batch = _dense_trainer(grad_precision="fp8",
                                        ckpt_dir=str(tmp_path))
        state = trainer.prepare()
        for _ in range(3):
            state, _ = trainer.step(state, batch)
        trainer.save(state, force=True)
        trainer.finalize()
        res_before = jax.device_get(state.wire_residual)
        assert float(optax.global_norm(res_before)) > 0

        trainer2, _ = _dense_trainer(grad_precision="fp8",
                                     ckpt_dir=str(tmp_path))
        restored = trainer2.prepare()
        assert int(restored.step) == int(state.step)
        assert _trees_bitwise(
            jax.device_get(restored.wire_residual), res_before)
        trainer2.finalize()

    def test_live_reshard_8_to_4_reshards_the_residual(self):
        """The acceptance pin: an 8→4 live reshard carries the
        residual through HostSnapshot and device_puts it against the
        survivor world's shardings — values identical, training
        resumes, and the residual keeps evolving."""
        trainer, batch = _dense_trainer(grad_precision="fp8")
        state = trainer.prepare()
        for _ in range(2):
            state, _ = trainer.step(state, batch)
        res_before = jax.device_get(state.wire_residual)
        assert float(optax.global_norm(res_before)) > 0

        state = trainer.live_reshard(state, devices=jax.devices()[:4])
        assert trainer.accelerated.mesh.devices.size == 4
        assert _trees_bitwise(
            jax.device_get(state.wire_residual), res_before)
        # the resharded residual is consistent with the new sharding:
        # another step runs and updates it
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])
        res_after = jax.device_get(state.wire_residual)
        assert not _trees_bitwise(res_after, res_before)


# -- live retune through the program cache ------------------------------------


class TestRetuneFsdpPrecisionZeroRecompile:
    @pytest.mark.slow  # PR 13 triage: the per-knob retune gate — the
    # prewarm/retune/program-cache mechanics stay tier-1 via PR 7's
    # test_optimizer e2e wedges and the serving retune/resize gates
    # (tests/test_serving.py); the fsdp-specific key identity stays
    # tier-1 below (test_program_key_carries_both_precisions)
    def test_prewarmed_fsdp_retune_swaps_with_zero_recompiles(self):
        """The tier-1 live-apply gate (the PR 11 pattern): retune()
        across dense-wire precisions through the program cache — a
        prewarmed fp8 wire applies with ZERO recompiles, and retuning
        BACK hits the original program."""
        trainer, batch = _dense_trainer()
        state = trainer.prepare()
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])
        assert trainer.fsdp_precision == "bf16"

        compiled = trainer.prewarm(fsdp_precision="fp8")
        assert compiled  # fp8 is a new program
        assert trainer.fsdp_precision == "bf16"  # prewarm must not switch
        assert get_context().fsdp_precision == "bf16"

        before = trainer.compile_count
        state = trainer.retune(state, fsdp_precision="fp8")
        assert trainer.compile_count == before  # ZERO recompiles
        assert trainer.fsdp_precision == "fp8"
        assert get_context().fsdp_precision == "fp8"  # trace knob pinned
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])

        # back to bf16: the startup program is still in the cache
        before = trainer.compile_count
        state = trainer.retune(state, fsdp_precision="bf16")
        assert trainer.compile_count == before
        assert trainer.fsdp_precision == "bf16"
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])

    def test_program_key_carries_both_precisions(self):
        trainer, _ = _dense_trainer(grad_precision="fp8")
        strategy = trainer._resolved_strategy(8)
        k_q = trainer._program_key(jax.devices(), strategy)
        assert "|fp=bf16" in k_q and "|gp=fp8" in k_q
        trainer.fsdp_precision = "fp8"
        k_fp = trainer._program_key(jax.devices(), strategy)
        assert "|fp=fp8" in k_fp and k_fp != k_q


# -- optimizer: the fsdp_precision knob family --------------------------------


class _Store:
    def __init__(self):
        self._s = {}

    def node_ids(self):
        return list(self._s)

    def latest(self, nid):
        return self._s.get(nid)


class _Snap:
    def __init__(self, step_p50):
        import time

        self.ts = time.time()
        self.step_p50 = step_p50
        self.dispatch_p50 = None
        self.exposed_comm_frac = None
        self.input_wait_frac = None


def _dense_model_info():
    """A gather-bound dense shape: at data=2 x fsdp=32 the per-step
    param traffic dominates, so the fp8 dense wire wins the ranking
    honestly."""
    return comm.ModelInfo(
        num_params=70_000_000_000, hidden_size=8192, num_layers=80,
        seq_len=2048,
    )


def _dense_running_report(fsdp_precision="bf16"):
    return comm.TrainerConfigReport(
        node_id=0, world=64, mesh_shape={"data": 2, "fsdp": 32},
        train_window=4, steps_per_call=1,
        fsdp_precision=fsdp_precision, global_batch=64,
    )


class TestOptimizerFsdpKnob:
    def _opt(self, store, published):
        from dlrover_tpu.master.optimizer import RuntimeOptimizer

        return RuntimeOptimizer(
            store, publish=published.append, mesh_candidates=False,
            device=DeviceSpec(hbm_bytes=95e9), min_speedup=1.02,
        )

    def test_family_parked_until_the_worker_reports_the_knob(self):
        store = _Store()
        store._s[0] = _Snap(16.6)
        opt = self._opt(store, [])
        opt.update_model_info(_dense_model_info())
        opt.update_running_config(comm.TrainerConfigReport(
            node_id=0, world=64, mesh_shape={"data": 2, "fsdp": 32},
            train_window=4, steps_per_call=1, global_batch=64,
        ))  # no fsdp_precision reported
        *_, fsdp_opts = opt._knob_options(opt._running)
        assert fsdp_opts == ["bf16"]  # parked
        opt.update_running_config(_dense_running_report())
        *_, fsdp_opts = opt._knob_options(opt._running)
        assert fsdp_opts == ["bf16", "fp8"]

    def test_replan_chooses_and_publishes_an_fsdp_plan(self):
        """Gather-bound dense spec → the fp8 dense wire wins; unchanged
        knobs publish as sentinels so the worker can tell a pure wire
        swap from a mesh/K change."""
        store = _Store()
        store._s[0] = _Snap(16.6)
        published = []
        opt = self._opt(store, published)
        opt.update_model_info(_dense_model_info())
        opt.update_running_config(_dense_running_report())
        d = opt.replan("test")
        assert d.outcome == "chosen", d.to_dict()
        assert d.chosen["fsdp_precision"] == "fp8"
        cfg = published[0]
        assert cfg.fsdp_precision == "fp8"
        assert cfg.steps_per_call == 0  # sentinel: unchanged
        assert cfg.mesh_shape is None
        assert cfg.moe_precision == ""

    def test_candidate_key_carries_the_knob(self):
        from dlrover_tpu.master.optimizer.runtime_optimizer import (
            CandidateScore,
        )

        a = CandidateScore(mesh=MeshPlan(data=2, fsdp=32),
                           steps_per_call=1, train_window=4,
                           moe_dispatch="", fsdp_precision="bf16")
        b = CandidateScore(mesh=MeshPlan(data=2, fsdp=32),
                           steps_per_call=1, train_window=4,
                           moe_dispatch="", fsdp_precision="fp8")
        assert a.key != b.key
        assert "|fp=fp8" in b.key

    def test_failed_apply_blacklists_the_fsdp_tuple(self):
        store = _Store()
        store._s[0] = _Snap(16.6)
        opt = self._opt(store, [])
        opt.update_model_info(_dense_model_info())
        opt.update_running_config(_dense_running_report())
        d = opt.replan("test")
        assert d.outcome == "chosen"
        key = d.chosen_key
        assert "|fp=fp8" in key
        opt.update_running_config(comm.TrainerConfigReport(
            node_id=0, world=64, mesh_shape={"data": 2, "fsdp": 32},
            train_window=4, steps_per_call=1,
            fsdp_precision="bf16", global_batch=64,
            plan_id=d.plan_id, apply_failed=True,
        ))
        assert key in opt._failed_keys
        d2 = opt.replan("retry")
        if d2 is not None and d2.outcome == "chosen":
            assert d2.chosen_key != key


class TestPlanHookRoutesFsdpPrecision:
    def test_fsdp_plan_reaches_request_retune(self):
        from dlrover_tpu.trainer.executor import OptimizerPlanHook

        class _Ex:
            def __init__(self):
                self.retunes = []

            def request_retune(self, **kw):
                self.retunes.append(kw)

        class _Client:
            def get_parallel_config(self):
                return comm.ParallelConfig(
                    fsdp_precision="fp8", plan_id="plan-fp",
                    trace_id="inc-fp", predicted_speedup=1.3)

        hook = OptimizerPlanHook(_Client(), poll_secs=0)
        ex = _Ex()
        hook._executor = ex
        hook.poll_once()
        assert ex.retunes[0]["fsdp_precision"] == "fp8"
        assert ex.retunes[0]["moe_precision"] is None
        assert ex.retunes[0]["steps_per_call"] is None
        assert ex.retunes[0]["plan_id"] == "plan-fp"


class TestExecutorNacksUnsupportedFsdpPlan:
    def test_probe_degraded_plan_is_negative_acked(self):
        """A backend whose fp8 probe fails must NOT ack an fp8 plan it
        silently runs as bf16 — the phantom apply would be re-chosen
        after every trigger, each cycle paying a futile drain."""
        from dlrover_tpu.trainer.executor import TrainExecutor

        class _Trainer:
            fsdp_precision = "bf16"
            moe_precision = "bf16"
            steps_per_call = 1
            dispatch_chunks = 1

            @staticmethod
            def _effective_precision(p):
                return "bf16"  # the probe failed: everything degrades

            class accelerated:  # noqa: N801 - attribute stand-in
                pass

        ex = TrainExecutor.__new__(TrainExecutor)
        ex._trainer = _Trainer()
        acks = []
        ex._report_trainer_config = (
            lambda **kw: acks.append(kw))
        ex._apply_plan_scoped({"fsdp_precision": "fp8",
                               "plan_id": "plan-x"}, "plan-x")
        assert acks and acks[0]["apply_failed"] is True
        assert acks[0]["plan_id"] == "plan-x"


# -- G109 per-family drift entries --------------------------------------------


class TestG109Families:
    def test_fsdp_family_clean_against_the_committed_baseline(self):
        from dlrover_tpu.analysis.graph_lint import (
            quantization_drift_audit,
        )

        rep = quantization_drift_audit(family="fsdp")
        assert rep.label.startswith("llama_tiny[fsdp,fp8]@")
        assert rep.findings == [], [f.render() for f in rep.findings]

    def test_grad_family_clean_against_the_committed_baseline(self):
        from dlrover_tpu.analysis.graph_lint import (
            quantization_drift_audit,
        )

        rep = quantization_drift_audit(family="grad")
        assert rep.label.startswith("llama_tiny[grad,fp8]@")
        assert rep.findings == [], [f.render() for f in rep.findings]

    def test_each_family_fires_independently(self):
        """A regressed family fails against ITS OWN ratchet — the
        entries are per family, so a dense-wire regression cannot hide
        under the MoE family's baseline (and vice versa)."""
        import json

        from dlrover_tpu.analysis.graph_lint import (
            check_quantization_drift,
            quantization_drift_baseline_path,
        )

        with open(quantization_drift_baseline_path()) as fh:
            entries = json.load(fh)["entries"]
        for fam_label in ("llama_tiny[fsdp,fp8]@cpu",
                          "llama_tiny[grad,fp8]@cpu",
                          "llama_tiny_moe[grouped_ep,fp8]@cpu"):
            assert fam_label in entries, entries.keys()
            base = entries[fam_label]["drift"]
            assert check_quantization_drift(base, base) == []  # clean
            fired = check_quantization_drift(
                max(base * 100, 1e-2), base)
            assert fired and fired[0].rule_id == "G109"

    def test_unknown_family_raises(self):
        from dlrover_tpu.analysis.graph_lint import (
            measure_quantization_drift,
        )

        with pytest.raises(ValueError, match="drift family"):
            measure_quantization_drift(family="int4")


# -- the e2e replan wedge + bench wedge (slow-marked per the triage) ----------


@pytest.mark.slow
class TestFsdpReplanWedge:
    """Slow-marked (~90 s): the full master→RPC→live-apply loop is
    tier-1-covered by PR 7's e2e wedges (test_optimizer) and the
    dense-wire guarantees by TestRetuneFsdpPrecisionZeroRecompile +
    the optimizer/plan-hook unit tests above — the tier-1 budget on
    this 1-core box (870 s for the whole suite) cannot carry another
    ~90 s wedge per knob family."""

    def test_optimizer_selects_fp8_and_worker_applies_live(
            self, tmp_path, monkeypatch):
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.local_master import start_local_master
        from dlrover_tpu.telemetry import EventKind, read_events
        from dlrover_tpu.trainer.conf import Configuration
        from dlrover_tpu.trainer.executor import (
            NodeRuntimeReportHook,
            OptimizerPlanHook,
            TrainExecutor,
            TrainHook,
        )

        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        ctx = get_context()
        monkeypatch.setattr(ctx, "replan_min_speedup", 1.02)
        # the live apply pins the chosen knobs into the Context (the
        # trace-time contract) — register restores so they don't leak
        # into later tests' trace-time resolution
        monkeypatch.setattr(ctx, "fsdp_precision", ctx.fsdp_precision)
        monkeypatch.setattr(ctx, "dispatch_chunks", ctx.dispatch_chunks)
        monkeypatch.setattr(ctx, "moe_precision", ctx.moe_precision)
        master = start_local_master()
        opt = master.servicer.runtime_optimizer
        opt._mesh_candidates = False
        opt._device = DeviceSpec(hbm_bytes=95e9)
        try:
            client = MasterClient(master.addr, node_id=0)
            # gather-bound dense shape that still fits the memory gate
            # (at data=2 x fsdp=4 the fsdp term dominates the step)
            client.report_model_info(comm.ModelInfo(
                num_params=8_000_000_000, hidden_size=8192,
                num_layers=32, seq_len=2048,
            ))
            trainer, batch = _dense_trainer(
                n_layers=4, mesh=MeshPlan(data=2, fsdp=4))
            steps = 24
            ex = TrainExecutor(
                trainer, train_iter_fn=lambda: [batch] * steps,
                hooks=[NodeRuntimeReportHook(client, every_steps=4,
                                             min_interval_s=0)],
                conf=Configuration({
                    "train_steps": steps, "log_every_steps": 0,
                    "train_window": 2, "preemption_grace": False,
                    "plan_poll_secs": 0, "runtime_report_steps": 0,
                }),
            )
            ex._master_client = client
            plan_hook = OptimizerPlanHook(client, poll_secs=0)
            plan_hook._executor = ex

            class _Drive(TrainHook):
                fired = False

                def after_step(self, step, metrics):
                    if step >= 8 and not _Drive.fired:
                        _Drive.fired = True
                        opt.replan("wedge")
                    if step >= 10 and step % 4 == 2:
                        plan_hook.poll_once()

            ex._hooks.append(_Drive())
            ex.train_and_evaluate()
            client.close()

            decisions = opt.decisions()
            chosen = [d for d in decisions if d["outcome"] == "chosen"]
            assert chosen, decisions
            d = chosen[-1]
            assert d["chosen"]["fsdp_precision"] == "fp8"
            assert d["applied"], d
            assert trainer.fsdp_precision == "fp8"
            done = [r for r in read_events(events_path)
                    if r.get("kind") == EventKind.OPTIMIZER_APPLY_DONE
                    and r.get("plan_id") == d["plan_id"]]
            assert done and done[-1]["recompiled"] == 0, done
            assert done[-1]["fsdp_precision"] == "fp8"
        finally:
            master.stop()


@pytest.mark.slow
class TestFsdpBenchWedge:
    """Slow-marked: seven executor legs; everything it gates beyond
    the bench plumbing — dequant-exact parity, recompiles, wire-bytes
    accounting — is already pinned tier-1 by the tests above."""

    def test_paired_legs_parity_recompiles_and_wire_bytes(self):
        import bench

        env_keys = {"BENCH_FSDP_STEPS": "8", "BENCH_FSDP_PAIRS": "1"}
        saved = {k: os.environ.get(k) for k in env_keys}
        os.environ.update(env_keys)
        try:
            rec = bench.fsdp_precision_result()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        assert rec["metric"] == "fsdp_wire_precision_ratio"
        assert "error" not in rec, rec
        detail = rec["detail"]
        assert detail["params_parity"] is True
        assert detail["recompiles_after_warmup"] == 0
        assert rec["pending_hardware"] is True
        wb = detail["wire_bytes"]
        # the dtype-aware formula: (2*1.125 + 4) / (3*4) on f32 params
        assert wb["predicted_ratio"] == pytest.approx(0.5208, abs=1e-3)
        assert wb["measured_ratio"] < 0.8
