"""Optimizers: WSAM two-gradient updates, fp32 master weights, dynamic
loss scaling, parallelism-aware clipping."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.optimizers import (
    DynamicGradScaler,
    all_finite,
    bf16_master_weights,
    clip_by_global_norm,
    global_norm,
    wsam,
)


def _quadratic_loss(w):
    # sharp in dim 0, flat in dim 1
    return 50.0 * w[0] ** 2 + 0.5 * w[1] ** 2


class TestWsam:
    def test_decoupled_step_matches_manual(self):
        w = jnp.array([1.0, 1.0])
        lr, rho, gamma = 0.1, 0.05, 0.9
        alpha = gamma / (1 - gamma)
        opt = wsam(optax.sgd(lr), rho=rho, gamma=gamma, learning_rate=lr)
        state = opt.init(w)
        g = jax.grad(_quadratic_loss)(w)
        updates, state = opt.update_with_grad_fn(
            g, state, w, jax.grad(_quadratic_loss)
        )
        # manual: e_w = rho*g/||g||; sharp = g(w+e) - g
        e_w = rho * g / jnp.linalg.norm(g)
        g_sam = jax.grad(_quadratic_loss)(w + e_w)
        expected = -lr * g - lr * alpha * (g_sam - g)
        np.testing.assert_allclose(updates, expected, rtol=1e-5)

    def test_coupled_step_matches_manual(self):
        w = jnp.array([0.5, -0.3])
        lr, rho, gamma = 0.05, 0.1, 0.8
        alpha = gamma / (1 - gamma)
        opt = wsam(optax.sgd(lr), rho=rho, gamma=gamma, decouple=False)
        state = opt.init(w)
        g = jax.grad(_quadratic_loss)(w)
        updates, _ = opt.update_with_grad_fn(
            g, state, w, jax.grad(_quadratic_loss)
        )
        e_w = rho * g / jnp.linalg.norm(g)
        g_sam = jax.grad(_quadratic_loss)(w + e_w)
        expected = -lr * ((1 - alpha) * g + alpha * g_sam)
        np.testing.assert_allclose(updates, expected, rtol=1e-5)

    @pytest.mark.slow  # PR 13 triage: a 17 s convergence loop — the
    # wsam step CONTRACT stays tier-1 via the exact manual-match tests
    # above and the accelerate integration below
    def test_converges_on_quadratic(self):
        def loss(w):
            return 5.0 * w[0] ** 2 + 0.5 * w[1] ** 2

        # moderate gamma: with a constant rho the SAM family orbits the
        # minimum in a limit cycle of amplitude ~ rho * alpha
        opt = wsam(optax.sgd(0.05), gamma=0.5, learning_rate=0.05)
        w = jnp.array([1.0, 1.0])
        state = opt.init(w)
        step = jax.jit(opt.update_with_grad_fn, static_argnums=(3,))
        for _ in range(300):
            g = jax.grad(loss)(w)
            updates, state = step(g, state, w, jax.grad(loss))
            w = optax.apply_updates(w, updates)
        assert float(loss(w)) < 2e-3

    def test_decouple_requires_learning_rate(self):
        with pytest.raises(ValueError):
            wsam(optax.sgd(0.1))

    def test_accelerate_integration(self):
        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.mesh import MeshPlan
        from dlrover_tpu.parallel.strategy import Strategy

        def init_fn(rng):
            return {"w": jax.random.normal(rng, (4, 2)),
                    "b": jnp.zeros((2,))}

        def loss_fn(params, batch, rng):
            pred = batch["x"] @ params["w"] + params["b"]
            return jnp.mean((pred - batch["y"]) ** 2), {}

        rngs = jax.random.split(jax.random.PRNGKey(0), 2)
        x = jax.random.normal(rngs[0], (16, 4))
        w_true = jax.random.normal(rngs[1], (4, 2))
        batch = {"x": x, "y": x @ w_true}
        result = accelerate(
            init_fn, loss_fn,
            wsam(optax.sgd(0.1), learning_rate=0.1),
            batch, strategy=Strategy(mesh=MeshPlan(data=-1)),
        )
        state = result.init_fn(jax.random.PRNGKey(1))
        sb = result.shard_batch(batch)
        losses = []
        for i in range(10):
            state, m = result.train_step(state, sb, jax.random.PRNGKey(i))
            losses.append(float(m["loss"]))
        assert losses[-1] < losses[0] * 0.5


class TestBf16MasterWeights:
    def test_small_updates_accumulate_via_master(self):
        # each update is far below bf16 resolution at magnitude 1.0; only
        # the fp32 master accumulates them
        p = {"w": jnp.ones((4,), jnp.bfloat16)}
        opt = bf16_master_weights(optax.sgd(1.0))
        state = opt.init(p)
        g = {"w": jnp.full((4,), 1e-4, jnp.bfloat16)}
        for _ in range(100):
            updates, state = opt.update(g, state, p)
            p = optax.apply_updates(p, updates)
        # 100 * 1e-4 = 0.01 drop; plain bf16 adds of 1e-4 onto 1.0 no-op
        master = jax.tree.leaves(state.master)[0]
        assert master.dtype == jnp.float32
        np.testing.assert_allclose(
            np.asarray(master), 1.0 - 1e-2, rtol=1e-3
        )
        assert float(p["w"][0]) < 1.0

    def test_fp32_params_pass_through(self):
        p = {"w": jnp.ones((2,), jnp.float32)}
        opt = bf16_master_weights(optax.sgd(0.5))
        state = opt.init(p)
        updates, state = opt.update({"w": jnp.ones((2,))}, state, p)
        np.testing.assert_allclose(np.asarray(updates["w"]), -0.5)


class TestGradScaler:
    def test_backoff_on_overflow_and_growth(self):
        scaler = DynamicGradScaler(init_scale=8.0, growth_interval=2)
        state = scaler.init()
        # overflow: scale halves
        state = scaler.update(state, jnp.asarray(False))
        assert float(state.scale) == 4.0
        # two finite steps: scale doubles
        state = scaler.update(state, jnp.asarray(True))
        state = scaler.update(state, jnp.asarray(True))
        assert float(state.scale) == 8.0

    def test_scale_unscale_roundtrip(self):
        scaler = DynamicGradScaler(init_scale=1024.0)
        state = scaler.init()
        loss = jnp.asarray(0.5)
        assert float(scaler.scale(loss, state)) == 512.0
        grads = {"w": jnp.asarray([2048.0, 1024.0])}
        unscaled, finite = scaler.unscale(grads, state)
        np.testing.assert_allclose(np.asarray(unscaled["w"]), [2.0, 1.0])
        assert bool(finite)

    def test_detects_non_finite(self):
        assert not bool(all_finite({"g": jnp.asarray([1.0, jnp.inf])}))
        assert bool(all_finite({"g": jnp.asarray([1.0, 2.0])}))


class TestClip:
    def test_clips_to_max_norm(self):
        clip = clip_by_global_norm(1.0)
        g = {"w": jnp.asarray([3.0, 4.0])}
        state = clip.init(g)
        clipped, _ = clip.update(g, state)
        np.testing.assert_allclose(
            float(global_norm(clipped)), 1.0, rtol=1e-5
        )

    def test_under_norm_untouched(self):
        clip = clip_by_global_norm(10.0)
        g = {"w": jnp.asarray([0.3, 0.4])}
        clipped, _ = clip.update(g, clip.init(g))
        np.testing.assert_allclose(np.asarray(clipped["w"]), [0.3, 0.4],
                                   rtol=1e-5)

    def test_shard_map_axis_names(self):
        from jax.sharding import Mesh, PartitionSpec as P
        try:
            from jax import shard_map  # jax >= 0.5
        except ImportError:
            from jax.experimental.shard_map import shard_map

        devices = np.array(jax.devices()[:4]).reshape(4)
        mesh = Mesh(devices, ("data",))
        g = jnp.arange(8.0)

        def f(g):
            return global_norm({"g": g}, axis_names=("data",))

        out = shard_map(
            f, mesh=mesh, in_specs=P("data"), out_specs=P()
        )(g)
        np.testing.assert_allclose(
            float(out), float(jnp.linalg.norm(g)), rtol=1e-5
        )
