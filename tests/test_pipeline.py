"""Pipeline parallelism on the 8-device virtual CPU mesh.

Parity target: atorch's PiPPy pipeline compiler produces the same math as
the unpipelined model; here the GPipe schedule (``parallel.pipeline``) is
checked against plain sequential application, forward and backward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_apply,
    pipeline_apply_interleaved,
    split_microbatches,
    stack_stages,
    stack_stages_interleaved,
)
from conftest import mesh_ctx


def _toy_stage(params, x):
    # one "layer" chunk: scan over the stage's stacked layers
    def layer(h, w):
        return jnp.tanh(h @ w), None

    out, _ = jax.lax.scan(layer, x, params)
    return out


class TestPipelineApply:
    def _sequential(self, stacked, x):
        def layer(h, w):
            return jnp.tanh(h @ w), None

        out, _ = jax.lax.scan(layer, x, stacked)
        return out

    def test_matches_sequential_forward(self):
        rng = np.random.RandomState(0)
        layers, d, batch, mb = 8, 16, 8, 4
        stacked = jnp.asarray(rng.randn(layers, d, d) * 0.3,
                              jnp.float32)
        x = jnp.asarray(rng.randn(batch, d), jnp.float32)

        expected = self._sequential(stacked, x)

        mesh = MeshPlan(pipe=4, data=2).build()
        with mesh_ctx(mesh):
            out_mb = pipeline_apply(
                _toy_stage,
                stack_stages(stacked, 4),
                split_microbatches(x, mb),
            )
            got = merge_microbatches(out_mb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-5, atol=1e-5)

    def test_gradients_match_sequential(self):
        rng = np.random.RandomState(1)
        layers, d, batch, mb = 4, 8, 8, 4
        stacked = jnp.asarray(rng.randn(layers, d, d) * 0.3, jnp.float32)
        x = jnp.asarray(rng.randn(batch, d), jnp.float32)

        def seq_loss(w):
            return jnp.sum(self._sequential(w, x) ** 2)

        def pipe_loss(w):
            out = pipeline_apply(
                _toy_stage, stack_stages(w, 2), split_microbatches(x, mb)
            )
            return jnp.sum(merge_microbatches(out) ** 2)

        expected = jax.grad(seq_loss)(stacked)
        mesh = MeshPlan(pipe=2, data=2, fsdp=2).build()
        with mesh_ctx(mesh):
            got = jax.jit(jax.grad(pipe_loss))(stacked)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-4, atol=1e-5)

    def test_interleaved_matches_sequential(self):
        # V=2 virtual stages over P=2 physical, M=4 microbatches
        rng = np.random.RandomState(1)
        layers, d, batch, mb = 8, 16, 8, 4
        stacked = jnp.asarray(rng.randn(layers, d, d) * 0.3, jnp.float32)
        x = jnp.asarray(rng.randn(batch, d), jnp.float32)
        expected = self._sequential(stacked, x)

        out_mb = pipeline_apply_interleaved(
            _toy_stage,
            stack_stages_interleaved(stacked, num_stages=2, num_virtual=2),
            split_microbatches(x, mb),
        )
        got = merge_microbatches(out_mb)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-5, atol=1e-5)

    def test_interleaved_m_equals_p(self):
        rng = np.random.RandomState(2)
        layers, d, batch, mb = 12, 8, 6, 3
        stacked = jnp.asarray(rng.randn(layers, d, d) * 0.3, jnp.float32)
        x = jnp.asarray(rng.randn(batch, d), jnp.float32)
        expected = self._sequential(stacked, x)
        out_mb = pipeline_apply_interleaved(
            _toy_stage,
            stack_stages_interleaved(stacked, num_stages=3, num_virtual=2),
            split_microbatches(x, mb),
        )
        np.testing.assert_allclose(
            np.asarray(merge_microbatches(out_mb)), np.asarray(expected),
            rtol=1e-5, atol=1e-5,
        )

    def test_interleaved_gradients_match(self):
        rng = np.random.RandomState(3)
        layers, d, batch, mb = 8, 8, 8, 4
        stacked = jnp.asarray(rng.randn(layers, d, d) * 0.3, jnp.float32)
        x = jnp.asarray(rng.randn(batch, d), jnp.float32)

        def seq_loss(w):
            return (self._sequential(w, x) ** 2).sum()

        def pp_loss(w):
            out_mb = pipeline_apply_interleaved(
                _toy_stage,
                stack_stages_interleaved(w, 2, 2),
                split_microbatches(x, mb),
            )
            return (merge_microbatches(out_mb) ** 2).sum()

        # stacking happens inside pp_loss, so both grads are in logical
        # [L, d, d] layer order and compare directly
        g_seq = jax.grad(seq_loss)(stacked)
        g_pp = jax.grad(pp_loss)(stacked)
        np.testing.assert_allclose(np.asarray(g_pp), np.asarray(g_seq),
                                   rtol=1e-4, atol=1e-4)

    def test_interleaved_rejects_too_few_microbatches(self):
        stacked = jnp.zeros((8, 4, 4))
        x = jnp.zeros((8, 4))
        with pytest.raises(ValueError, match="microbatches >= stages"):
            pipeline_apply_interleaved(
                _toy_stage,
                stack_stages_interleaved(stacked, 4, 2),
                split_microbatches(x, 2),
            )

    def test_interleaved_llama_matches_plain(self):
        config = llama.llama_tiny(num_layers=4)
        params = llama.init(jax.random.PRNGKey(0), config)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, config.vocab_size, (4, 16))
        )
        rng = jax.random.PRNGKey(1)
        plain, _ = llama.apply(params, ids, config, rng)
        inter, _ = llama.apply_pipelined(
            params, ids, config, num_stages=2, num_microbatches=2,
            rng=rng, num_virtual=2,
        )
        np.testing.assert_allclose(np.asarray(inter), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)

    def test_rejects_indivisible_microbatch(self):
        with pytest.raises(ValueError):
            split_microbatches(jnp.zeros((7, 3)), 4)
        with pytest.raises(ValueError):
            stack_stages(jnp.zeros((6, 3)), 4)


class TestLlamaPipelined:
    def test_matches_unpipelined_apply(self):
        config = llama.llama_tiny(num_layers=4)
        params = llama.init(jax.random.PRNGKey(0), config)
        input_ids = jax.random.randint(
            jax.random.PRNGKey(1), (4, 16), 0, config.vocab_size
        )
        expected, _aux = llama.apply(params, input_ids, config)

        mesh = MeshPlan(pipe=2, data=2, tensor=2).build()
        with mesh_ctx(mesh):
            got, _aux2 = jax.jit(
                lambda p, ids: llama.apply_pipelined(
                    p, ids, config, num_stages=2, num_microbatches=2
                )
            )(params, input_ids)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(expected), rtol=2e-4, atol=2e-4
        )

    def test_trains_end_to_end_with_pp_rules(self):
        """Full train step: PP rules place layers on "pipe"; loss falls."""
        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.strategy import Strategy

        config = llama.llama_tiny(num_layers=4)

        def loss_fn(params, batch, rng):
            logits, _ = llama.apply_pipelined(
                params, batch["input_ids"], config,
                num_stages=2, num_microbatches=2, rng=rng,
            )
            from dlrover_tpu.models.losses import masked_lm_loss

            return masked_lm_loss(logits, batch["labels"]), {}

        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, config.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, config.vocab_size
            ),
        }
        strategy = Strategy(
            mesh=MeshPlan(pipe=2, data=2, tensor=2),
            rule_set="llama_pp",
        )
        result = accelerate(
            llama.make_init_fn(config), loss_fn,
            optax.adam(1e-2), batch, strategy=strategy,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for i in range(3):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_strategy_drives_interleaved_schedule(self):
        """Round-2 verdict #3: num_virtual is a Strategy field, survives
        JSON round-trip, and drives the circular schedule end-to-end on
        the sharded mesh."""
        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.strategy import Strategy

        strategy = Strategy(
            mesh=MeshPlan(pipe=2, data=2, tensor=2),
            rule_set="llama_pp",
            num_virtual=2,
        )
        # persistence: the knob must survive save/load like the rest
        assert Strategy.from_json(strategy.to_json()).num_virtual == 2

        config = llama.llama_tiny(num_layers=4)

        def loss_fn(params, batch, rng):
            from dlrover_tpu.models.losses import masked_lm_loss

            logits, _ = llama.apply_pipelined(
                params, batch["input_ids"], config,
                num_stages=2, num_microbatches=2, rng=rng,
                num_virtual=strategy.num_virtual,
            )
            return masked_lm_loss(logits, batch["labels"]), {}

        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, config.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, config.vocab_size
            ),
        }
        result = accelerate(
            llama.make_init_fn(config), loss_fn,
            optax.adam(1e-2), batch, strategy=strategy,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for i in range(3):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]


class TestUnevenStages:
    """Per-stage layer counts (round-4 verdict weak #3 / item 8): a
    lighter first/last stage, and layer counts that don't divide by the
    stage count — reference's uneven stage placement
    (atorch base_stage_planner.py:125)."""

    def test_uneven_gpipe_matches_plain(self):
        # L=6 over P=4 stages: [2, 2, 1, 1] — indivisible without padding
        config = llama.llama_tiny(num_layers=6)
        params = llama.init(jax.random.PRNGKey(0), config)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, config.vocab_size, (4, 16))
        )
        rng = jax.random.PRNGKey(1)
        plain, _ = llama.apply(params, ids, config, rng)
        got, _ = llama.apply_pipelined(
            params, ids, config, num_stages=4, num_microbatches=2,
            rng=rng, stage_depths=(2, 2, 1, 1),
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)

    def test_uneven_interleaved_matches_plain(self):
        # V=2, P=2 with lighter FIRST physical stage: visit-order depths
        # (1, 2, 1, 2) give stage 0 a total of 2 layers, stage 1 of 4
        config = llama.llama_tiny(num_layers=6)
        params = llama.init(jax.random.PRNGKey(0), config)
        ids = jnp.asarray(
            np.random.RandomState(1).randint(0, config.vocab_size, (4, 16))
        )
        rng = jax.random.PRNGKey(2)
        plain, _ = llama.apply(params, ids, config, rng)
        got, _ = llama.apply_pipelined(
            params, ids, config, num_stages=2, num_microbatches=2,
            rng=rng, num_virtual=2, stage_depths=(1, 2, 1, 2),
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)

    def test_uneven_gradients_match(self):
        from dlrover_tpu.models.losses import masked_lm_loss

        config = llama.llama_tiny(num_layers=3)
        params = llama.init(jax.random.PRNGKey(0), config)
        ids = jnp.asarray(
            np.random.RandomState(2).randint(0, config.vocab_size, (4, 16))
        )
        labels = jnp.asarray(
            np.random.RandomState(3).randint(0, config.vocab_size, (4, 16))
        )
        rng = jax.random.PRNGKey(0)

        def loss_plain(p):
            logits, _ = llama.apply(p, ids, config, rng)
            return masked_lm_loss(logits, labels)

        def loss_uneven(p):
            logits, _ = llama.apply_pipelined(
                p, ids, config, num_stages=2, num_microbatches=2,
                rng=rng, stage_depths=(2, 1),
            )
            return masked_lm_loss(logits, labels)

        g_plain = jax.grad(loss_plain)(params)
        g_uneven = jax.grad(loss_uneven)(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-3, atol=5e-3
            ),
            g_plain, g_uneven,
        )

    # budget triage (PR 16): the uneven-stage oracle
    # (test_uneven_gradients_match) and the elastic shrink wedge stay
    # tier-1; the sharded-mesh cross product rides slow
    @pytest.mark.slow
    def test_uneven_on_sharded_mesh(self):
        """Uneven depths through the full accelerate() path on the pipe
        mesh, driven from the Strategy (knob survives JSON round-trip)."""
        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.strategy import Strategy

        strategy = Strategy(
            mesh=MeshPlan(pipe=2, data=2, tensor=2),
            rule_set="llama_pp",
            stage_depths=(2, 1),
        )
        assert Strategy.from_json(strategy.to_json()).stage_depths == (2, 1)

        config = llama.llama_tiny(num_layers=3)

        def loss_fn(params, batch, rng):
            from dlrover_tpu.models.losses import masked_lm_loss

            logits, _ = llama.apply_pipelined(
                params, batch["input_ids"], config,
                num_stages=2, num_microbatches=2, rng=rng,
                stage_depths=strategy.stage_depths,
            )
            return masked_lm_loss(logits, batch["labels"]), {}

        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, config.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, config.vocab_size
            ),
        }
        result = accelerate(
            llama.make_init_fn(config), loss_fn,
            optax.adam(1e-2), batch, strategy=strategy,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for i in range(3):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
        assert losses[-1] < losses[0]

    def test_uneven_rejects_bad_depths(self):
        from dlrover_tpu.parallel.pipeline import (
            stack_stages_interleaved_uneven,
            stack_stages_uneven,
        )

        with pytest.raises(ValueError):  # sum != L
            stack_stages_uneven(jnp.zeros((6, 3)), (2, 2, 3))
        with pytest.raises(ValueError):  # non-positive depth
            stack_stages_uneven(jnp.zeros((6, 3)), (6, 0))
        with pytest.raises(ValueError):  # wrong chunk count for V x P
            stack_stages_interleaved_uneven(
                jnp.zeros((6, 3)), num_stages=2, num_virtual=2,
                depths=(3, 3),
            )
        with pytest.raises(ValueError):  # gpipe path: len != num_stages
            config = llama.llama_tiny(num_layers=4)
            params = llama.init(jax.random.PRNGKey(0), config)
            llama.apply_pipelined(
                params, jnp.zeros((2, 8), jnp.int32), config,
                num_stages=2, num_microbatches=2,
                stage_depths=(2, 1, 1),
            )

    def test_uneven_stacking_mask_layout(self):
        from dlrover_tpu.parallel.pipeline import (
            stack_stages_interleaved_uneven,
            stack_stages_uneven,
        )

        w = jnp.arange(6, dtype=jnp.float32).reshape(6, 1)
        stacked, mask = stack_stages_uneven(w, (3, 2, 1))
        assert stacked.shape == (3, 3, 1)
        np.testing.assert_array_equal(
            np.asarray(mask),
            [[1, 1, 1], [1, 1, 0], [1, 0, 0]],
        )
        # padded slots are zero, real slots keep their layers in order
        np.testing.assert_array_equal(
            np.asarray(stacked[:, :, 0]),
            [[0, 1, 2], [3, 4, 0], [5, 0, 0]],
        )

        stacked_vp, mask_vp = stack_stages_interleaved_uneven(
            w, num_stages=2, num_virtual=2, depths=(1, 2, 2, 1)
        )
        assert stacked_vp.shape == (2, 2, 2, 1)
        # visit order: round 0 = chunks (1, 2), round 1 = chunks (2, 1)
        np.testing.assert_array_equal(
            np.asarray(stacked_vp[:, :, :, 0]),
            [[[0, 0], [1, 2]], [[3, 4], [5, 0]]],
        )
        np.testing.assert_array_equal(
            np.asarray(mask_vp),
            [[[1, 0], [1, 1]], [[1, 1], [1, 0]]],
        )

    def test_outer_head_sharded_over_pipe(self):
        """The post-pipeline final-norm/head must not replicate over the
        pipe axis: with a pipe mesh in scope the logits carry "pipe" on
        the batch dim (the replicated->sharded hop is a comm-free local
        slice, and it cuts norm+head compute by the pipe degree)."""
        config = llama.llama_tiny(num_layers=4)
        params = llama.init(jax.random.PRNGKey(0), config)
        ids = jnp.zeros((8, 16), jnp.int32)
        mesh = MeshPlan(pipe=2, data=2, tensor=2).build()
        with mesh_ctx(mesh):
            logits, _ = jax.jit(
                lambda p, i: llama.apply_pipelined(
                    p, i, config, num_stages=2, num_microbatches=2
                )
            )(params, ids)
        spec = logits.sharding.spec
        batch_spec = spec[0] if len(spec) else None
        flat = (batch_spec if isinstance(batch_spec, tuple)
                else (batch_spec,))
        assert "pipe" in flat, f"head output not pipe-sharded: {spec}"


class TestElasticPipelined:
    """Elastic world change UNDER pipeline parallelism: the pipe/tensor
    axes are topology-bound and survive the shrink (adjust_to_world),
    data/fsdp absorb it with grad-accum keeping the global batch; the
    checkpoint restores through the shrunk pipelined shardings and the
    training trajectory continues. The reference's elasticity only
    reshapes the DP degree — this proves the same guarantee holds with
    a live pipe axis."""

    def test_world_shrink_preserves_pipe_and_trajectory(self, tmp_path):
        from dlrover_tpu.models.losses import masked_lm_loss
        from dlrover_tpu.parallel.strategy import Strategy
        from dlrover_tpu.trainer.elastic import ElasticTrainer

        config = llama.llama_tiny(num_layers=4)
        strategy = Strategy(
            mesh=MeshPlan(pipe=2, data=2, tensor=2),
            rule_set="llama_pp", global_batch_size=8,
        )

        def loss_fn(params, batch, rng):
            logits, _ = llama.apply_pipelined(
                params, batch["input_ids"], config,
                num_stages=2, num_microbatches=2, rng=rng,
            )
            return masked_lm_loss(logits, batch["labels"]), {}

        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, config.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, config.vocab_size
            ),
        }
        devices = jax.devices()
        assert len(devices) >= 8
        trainer = ElasticTrainer(
            llama.make_init_fn(config), loss_fn, optax.adamw(1e-3),
            batch, strategy=strategy, ckpt_dir=str(tmp_path),
            devices=devices[:8],
        )
        state = trainer.prepare()
        for i in range(2):
            state, metrics = trainer.step(state, batch)
        trainer.save(state, force=True)
        assert trainer.latest_checkpoint_step() == int(state.step)

        # control step on the unshrunk world (on a copy: donation)
        _, ctrl = trainer.step(
            jax.tree.map(lambda x: x.copy(), state), batch
        )
        loss_ctrl = float(jax.device_get(ctrl["loss"]))

        state = trainer.on_world_change(state, devices=devices[:4])
        new_plan = trainer.accelerated.strategy.mesh
        assert new_plan.pipe == 2 and new_plan.tensor == 2, new_plan
        assert trainer.accelerated.strategy.grad_accum_steps == 2

        restored = trainer.restore_state()
        assert restored is not None
        state, metrics = trainer.step(restored, batch)
        loss_shrunk = float(jax.device_get(metrics["loss"]))
        trainer.finalize()

        assert abs(loss_shrunk - loss_ctrl) < max(
            5e-3, 5e-3 * abs(loss_ctrl)
        ), f"pipelined trajectory diverged: {loss_shrunk} vs {loss_ctrl}"


class TestUnevenConfigSweep:
    """Schedule-shape sweep for the uneven paths: corner configs that
    the targeted tests don't hit — single-layer chunks everywhere,
    M > P, V=3 rounds, heaviest-chunk-first vs -last layouts."""

    @pytest.mark.parametrize(
        "num_layers,num_stages,num_mb,num_virtual,depths",
        [
            (5, 4, 8, 1, (2, 1, 1, 1)),   # heaviest first, M > P
            (5, 4, 4, 1, (1, 1, 1, 2)),   # heaviest last
            (4, 2, 4, 1, (3, 1)),          # strongly skewed
            (7, 2, 3, 3, (2, 1, 1, 1, 1, 1)),  # V=3, mostly single-layer
            (10, 3, 3, 3, (2, 1, 1, 1, 1, 1, 1, 1, 1)),  # V=3, P=3
        ],
    )
    def test_matches_plain(self, num_layers, num_stages, num_mb,
                           num_virtual, depths):
        assert sum(depths) == num_layers
        config = llama.llama_tiny(num_layers=num_layers)
        params = llama.init(jax.random.PRNGKey(num_layers), config)
        ids = jnp.asarray(
            np.random.RandomState(num_layers).randint(
                0, config.vocab_size, (num_mb * 2, 16)
            )
        )
        rng = jax.random.PRNGKey(7)
        plain, _ = llama.apply(params, ids, config, rng)
        piped, _ = llama.apply_pipelined(
            params, ids, config, num_stages=num_stages,
            num_microbatches=num_mb, rng=rng, num_virtual=num_virtual,
            stage_depths=depths,
        )
        np.testing.assert_allclose(np.asarray(piped), np.asarray(plain),
                                   rtol=2e-4, atol=2e-4)
