"""Brain service: datastores, optimization algorithms, RPC service,
config hot-reload, master-side optimizer integration."""

import json
import os
import time

import pytest

from dlrover_tpu.brain.algorithms import algorithm_names, get_algorithm
from dlrover_tpu.brain.client import (
    BrainClient,
    BrainResourceOptimizer,
    BrainStatsReporter,
)
from dlrover_tpu.brain.config import BrainConfig
from dlrover_tpu.brain.datastore import (
    MemoryDatastore,
    SqliteDatastore,
    new_datastore,
)
from dlrover_tpu.brain.messages import (
    BrainJobMetrics,
    MetricType,
    OptimizeRequest,
)
from dlrover_tpu.brain.service import BrainService, BrainServicer
from dlrover_tpu.common.constants import JobStage, NodeType
from dlrover_tpu.common.node import NodeResource
from dlrover_tpu.master.stats.training_metrics import RuntimeMetric


def _runtime(store, uuid, speed, workers, ps_used_cpu=2.0, ps_cpu=8.0,
             name="job-a"):
    store.persist_metrics(BrainJobMetrics(
        job_uuid=uuid, job_name=name, metric_type=MetricType.RUNTIME_INFO,
        payload={
            "speed": speed, "workers": workers,
            "nodes": {
                NodeType.PS: [{"name": "ps-0", "cpu": ps_cpu,
                               "used_cpu": ps_used_cpu,
                               "memory": 16384, "used_memory": 9000}],
                NodeType.WORKER: [{} for _ in range(workers)],
            },
        },
    ))


class TestDatastore:
    def test_memory_roundtrip(self):
        store = MemoryDatastore()
        _runtime(store, "u1", 10, 2)
        rows = store.get_job_metrics("u1", MetricType.RUNTIME_INFO)
        assert len(rows) == 1 and rows[0].payload["speed"] == 10

    def test_sqlite_roundtrip(self, tmp_path):
        store = SqliteDatastore(str(tmp_path / "brain.db"))
        _runtime(store, "u1", 10, 2)
        _runtime(store, "u2", 5, 1)
        assert sorted(store.list_job_uuids()) == ["u1", "u2"]
        rows = store.get_job_metrics("u1")
        assert rows[0].payload["workers"] == 2
        # durable across connections
        store2 = SqliteDatastore(str(tmp_path / "brain.db"))
        assert sorted(store2.list_job_uuids()) == ["u1", "u2"]

    def test_spec_factory(self, tmp_path):
        assert isinstance(new_datastore("memory"), MemoryDatastore)
        assert isinstance(
            new_datastore(f"sqlite://{tmp_path}/x.db"), SqliteDatastore
        )
        with pytest.raises(ValueError):
            new_datastore("mysql://nope")


class TestAlgorithms:
    def test_registry_covers_reference_algorithms(self):
        names = algorithm_names()
        for expected in [
            "optimize_job_ps_cold_create_resource",
            "optimize_job_ps_create_resource",
            "optimize_job_ps_init_adjust_resource",
            "optimize_job_ps_oom_resource",
            "optimize_job_hot_ps_resource",
            "optimize_job_worker_create_resource",
            "optimize_job_worker_create_oom_resource",
            "optimize_job_worker_resource",
        ]:
            assert expected in names

    def test_worker_resource_grows_with_headroom(self):
        store = MemoryDatastore()
        for i in range(8):
            _runtime(store, "u1", speed=4.0 * 2, workers=2,
                     ps_used_cpu=2.0)
        plan = get_algorithm("optimize_job_worker_resource")(
            store, OptimizeRequest(job_uuid="u1", config={})
        )
        assert plan.success
        # util 0.25, threshold 0.8 -> capped at 2x current
        assert plan.group_resources[NodeType.WORKER].count == 4

    def test_worker_resource_stops_when_ps_saturated(self):
        store = MemoryDatastore()
        for _ in range(8):
            _runtime(store, "u1", speed=8, workers=2, ps_used_cpu=7.5)
        plan = get_algorithm("optimize_job_worker_resource")(
            store, OptimizeRequest(job_uuid="u1")
        )
        assert not plan.success and "saturated" in plan.reason

    def test_worker_resource_stops_on_efficiency_drop(self):
        store = MemoryDatastore()
        for _ in range(4):
            _runtime(store, "u1", speed=20, workers=2)
        for _ in range(4):
            _runtime(store, "u1", speed=20, workers=4)  # no speedup
        plan = get_algorithm("optimize_job_worker_resource")(
            store, OptimizeRequest(job_uuid="u1")
        )
        assert not plan.success

    def test_hot_ps_migration(self):
        store = MemoryDatastore()
        _runtime(store, "u1", speed=5, workers=2, ps_used_cpu=7.6)
        plan = get_algorithm("optimize_job_hot_ps_resource")(
            store, OptimizeRequest(job_uuid="u1")
        )
        assert plan.success and plan.node_resources["ps-0"]["cpu"] == 16.0

    def test_ps_init_adjust_from_model(self):
        store = MemoryDatastore()
        store.persist_metrics(BrainJobMetrics(
            job_uuid="u1", metric_type=MetricType.MODEL_FEATURE,
            payload={"param_count": 8_000_000_000},
        ))
        plan = get_algorithm("optimize_job_ps_init_adjust_resource")(
            store, OptimizeRequest(job_uuid="u1")
        )
        assert plan.success
        group = plan.group_resources[NodeType.PS]
        assert group.count == 8  # 8B params * 16B -> capped at 8 PSs
        assert group.memory >= 16384

    def test_oom_doubles_memory(self):
        plan = get_algorithm("optimize_job_worker_create_oom_resource")(
            MemoryDatastore(),
            OptimizeRequest(job_uuid="u1",
                            config={"current_memory": 4096}),
        )
        assert plan.group_resources[NodeType.WORKER].memory == 8192

    def test_create_learns_from_similar_finished_jobs(self):
        store = MemoryDatastore()
        # a finished run of the same recurring job
        store.persist_metrics(BrainJobMetrics(
            job_uuid="old", job_name="nightly-20260701",
            metric_type=MetricType.JOB_META,
            payload={"name": "nightly-20260701"},
        ))
        for _ in range(3):
            _runtime(store, "old", speed=10, workers=6,
                     name="nightly-20260701")
        store.persist_metrics(BrainJobMetrics(
            job_uuid="old", job_name="nightly-20260701",
            metric_type=MetricType.JOB_EXIT_REASON,
            payload={"reason": "succeeded"},
        ))
        plan = get_algorithm("optimize_job_worker_create_resource")(
            store, OptimizeRequest(job_uuid="new",
                                   job_name="nightly-20260728"),
        )
        assert plan.group_resources[NodeType.WORKER].count == 6
        ps_plan = get_algorithm("optimize_job_ps_create_resource")(
            store, OptimizeRequest(job_uuid="new",
                                   job_name="nightly-20260728"),
        )
        # 1.25x headroom over the hottest observed PS
        assert ps_plan.group_resources[NodeType.PS].cpu == pytest.approx(2.5)

    def test_cold_create_without_history(self):
        plan = get_algorithm("optimize_job_ps_create_resource")(
            MemoryDatastore(), OptimizeRequest(job_name="never-seen")
        )
        assert plan.group_resources[NodeType.PS].count == 1


class TestConfig:
    def test_defaults_and_hot_reload(self, tmp_path):
        path = tmp_path / "brain.json"
        path.write_text(json.dumps({
            "stage_algorithms": {JobStage.RUNNING: "optimize_job_hot_ps_resource"},
            "algorithm_configs": {
                "optimize_job_worker_resource": {"max_workers": 16},
            },
        }))
        cfg = BrainConfig(str(path))
        assert cfg.algorithm_for(JobStage.RUNNING) == (
            "optimize_job_hot_ps_resource"
        )
        assert cfg.algorithm_for(JobStage.CREATE) == (
            "optimize_job_ps_create_resource"
        )
        assert cfg.algorithm_config(
            "optimize_job_worker_resource"
        )["max_workers"] == 16
        # rewrite -> picked up on next read (mtime-based)
        time.sleep(0.01)
        path.write_text(json.dumps({
            "stage_algorithms": {JobStage.RUNNING: "optimize_job_worker_resource"},
        }))
        os.utime(path)
        assert cfg.algorithm_for(JobStage.RUNNING) == (
            "optimize_job_worker_resource"
        )


class TestServiceOverRpc:
    @pytest.fixture()
    def service(self):
        svc = BrainService(port=0)
        svc.start()
        yield svc
        svc.stop()

    def test_persist_optimize_query_roundtrip(self, service):
        client = BrainClient(f"127.0.0.1:{service.port}")
        reporter = BrainStatsReporter("u1", "job-a", client=client)
        for _ in range(8):
            reporter.report_runtime_stats(RuntimeMetric(
                speed=8.0,
                running_nodes={
                    NodeType.WORKER: [{}, {}],
                    NodeType.PS: [{"name": "ps-0", "cpu": 8,
                                   "used_cpu": 2.0, "memory": 16384}],
                },
            ))
        plan = client.optimize(OptimizeRequest(
            job_uuid="u1", job_name="job-a", stage=JobStage.RUNNING,
        ))
        assert plan.success
        assert plan.group_resources[NodeType.WORKER].count == 4
        rows = client.get_job_metrics("u1", MetricType.RUNTIME_INFO)
        assert len(rows) == 8
        client.close()

    def test_master_side_optimizer(self, service):
        client = BrainClient(f"127.0.0.1:{service.port}")
        opt = BrainResourceOptimizer("job-a", client=client)
        opt.update_job_uuid("u2")
        # no data yet: RUNNING stage declines, returns None
        assert opt.generate_opt_plan(JobStage.RUNNING) is None
        # OOM recovery always produces a grown plan
        res = opt.generate_oom_recovery_plan(
            "worker-1", NodeResource(cpu=4, memory=4096)
        )
        assert res.memory == 8192
        client.close()

    def test_unknown_message_rejected(self, service):
        servicer = service.servicer
        from dlrover_tpu.common.comm import Response

        out = servicer.report(Response())
        assert not out.success


class TestServicerAlgorithms:
    def test_explicit_algorithm_override(self):
        servicer = BrainServicer()
        plan = servicer.optimize(OptimizeRequest(
            job_uuid="u", job_name="j",
            algorithm="optimize_job_ps_cold_create_resource",
        ))
        assert plan.success
        assert plan.group_resources[NodeType.PS].count == 1

    def test_unknown_stage_fails_cleanly(self):
        plan = BrainServicer().optimize(
            OptimizeRequest(stage="not-a-stage")
        )
        assert not plan.success
