"""Telemetry subsystem: metrics registry + exposition, event timeline,
derived MTTR (preempt drain / NaN rollback in-process; hang relaunch is
covered by the chaos tests), Chrome trace export, the instrumented-run
pins (zero recompiles, ≤5% overhead), lagged master reporting, the
exporter, and the on-demand profile-signal window."""

import json
import os
import signal
import time

import jax
import jax.numpy as jnp
import optax
import pytest

from dlrover_tpu.common.config import get_context
from dlrover_tpu.telemetry import events as events_mod
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.telemetry import (
    EventKind,
    emit_event,
    mttr_report,
    names as tm,
    read_events,
    span,
    tracing,
)
from dlrover_tpu.telemetry.cli import main as telemetry_cli
from dlrover_tpu.telemetry.metrics import (
    Histogram,
    MetricsRegistry,
    get_registry,
    process_registry,
)
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import (
    ReportModelInfoHook,
    TrainExecutor,
    TrainHook,
)


@pytest.fixture(autouse=True)
def _telemetry_on():
    """Every test starts from the default-enabled state and leaves the
    process-global Context clean for the rest of the tier-1 run."""
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


def _make_trainer(**kwargs):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)), "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (4, 2))}
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.sgd(0.1), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)), **kwargs,
    )
    return trainer, batch


# -- registry ---------------------------------------------------------------


class TestMetricsRegistry:
    def test_counter_gauge_histogram_roundtrip(self):
        reg = MetricsRegistry()
        c = reg.counter(tm.TRAIN_STEPS)
        c.inc()
        c.inc(2.0)
        assert c.value == 3.0
        g = reg.gauge(tm.DISPATCH_WINDOW_OCCUPANCY)
        g.set(4)
        g.dec()
        assert g.value == 3.0
        h = reg.histogram(tm.STEP_TIME)
        for v in (0.001, 0.002, 0.004, 0.1):
            h.observe(v)
        assert h.count == 4 and h.sum == pytest.approx(0.107)

    def test_creation_is_idempotent_and_type_checked(self):
        reg = MetricsRegistry()
        assert reg.counter(tm.TRAIN_STEPS) is reg.counter(tm.TRAIN_STEPS)
        with pytest.raises(ValueError):
            reg.gauge(tm.TRAIN_STEPS)

    def test_percentiles_from_buckets(self):
        h = Histogram("h", buckets=(0.01, 0.1, 1.0))
        for _ in range(90):
            h.observe(0.005)
        for _ in range(10):
            h.observe(0.5)
        p50, p95 = h.percentile(0.5), h.percentile(0.95)
        assert p50 is not None and p50 <= 0.01
        assert 0.1 < p95 <= 1.0
        assert Histogram("e", buckets=(1,)).percentile(0.5) is None

    def test_overflow_marker_on_clamped_tails(self):
        """A quantile landing in the +Inf bucket clamps to the last
        finite bound — with_overflow exposes the clamp so diagnosis
        verdicts treat the value as a LOWER bound, not a measurement."""
        h = Histogram("h", buckets=(0.01, 0.1, 1.0))
        for _ in range(10):
            h.observe(50.0)  # way past the last finite bound
        value, overflow = h.percentile(0.5, with_overflow=True)
        assert value == 1.0 and overflow is True
        assert h.percentile(0.5) == 1.0  # legacy shape unchanged
        h2 = Histogram("h2", buckets=(0.01, 0.1, 1.0))
        h2.observe(0.05)
        value, overflow = h2.percentile(0.5, with_overflow=True)
        assert overflow is False and value <= 0.1
        # empty histogram: (None, False)
        h3 = Histogram("h3", buckets=(1.0,))
        assert h3.percentile(0.5, with_overflow=True) == (None, False)

    def test_labeled_series_share_one_exposition_family(self):
        reg = MetricsRegistry()
        reg.gauge(tm.NODE_RSS_MB, labels={"node": "0"}).set(10)
        reg.gauge(tm.NODE_RSS_MB, labels={"node": "1"}).set(20)
        text = reg.render_prometheus()
        assert text.count("# TYPE dlrover_node_rss_mb gauge") == 1
        assert 'dlrover_node_rss_mb{node="0"} 10' in text
        assert 'dlrover_node_rss_mb{node="1"} 20' in text
        assert reg.get(tm.NODE_RSS_MB, labels={"node": "1"}).value == 20
        # a family must hold ONE kind — a labeled sibling of another
        # kind would make the rendered TYPE header lie
        with pytest.raises(ValueError):
            reg.counter(tm.NODE_RSS_MB, labels={"node": "2"})

    def test_windowed_percentile_from_count_deltas(self):
        # the speed log diffs two snapshots so a late regression shows
        # up even after many fast observations (lifetime-cumulative
        # quantiles would bury it)
        from dlrover_tpu.telemetry.metrics import percentile_from_counts

        h = Histogram("h", buckets=(0.01, 0.1, 1.0))
        for _ in range(1000):
            h.observe(0.005)  # long fast history
        snap = h.snapshot_counts()
        for _ in range(10):
            h.observe(0.5)  # the regression window
        window = [c - p for c, p in zip(h.snapshot_counts(), snap)]
        p50 = percentile_from_counts(h.bounds, window, 0.5)
        assert p50 is not None and p50 > 0.1  # window-only, not 0.005
        assert h.percentile(0.5) <= 0.01  # cumulative stays fast

    def test_prometheus_exposition_format(self):
        reg = MetricsRegistry()
        reg.counter(tm.TRAIN_STEPS, help="steps").inc(5)
        h = reg.histogram(tm.STEP_TIME, buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(5.0)
        text = reg.render_prometheus()
        assert "# TYPE dlrover_train_steps_total counter" in text
        assert "dlrover_train_steps_total 5" in text
        # buckets are CUMULATIVE and +Inf equals the total count
        assert 'dlrover_step_time_seconds_bucket{le="0.1"} 1' in text
        assert 'dlrover_step_time_seconds_bucket{le="1"} 2' in text
        assert 'dlrover_step_time_seconds_bucket{le="+Inf"} 3' in text
        assert "dlrover_step_time_seconds_count 3" in text

    def test_disabled_knob_hands_out_null_handles(self):
        get_context().telemetry_enabled = False
        reg = get_registry()
        c = reg.counter(tm.TRAIN_STEPS)
        c.inc(100)
        assert c.value == 0.0
        assert reg.render_prometheus() == ""
        get_context().telemetry_enabled = True
        assert isinstance(get_registry(), MetricsRegistry)


# -- events + MTTR derivation ----------------------------------------------


class TestEventTimeline:
    def test_emit_and_read_roundtrip(self, tmp_path, monkeypatch):
        path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", path)
        rec = emit_event(EventKind.CKPT_SAVE, step=7, stage_seconds=0.1)
        assert rec["seq"] > 0 and rec["pid"] == os.getpid()
        emit_event(EventKind.WORKER_FAILED, error_code="EXIT_9")
        out = read_events(path)
        assert [r["kind"] for r in out] == [
            EventKind.CKPT_SAVE, EventKind.WORKER_FAILED]
        assert out[0]["step"] == 7
        assert out[1]["error_code"] == "EXIT_9"
        assert {"ts", "mono", "pid", "node"} <= set(out[0])

    def test_malformed_lines_are_skipped(self, tmp_path):
        path = tmp_path / "events.jsonl"
        path.write_text(
            '{"kind": "train_start", "ts": 1.0}\n'
            "{torn write\n"
            '{"kind": "train_end", "ts": 2.0}\n'
        )
        assert [r["kind"] for r in read_events(str(path))] == [
            "train_start", "train_end"]

    def test_disabled_telemetry_emits_nothing(self, tmp_path,
                                              monkeypatch):
        path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", path)
        get_context().telemetry_enabled = False
        assert emit_event(EventKind.CKPT_SAVE) == {}
        assert not os.path.exists(path)

    def test_size_capped_rotation_keeps_the_pair_readable(
            self, tmp_path, monkeypatch):
        """Past DLROVER_TPU_EVENTS_MAX_MB the file rotates to `.1`;
        read_events (and so mttr/goodput) reads the rotated pair, so a
        failure edge in the old file still pairs with a recovery edge
        in the new one."""
        path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", path)
        # ~2 KB cap: a handful of records trigger rotation
        monkeypatch.setenv("DLROVER_TPU_EVENTS_MAX_MB",
                           str(2048 / (1024 * 1024)))
        emit_event(EventKind.WORKER_FAILED, error_code="EXIT_9")
        for i in range(20):
            emit_event(EventKind.CKPT_SAVE, step=i, stage_seconds=0.01)
        assert os.path.exists(path + ".1"), "never rotated"
        emit_event(EventKind.WORKERS_STARTED, round=1)
        records = read_events(path)
        kinds = [r["kind"] for r in records]
        assert EventKind.WORKERS_STARTED in kinds
        # the failure edge may have aged out past the retained pair on
        # aggressive caps, but with this cadence it must survive here
        assert EventKind.WORKER_FAILED in kinds
        rep = mttr_report(records)
        assert rep["detail"]["by_scenario"]["worker_failure"]["count"] == 1

    def test_writer_follows_an_external_rotation(self, tmp_path,
                                                 monkeypatch):
        """Multi-process semantics: after ANOTHER process renames the
        shared file, this process's cached fd no longer matches the
        path's inode — the next emit must reopen the fresh file, not
        keep appending to the rotated one forever."""
        path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", path)
        monkeypatch.delenv("DLROVER_TPU_EVENTS_MAX_MB", raising=False)
        emit_event(EventKind.TRAIN_START, step=0)
        os.rename(path, path + ".1")  # "the other process rotated"
        emit_event(EventKind.TRAIN_END, step=5)
        # the new record landed in a FRESH file at the shared path
        assert os.path.exists(path)
        fresh = [r["kind"] for r in events_mod._read_one(path)]
        assert fresh == [EventKind.TRAIN_END]
        # and the pair view still shows both
        assert [r["kind"] for r in read_events(path)] == [
            EventKind.TRAIN_START, EventKind.TRAIN_END]


def _ev(kind, ts, mono=None, pid=1, **kw):
    rec = {"kind": kind, "ts": ts, "pid": pid,
           "mono": mono if mono is not None else ts, "node": "0"}
    rec.update(kw)
    return rec


class TestMttrDerivation:
    def test_pairs_each_failure_kind_with_its_recovery(self):
        events = [
            _ev(EventKind.WORKERS_STARTED, 0.0),  # boot: not a recovery
            _ev(EventKind.WORKER_FAILED, 10.0, error_code="EXIT_137"),
            _ev(EventKind.WORKERS_STARTED, 12.5),
            _ev(EventKind.NONFINITE_STEP, 20.0),
            _ev(EventKind.ROLLBACK_RESTORED, 21.0),
            _ev(EventKind.PREEMPT_NOTICE, 30.0),
            _ev(EventKind.PREEMPT_DRAIN_DONE, 30.75),
            _ev(EventKind.HANG_DETECTED, 40.0),
            _ev(EventKind.WORKERS_STARTED, 44.0),
        ]
        rep = mttr_report(events)
        by = rep["detail"]["by_scenario"]
        assert rep["detail"]["incidents"] == 4
        assert by["worker_failure"]["mean_s"] == 2.5
        assert by["nonfinite_rollback"]["mean_s"] == 1.0
        assert by["preemption_drain"]["mean_s"] == 0.75
        assert by["hang"]["mean_s"] == 4.0
        assert rep["value"] == pytest.approx(
            (2.5 + 1 + 0.75 + 4) / 4, abs=1e-3)  # report rounds to ms
        assert "error" not in rep

    def test_failure_burst_is_one_incident(self):
        events = [
            _ev(EventKind.WORKER_FAILED, 10.0),
            _ev(EventKind.WORKER_FAILED, 10.1),
            _ev(EventKind.WORKER_FAILED, 10.2),
            _ev(EventKind.WORKERS_STARTED, 15.0),
        ]
        rep = mttr_report(events)
        assert rep["detail"]["incidents"] == 1
        # anchored at the FIRST failure edge
        assert rep["value"] == 5.0

    def test_monotonic_clock_used_within_a_process(self):
        # wall clocks disagree wildly; mono deltas are the truth
        events = [
            _ev(EventKind.NONFINITE_STEP, 100.0, mono=50.0, pid=7),
            _ev(EventKind.ROLLBACK_RESTORED, 900.0, mono=52.0, pid=7),
        ]
        assert mttr_report(events)["value"] == 2.0
        # different pids: mono is meaningless, fall back to wall
        events[1]["pid"] = 8
        assert mttr_report(events)["value"] == 800.0

    def test_unrecovered_incident_is_reported_as_error(self):
        rep = mttr_report([_ev(EventKind.HANG_DETECTED, 1.0)])
        assert rep["detail"]["unrecovered"] == 1
        assert "error" in rep


class TestMttrFromChaosRuns:
    """`python -m dlrover_tpu.telemetry mttr` over timelines produced by
    REAL executor fault paths (the chaos tests add the agent-level hang
    relaunch scenario on top of these)."""

    def _mttr(self, path, capsys):
        rc = telemetry_cli(["mttr", "--events", path])
        report = json.loads(capsys.readouterr().out.strip())
        return rc, report

    def test_preempt_drain_mttr_derived(self, tmp_path, monkeypatch,
                                        capsys):
        path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", path)
        trainer, batch = _make_trainer(ckpt_dir=str(tmp_path / "ckpt"))

        class PreemptAt(TrainHook):
            def before_step(self, step):
                if step == 6:
                    os.kill(os.getpid(), signal.SIGTERM)

        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 100,
            hooks=[PreemptAt()],
            conf=Configuration({
                "train_steps": 50, "log_every_steps": 0,
                "train_window": 4,
            }),
        )
        out = executor.train_and_evaluate()
        assert out.get("preempted") is True
        rc, report = self._mttr(path, capsys)
        assert rc == 0, report
        drain = report["detail"]["by_scenario"]["preemption_drain"]
        assert drain["count"] == 1
        assert report["value"] > 0

    def test_nan_rollback_mttr_derived(self, tmp_path, monkeypatch,
                                       capsys):
        from dlrover_tpu.checkpoint import CheckpointInterval

        path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", path)
        trainer, batch = _make_trainer(
            ckpt_dir=str(tmp_path / "ckpt"),
            ckpt_interval=CheckpointInterval(steps=2),
        )
        nan_batch = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
        poisoned = {"armed": True}

        def batches():
            for i in range(100):
                if i == 3 and poisoned["armed"]:
                    poisoned["armed"] = False
                    yield nan_batch
                else:
                    yield batch

        executor = TrainExecutor(
            trainer, train_iter_fn=batches,
            conf=Configuration({
                "train_steps": 6, "log_every_steps": 0,
                "check_finite_every_steps": 1,
                "on_nonfinite": "rollback", "preemption_grace": False,
            }),
        )
        out = executor.train_and_evaluate()
        assert out["step"] >= 6
        rc, report = self._mttr(path, capsys)
        assert rc == 0, report
        rb = report["detail"]["by_scenario"]["nonfinite_rollback"]
        assert rb["count"] == 1
        kinds = [r["kind"] for r in read_events(path)]
        assert EventKind.NONFINITE_STEP in kinds
        assert EventKind.ROLLBACK_RESTORED in kinds
        assert EventKind.CKPT_SAVE in kinds


# -- the instrumented-run acceptance pins ----------------------------------


def _cache_sizes(trainer):
    total = 0
    result = trainer.accelerated
    for fn in (result.train_step, result.train_step_multi):
        if fn is None:
            continue
        inner = getattr(fn, "__wrapped__", fn)
        total += int(getattr(inner, "_cache_size", lambda: 0)())
    return total


class _TimedRegion(TrainHook):
    def __init__(self, trainer, warmup):
        self.trainer = trainer
        self.warmup = warmup
        self.t0 = None
        self.cache_at_t0 = None

    def before_step(self, step):
        if step == self.warmup + 1 and self.t0 is None:
            self.cache_at_t0 = _cache_sizes(self.trainer)
            self.t0 = time.perf_counter()


def _timed_loop(telemetry_on, steps=480, warmup=8):
    get_context().telemetry_enabled = telemetry_on
    trainer, batch = _make_trainer()
    timer = _TimedRegion(trainer, warmup)
    executor = TrainExecutor(
        trainer,
        train_iter_fn=lambda: iter([batch] * (warmup + steps)),
        hooks=[timer],
        conf=Configuration({
            "train_steps": warmup + steps, "log_every_steps": 0,
            "check_finite_every_steps": 1, "train_window": 4,
            "preemption_grace": False,
        }),
    )
    executor.train_and_evaluate()
    dt = time.perf_counter() - timer.t0
    recompiles = _cache_sizes(trainer) - timer.cache_at_t0
    get_context().telemetry_enabled = True
    return dt, recompiles


class TestInstrumentedRunPins:
    def test_exposition_trace_overhead_and_zero_recompiles(self):
        """The acceptance pin: one short instrumented run yields a
        well-formed Prometheus exposition and a Perfetto-openable trace,
        with zero recompiles and ≤5% step-loop overhead vs the bare
        loop. Run-to-run drift on a shared 1-core host (±10%) dwarfs
        the real per-step cost (~1-2µs), so the gate compares
        BACK-TO-BACK pairs (alternating order) and takes the median of
        per-pair ratios — adjacent runs share the drift."""
        steps = 480
        process_registry().reset()
        tracing.clear()
        recompiles = 0
        inst_runs = 0

        def leg(instrumented, best_of):
            """One timed leg; ``best_of`` > 1 takes the MIN over
            repeats — the classic floor estimator that filters one-off
            scheduler stalls, which on this box are the whole residual
            flake (the true cost is a lower envelope)."""
            nonlocal recompiles, inst_runs
            best = None
            for _ in range(best_of):
                dt, rc = _timed_loop(instrumented, steps)
                recompiles += rc
                if instrumented:
                    inst_runs += 1
                best = dt if best is None else min(best, dt)
            return best

        def paired_median(pairs=3, best_of=1):
            ratios = []
            for i in range(pairs):
                if i % 2 == 0:
                    dt_b = leg(False, best_of)
                    dt_i = leg(True, best_of)
                else:
                    dt_i = leg(True, best_of)
                    dt_b = leg(False, best_of)
                ratios.append(dt_i / dt_b)
            return sorted(ratios)[len(ratios) // 2]

        # De-flake (ISSUE 9 satellite): a single attempt's median
        # still failed ~1/3 of CLEAN-tree runs on this shared 1-core
        # box. Up to 3 attempts, gate on the MINIMUM of the attempt
        # medians, stopping early on the first pass (the common case
        # stays one attempt of 3 pairs). Min-selection is DELIBERATELY
        # biased low — noise on a baseline leg can deflate a ratio
        # too, so a marginal real regression (~6-7%) could slip one
        # attempt — and that is the accepted trade: the gate is a
        # tripwire for the LARGE instrumentation regressions this
        # suite has actually caught (≥10%, e.g. PR 8's capture
        # placement at 11-15%), where every attempt fails, while a
        # clean tree stops failing tier-1 one run in three.
        # Retry attempts escalate to BEST-OF-2 legs (ISSUE 15
        # satellite): min-of-medians alone still left a ~1/27 residual
        # flake — one scheduler stall landing on a baseline leg of
        # every attempt. Taking each retry leg as the min of two runs
        # floors out single-run stalls on either side; the common case
        # (first attempt passes) costs exactly what it used to.
        medians = [paired_median()]
        while medians[-1] - 1.0 > 0.05 and len(medians) < 3:
            medians.append(paired_median(best_of=2))
        assert recompiles == 0, "recompile inside the timed region"
        overhead = min(medians) - 1.0
        assert overhead <= 0.05, (
            f"telemetry overhead {overhead:.1%} above the 5% budget "
            f"(attempt medians {[round(m, 3) for m in medians]})"
        )

        # Prometheus exposition reflects the instrumented runs
        text = process_registry().render_prometheus()
        assert "# TYPE dlrover_step_time_seconds histogram" in text
        assert "# TYPE dlrover_train_steps_total counter" in text
        h = process_registry().get(tm.STEP_TIME)
        assert h.count >= inst_runs * steps
        c = process_registry().get(tm.TRAIN_STEPS)
        assert c.value >= inst_runs * steps
        assert process_registry().get(
            tm.STEP_DISPATCH_TIME).count >= inst_runs * steps
        assert process_registry().get(tm.STEP_HOST_SYNC_TIME).count > 0

        # Chrome/Perfetto trace export carries the pipeline spans
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            out = os.path.join(d, "trace.json")
            n = tracing.export_chrome_trace(out)
            assert n > 0
            payload = json.load(open(out))
            names_seen = {e["name"] for e in payload["traceEvents"]}
            assert "step_dispatch" in names_seen
            assert "host_sync" in names_seen
            for e in payload["traceEvents"]:
                assert e["ph"] == "X" and "ts" in e and "dur" in e

    def test_window_and_lag_gauges_track_the_pipeline(self):
        process_registry().reset()
        trainer, batch = _make_trainer()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 40,
            conf=Configuration({
                "train_steps": 40, "log_every_steps": 0,
                "train_window": 4, "preemption_grace": False,
            }),
        )
        executor.train_and_evaluate()
        g = process_registry().get(tm.DISPATCH_WINDOW_OCCUPANCY)
        lag = process_registry().get(tm.LAGGED_METRIC_AGE)
        assert g is not None and 0 <= g.value <= 4
        # after the final drain the lag of the LAST materialization is 0
        assert lag is not None and lag.value == 0


# -- lagged master reporting (stats reporter under the async window) --------


class _MaterializeTracker(TrainHook):
    """Records the newest step whose metrics have reached the host —
    placed BEFORE the report hook, so at report time it reflects what
    has genuinely materialized."""

    def __init__(self):
        self.newest = 0

    def after_step(self, step, metrics):
        self.newest = max(self.newest, step)


class TestLaggedReporting:
    def test_reported_global_step_never_ahead_of_materialized(self):
        tracker = _MaterializeTracker()
        reported = []

        class Client:
            def report_global_step(self, step, **kw):
                # the invariant under train_window > 0: a step may only
                # be reported once its metrics are host-materialized
                assert step <= tracker.newest, (
                    f"reported step {step} ahead of materialized "
                    f"{tracker.newest}"
                )
                reported.append(step)

            def report_model_info(self, info):
                pass

        trainer, batch = _make_trainer()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 64,
            hooks=[tracker, ReportModelInfoHook(Client(), every_steps=4)],
            conf=Configuration({
                "train_steps": 64, "log_every_steps": 0,
                "train_window": 4, "preemption_grace": False,
            }),
        )
        executor.train_and_evaluate()
        assert reported == list(range(4, 65, 4))

    def test_dead_master_counts_failures_and_never_raises(self):
        process_registry().reset()

        class DeadClient:
            def report_global_step(self, step, **kw):
                raise ConnectionError("master gone")

            def report_model_info(self, info):
                raise ConnectionError("master gone")

        trainer, batch = _make_trainer()
        hook = ReportModelInfoHook(DeadClient(), param_count=10,
                                   every_steps=1)
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 6,
            hooks=[hook],
            conf=Configuration({
                "train_steps": 6, "log_every_steps": 0,
                "train_window": 4, "preemption_grace": False,
            }),
        )
        out = executor.train_and_evaluate()  # must not raise
        assert out["step"] == 6
        failures = process_registry().get(tm.MASTER_REPORT_FAILURES)
        # 6 per-step reports + the begin() model-info report
        assert failures is not None and failures.value == 7
        ok = process_registry().get(tm.MASTER_REPORTS)
        assert ok is None or ok.value == 0


# -- exporter + CLI ---------------------------------------------------------


class TestExporterAndCli:
    def test_http_exposition_and_events(self):
        import urllib.request

        from dlrover_tpu.telemetry.exporter import MetricsExporter

        process_registry().counter(tm.TRAIN_STEPS).inc(3)
        emit_event(EventKind.TRAIN_START, step=0)
        exporter = MetricsExporter(port=0).start()
        try:
            base = f"http://127.0.0.1:{exporter.port}"
            body = urllib.request.urlopen(
                base + "/metrics", timeout=5).read().decode()
            assert "dlrover_train_steps_total" in body
            events = json.loads(urllib.request.urlopen(
                base + "/events?n=5", timeout=5).read().decode())
            assert isinstance(events, list) and events
            assert urllib.request.urlopen(
                base + "/healthz", timeout=5).status == 200
        finally:
            exporter.stop()

    def test_tpurun_metrics_dumps_local_registry(self, capsys):
        from dlrover_tpu.trainer.run import main as tpurun

        process_registry().counter(tm.TRAIN_STEPS).inc()
        assert tpurun(["metrics"]) == 0
        assert "dlrover_train_steps_total" in capsys.readouterr().out

    def test_cli_events_filter(self, tmp_path, capsys):
        path = str(tmp_path / "ev.jsonl")
        with open(path, "w") as fh:
            fh.write(json.dumps(
                {"kind": "train_start", "ts": 1.0}) + "\n")
            fh.write(json.dumps(
                {"kind": "ckpt_save", "ts": 2.0}) + "\n")
        assert telemetry_cli(
            ["events", "--events", path, "--kind", "ckpt_save"]) == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 1 and json.loads(out[0])["kind"] == "ckpt_save"

    def test_mttr_cli_requires_a_timeline(self, monkeypatch):
        monkeypatch.delenv("DLROVER_TPU_EVENTS_FILE", raising=False)
        get_context().telemetry_events_file = ""
        assert telemetry_cli(["mttr"]) == 2


# -- on-demand device-profile window ----------------------------------------


class TestProfileSignalWindow:
    def test_sigusr2_opens_one_bounded_window(self, monkeypatch):
        calls = {"start": [], "stop": 0}
        monkeypatch.setattr(
            jax.profiler, "start_trace",
            lambda d: calls["start"].append(d))

        def _stop():
            calls["stop"] += 1

        monkeypatch.setattr(jax.profiler, "stop_trace", _stop)

        class KickAt(TrainHook):
            def before_step(self, step):
                if step == 4:
                    os.kill(os.getpid(), signal.SIGUSR2)

        trainer, batch = _make_trainer()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 12,
            hooks=[KickAt()],
            conf=Configuration({
                "train_steps": 12, "log_every_steps": 0,
                "train_window": 2, "preemption_grace": False,
                "profile_signal": "USR2", "trace_num_steps": 2,
            }),
        )
        executor.train_and_evaluate()
        assert len(calls["start"]) == 1
        assert "dlrover_tpu_xprof" in calls["start"][0]
        assert calls["stop"] == 1
        # disposition restored: a later USR2 must not re-arm profiling
        assert signal.getsignal(signal.SIGUSR2) in (
            signal.SIG_DFL, signal.Handlers.SIG_DFL)
