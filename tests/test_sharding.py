"""Dynamic data sharding: splitters, queues, recovery, checkpoint."""

from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.shard.batch_dataset_manager import BatchDatasetManager
from dlrover_tpu.master.shard.dataset_splitter import (
    DatasetSplitter,
    StreamingDatasetSplitter,
    TableDatasetSplitter,
    TextDatasetSplitter,
)
from dlrover_tpu.master.shard.task_manager import TaskManager


class TestSplitters:
    def test_table_splitter_epochs(self):
        sp = TableDatasetSplitter("ds", 100, shard_size=32, num_epochs=2)
        shards = sp.create_shards()
        assert [s.size for s in shards] == [32, 32, 32, 4]
        assert not sp.epoch_finished()
        sp.create_shards()
        assert sp.epoch_finished()
        assert sp.create_shards() == []

    def test_text_splitter_indices(self):
        sp = TextDatasetSplitter("ds", 10, shard_size=4, num_epochs=1,
                                 shuffle=True)
        shards = sp.create_shards()
        all_indices = sorted(
            i for s in shards for i in s.record_indices
        )
        assert all_indices == list(range(10))

    def test_streaming_splitter_grows(self):
        sp = StreamingDatasetSplitter("ds", 10, shard_size=5)
        assert len(sp.create_shards()) == 2
        sp.add_records(7)
        shards = sp.create_shards()
        assert [s.size for s in shards] == [5, 2]
        assert not sp.epoch_finished()
        sp.mark_finished()
        assert sp.epoch_finished()

    def test_factory(self):
        sp = DatasetSplitter.create("d", 10, 2, 1, storage_type="text",
                                    num_minibatches_per_shard=3)
        assert isinstance(sp, TextDatasetSplitter)
        assert sp.shard_size == 6


class TestBatchDatasetManager:
    def _manager(self, size=20, shard=5, epochs=1):
        sp = TableDatasetSplitter("ds", size, shard, epochs)
        return BatchDatasetManager(sp)

    def test_dispatch_and_complete(self):
        m = self._manager()
        t0 = m.get_task(node_id=0)
        t1 = m.get_task(node_id=1)
        assert t0.task_id != t1.task_id
        assert len(m.doing) == 2
        ok, task = m.report_task_status(t0.task_id, success=True)
        assert ok and task.shard.size == 5
        assert t0.task_id not in m.doing

    def test_failure_requeues_front(self):
        m = self._manager()
        t0 = m.get_task(0)
        m.report_task_status(t0.task_id, success=False)
        again = m.get_task(0)
        assert again.shard.start == t0.shard.start

    def test_batch_done_completes_by_record_count(self):
        m = self._manager(size=10, shard=5)
        t0 = m.get_task(0)
        assert m.report_batch_done(0, 3) == []
        completed = m.report_batch_done(0, 2)
        assert completed == [t0.task_id]

    def test_dead_worker_recovery(self):
        m = self._manager()
        t0 = m.get_task(0)
        m.get_task(1)
        m.recover_tasks(0)
        assert all(d.node_id != 0 for d in m.doing.values())
        assert any(t.task_id == t0.task_id for t in m.todo)

    def test_completed(self):
        m = self._manager(size=5, shard=5)
        t = m.get_task(0)
        assert not m.completed()
        m.report_task_status(t.task_id, True)
        assert m.completed()

    def test_checkpoint_roundtrip(self):
        m = self._manager(size=20, shard=5)
        t = m.get_task(0)  # one doing
        ckpt = m.checkpoint()
        # a fresh manager on a restarted master
        m2 = self._manager(size=20, shard=5)
        m2.restore_checkpoint(ckpt)
        # all 4 shards pending again (doing shard included)
        starts = sorted(t.shard.start for t in m2.todo)
        assert starts == [0, 5, 10, 15]
        assert t.shard.start in starts


class TestTaskManager:
    def test_end_to_end_dataset_flow(self):
        sm = SpeedMonitor()
        tm = TaskManager(speed_monitor=sm)
        tm.new_dataset("train", dataset_size=12, batch_size=3,
                       num_epochs=1, num_minibatches_per_shard=2)
        served = 0
        while True:
            task = tm.get_dataset_task(0, "train")
            if task.task_id < 0:
                break
            served += 1
            tm.report_dataset_task("train", task.task_id, success=True)
        assert served == 2  # 12 records / (3*2) per shard
        assert tm.finished()

    def test_recover_on_node_failure(self):
        tm = TaskManager()
        tm.new_dataset("train", 12, 3, num_minibatches_per_shard=2)
        t = tm.get_dataset_task(5, "train")
        assert t.task_id >= 0
        tm.recover_tasks(5)
        t2 = tm.get_dataset_task(6, "train")
        assert t2.shard.start == t.shard.start

    def test_shard_checkpoint_through_manager(self):
        tm = TaskManager()
        tm.new_dataset("train", 12, 3, num_minibatches_per_shard=2)
        tm.get_dataset_task(0, "train")
        ckpt = tm.get_shard_checkpoint("train")
        tm2 = TaskManager()
        tm2.new_dataset("train", 12, 3, num_minibatches_per_shard=2)
        tm2.restore_shard_checkpoint("train", ckpt)
        count = 0
        while tm2.get_dataset_task(0, "train").task_id >= 0:
            count += 1
        assert count == 2
