"""Native C++ runtime: build, shm ring transport, host ops.

Parity targets: tfplus scaffold (`tfplus/tfplus/cc/demo.cc` loaded via
`python/demo.py`), atorch shm transport (`atorch/atorch/data/
shm_context.py`, `shm_dataloader.py`).
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from dlrover_tpu.native import native_available

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native toolchain unavailable"
)


class TestBuild:
    def test_library_builds_and_loads(self):
        from dlrover_tpu.native import load_library

        lib = load_library()
        assert lib.shm_ring_create is not None


class TestHostOps:
    def test_pack_sequences_matches_fallback(self):
        from dlrover_tpu.native.host_ops import pack_sequences

        tokens = np.arange(20, dtype=np.int32)
        offsets = np.array([0, 3, 10, 20], dtype=np.int64)
        ids_n, mask_n = pack_sequences(tokens, offsets, 8, pad_id=-1)
        ids_p, mask_p = pack_sequences(
            tokens, offsets, 8, pad_id=-1, use_native=False
        )
        np.testing.assert_array_equal(ids_n, ids_p)
        np.testing.assert_array_equal(mask_n, mask_p)
        # seq 2 has 10 tokens -> truncated to 8
        np.testing.assert_array_equal(ids_n[2], np.arange(10, 18))

    def test_shuffle_native_matches_fallback_and_is_permutation(self):
        from dlrover_tpu.native.host_ops import shuffle_indices

        got = shuffle_indices(100, seed=42)
        ref = shuffle_indices(100, seed=42, use_native=False)
        np.testing.assert_array_equal(got, ref)
        np.testing.assert_array_equal(np.sort(got), np.arange(100))
        assert not np.array_equal(got, np.arange(100))

    def test_shift_labels(self):
        from dlrover_tpu.native.host_ops import shift_labels

        ids = np.array([[1, 2, 3, 0]], np.int32)
        mask = np.array([[1, 1, 1, 0]], np.int32)
        labels = shift_labels(ids, mask)
        np.testing.assert_array_equal(labels, [[2, 3, -100, -100]])
        ref = shift_labels(ids, mask, use_native=False)
        np.testing.assert_array_equal(labels, ref)


class TestShmRing:
    def test_roundtrip_same_process(self):
        from dlrover_tpu.native.shm_ring import ShmBatchRing

        name = f"/dlrover_test_{os.getpid()}_rt"
        with ShmBatchRing(name, slot_bytes=1 << 16, n_slots=4) as ring:
            batch = {
                "x": np.arange(12, dtype=np.float32).reshape(3, 4),
                "y": np.array([1, 2, 3], np.int64),
            }
            ring.put(batch)
            assert ring.qsize() == 1
            got = ring.get()
            np.testing.assert_array_equal(got["x"], batch["x"])
            np.testing.assert_array_equal(got["y"], batch["y"])

    def test_oversized_batch_rejected(self):
        from dlrover_tpu.native.shm_ring import ShmBatchRing

        name = f"/dlrover_test_{os.getpid()}_big"
        with ShmBatchRing(name, slot_bytes=256, n_slots=2) as ring:
            with pytest.raises(ValueError):
                ring.put({"x": np.zeros(1000, np.float32)})

    def test_close_unblocks_consumer(self):
        from dlrover_tpu.native.shm_ring import RingClosed, ShmBatchRing

        name = f"/dlrover_test_{os.getpid()}_close"
        with ShmBatchRing(name, slot_bytes=1 << 12, n_slots=2) as ring:
            ring.put({"x": np.ones(2, np.float32)})
            ring.close()
            ring.get()  # drains the queued batch
            with pytest.raises(RingClosed):
                ring.get(timeout=5)

    def test_cross_process_transport(self):
        from dlrover_tpu.native.shm_ring import RingClosed, ShmBatchRing

        name = f"/dlrover_test_{os.getpid()}_xp"
        ring = ShmBatchRing(name, slot_bytes=1 << 16, n_slots=4)
        try:
            ctx = mp.get_context("spawn")
            proc = ctx.Process(
                target=_producer_entry, args=(name, 5), daemon=True
            )
            proc.start()
            got = []
            while True:
                try:
                    got.append(ring.get(timeout=30))
                except RingClosed:
                    break
            proc.join(timeout=10)
            assert len(got) == 5
            for i, b in enumerate(got):
                np.testing.assert_array_equal(
                    b["step"], np.full((4,), i, np.int32)
                )
        finally:
            ring.free()


def _producer_entry(name: str, n: int):
    from dlrover_tpu.native.shm_ring import ShmBatchRing

    ring = ShmBatchRing.attach(name, slot_bytes=1 << 16)
    for i in range(n):
        ring.put({"step": np.full((4,), i, np.int32)})
    ring.close()


class TestShmDataLoader:
    def test_end_to_end_with_coworkers(self):
        from dlrover_tpu.trainer.shm_dataloader import ShmDataLoader

        with ShmDataLoader(_range_producer, num_workers=2,
                           slot_bytes=1 << 16, n_slots=4) as loader:
            batches = list(loader)
        seen = sorted(int(b["value"][0]) for b in batches)
        assert seen == list(range(8))

    def test_prefetcher_preserves_order(self):
        from dlrover_tpu.trainer.shm_dataloader import DevicePrefetcher

        out = list(DevicePrefetcher(iter(range(10)), lambda x: x * 2))
        assert out == [x * 2 for x in range(10)]


def _range_producer(worker_rank: int, num_workers: int):
    for i in range(worker_rank, 8, num_workers):
        yield {"value": np.full((2,), i, np.int32)}
