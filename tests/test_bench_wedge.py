"""Wedge-proofing of the headline bench (the round-3 tunnel incident).

Three properties, each driven through ``python bench.py`` like the
driver does:

- a failed backend probe emits ERROR artifacts that embed the last
  committed good measurement (``last_good``) instead of erasing the
  provenance chain;
- a measurement that hangs (wedged compile) is KILLED by the
  supervisor's subprocess timeout and reported, never hung;
- the happy path still produces a real measurement through the
  supervisor -> worker indirection.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(env_overrides, timeout=560):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env.update(env_overrides)
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )


def _tail_json(proc):
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no JSON output; stderr: {proc.stderr[-2000:]}"
    return json.loads(lines[-1])


@pytest.mark.slow
def test_failed_probe_preserves_last_good(tmp_path):
    """A wedged/unavailable backend (simulated: bogus platform name)
    must fail BOTH phases loudly while each error artifact points at
    the last committed good number and the commit that carries it."""
    mttr_path = str(tmp_path / "MTTR.json")
    proc = _run_bench({
        "BENCH_PLATFORM": "bogus-platform",
        "BENCH_MTTR_PATH": mttr_path,
    })
    assert proc.returncode == 1
    rec = _tail_json(proc)
    assert rec["metric"] == "llama_pretrain_mfu"
    assert rec["value"] == 0.0 and rec["error"]
    # provenance chain intact: the round-2 driver-verified MFU
    assert rec["last_good"]["value"] > 0.4, rec
    assert rec["last_good"]["commit"], rec
    assert rec["last_good"]["artifact"].startswith("BENCH_r"), rec

    with open(mttr_path) as f:
        mttr = json.loads(f.read())
    assert mttr["metric"] == "recovery_mttr_s"
    assert mttr["value"] == 0.0 and mttr["error"]
    # the committed measurement survives the error record (the chain
    # must carry whatever the last on-chip capture WAS — even a capture
    # that missed the 90 s budget, like r5's anomalous 91.9 s — so no
    # upper bound here: this asserts provenance, not performance)
    assert 0 < mttr["last_good"]["value"] < float("inf"), mttr
    assert mttr["last_good"]["commit"], mttr
    # and the probe was retried once before giving up
    assert proc.stderr.count("retrying once") >= 1, proc.stderr[-1500:]


def test_hung_measurement_is_killed_not_hung(tmp_path):
    """BENCH_MFU_TIMEOUT bounds the worker: a wedged measurement dies
    with the worker subprocess; the bench reports and preserves
    last_good. The hang is INJECTED (BENCH_MFU_TEST_HANG blocks on an
    event inside the timed region) so the contract is provable
    compile-independently — the old formulation raced the 3s timeout
    against real compile time, which a warm persistent compile cache
    wins, turning the test into an environmental coin flip."""
    proc = _run_bench({
        "BENCH_PLATFORM": "cpu",  # probe succeeds fast
        "BENCH_SKIP_RECOVERY": "1",
        "BENCH_MFU_TIMEOUT": "3",
        "BENCH_MFU_TEST_HANG": "1",
        "JAX_PLATFORMS": "cpu",
    }, timeout=420)
    assert proc.returncode == 1
    rec = _tail_json(proc)
    assert "worker killed" in rec["error"], rec
    # both attempts bounded, re-probe ran between them
    assert "attempt 2" in rec["error"], rec
    assert rec["last_good"]["value"] > 0, rec


@pytest.mark.slow
def test_smoke_mfu_through_supervisor():
    """Happy path: the supervisor->worker indirection still measures."""
    proc = _run_bench({
        "BENCH_PLATFORM": "cpu",
        "BENCH_SKIP_RECOVERY": "1",
        "BENCH_STEPS": "2",
        "JAX_PLATFORMS": "cpu",
    })
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = _tail_json(proc)
    assert rec["metric"] == "llama_pretrain_mfu"
    assert rec["value"] > 0 and "error" not in rec
    assert rec["detail"]["final_loss"] > 0


@pytest.mark.slow
def test_smoke_packed_preset():
    """BENCH_PACKED: segmented batches flow through the whole bench and
    attention FLOPs are counted per document (doc_len caps the span)."""
    proc = _run_bench({
        "BENCH_PLATFORM": "cpu",
        "BENCH_SKIP_RECOVERY": "1",
        "BENCH_STEPS": "2",
        "BENCH_PACKED": "1",
        "BENCH_DOC_LEN": "32",
        "JAX_PLATFORMS": "cpu",
    })
    assert proc.returncode == 0, proc.stderr[-2000:]
    rec = _tail_json(proc)
    assert rec["value"] > 0 and "error" not in rec


def test_dispatch_wedge_hits_target_with_parity_and_no_recompiles():
    """The ISSUE 3 acceptance wedge, in-process (tier-1): on the tiny
    CPU-mesh model, window=4 + steps_per_call=8 must reach >= 1.5x
    steps/sec over the synchronous loop, with ZERO recompiles after
    warmup and bitwise-identical final params across all three modes
    ({sync, window, window+scan} over the same batch stream)."""
    import bench

    env_keys = {"BENCH_DISPATCH_STEPS": "128"}
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    try:
        rec = bench.dispatch_result()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rec["metric"] == "dispatch_pipeline_speedup"
    assert "error" not in rec, rec
    detail = rec["detail"]
    assert detail["params_bitwise_identical"] is True
    assert detail["recompiles_after_warmup"] == 0
    assert detail["train_window"] == 4
    assert detail["steps_per_call"] == 8
    # the acceptance bar (vs_baseline normalizes against the 1.5x
    # target; measured ~2.4x on the idle tier-1 box — headroom for a
    # loaded one)
    assert rec["value"] >= bench.DISPATCH_SPEEDUP_TARGET, rec
    assert rec["vs_baseline"] >= 1.0


def test_phase1_wedge_preserves_last_good():
    """A phase-1 recovery worker that never reaches a committed
    checkpoint (the observed mid-session tunnel wedge: device client up,
    first compile never returns) must produce an error artifact that
    still embeds the last committed MTTR — the in-function error
    returns go through _error_line like every other failure path."""
    import bench

    env_keys = {"BENCH_PLATFORM": "cpu", "BENCH_RECOVERY_TIMEOUT": "2"}
    saved = {k: os.environ.get(k) for k in env_keys}
    os.environ.update(env_keys)
    try:
        rec = bench.recovery_result()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert rec["metric"] == "recovery_mttr_s"
    assert rec["value"] == 0.0 and rec["error"]
    assert 0 < rec["last_good"]["value"] < float("inf"), rec
    assert rec["last_good"]["commit"], rec
