"""Data-plane observability (ISSUE 9): shard-dispatch & input-pipeline
accounting with input-bound diagnosis.

Worker side: ShardingClient fetch/complete instruments + the batch-done
credit-restore fix, DevicePreloader queue-depth/wait instruments, the
executor's input-wait fraction (absent-not-zero). Master side:
per-dataset shard-lifecycle gauges (created at first dispatch,
retracted at completion), timeout-recovery events, mid-epoch
checkpoint-resume accounting. Diagnosis + control: the straggler
verdict's input-bound label, the runtime optimizer's input-bound
replan gate, the goodput input-wait column, and the ``tpurun data``
CLI (live + forensic must agree on shard counts). The e2e wedge: one
node's dataloader injected ~30 ms/batch slow is labeled INPUT-bound
(not comm/compute) and program replans are declined with
``PLAN_REJECTED reason=input_bound`` until the injection clears."""

import io
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.sharding_client import (
    IndexShardingClient,
    ShardingClient,
)
from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
from dlrover_tpu.master.monitor.straggler import StragglerDetector
from dlrover_tpu.master.optimizer import RuntimeOptimizer
from dlrover_tpu.master.shard.task_manager import TaskManager
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.telemetry import (
    EventKind,
    names as tm,
    read_events,
    recent_events,
)
from dlrover_tpu.telemetry.events import clear_ring
from dlrover_tpu.telemetry.goodput import derive_goodput
from dlrover_tpu.telemetry.metrics import process_registry
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.data import DevicePreloader, ElasticDataLoader
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import (
    ElasticDataShardReportHook,
    NodeRuntimeReportHook,
    TrainExecutor,
    TrainHook,
)

BOUNDS = [0.001, 0.005, 0.01, 0.05, 0.1, 1.0]


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


def _make_trainer(**kwargs):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)), "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (4, 2))}
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.sgd(0.1), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)), **kwargs,
    )
    return trainer, batch


def _run_json_cli(argv):
    """Invoke `tpurun <argv>` capturing stdout as parsed JSON."""
    from dlrover_tpu.trainer.run import main as tpurun

    buf, prev = io.StringIO(), sys.stdout
    sys.stdout = buf
    try:
        rc = tpurun(argv)
    finally:
        sys.stdout = prev
    return rc, json.loads(buf.getvalue())


# -- worker side: sharding client ---------------------------------------------


class _FlakyClient:
    """Minimal master-client stand-in whose batch-done RPC fails N
    times before succeeding."""

    def __init__(self, failures=0):
        self.failures = failures
        self.records = []

    def report_dataset_shard_params(self, **kw):
        pass

    def get_task(self, name):
        return None

    def report_batch_done(self, name, records):
        if self.failures > 0:
            self.failures -= 1
            raise OSError("master briefly away")
        self.records.append(records)


class TestShardingClientInstrumentation:
    def test_fetch_and_complete_instruments(self):
        process_registry().reset()
        master = start_local_master()
        try:
            client = MasterClient(master.addr, node_id=0)
            sc = ShardingClient(client, "inst-ds", batch_size=4,
                                dataset_size=16,
                                num_minibatches_per_shard=2)
            while sc.fetch_shard() is not None:
                sc.report_task_done()
            reg = process_registry()
            assert reg.get(tm.DATA_SHARDS_FETCHED).value == 2
            assert reg.get(tm.DATA_SHARDS_COMPLETED).value == 2
            # the fetch RPC latency was measured (one probe returns
            # None at exhaustion — observed too, it is a real wait)
            assert reg.get(tm.DATA_SHARD_FETCH_TIME).count >= 2
            client.close()
        finally:
            master.stop()

    def test_failed_batch_report_restores_the_credit(self):
        """The lost-credit fix: a failed report RPC must re-queue the
        pending count (and count the retry) so the shard completes by
        the NEXT report instead of a timeout re-dispatch that re-reads
        consumed data."""
        process_registry().reset()
        fake = _FlakyClient(failures=1)
        sc = ShardingClient(fake, "flaky-ds", batch_size=4,
                            dataset_size=16)
        with pytest.raises(OSError):
            sc.report_batch_done(2)
        # the credit survived the failure and was counted as a retry
        assert process_registry().get(
            tm.DATA_BATCH_REPORT_RETRIES).value == 1
        sc.report_batch_done(1)
        # 2 restored + 1 new = 3 batches x 4 records
        assert fake.records == [12]

    def test_successful_report_clears_the_pending_count(self):
        fake = _FlakyClient()
        sc = ShardingClient(fake, "ok-ds", batch_size=4, dataset_size=16)
        sc.report_batch_done(2)
        sc.report_batch_done(1)
        assert fake.records == [8, 4]


# -- worker side: prefetcher --------------------------------------------------


class TestDevicePreloaderInstrumentation:
    def test_foreground_depth_and_producer_wait(self):
        process_registry().reset()
        pl = DevicePreloader([{"x": i} for i in range(8)],
                             put_fn=lambda b: b)
        assert len(list(pl)) == 8
        reg = process_registry()
        assert reg.get(tm.DATA_PRODUCER_WAIT_TIME).count >= 7
        assert reg.get(tm.DATA_PREFETCH_QUEUE_DEPTH) is not None

    def test_background_consumer_wait_marks_a_slow_producer(self):
        process_registry().reset()

        def slow_source():
            for i in range(4):
                time.sleep(0.02)
                yield {"x": i}

        pl = DevicePreloader(slow_source(), put_fn=lambda b: b,
                             background=True)
        assert len(list(pl)) == 4
        h = process_registry().get(tm.DATA_CONSUMER_WAIT_TIME)
        assert h is not None and h.count >= 4
        # the consumer genuinely waited on the starved queue
        assert h.sum > 0.04


# -- worker side: executor input wait -----------------------------------------


def _run_executor(trainer, batch, iter_fn, hooks=None, steps=12,
                  window=2):
    executor = TrainExecutor(
        trainer, train_iter_fn=iter_fn, hooks=hooks or [],
        conf=Configuration({
            "train_steps": steps, "log_every_steps": 0,
            "train_window": window, "preemption_grace": False,
        }),
    )
    return executor.train_and_evaluate()


class TestExecutorInputWait:
    def test_gauge_absent_until_measured_then_tracks_starvation(self):
        process_registry().reset()
        clear_ring()
        trainer, batch = _make_trainer()

        def starved():
            for _ in range(12):
                time.sleep(0.03)
                yield batch

        # absent BEFORE any run: a scrape must not read a fake 0
        assert process_registry().get(tm.INPUT_WAIT_FRAC) is None
        _run_executor(trainer, batch, starved)
        g = process_registry().get(tm.INPUT_WAIT_FRAC)
        assert g is not None and g.value > 0.5, g
        assert process_registry().get(tm.INPUT_WAIT_TIME).count >= 12
        # the drain's fetch-free tail windows must NOT zero the gauge
        # (asserted by the > 0.5 above: the last materializations are
        # back-to-back with no fetches between them)
        te = [r for r in recent_events()
              if r["kind"] == EventKind.TRAIN_END]
        assert te and te[-1]["input_wait_s"] > 0.2

        # a fast source drops the fraction back toward 0
        _run_executor(trainer, batch, lambda: iter([batch] * 12))
        assert process_registry().get(tm.INPUT_WAIT_FRAC).value < 0.3

    def test_runtime_report_carries_the_fraction(self):
        process_registry().reset()
        trainer, batch = _make_trainer()
        payloads = []

        class Client:
            node_id = 0

            def report_node_runtime(self, **kw):
                payloads.append(kw)

        hook = NodeRuntimeReportHook(Client(), every_steps=4,
                                     min_interval_s=0)
        _run_executor(trainer, batch, lambda: iter([batch] * 12),
                      hooks=[hook], steps=12)
        hook.end(None)
        assert payloads
        # the field exists and is a measured float (fast iterator: ~0)
        assert payloads[-1]["input_wait_frac"] is not None
        assert payloads[-1]["input_wait_frac"] < 0.5


# -- master side: shard-lifecycle accounting ----------------------------------


class TestMasterShardAccounting:
    def _manager(self, size=24, batch=4, epochs=1):
        t = TaskManager()
        t.new_dataset("acc-ds", size, batch, num_epochs=epochs,
                      num_minibatches_per_shard=2)
        return t

    def test_gauges_absent_before_dispatch_and_retract_on_completion(
            self):
        process_registry().reset()
        clear_ring()
        t = self._manager()
        labels = {"dataset": "acc-ds"}
        reg = process_registry()
        assert reg.get(tm.DATA_SHARDS_TODO, labels=labels) is None
        task = t.get_dataset_task(0, "acc-ds")
        assert reg.get(tm.DATA_SHARDS_TODO, labels=labels).value == 2
        assert reg.get(tm.DATA_SHARDS_DOING, labels=labels).value == 1
        # record credits complete the shard; per-node counters follow
        t.report_batch_done("acc-ds", 0, 8)
        assert reg.get(tm.DATA_SHARDS_DONE, labels=labels).value == 1
        assert reg.get(tm.DATA_NODE_SHARDS_COMPLETED,
                       labels={"node": "0"}).value == 1
        assert reg.get(tm.DATA_NODE_RECORDS_DONE,
                       labels={"node": "0"}).value == 8
        assert reg.get(tm.DATA_SHARD_LATENCY).count == 1
        assert reg.get(tm.DATA_EPOCH_PROGRESS,
                       labels=labels).value == pytest.approx(8 / 24)
        while True:
            task = t.get_dataset_task(1, "acc-ds")
            if task.task_id < 0:
                break
            t.report_batch_done("acc-ds", 1, 8)
        assert t.finished()
        # completion RETRACTS the lifecycle gauges (absent-not-zero)
        assert reg.get(tm.DATA_SHARDS_TODO, labels=labels) is None
        assert reg.get(tm.DATA_EPOCH_PROGRESS, labels=labels) is None
        ends = [r for r in recent_events()
                if r["kind"] == EventKind.DATA_EPOCH_END]
        assert ends and ends[-1]["shards_done"] == 3
        assert ends[-1]["records_done"] == 24 and ends[-1]["final"]

    def test_timeout_recovery_emits_event_and_counter(self):
        process_registry().reset()
        clear_ring()
        t = self._manager()
        t.get_dataset_task(5, "acc-ds")
        time.sleep(0.03)
        t.scan_timeout_tasks_once(timeout_secs=0.01)
        assert process_registry().get(
            tm.DATA_SHARDS_TIMEOUT_RECOVERED).value == 1
        ev = [r for r in recent_events()
              if r["kind"] == EventKind.DATA_SHARD_TIMEOUT]
        assert ev and ev[-1]["dataset"] == "acc-ds"
        assert ev[-1]["error_code"] == "DATA_SHARD_TIMEOUT"
        assert ev[-1]["count"] == 1
        # the recovered shard is dispatchable again
        assert t.get_dataset_task(6, "acc-ds").task_id >= 0

    def test_timeout_monitor_cadence_respects_test_speedups(
            self, monkeypatch):
        """The satellite: the monitor's scan cadence follows the
        configured timeout (re-read per cycle), so shrinking
        seconds_to_timeout_task under test no longer waits out a
        hardcoded 30 s sleep before the first scan."""
        process_registry().reset()
        monkeypatch.setattr(get_context(), "seconds_to_timeout_task",
                            0.05)
        t = self._manager()
        t.get_dataset_task(0, "acc-ds")
        t.start()
        try:
            deadline = time.time() + 5.0
            while time.time() < deadline:
                c = process_registry().get(
                    tm.DATA_SHARDS_TIMEOUT_RECOVERED)
                if c is not None and c.value >= 1:
                    break
                time.sleep(0.05)
            else:
                pytest.fail("timeout monitor never scanned under a "
                            "sub-second seconds_to_timeout_task")
        finally:
            t.stop()

    def test_snapshot_rate_spans_the_union_of_node_windows(self):
        """ETA denominators: the aggregate rate must cover min(first)
        -> max(last) across nodes — a late-joining node's short span
        would overstate the rate and quote an ETA several times too
        short."""
        t = self._manager(size=48)  # 6 shards of 8
        t.get_dataset_task(0, "acc-ds")
        t.report_batch_done("acc-ds", 0, 8)
        t.get_dataset_task(1, "acc-ds")
        t.report_batch_done("acc-ds", 1, 8)
        d = t.get_dataset("acc-ds")
        # offset completion windows: node 0 over [100,110], node 1
        # (an elastic late joiner) over [160,170]
        d._node_first_ts.update({0: 100.0, 1: 160.0})
        d._node_last_ts.update({0: 110.0, 1: 170.0})
        snap = d.snapshot()
        union_rate = 16 / 70.0
        assert snap["eta_s"] == pytest.approx((48 - 16) / union_rate,
                                              rel=0.01)

    def test_overlapping_epochs_account_to_the_tasks_own_epoch(self):
        """Epochs overlap by design (get_task refills lazily while the
        previous epoch's last shards are still doing elsewhere): a late
        epoch-1 completion must close epoch 1 — not inflate epoch 2's
        progress or suppress its DATA_EPOCH_END forever."""
        process_registry().reset()
        clear_ring()
        t = TaskManager()
        t.new_dataset("epoch-ds", 16, 4, num_epochs=2,
                      num_minibatches_per_shard=2)  # 2 shards/epoch
        a = t.get_dataset_task(0, "epoch-ds")  # epoch 1
        b = t.get_dataset_task(1, "epoch-ds")  # epoch 1, todo empty
        assert a.epoch == b.epoch == 1
        t.report_batch_done("epoch-ds", 1, 8)  # B's shard completes
        # B moves on: the lazy refill rolls the splitter to epoch 2
        # while A's epoch-1 shard is STILL doing
        c = t.get_dataset_task(1, "epoch-ds")
        assert c.epoch == 2
        ends = [r for r in recent_events()
                if r["kind"] == EventKind.DATA_EPOCH_END]
        assert not ends  # epoch 1 not drained yet
        # A's late epoch-1 completion closes epoch 1, not epoch 2
        t.report_batch_done("epoch-ds", 0, 8)
        ends = [r for r in recent_events()
                if r["kind"] == EventKind.DATA_EPOCH_END]
        assert ends and ends[-1]["epoch"] == 1
        assert not ends[-1]["final"]
        # epoch 2's progress gauge saw none of epoch 1's records
        g = process_registry().get(tm.DATA_EPOCH_PROGRESS,
                                   labels={"dataset": "epoch-ds"})
        assert g is not None and g.value == 0.0

    def test_data_report_shape(self):
        t = self._manager()
        task = t.get_dataset_task(0, "acc-ds")
        t.report_batch_done("acc-ds", 0, 8)
        report = t.data_report()
        d = report["datasets"]["acc-ds"]
        assert d["shards_done"] == 1 and d["records_done"] == 8
        assert d["todo"] == 2 and d["doing"] == 0
        assert d["epoch_progress"] == pytest.approx(8 / 24, abs=1e-4)
        assert report["nodes"]["0"]["shards_completed"] == 1
        assert task.task_id >= 0


class TestShardCheckpointResumeGauges:
    def test_mid_epoch_resume_gauges_agree_with_remaining_records(self):
        """The satellite: restore from get_shard_checkpoint and the
        restored todo/doing/done + epoch-progress gauges must agree
        with the records ACTUALLY remaining."""
        process_registry().reset()
        t1 = TaskManager()
        t1.new_dataset("ckpt-ds", 40, 4, num_minibatches_per_shard=2)
        first = t1.get_dataset_task(0, "ckpt-ds")
        t1.report_batch_done("ckpt-ds", 0, 8)  # 1 shard done
        t1.get_dataset_task(0, "ckpt-ds")      # 1 doing at checkpoint
        ckpt = t1.get_shard_checkpoint("ckpt-ds")
        assert first.task_id >= 0

        process_registry().reset()  # the restarted master's registry
        t2 = TaskManager()
        t2.new_dataset("ckpt-ds", 40, 4, num_minibatches_per_shard=2)
        t2.restore_shard_checkpoint("ckpt-ds", ckpt)
        reg = process_registry()
        labels = {"dataset": "ckpt-ds"}
        # 5 shards total: 1 done, 1 doing + 3 todo -> 4 restored todo
        assert reg.get(tm.DATA_SHARDS_TODO, labels=labels).value == 4
        assert reg.get(tm.DATA_SHARDS_DOING, labels=labels).value == 0
        assert reg.get(tm.DATA_SHARDS_DONE, labels=labels).value == 1
        # 8 of 40 records consumed pre-restart
        assert reg.get(tm.DATA_EPOCH_PROGRESS, labels=labels).value \
            == pytest.approx(8 / 40)
        # and the remaining records really are 32
        remaining = sum(task.shard.size for task in t2.get_dataset(
            "ckpt-ds").todo)
        assert remaining == 32
        report = t2.data_report()["datasets"]["ckpt-ds"]
        assert report["records_done"] == 8
        assert report["shards_done"] == 1


# -- diagnosis: the input-bound bound label -----------------------------------


def _ingest(store, det, node, ms, steps_total, counts, ts,
            input_frac=None, comm_frac=None):
    store.ingest(comm.NodeRuntimeReport(
        node_id=node, timestamp=ts, step=int(steps_total),
        steps_total=float(steps_total), bounds=BOUNDS,
        step_time_counts=list(counts),
        input_wait_frac=input_frac, exposed_comm_frac=comm_frac,
    ), now=ts)
    det.observe(node, now=ts)


def _counts_at(ms, steps):
    import bisect

    counts = [0] * (len(BOUNDS) + 1)
    idx = bisect.bisect_left(BOUNDS, ms / 1000.0)
    counts[min(idx, len(BOUNDS))] += steps
    return counts


class _Feeder:
    """Cumulative per-node report feeder for synthetic windows."""

    def __init__(self, store, det):
        self.store, self.det = store, det
        self.cum = {}

    def feed(self, node, ms, ts, input_frac=None, comm_frac=None):
        s = self.cum.setdefault(node, {
            "c": [0] * (len(BOUNDS) + 1), "n": 0})
        s["c"] = [a + b for a, b in zip(s["c"], _counts_at(ms, 8))]
        s["n"] += 8
        _ingest(self.store, self.det, node, ms, s["n"], s["c"], ts,
                input_frac=input_frac, comm_frac=comm_frac)


class TestInputBoundVerdict:
    def _flag(self, slow_input, slow_comm, peer_input=0.02,
              peer_comm=0.1):
        store = NodeRuntimeStore()
        det = StragglerDetector(store, ratio=2.0, confirm_windows=3,
                                hang_secs=0)
        f = _Feeder(store, det)
        now = time.time()
        for w in range(3):
            f.feed(0, 5, now + w, input_frac=peer_input,
                   comm_frac=peer_comm)
            f.feed(1, 5, now + w, input_frac=peer_input,
                   comm_frac=peer_comm)
            f.feed(2, 50, now + w, input_frac=slow_input,
                   comm_frac=slow_comm)
        assert det.stragglers() == [2]
        return det.verdicts()[2]["evidence"]

    def test_starved_node_is_input_bound_with_peer_evidence(self):
        # a starved pipeline inflates the exposed-comm residual TOO —
        # without the input leg this node would read comm-bound
        ev = self._flag(slow_input=0.95, slow_comm=0.9)
        assert ev["bound"] == "input-bound"
        assert ev["input_wait_frac"] == pytest.approx(0.95)
        assert ev["peer_median_input_wait_frac"] == pytest.approx(0.02)

    def test_input_tracking_peers_falls_through_to_comm_bound(self):
        ev = self._flag(slow_input=0.05, slow_comm=0.9)
        assert ev["bound"] == "comm-bound"

    def test_everything_tracking_peers_is_compute_bound(self):
        ev = self._flag(slow_input=0.05, slow_comm=0.15)
        assert ev["bound"] == "compute-bound"


# -- control: the optimizer's input-bound replan gate -------------------------


def _running_report(**kw):
    kw.setdefault("node_id", 0)
    kw.setdefault("world", 8)
    kw.setdefault("mesh_shape", {"pipe": 1, "data": 8, "fsdp": 1,
                                 "seq": 1, "tensor": 1})
    kw.setdefault("train_window", 4)
    kw.setdefault("steps_per_call", 1)
    kw.setdefault("global_batch", 16)
    return comm.TrainerConfigReport(**kw)


def _starved_store(det=None):
    store = NodeRuntimeStore()
    det = det or StragglerDetector(store, ratio=2.0,
                                   confirm_windows=3, hang_secs=0)
    f = _Feeder(store, det)
    now = time.time()
    for w in range(3):
        f.feed(0, 5, now + w, input_frac=0.01)
        f.feed(1, 5, now + w, input_frac=0.02)
        f.feed(2, 50, now + w, input_frac=0.95)
    return store, det, f, now


def _optimizer(store):
    opt = RuntimeOptimizer(store, publish=lambda cfg: None)
    opt.update_model_info(comm.ModelInfo(
        num_params=10_000, hidden_size=32, num_layers=2, seq_len=16))
    opt.update_running_config(_running_report())
    return opt


class TestOptimizerInputBoundGate:
    def test_starved_job_rejects_program_replans_with_evidence(self):
        clear_ring()
        store, det, f, now = _starved_store()
        opt = _optimizer(store)
        d = opt.replan("straggler:2")
        assert d.outcome == "rejected"
        assert d.reason == "input_bound"
        assert d.input_bound["input_bound_node"] == 2
        assert (d.input_bound["input_wait_frac"]
                - d.input_bound["peer_median_input_wait_frac"]) >= 0.1
        rej = [r for r in recent_events()
               if r["kind"] == EventKind.OPTIMIZER_PLAN_REJECTED
               and r.get("reason") == "input_bound"]
        assert rej and rej[-1]["input_bound_node"] == 2

    def test_starvation_clearing_lets_replans_proceed(self):
        store, det, f, now = _starved_store()
        opt = _optimizer(store)
        assert opt.replan("straggler:2").reason == "input_bound"
        # the gate consumed NO cooldown: once the starvation clears
        # the next pass decides on the merits immediately
        for w in range(3, 5):
            f.feed(0, 5, now + w, input_frac=0.01)
            f.feed(1, 5, now + w, input_frac=0.02)
            f.feed(2, 5, now + w, input_frac=0.02)
        d = opt.replan("recovered:2")
        assert d.reason != "input_bound"

    def test_knob_disables_the_gate(self, monkeypatch):
        monkeypatch.setattr(get_context(), "replan_input_bound_gate",
                            False)
        store, det, f, now = _starved_store()
        opt = _optimizer(store)
        d = opt.replan("straggler:2")
        assert d.reason != "input_bound"

    def test_uniform_cluster_wide_starvation_still_gates(self):
        """The most common input-bound mode — every node starved by a
        shared slow source — shows NO peer excess; the absolute
        median backstop must still gate program replans."""
        store = NodeRuntimeStore()
        det = StragglerDetector(store, ratio=2.0, confirm_windows=3,
                                hang_secs=0)
        f = _Feeder(store, det)
        now = time.time()
        for w in range(3):
            for node in (0, 1, 2):
                f.feed(node, 50, now + w, input_frac=0.8)
        opt = _optimizer(store)
        d = opt.replan("tick")
        assert d.reason == "input_bound", (d.outcome, d.reason)
        assert d.input_bound["median_input_wait_frac"] >= 0.5

    def test_no_input_measurements_means_no_gate(self):
        store = NodeRuntimeStore()
        det = StragglerDetector(store, ratio=2.0, confirm_windows=3,
                                hang_secs=0)
        f = _Feeder(store, det)
        now = time.time()
        for w in range(3):
            f.feed(0, 5, now + w)
            f.feed(1, 50, now + w)
        opt = _optimizer(store)
        d = opt.replan("straggler:1")
        assert d is None or d.reason != "input_bound"


# -- goodput: the input-wait column -------------------------------------------


def _ev(kind, ts, pid=1, **kw):
    return {"kind": kind, "ts": ts, "mono": ts, "pid": pid,
            "node": "0", **kw}


class TestGoodputInputWaitColumn:
    def test_column_sums_train_end_fields(self):
        events = [
            _ev(EventKind.TRAIN_START, 0.0, pid=2),
            _ev(EventKind.TRAIN_END, 100.0, pid=2, input_wait_s=12.5),
            _ev(EventKind.TRAIN_START, 0.0, pid=3, node="1"),
            _ev(EventKind.TRAIN_END, 100.0, pid=3, node="1",
                input_wait_s=2.5),
        ]
        rep = derive_goodput(events)
        col = rep["detail"]["input_wait"]
        assert col["seconds"] == pytest.approx(15.0)
        assert col["workers"] == 2
        assert col["fraction_of_productive"] == pytest.approx(
            15.0 / 100.0, abs=0.01)

    def test_absent_without_measurements(self):
        events = [
            _ev(EventKind.TRAIN_START, 0.0, pid=2),
            _ev(EventKind.TRAIN_END, 10.0, pid=2),
        ]
        assert "input_wait" not in derive_goodput(events)["detail"]


# -- the tpurun data CLI gate (live + forensic agree) -------------------------


class TestDataCliGate:
    def test_live_and_forensic_agree_on_shard_counts(self, tmp_path,
                                                     monkeypatch):
        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        process_registry().reset()
        master = start_local_master()
        try:
            client = MasterClient(master.addr, node_id=0)
            sc = ShardingClient(client, "cli-ds", batch_size=4,
                                dataset_size=24,
                                num_minibatches_per_shard=2)
            while sc.fetch_shard() is not None:
                sc.report_batch_done(2)  # 8 records completes a shard
            rc1, live = _run_json_cli(
                ["data", "--addr", master.addr, "--json"])
            rc2, forensic = _run_json_cli(
                ["data", "--events", events_path, "--json"])
            assert rc1 == 0 and rc2 == 0
            lv, fv = (live["datasets"]["cli-ds"],
                      forensic["datasets"]["cli-ds"])
            assert lv["shards_done"] == fv["shards_done"] == 3
            assert lv["records_done"] == fv["records_done"] == 24
            assert lv["completed"] and fv["completed"]
            # the text views render without error too
            from dlrover_tpu.trainer.run import main as tpurun

            assert tpurun(["data", "--addr", master.addr]) == 0
            assert tpurun(["data", "--events", events_path]) == 0
            client.close()
        finally:
            master.stop()


# -- overhead gate ------------------------------------------------------------


class _TimedRegion(TrainHook):
    def __init__(self, warmup):
        self.warmup = warmup
        self.t0 = None

    def before_step(self, step):
        if step == self.warmup + 1 and self.t0 is None:
            self.t0 = time.perf_counter()


class TestDataPlaneOverheadGate:
    def test_overhead_within_budget(self):
        """≤5% paired-median overhead for the data-plane hooks (the
        preloader instruments + the executor's input-wait clock), on
        vs off, with the PR 8 methodology hardened per the de-flake
        satellite: up to 3 attempts of 3 back-to-back pairs each,
        gating on the MINIMUM of the attempt medians — the true cost
        is a lower envelope, and one noisy attempt on a shared 1-core
        box must not fail a clean tree."""
        steps, warmup = 280, 8
        ctx = get_context()
        trainer, batch = _make_trainer()

        def run(telemetry):
            ctx.telemetry_enabled = telemetry
            timer = _TimedRegion(warmup)
            preloader = DevicePreloader(
                iter([batch] * (warmup + steps)), put_fn=lambda b: b)
            executor = TrainExecutor(
                trainer, train_iter_fn=lambda: iter(preloader),
                hooks=[timer],
                conf=Configuration({
                    "train_steps": warmup + steps,
                    "log_every_steps": 0, "train_window": 4,
                    "preemption_grace": False,
                }),
            )
            executor.train_and_evaluate()
            ctx.telemetry_enabled = True
            return time.perf_counter() - timer.t0

        def attempt():
            ratios = []
            for i in range(3):
                if i % 2 == 0:
                    dt_b = run(False)
                    dt_i = run(True)
                else:
                    dt_i = run(True)
                    dt_b = run(False)
                ratios.append(dt_i / dt_b)
            return sorted(ratios)[len(ratios) // 2]

        medians = []
        for _ in range(3):
            medians.append(attempt())
            if medians[-1] - 1.0 <= 0.05:
                break
        overhead = min(medians) - 1.0
        assert overhead <= 0.05, (
            f"data-plane overhead {overhead:.1%} above the 5% budget "
            f"(attempt medians {[round(m, 3) for m in medians]})"
        )


# -- the e2e input-bound wedge ------------------------------------------------


class _SlowBatches:
    """Wraps a loader: ~30 ms of host latency per batch — the injected
    input starvation (the dataloader is slow; the device step is not)."""

    def __init__(self, inner, seconds):
        self.inner = inner
        self.seconds = seconds

    def __iter__(self):
        for item in self.inner:
            time.sleep(self.seconds)
            yield item


def _wedge_dataset(batch, n_batches=40, batch_size=16):
    xs = np.asarray(batch["x"], np.float32)
    ys = np.asarray(batch["y"], np.float32)
    samples = []
    for i in range(n_batches * batch_size):
        samples.append({"x": xs[i % 16], "y": ys[i % 16]})
    return samples


def _run_wedge_node(trainer, batch, master, node_id, dataset_name,
                    slow_s=0.0):
    """One 'node' of the wedge: the FULL data path — IndexShardingClient
    pulling shards from the real master, ElasticDataLoader assembling
    batches, the shard-report hook crediting them back — under a real
    executor with the real runtime-report hook."""
    process_registry().reset()
    client = MasterClient(master.addr, node_id=node_id)
    batch_size, n_batches = 16, 40
    dataset = _wedge_dataset(batch, n_batches, batch_size)
    sharding = IndexShardingClient(
        client, dataset_name, batch_size=batch_size,
        dataset_size=len(dataset), num_minibatches_per_shard=2)
    loader = ElasticDataLoader(dataset, batch_size,
                               sharding_client=sharding)

    def iter_fn():
        return iter(_SlowBatches(loader, slow_s) if slow_s else loader)

    hooks = [
        ElasticDataShardReportHook(sharding, batch_size),
        NodeRuntimeReportHook(client, every_steps=6, min_interval_s=0),
    ]
    executor = TrainExecutor(
        trainer, train_iter_fn=iter_fn, hooks=hooks,
        conf=Configuration({
            "train_steps": 0,  # run the dataset to exhaustion
            "log_every_steps": 0, "train_window": 2,
            "preemption_grace": False,
        }),
    )
    out = executor.train_and_evaluate()
    client.close()
    return out


class TestInputBoundWedge:
    def test_starved_node_is_input_bound_and_gates_replans(
            self, tmp_path, monkeypatch):
        """The acceptance wedge: one node of three with a ~30 ms/batch
        slow dataloader on the CPU mesh → the diagnosis labels THAT
        node input-bound (with peer-median evidence, not
        comm/compute), the optimizer declines a program replan with
        PLAN_REJECTED reason=input_bound under the SAME incident trace
        id, removing the injection flips the label back and replans
        proceed — all visible in tpurun data / plan / trace."""
        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        ctx = get_context()
        monkeypatch.setattr(ctx, "diagnosis_confirm_windows", 3)
        monkeypatch.setattr(ctx, "diagnosis_straggler_ratio", 2.0)
        master = start_local_master()
        try:
            trainer, batch = _make_trainer()
            # the optimizer needs the running config + model facts
            seed = MasterClient(master.addr, node_id=0)
            seed.report_trainer_config(
                world=1,
                mesh_shape={"pipe": 1, "data": 1, "fsdp": 1, "seq": 1,
                            "tensor": 1},
                train_window=2, steps_per_call=1, global_batch=16)
            seed.report_model_info(comm.ModelInfo(
                num_params=10, hidden_size=4, num_layers=1,
                seq_len=16))
            seed.close()

            # fast peers anchor the medians, then the starved node
            _run_wedge_node(trainer, batch, master, 0, "wedge-0")
            _run_wedge_node(trainer, batch, master, 1, "wedge-1")
            _run_wedge_node(trainer, batch, master, 2, "wedge-2",
                            slow_s=0.03)

            det = master.servicer.straggler_detector
            assert det.stragglers() == [2], det.verdicts()
            verdict = det.verdicts()[2]
            ev = verdict["evidence"]
            assert ev["bound"] == "input-bound", ev
            assert ev["input_wait_frac"] \
                - ev["peer_median_input_wait_frac"] >= 0.1
            trace_id = verdict["trace_id"]

            # the verdict listener replanned; the gate declined the
            # program plan, and the decision joins the SAME incident
            decisions = master.servicer.runtime_optimizer.decisions()
            gated = [d for d in decisions
                     if d["reason"] == "input_bound"]
            assert gated, decisions
            assert gated[-1]["trace_id"] == trace_id
            assert gated[-1]["input_bound"]["input_bound_node"] == 2

            records = read_events(events_path)
            rejected = [
                r for r in records
                if r["kind"] == EventKind.OPTIMIZER_PLAN_REJECTED
                and r.get("reason") == "input_bound"
            ]
            assert rejected and rejected[-1]["trace_id"] == trace_id

            # remove the injection: the label clears and replans
            # proceed on the merits (no longer input_bound-gated)
            _run_wedge_node(trainer, batch, master, 2, "wedge-2b")
            assert det.stragglers() == [], det.verdicts()
            post = [
                d for d in
                master.servicer.runtime_optimizer.decisions()
                if d["trigger"] == "recovered:2"
            ]
            assert post, "recovery never triggered a replan"
            assert post[-1]["reason"] != "input_bound"

            # the shard ledger flowed end-to-end: live + forensic data
            # CLIs agree on the wedge datasets' shard counts
            rc_live, live = _run_json_cli(
                ["data", "--addr", master.addr, "--json"])
            rc_for, forensic = _run_json_cli(
                ["data", "--events", events_path, "--json"])
            assert rc_live == 0 and rc_for == 0
            for name in ("wedge-0", "wedge-1", "wedge-2"):
                assert live["datasets"][name]["shards_done"] \
                    == forensic["datasets"][name]["shards_done"] == 20
                assert live["datasets"][name]["completed"]

            # plan + trace views over the same incident render
            from dlrover_tpu.trainer.run import main as tpurun

            assert tpurun(["plan", "--events", events_path]) == 0
            trace_out = str(tmp_path / "trace.json")
            assert tpurun(["trace", "--events", events_path,
                           "--out", trace_out]) == 0
            assert json.load(open(trace_out))["traceEvents"]
        finally:
            master.stop()
