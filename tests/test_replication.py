"""Peer-redundant host snapshots: the checkpoint-free recovery plane.

Unit matrix for ``checkpoint/replication.py`` + ``master/replication.py``
(codec, partition, HRW stability, budget admission, store commit
semantics) and the in-process fault-injection matrix: holder death
mid-transfer -> next-replica fallback, chunk corruption caught by the
crc, cadence expiry -> storage fallback, plus the trainer-level
bitwise peer-restore contract. The subprocess SIGKILL wedge lives in
tests/test_chaos.py.
"""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.checkpoint import replication as repl
from dlrover_tpu.checkpoint.manager import HostSnapshot
from dlrover_tpu.common.config import get_context
from dlrover_tpu.diagnosis.fault_injection import (
    corrupt_replica_chunk,
    freeze_replicator,
    kill_channel_after,
)
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.master.replication import ReplicaDirectory, hrw_peers
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.rpc.client import RpcChannel
from dlrover_tpu.trainer.elastic import ElasticTrainer


@pytest.fixture()
def replica_ctx(monkeypatch, tmp_path):
    """Turn the plane on with test pacing, restoring every Context knob
    (the singleton leaks across test files otherwise)."""
    ctx = get_context()
    saved = {k: getattr(ctx, k) for k in (
        "snapshot_replicas", "peer_restore", "replica_cadence_steps",
        "replica_min_interval_secs", "replica_budget_mb",
        "replica_chunk_kb",
    )}
    ctx.snapshot_replicas = 1
    ctx.peer_restore = True
    ctx.replica_cadence_steps = 2
    ctx.replica_min_interval_secs = 0.0
    ctx.replica_budget_mb = 64.0
    ctx.replica_chunk_kb = 4
    monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE",
                       str(tmp_path / "events.jsonl"))
    yield ctx
    for k, v in saved.items():
        setattr(ctx, k, v)


def _events(tmp_path):
    from dlrover_tpu.telemetry import read_events

    return read_events(str(tmp_path / "events.jsonl"))


# -- codec + partition --------------------------------------------------------


class TestChunkCodec:
    def test_round_trip(self):
        f = repl.encode_chunk(kind="chunk", owner=2, step=9, leaf=1,
                              lo=8, hi=16, seq=3, payload=b"x" * 8)
        header, payload = repl.decode_chunk(f)
        assert payload == b"x" * 8
        assert (header["owner"], header["step"], header["leaf"],
                header["seq"]) == (2, 9, 1, 3)

    def test_crc_catches_payload_flip(self):
        f = bytearray(repl.encode_chunk(
            kind="chunk", owner=0, step=1, leaf=0, lo=0, hi=4, seq=0,
            payload=b"abcd"))
        f[-2] ^= 0xFF
        with pytest.raises(repl.ChunkCorruptionError):
            repl.decode_chunk(bytes(f))

    def test_header_crc_catches_placement_flip(self):
        """The payload crc cannot protect the PLACEMENT facts: a bit
        flip inside the JSON header (lo/hi/leaf) would write good
        bytes to the wrong region. The header carries its own crc."""
        f = repl.encode_chunk(kind="chunk", owner=0, step=1, leaf=0,
                              lo=0, hi=4, seq=0, payload=b"abcd")
        (hlen,) = __import__("struct").unpack_from(">I", f, 0)
        header = bytearray(f[4:4 + hlen])
        # flip a digit inside the header (keep it parseable JSON)
        idx = header.find(b'"lo":0') + len(b'"lo":')
        header[idx:idx + 1] = b"2"
        mangled = f[:4] + bytes(header) + f[4 + hlen:]
        with pytest.raises(repl.ChunkCorruptionError):
            repl.decode_chunk(mangled)

    def test_length_prefix_catches_truncation(self):
        f = repl.encode_chunk(kind="chunk", owner=0, step=1, leaf=0,
                              lo=0, hi=8, seq=0, payload=b"abcdefgh")
        with pytest.raises(repl.ChunkCorruptionError):
            repl.decode_chunk(f[:-3])

    def test_owner_slices_partition_exactly(self):
        for nbytes in (0, 1, 7, 64, 1001):
            for k in (1, 2, 3, 5):
                spans = [repl.owner_slice(nbytes, k, r)
                         for r in range(k)]
                assert spans[0][0] == 0 and spans[-1][1] == nbytes
                for (_, a_hi), (b_lo, _) in zip(spans, spans[1:]):
                    assert a_hi == b_lo  # contiguous, disjoint


class TestHRWAssignment:
    def test_rendezvous_stable_under_resize(self):
        """Removing one node must not reshuffle the surviving pairs:
        every owner's peer list changes ONLY where the departed node
        appeared — the property that keeps old replicas valid across
        an elastic resize."""
        group = [0, 1, 2, 3, 4]
        before = {o: hrw_peers(o, group, 2) for o in group}
        survivors = [0, 1, 3, 4]
        after = {o: hrw_peers(o, survivors, 2) for o in survivors}
        for owner in survivors:
            kept = [p for p in before[owner] if p != 2]
            # the surviving prefix is preserved; only the slot node 2
            # occupied (if any) is refilled from the next rank
            assert after[owner][:len(kept)] == kept

    def test_budget_admission_degrades_never_ooms(self):
        # 2 nodes, shares of 12 MB each: a 10 MB holder budget cannot
        # fit ANY replica -> the plan degrades to k=0 with a logged
        # verdict instead of shipping bytes that would OOM the holder
        d = ReplicaDirectory()
        for n in range(2):
            d.register(n, f"h{n}:1", budget_mb=10.0, snapshot_mb=24.0,
                       step=1)
        out = d.admitted_replicas(1)
        assert out["replicas"] == 0 and out["degraded"]
        assert "budget" in out["reason"]
        # a 20 MB budget fits the 12 MB share: k=1 admitted
        for n in range(2):
            d.register(n, f"h{n}:1", budget_mb=20.0, snapshot_mb=24.0,
                       step=1)
        out = d.admitted_replicas(1)
        assert out["replicas"] == 1 and not out["degraded"]
        # roomy budgets admit the full k on a bigger group
        d3 = ReplicaDirectory()
        for n in range(3):
            d3.register(n, f"h{n}:1", budget_mb=1000.0,
                        snapshot_mb=24.0, step=1)
        assert d3.admitted_replicas(2)["replicas"] == 2

    def test_recovery_plan_excludes_failed_holders(self):
        d = ReplicaDirectory()
        for n in range(3):
            d.register(n, f"h{n}:1", budget_mb=0.0, snapshot_mb=8.0,
                       step=1)
        d.mark_failed(0)
        plan = d.recovery_plan(2)
        assert "0" in plan["owners"]  # the DEAD node's regions are
        # exactly what a rebuild needs...
        holders = [h["node_id"] for h in plan["owners"]["0"]]
        assert 0 not in holders  # ...served by its surviving peers
        assert holders  # and there are some
        # re-registration (the node came back) restores holder status
        d.register(0, "h0:1", budget_mb=0.0, snapshot_mb=8.0, step=2)
        holders = [h["node_id"]
                   for h in d.recovery_plan(2)["owners"]["0"]]
        assert holders[0] == 0

    def test_diagnosis_hang_verdict_marks_holder_failed(self):
        """The diagnosis plane's verdict listener is one of the three
        node-loss signals: the directory must react to the EXACT
        verdict string the StragglerDetector emits (a near-miss
        constant would make this signal silently dead code)."""
        from dlrover_tpu.master.monitor.straggler import (
            VERDICT_HEALTHY,
            VERDICT_HUNG,
        )

        d = ReplicaDirectory()
        for n in range(2):
            d.register(n, f"h{n}:1", budget_mb=0.0, snapshot_mb=8.0,
                       step=1)
        d.on_verdict(0, VERDICT_HUNG)
        holders = [h["node_id"]
                   for h in d.recovery_plan(1)["owners"]["0"]]
        assert 0 not in holders
        d.on_verdict(0, VERDICT_HEALTHY)
        holders = [h["node_id"]
                   for h in d.recovery_plan(1)["owners"]["0"]]
        assert holders[0] == 0

    def test_negative_budget_lends_nothing_but_still_replicates_out(
            self):
        """replica_budget_mb < 0 = "lend no DRAM": the node is never a
        peer-replica holder, but it remains an OWNER whose regions
        replicate out (and its store exempts its own commits)."""
        d = ReplicaDirectory()
        d.register(0, "h0:1", budget_mb=-1.0, snapshot_mb=8.0, step=1)
        d.register(1, "h1:1", budget_mb=64.0, snapshot_mb=8.0, step=1)
        d.register(2, "h2:1", budget_mb=64.0, snapshot_mb=8.0, step=1)
        for owner in (1, 2):
            peers = [p["node_id"]
                     for p in d.plan_for(owner, 2)["peers"]]
            assert 0 not in peers, peers
        # node 0's own regions still have holders in the recovery plan
        holders = [h["node_id"]
                   for h in d.recovery_plan(2)["owners"]["0"]]
        assert holders[0] == 0 and set(holders) - {0}, holders
        # store-side: own commits are budget-exempt, peer chunks refuse
        store = repl.ReplicaStore(budget_bytes=1, self_owner=0)
        own = _frames(owner=0, group=(0,))
        for f in own:
            assert store.put_frame(f)[0]
        peer = _frames(owner=5, group=(5,))
        ok, reason = store.put_frame(peer[0])
        assert not ok and reason == "budget"

    def test_store_only_holder_never_joins_the_partition(self):
        d = ReplicaDirectory()
        d.register(0, "h0:1", budget_mb=0.0, snapshot_mb=8.0, step=1)
        d.register(9, "h9:1", budget_mb=64.0, snapshot_mb=0.0, step=-1)
        plan = d.plan_for(0, 1)
        assert plan["group"] == [0]  # owner partition excludes node 9
        assert [p["node_id"] for p in plan["peers"]] == [9]  # but it
        # IS the replica holder
        assert "9" not in d.recovery_plan(1)["owners"]


# -- store commit semantics ---------------------------------------------------


def _leaves():
    return [np.arange(96, dtype=np.float32).reshape(12, 8),
            np.asarray(11, dtype=np.int64)]


def _frames(owner=0, step=5, group=(0,), chunk=16, leaves=None,
            meta=None):
    return repl.build_region_frames(
        owner=owner, step=step, leaves=leaves or _leaves(),
        group=list(group), meta=meta or {"rng": [1, 2], "host_step": step},
        chunk_bytes=chunk)


class TestReplicaStore:
    def test_commit_requires_complete_chunks(self):
        store = repl.ReplicaStore()
        frames = _frames()
        # manifest without one data chunk: refuse to commit
        ok, reason = store.put_frame(frames[-1])
        assert not ok and "incomplete" in reason
        for f in frames[:-1]:
            assert store.put_frame(f)[0]
        assert store.inventory() == {}  # still uncommitted
        assert store.put_frame(frames[-1])[0]
        assert store.inventory()["0"]["step"] == 5

    def test_stale_push_cannot_roll_back_a_fresher_commit(self):
        store = repl.ReplicaStore()
        for f in _frames(step=7):
            assert store.put_frame(f)[0]
        old = _frames(step=5)
        for f in old[:-1]:
            store.put_frame(f)
        ok, reason = store.put_frame(old[-1])
        assert not ok and reason == "stale"
        assert store.inventory()["0"]["step"] == 7

    def test_two_deep_retention_keeps_the_previous_step_fetchable(self):
        """During a multi-owner push wave, one owner's fresh commit
        must not discard the only step every owner still covers: the
        store retains TWO committed steps per owner, and the fetch
        sweep (best_common_step) sees both."""
        store = repl.ReplicaStore()
        for step in (16, 32):
            for f in _frames(step=step):
                assert store.put_frame(f)[0]
        inv = store.inventory()["0"]
        assert inv["step"] == 32
        assert set(inv["steps"]) == {"16", "32"}
        # chunks of BOTH retained steps are servable
        assert store.fetch(0, 16, 0, 0) is not None
        assert store.fetch(0, 32, 0, 0) is not None
        # a third commit evicts the oldest
        for f in _frames(step=48):
            assert store.put_frame(f)[0]
        assert set(store.inventory()["0"]["steps"]) == {"32", "48"}
        assert store.fetch(0, 16, 0, 0) is None

    def test_budget_refusal_not_oom(self):
        store = repl.ReplicaStore(budget_bytes=64)
        frames = _frames()
        ok, reason = store.put_frame(frames[0])
        assert not ok and reason == "budget"

    def test_mid_push_death_staged_bytes_reclaimed(self):
        """A pusher that dies mid-transfer (chunks staged, manifest
        never arrives) must not pin the holder's replica budget
        forever: the staged cycle is TTL-reclaimed so later pushes
        from live peers still fit."""
        store = repl.ReplicaStore(budget_bytes=4096,
                                  staged_ttl_secs=0.05)
        torn = _frames(owner=0, chunk=512)
        for f in torn[:-1]:  # everything but the sealing manifest
            assert store.put_frame(f)[0]
        orphaned = store.resident_bytes()
        assert orphaned > 0
        time.sleep(0.1)
        # a later put (any owner) reaps the stale cycle first, so the
        # fresh push is admitted instead of bouncing off "budget"
        fresh = _frames(owner=1, group=(1,), chunk=512)
        for f in fresh:
            ok, reason = store.put_frame(f)
            assert ok, reason
        assert store.inventory()["1"]["step"] == 5
        assert store.resident_bytes() < orphaned + 4096
        # and the torn cycle's bytes are gone from the ledger
        committed = sum(
            len(fr) for fr in
            store._committed[1][0]["chunks"].values())
        assert store.resident_bytes() == committed

    def test_corrupt_frame_rejected_on_put(self):
        store = repl.ReplicaStore()
        f = bytearray(_frames()[0])
        f[-1] ^= 0xFF
        ok, reason = store.put_frame(bytes(f))
        assert not ok and "corrupt" in reason


# -- fetch matrix over real RPC ----------------------------------------------


def _serve_full_copy(group=(0, 1), step=7, leaves=None, chunk=32):
    """Two holders, each holding EVERY owner's committed regions."""
    leaves = leaves or _leaves()
    stores, servers, addrs = {}, {}, {}
    for holder in (0, 1):
        stores[holder] = repl.ReplicaStore()
    for owner in group:
        frames = repl.build_region_frames(
            owner=owner, step=step, leaves=leaves, group=list(group),
            meta={"rng": [1, 2], "host_step": step}, chunk_bytes=chunk)
        for holder in (0, 1):
            for f in frames:
                assert stores[holder].put_frame(f)[0]
    for holder in (0, 1):
        srv, port = repl.start_replica_server(stores[holder],
                                              host="127.0.0.1")
        servers[holder] = srv
        addrs[holder] = f"127.0.0.1:{port}"
    return stores, servers, addrs, leaves


def _abstract(leaves):
    return [jax.ShapeDtypeStruct(np.asarray(x).shape,
                                 np.asarray(x).dtype) for x in leaves]


class TestFetchMatrix:
    def _factory(self):
        chans = {}

        def factory(addr):
            ch = chans.get(addr)
            if ch is None:
                ch = RpcChannel(addr, timeout=5.0, retries=2,
                                backoff=0.05)
                chans[addr] = ch
            return ch

        return factory, chans

    def test_holder_death_mid_transfer_falls_to_next_replica(self):
        stores, servers, addrs, leaves = _serve_full_copy()
        factory, chans = self._factory()
        try:
            holders = {o: [{"node_id": 0, "addr": addrs[0]},
                           {"node_id": 1, "addr": addrs[1]}]
                       for o in (0, 1)}
            # prime the channel, then let holder 0 die after 3 more
            # calls — MID-stream, with chunks already fetched from it
            factory(addrs[0])
            stats = kill_channel_after(chans[addrs[0]], 3)
            out, meta, step, _ = repl.fetch_tree(
                _abstract(leaves), holders, factory)
            assert step == 7
            np.testing.assert_array_equal(out[0], leaves[0])
            np.testing.assert_array_equal(
                out[1].reshape(()), leaves[1])
            assert stats.injected > 0, "holder never actually died"
        finally:
            for s in servers.values():
                s.stop(grace=0)

    def test_every_holder_dead_is_terminal_not_a_wedge(self):
        stores, servers, addrs, leaves = _serve_full_copy()
        factory, chans = self._factory()
        try:
            holders = {0: [{"node_id": 0, "addr": addrs[0]},
                           {"node_id": 1, "addr": addrs[1]}]}
            factory(addrs[0])
            factory(addrs[1])
            kill_channel_after(chans[addrs[0]], 1)
            kill_channel_after(chans[addrs[1]], 1)
            t0 = time.monotonic()
            with pytest.raises(repl.PeerRestoreError):
                repl.fetch_tree(_abstract(leaves), holders, factory)
            assert time.monotonic() - t0 < 30, "terminal case wedged"
        finally:
            for s in servers.values():
                s.stop(grace=0)

    def test_corrupt_chunk_caught_by_checksum_and_survived(self):
        from dlrover_tpu.telemetry import get_registry, names as tm

        stores, servers, addrs, leaves = _serve_full_copy()
        factory, _ = self._factory()
        try:
            key = corrupt_replica_chunk(stores[0], owner=0)
            assert key is not None
            before = get_registry().counter(
                tm.REPLICA_CHUNK_CORRUPTIONS).value
            holders = {o: [{"node_id": 0, "addr": addrs[0]},
                           {"node_id": 1, "addr": addrs[1]}]
                       for o in (0, 1)}
            out, _meta, step, _ = repl.fetch_tree(
                _abstract(leaves), holders, factory)
            np.testing.assert_array_equal(out[0], leaves[0])
            after = get_registry().counter(
                tm.REPLICA_CHUNK_CORRUPTIONS).value
            assert after > before, "the crc never fired"
        finally:
            for s in servers.values():
                s.stop(grace=0)

    def test_structure_mismatch_refused(self):
        stores, servers, addrs, leaves = _serve_full_copy()
        factory, _ = self._factory()
        try:
            holders = {o: [{"node_id": 1, "addr": addrs[1]}]
                       for o in (0, 1)}
            wrong = [jax.ShapeDtypeStruct((3, 3), np.float32)]
            with pytest.raises(repl.PeerRestoreError):
                repl.fetch_tree(wrong, holders, factory)
        finally:
            for s in servers.values():
                s.stop(grace=0)


# -- the trainer-level contract ----------------------------------------------


def _linear_trainer(master=None, node_id=0, ckpt_dir=""):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)), "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (4, 2))}
    client = (MasterClient(master.addr, node_id=node_id)
              if master is not None else None)
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.adam(0.1), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)),
        master_client=client, ckpt_dir=ckpt_dir,
    )
    return trainer, batch


def _register_holder(master, node_id=9):
    """An in-process surviving-peer store registered with the master."""
    store = repl.ReplicaStore()
    srv, port = repl.start_replica_server(store, host="127.0.0.1")
    client = MasterClient(master.addr, node_id=node_id)
    client.report_replica_endpoint(
        addr=f"127.0.0.1:{port}", budget_mb=64.0, snapshot_mb=0.0,
        step=-1)
    client.close()
    return store, srv


def _push_through_replicator(trainer, state, master, store):
    """One real replication cycle: trainer snapshot -> replicator ->
    the registered holder's store, over real RPC."""
    replicator = repl.SnapshotReplicator(
        trainer._master_client, node_id=0)
    try:
        snap = trainer.snapshot(state)
        assert replicator.submit(snap.tree, snap.meta, snap.step)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            if store.inventory().get("0"):
                break
            time.sleep(0.05)
        assert store.inventory().get("0"), "push never landed"
        return snap
    finally:
        replicator.stop()


class TestTrainerPeerRestore:
    def test_bitwise_rebuild_from_surviving_peer(self, replica_ctx,
                                                 tmp_path):
        """The acceptance contract in-process: train -> replicate ->
        'lose' the node -> a fresh trainer peer-restores from the
        surviving holder's DRAM and its next step is BITWISE the
        uninterrupted trainer's — same params, same rng stream, zero
        storage reads (no checkpoint dir even exists)."""
        master = start_local_master()
        try:
            store, srv = _register_holder(master, node_id=9)
            trainerA, batch = _linear_trainer(master, node_id=0)
            state = trainerA.prepare()
            for _ in range(3):
                state, _ = trainerA.step(state, batch)
            snap = _push_through_replicator(trainerA, state, master,
                                            store)
            # the node is lost: its own store is gone, the master hears
            # about the failure (the diagnosis/report path the wedge
            # exercises end-to-end)
            report_client = MasterClient(master.addr, node_id=0)
            report_client.report_failure(
                node_rank=0, restart_count=0, error_data="chaos",
                level="node")
            report_client.close()
            plan = MasterClient(master.addr, node_id=0)\
                .get_recovery_plan()
            assert [h["node_id"] for h in plan["owners"]["0"]] == [9]

            trainerB, _ = _linear_trainer(master, node_id=0)
            stateB = trainerB.prepare()
            assert trainerB._host_step == 3
            # rebuilt state is bitwise the snapshot
            for a, b in zip(jax.tree.leaves(snap.tree),
                            jax.tree.leaves(jax.device_get(stateB))):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
            # the rng stream continues exactly: one more step each side
            state, _ = trainerA.step(state, batch)
            stateB, _ = trainerB.step(stateB, batch)
            for a, b in zip(jax.tree.leaves(jax.device_get(state)),
                            jax.tree.leaves(jax.device_get(stateB))):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
            # the recovery is evented: DONE with zero storage bytes,
            # and the mttr derivation pairs the peer_rebuild scenario
            records = _events(tmp_path)
            done = [r for r in records
                    if r["kind"] == "peer_rebuild_done"]
            assert done and done[0]["storage_bytes"] == 0
            assert done[0]["bytes_from_peers"] > 0
            from dlrover_tpu.telemetry.mttr import mttr_report

            report = mttr_report(records)
            pr = report["detail"]["by_scenario"].get("peer_rebuild")
            assert pr and pr["count"] >= 1, report
        finally:
            srv.stop(grace=0)
            master.stop()

    def test_stale_replica_falls_back_to_newer_checkpoint(
            self, replica_ctx, tmp_path):
        """The expired-cadence fault: the replicator froze at step 3,
        a checkpoint committed at a later step — recovery must prefer
        the NEWER storage copy (with an error-coded fallback event),
        not silently rewind the job to the stale replica."""
        master = start_local_master()
        ckpt_dir = str(tmp_path / "ckpt")
        try:
            store, srv = _register_holder(master, node_id=9)
            trainerA, batch = _linear_trainer(master, node_id=0,
                                              ckpt_dir=ckpt_dir)
            state = trainerA.prepare()
            for _ in range(3):
                state, _ = trainerA.step(state, batch)
            replicator = repl.SnapshotReplicator(
                trainerA._master_client, node_id=0)
            try:
                snap = trainerA.snapshot(state)
                replicator.submit(snap.tree, snap.meta, snap.step)
                deadline = time.monotonic() + 20
                while time.monotonic() < deadline and \
                        not store.inventory().get("0"):
                    time.sleep(0.05)
                # the injected fault: cadence expires here — no more
                # pushes — while training continues and checkpoints
                freeze_replicator(replicator)
                for _ in range(2):
                    state, _ = trainerA.step(state, batch)
                snap5 = trainerA.snapshot(state)
                assert not replicator.submit(snap5.tree, snap5.meta,
                                             snap5.step)
            finally:
                replicator.stop()
            trainerA.save(state)  # step 5 committed to storage
            trainerA.finalize()

            trainerB, _ = _linear_trainer(master, node_id=0,
                                          ckpt_dir=ckpt_dir)
            stateB = trainerB.prepare()
            assert trainerB._host_step == 5, (
                "recovery adopted the stale replica over the newer "
                "checkpoint")
            records = _events(tmp_path)
            fb = [r for r in records
                  if r["kind"] == "peer_rebuild_fallback"]
            assert fb and fb[-1]["error_code"] == "REPLICA_STALE"
            # a by-design degradation must not strand an unpaired
            # peer_rebuild incident in the derived MTTR report (BEGIN
            # opens only once a transfer actually starts; FALLBACK
            # closes a mid-transfer abort)
            from dlrover_tpu.telemetry.mttr import mttr_report

            assert "error" not in mttr_report(records), \
                mttr_report(records)
            del stateB
        finally:
            srv.stop(grace=0)
            master.stop()

    def test_no_replicas_configured_is_a_clean_noop(self, tmp_path):
        """With the plane off the prepare ladder must not touch the
        master at all (snapshot_replicas=0 is the default deploy)."""
        trainer, _ = _linear_trainer()
        state = trainer.prepare()
        assert int(state.step) == 0


# -- executor auto-wiring -----------------------------------------------------


class TestExecutorReplicaHook:
    def test_hook_autowires_and_pushes_on_cadence(self, replica_ctx,
                                                  tmp_path):
        from dlrover_tpu.trainer.conf import Configuration
        from dlrover_tpu.trainer.executor import (
            SnapshotReplicaHook,
            TrainExecutor,
        )

        master = start_local_master()
        try:
            store, srv = _register_holder(master, node_id=9)
            trainer, batch = _linear_trainer(master, node_id=0)
            executor = TrainExecutor(
                trainer,
                train_iter_fn=lambda: [batch] * 12,
                master_client=trainer._master_client,
                conf=Configuration({
                    "train_steps": 12, "log_every_steps": 0,
                    "train_window": 2, "preemption_grace": False,
                    "plan_poll_secs": 0, "runtime_report_steps": 0,
                }),
            )
            hooks = [h for h in executor._hooks
                     if isinstance(h, SnapshotReplicaHook)]
            assert len(hooks) == 1, "replica hook did not auto-wire"
            executor.train_and_evaluate()
            inv = store.inventory().get("0")
            assert inv, "no replica landed on the surviving peer"
            assert inv["manifest"]["meta"]["host_step"] >= 2
            records = _events(tmp_path)
            assert any(r["kind"] == "replica_pushed" for r in records)
        finally:
            srv.stop(grace=0)
            master.stop()


# -- HostSnapshot edge cases (ISSUE satellite) --------------------------------


class TestHostSnapshotEdges:
    def test_nbytes_counts_non_numpy_leaves(self):
        snap = HostSnapshot(step=0, tree={
            "w": np.zeros((4, 4), np.float32),
            "scalar": 3.5,          # python float leaf
            "count": 7,             # python int leaf
        }, meta={})
        base = 4 * 4 * 4
        assert snap.nbytes() > base  # the scalars are sized, not 0

    def test_take_under_donation_does_not_alias(self):
        """A donated step dispatched AFTER take() must not scribble the
        snapshot (on CPU, device_get can return zero-copy views of the
        live buffers the next step donates)."""
        import jax.numpy as jnp

        @jax.jit
        def poison(x):
            return x * jnp.nan

        donated = jax.jit(lambda x: x + 1.0, donate_argnums=0)
        state = jnp.arange(512, dtype=jnp.float32)
        snap = HostSnapshot.take({"x": state})
        want = np.asarray(snap.tree["x"]).copy()
        out = donated(state)  # donates the buffer take() read
        _ = poison(out).block_until_ready()
        np.testing.assert_array_equal(snap.tree["x"], want)

    def test_restore_into_smaller_mesh(self):
        """A snapshot taken on the 8-device world must land in a
        4-device submesh's shardings — the survivor-mesh contract of
        the peer-rebuild path (Universal Checkpointing: the rebuilt
        host tree reshards to whatever the new mesh wants)."""
        devices = jax.devices()
        if len(devices) < 8:
            pytest.skip("needs the 8-device CPU mesh")

        def init_fn(rng):
            return {"w": jax.random.normal(rng, (8, 4))}

        def loss_fn(params, batch, rng):
            return jnp.mean((batch["x"] @ params["w"]) ** 2), {}

        x = np.ones((8, 8), np.float32)
        batch = {"x": jnp.asarray(x)}
        big = ElasticTrainer(init_fn, loss_fn, optax.sgd(0.1), batch,
                             strategy=Strategy(mesh=MeshPlan(data=-1)))
        state = big.prepare()
        state, _ = big.step(state, batch)
        snap = big.snapshot(state)
        small = ElasticTrainer(init_fn, loss_fn, optax.sgd(0.1), batch,
                               strategy=Strategy(mesh=MeshPlan(data=-1)),
                               devices=devices[:4])
        small.prepare()
        restored = snap.restore(small.accelerated.state_sharding)
        jax.block_until_ready(restored)
        for a, b in zip(jax.tree.leaves(snap.tree),
                        jax.tree.leaves(jax.device_get(restored))):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# -- rpc retry hardening (ISSUE satellite) ------------------------------------


class TestRetryHardening:
    def test_backoff_is_jittered_and_exponential(self):
        from dlrover_tpu.rpc.client import retry_backoff_s

        for i in range(4):
            lows = 0.5 * min(30.0, 1.0 * 2 ** i)
            highs = min(30.0, 1.0 * 2 ** i)
            draws = {retry_backoff_s(i) for _ in range(16)}
            assert all(lows <= d < highs or d == highs for d in draws)
            assert len(draws) > 1, "no jitter: workers re-synchronize"

    def test_flaky_servicer_retries_counted_and_desynchronized(self):
        """The satellite pin: a flaky master exercises the production
        retry path — every retry spends the counted budget, and two
        clients' sleep schedules must NOT be identical (the old fixed
        sleep synchronized the whole fleet into stampedes)."""
        from unittest import mock

        from dlrover_tpu.diagnosis.fault_injection import make_flaky
        from dlrover_tpu.telemetry import get_registry, names as tm

        master = start_local_master()
        try:
            sleeps = []
            with mock.patch("dlrover_tpu.rpc.client.time.sleep",
                            side_effect=lambda s: sleeps.append(s)):
                before = get_registry().counter(tm.RPC_RETRIES).value
                schedules = []
                for seed in (3, 4):
                    client = MasterClient(master.addr, node_id=0)
                    make_flaky(client._channel, drop_rate=0.4,
                               seed=seed)
                    mark = len(sleeps)
                    for _ in range(6):
                        try:
                            client.report_heartbeat()
                        except Exception:  # noqa: BLE001 — a call may
                            # exhaust its whole retry budget; the test
                            # only cares about the sleep schedule
                            pass
                    schedules.append(tuple(
                        round(s, 6) for s in sleeps[mark:]))
                    client.close()
                after = get_registry().counter(tm.RPC_RETRIES).value
            assert after - before >= 2, "no retry was ever counted"
            assert all(schedules), "injection never fired"
            assert schedules[0] != schedules[1], (
                "two workers slept the identical schedule — the "
                "stampede is back")
        finally:
            master.stop()


# -- derivations --------------------------------------------------------------


class TestDerivations:
    def test_goodput_gains_the_peer_rebuild_bucket(self):
        from dlrover_tpu.telemetry.goodput import (
            BUCKET_PRIORITY,
            derive_goodput,
        )

        assert "peer_rebuild" in BUCKET_PRIORITY
        t = time.time()
        records = [
            {"kind": "train_start", "ts": t, "pid": 1, "mono": 0.0},
            {"kind": "peer_rebuild_begin", "ts": t + 1, "pid": 1,
             "mono": 1.0},
            {"kind": "peer_rebuild_done", "ts": t + 3, "pid": 1,
             "mono": 3.0, "step": 4},
            {"kind": "train_end", "ts": t + 10, "pid": 1,
             "mono": 10.0},
        ]
        ledger = derive_goodput(records)
        assert ledger["detail"]["buckets"]["peer_rebuild"][
            "seconds"] == pytest.approx(2.0, abs=0.01)

    def test_dlr008_covers_the_new_failure_kinds(self):
        from dlrover_tpu.analysis.ast_rules import (
            FAILURE_EVENT_ATTRS,
            FAILURE_EVENT_VALUES,
        )

        for attr in ("REPLICA_PUSH_FAILED", "REPLICA_PLAN_DEGRADED",
                     "REPLICA_HOLDER_LOST", "PEER_REBUILD_FALLBACK"):
            assert attr in FAILURE_EVENT_ATTRS
        for val in ("replica_push_failed", "replica_plan_degraded",
                    "replica_holder_lost", "peer_rebuild_fallback"):
            assert val in FAILURE_EVENT_VALUES
