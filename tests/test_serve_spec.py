"""Speculative decoding (ISSUE 18): n-gram self-drafting + batched
multi-token verify.

Tier-1 core: the bitwise-to-greedy oracle at every acceptance pattern
(0%, 100%, alternating, per-slot mixed K — drafts are injected, so
each pattern is forced, not hoped for), on f32 AND int8 pools, with
the prefix pool on, and across a live slot resize mid-stream; the
zero-steady-state-recompile pin (>= 32 verify steps, and across a
live K retune applied prewarm-then-swap); the drafted = accepted +
wasted conservation ledger from per-record counts through the router
totals to the event forensics; the failed-verify credit restore; the
planner's evidence-only pricing (zero evidence == exactly the K=0
estimate); and the optimizer's K enumeration under the master switch.
"""

import time

import jax
import numpy as np
import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.models import llama
from dlrover_tpu.parallel import planner
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.serving.engine import ServeEngine, ServeExecutor
from dlrover_tpu.serving.router import RequestRouter
from dlrover_tpu.serving.spec_decode import NgramProposer
from dlrover_tpu.telemetry import EventKind, recent_events
from dlrover_tpu.telemetry.events import clear_ring
from dlrover_tpu.telemetry.metrics import process_registry
from dlrover_tpu.telemetry import names as tm


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


TINY = llama.llama_tiny()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init(jax.random.PRNGKey(0), TINY)


@pytest.fixture(scope="module")
def plain_engine(tiny_params):
    eng = ServeEngine(
        TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                rule_set="llama"),
        serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
    )
    eng.prepare(tiny_params)
    return eng


@pytest.fixture(scope="module")
def spec_engine(tiny_params):
    eng = ServeEngine(
        TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                rule_set="llama"),
        serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
        spec_draft_len=4,
    )
    eng.prepare(tiny_params)
    return eng


def _prompt(n=6, seed=0):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, TINY.vocab_size, size=(n,))]


def _jobs(n=4, max_new=10, seed0=50, plen=6):
    return [(f"r{i}", _prompt(plen, seed=seed0 + i), max_new)
            for i in range(n)]


def _serve(eng, jobs, proposer=None):
    """Serve ``jobs`` ([(rid, prompt, max_new)]) on a fresh slot pool;
    returns {rid: record}."""
    eng.cache = eng.fresh_cache()
    ex = ServeExecutor(eng, serve_window=1, spec_proposer=proposer)
    for rid, prompt, max_new in jobs:
        ex.submit(prompt, max_new_tokens=max_new, request_id=rid)
    return {r["request_id"]: r for r in ex.serve()}, ex


# -- injectable proposers: each forces one acceptance pattern -----------------


class _OracleProposer:
    """Drafts the TRUE greedy continuation (from a reference serve) —
    forces 100% acceptance."""

    def __init__(self, refs):
        # {prompt tuple -> full reference token list}
        self._refs = dict(refs)

    def _stream(self, history):
        for p, stream in self._refs.items():
            if len(history) >= len(p) and tuple(history[:len(p)]) == p:
                return stream, len(history) - len(p)
        return None, 0

    def propose(self, history, k):
        stream, done = self._stream(history)
        if stream is None:
            return []
        return list(stream[done:done + k])


class _WrongProposer(_OracleProposer):
    """Drafts provably-wrong tokens (true-next + 1 mod vocab) —
    forces 0% acceptance while still paying full drafts."""

    def propose(self, history, k):
        return [(t + 1) % TINY.vocab_size
                for t in super().propose(history, k)]


class _AlternatingProposer(_OracleProposer):
    """Oracle on even calls, wrong on odd — acceptance flips every
    verify step."""

    def __init__(self, refs):
        super().__init__(refs)
        self._n = 0

    def propose(self, history, k):
        right = super().propose(history, k)
        self._n += 1
        if self._n % 2:
            return right
        return [(t + 1) % TINY.vocab_size for t in right]


class _MixedProposer(_OracleProposer):
    """Per-slot mixed K in ONE program: full-K oracle drafts for some
    prompts, shorter drafts for others, nothing for the rest."""

    def __init__(self, refs, full, short):
        super().__init__(refs)
        self._full = {tuple(p) for p in full}
        self._short = {tuple(p) for p in short}

    def propose(self, history, k):
        stream, done = self._stream(history)
        if stream is None:
            return []
        for p in self._full:
            if tuple(history[:len(p)]) == p:
                return list(stream[done:done + k])
        for p in self._short:
            if tuple(history[:len(p)]) == p:
                return list(stream[done:done + max(1, k // 2)])
        return []


# -- the host-side n-gram proposer --------------------------------------------


class TestNgramProposer:
    def test_longest_ngram_wins_and_self_match_falls_back(self):
        p = NgramProposer()
        # suffix [5,6,7] re-occurs at 0: continuation is h[3:6]
        h = [5, 6, 7, 9, 5, 6, 7]
        assert p.propose(h, 3) == [9, 5, 6]

    def test_no_match_returns_empty_and_k0_is_empty(self):
        p = NgramProposer()
        assert p.propose([1, 2, 3, 4], 2) == []
        assert p.propose([1, 2, 1], 0) == []

    def test_incremental_sync_sees_new_tokens(self):
        p = NgramProposer()
        h = [3, 4, 5]
        assert p.propose(h, 2) == []
        h = h + [8, 3, 4]
        # suffix [3,4] matched at 0 -> continuation [5,8]
        assert p.propose(h, 2) == [5, 8]

    def test_draft_never_exceeds_k(self):
        p = NgramProposer()
        h = [1, 2, 9, 9, 9, 1, 2]
        got = p.propose(h, 3)
        assert got == [9, 9, 9]
        assert p.propose(h, 1) == [9]

    def test_periodic_tail_extends_to_full_k(self):
        # A period-d loop near the tail must draft k tokens, not d:
        # the match at distance d is extended periodically instead of
        # truncating where the literal continuation hits end-of-history.
        p = NgramProposer()
        assert p.propose([7, 7, 7, 7], 4) == [7, 7, 7, 7]
        q = NgramProposer()
        assert q.propose([3, 8, 3, 8, 3, 8], 5) == [3, 8, 3, 8, 3]


# -- THE oracle: bitwise-to-greedy at every acceptance pattern ----------------


class TestBitwiseParity:
    def _reference(self, plain_engine, jobs):
        got, _ = _serve(plain_engine, jobs)
        return {rid: r["tokens"] for rid, r in got.items()}

    def test_forced_acceptance_patterns_bitwise(self, plain_engine,
                                                spec_engine):
        jobs = _jobs(4, max_new=10)
        expect = self._reference(plain_engine, jobs)
        refs = {tuple(p): expect[rid] for rid, p, _ in jobs}
        legs = {
            "ngram": None,  # natural self-drafting
            "all-wrong": lambda: _WrongProposer(refs),
            "oracle": lambda: _OracleProposer(refs),
            "alternating": lambda: _AlternatingProposer(refs),
        }
        for name, factory in legs.items():
            got, _ = _serve(spec_engine, jobs, proposer=factory)
            for rid, _, _ in jobs:
                assert got[rid]["tokens"] == expect[rid], (name, rid)
                d = got[rid]["spec_drafted_tokens"]
                a = got[rid]["spec_accepted_tokens"]
                assert 0 <= a <= d, (name, rid)
                if name == "oracle":
                    assert d > 0 and a == d, (rid, d, a)
                if name == "all-wrong":
                    assert d > 0 and a == 0, (rid, d, a)

    def test_per_slot_mixed_draft_lengths_bitwise(self, plain_engine,
                                                  spec_engine):
        jobs = _jobs(4, max_new=10)
        expect = self._reference(plain_engine, jobs)
        refs = {tuple(p): expect[rid] for rid, p, _ in jobs}
        full = [jobs[0][1]]
        short = [jobs[1][1]]  # jobs 2,3 draft nothing -> n_draft 0
        got, _ = _serve(
            spec_engine, jobs,
            proposer=lambda: _MixedProposer(refs, full, short))
        for rid, _, _ in jobs:
            assert got[rid]["tokens"] == expect[rid], rid
        assert got["r0"]["spec_drafted_tokens"] \
            > got["r1"]["spec_drafted_tokens"] > 0
        assert got["r2"]["spec_drafted_tokens"] == 0
        assert got["r3"]["spec_drafted_tokens"] == 0

    def test_int8_pool_bitwise(self, tiny_params):
        kw = dict(
            strategy=Strategy(mesh=MeshPlan(data=-1),
                              rule_set="llama"),
            serve_slots=2, prefill_chunk=8, max_seq=48, page_size=8,
            kv_precision="int8",
        )
        plain = ServeEngine(TINY, **kw)
        plain.prepare(tiny_params)
        spec = ServeEngine(TINY, spec_draft_len=3, **kw)
        spec.prepare(tiny_params)
        jobs = _jobs(3, max_new=8, seed0=90)
        expect, _ = _serve(plain, jobs)
        got, _ = _serve(spec, jobs)
        for rid, _, _ in jobs:
            assert got[rid]["tokens"] == expect[rid]["tokens"], rid

    def test_prefix_pool_reuse_composes_bitwise(self, plain_engine,
                                                tiny_params):
        eng = ServeEngine(
            TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                    rule_set="llama"),
            serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
            prefix_pool_pages=8, spec_draft_len=4,
        )
        eng.prepare(tiny_params)
        seed_prompt = _prompt(24, seed=70)
        _serve(eng, [("seed", seed_prompt, 2)])
        # the query reuses seeded pages AND speculates — both on
        ref, _ = _serve(plain_engine, [("q", seed_prompt, 6)])
        got, _ = _serve(eng, [("q", seed_prompt, 6)])
        assert got["q"]["prefix_hit_tokens"] > 0
        assert got["q"]["tokens"] == ref["q"]["tokens"]

    def test_live_slot_resize_mid_stream_bitwise(self, plain_engine,
                                                 spec_engine):
        jobs = _jobs(3, max_new=12, seed0=120)
        expect = self._reference(plain_engine, jobs)
        spec_engine.cache = spec_engine.fresh_cache()
        ex = ServeExecutor(spec_engine, serve_window=1)
        for rid, prompt, max_new in jobs:
            ex.submit(prompt, max_new_tokens=max_new, request_id=rid)
        ex.serve(max_steps=2, until_idle=False)
        ex.request_retune(serve_slots=8)
        done = {r["request_id"]: r for r in ex.serve()}
        assert spec_engine.program.spec.num_slots == 8
        for rid, _, _ in jobs:
            assert done[rid]["tokens"] == expect[rid], rid
        # restore the module engine's canonical knobs
        ex.request_retune(serve_slots=4)
        ex._drain_window()
        ex._apply_retune()
        assert spec_engine.program.spec.num_slots == 4


# -- zero steady-state recompiles ---------------------------------------------


class TestZeroRecompile:
    def test_32_step_pin_across_every_acceptance_pattern(
            self, plain_engine, spec_engine):
        jobs = [("pin", _prompt(6, seed=200), 36)]
        expect, _ = _serve(plain_engine, jobs)
        refs = {tuple(jobs[0][1]): expect["pin"]["tokens"]}
        _serve(spec_engine, jobs)  # warm every program once
        compiles = spec_engine.compile_count
        cache_size = spec_engine.program.compiled_cache_size()
        # all-wrong drafting = 1 token/verify-step = the most steps
        got, ex = _serve(spec_engine, jobs,
                         proposer=lambda: _WrongProposer(refs))
        assert got["pin"]["tokens"] == expect["pin"]["tokens"]
        assert ex.decode_steps >= 32
        # and a 100%-acceptance leg reuses the same program too
        got2, _ = _serve(spec_engine, jobs,
                         proposer=lambda: _OracleProposer(refs))
        assert got2["pin"]["tokens"] == expect["pin"]["tokens"]
        assert spec_engine.compile_count == compiles
        assert spec_engine.program.compiled_cache_size() == cache_size

    def test_live_k_retune_prewarm_then_zero_compile_swap(
            self, plain_engine, spec_engine, tiny_params):
        jobs = _jobs(2, max_new=8, seed0=140)
        expect = {rid: r["tokens"]
                  for rid, r in _serve(plain_engine, jobs)[0].items()}
        # standby compile of the K=2 program is allowed...
        spec_engine.prewarm(spec_draft_len=2)
        compiles = spec_engine.compile_count
        # ...the live apply must be a pure program swap
        recompiled = spec_engine.retune(spec_draft_len=2, slot_map={})
        assert recompiled == 0
        assert spec_engine.program.spec_k == 2
        got, _ = _serve(spec_engine, jobs)
        for rid, _, _ in jobs:
            assert got[rid]["tokens"] == expect[rid], rid
        assert spec_engine.compile_count == compiles
        # restore the module engine's canonical K (cached: no compile)
        assert spec_engine.retune(spec_draft_len=4, slot_map={}) == 0
        assert spec_engine.program.spec_k == 4

    def test_executor_retune_path_applies_k_with_negative_ack_guard(
            self, spec_engine):
        """The plan path: request_retune(spec_draft_len=...) applies at
        the drained boundary through the same prewarm-protected swap."""
        spec_engine.cache = spec_engine.fresh_cache()
        ex = ServeExecutor(spec_engine, serve_window=1)
        ex._ensure_prepared()
        spec_engine.prewarm(spec_draft_len=3)
        compiles = spec_engine.compile_count
        ex.request_retune(spec_draft_len=3, plan_id="k3")
        ex._apply_retune()
        assert spec_engine.program.spec_k == 3
        assert spec_engine.compile_count == compiles
        ex.request_retune(spec_draft_len=4)  # restore module knobs
        ex._apply_retune()
        assert spec_engine.program.spec_k == 4


# -- conservation: drafted = accepted + wasted, everywhere --------------------


class TestSpecLedger:
    def test_per_record_and_registry_conservation(self, spec_engine):
        reg = process_registry()
        d0 = reg.counter(tm.SERVE_SPEC_DRAFTED).value
        a0 = reg.counter(tm.SERVE_SPEC_ACCEPTED).value
        w0 = reg.counter(tm.SERVE_SPEC_WASTED).value
        # repetitive prompts so natural n-gram drafting fires
        jobs = [(f"p{i}", [7, 8, 9] * 4, 10) for i in range(3)]
        got, ex = _serve(spec_engine, jobs)
        drafted = sum(r["spec_drafted_tokens"] for r in got.values())
        accepted = sum(r["spec_accepted_tokens"] for r in got.values())
        assert drafted > 0
        for r in got.values():
            assert 0 <= r["spec_accepted_tokens"] \
                <= r["spec_drafted_tokens"]
        # registry counters tie out against the records exactly
        assert reg.counter(tm.SERVE_SPEC_DRAFTED).value - d0 == drafted
        assert reg.counter(tm.SERVE_SPEC_ACCEPTED).value - a0 \
            == accepted
        assert reg.counter(tm.SERVE_SPEC_WASTED).value - w0 \
            == drafted - accepted
        assert ex._spec_drafted_total == drafted
        assert ex._spec_accepted_total == accepted

    def test_router_totals_live_and_forensic_agree(self):
        clear_ring()
        r = RequestRouter(lease_timeout_secs=120.0)
        counts = [(12, 7), (4, 0), (9, 9)]
        rids = [r.submit([1, 2, 3], 4) for _ in counts]
        r.lease(0, len(counts))
        for rid, (d, a) in zip(rids, counts):
            assert r.complete(0, rid, [5, 6], spec_drafted_tokens=d,
                              spec_accepted_tokens=a)
        spec = r.report()["spec"]
        want_d = sum(d for d, _ in counts)
        want_a = sum(a for _, a in counts)
        assert spec["drafted_tokens"] == want_d
        assert spec["accepted_tokens"] == want_a
        assert spec["wasted_tokens"] == want_d - want_a
        assert spec["accept_rate"] == round(want_a / want_d, 4)
        assert r.spec_summary() == spec
        # forensic: the completion events carry the same columns
        evs = [e for e in recent_events()
               if e["kind"] == EventKind.SERVE_REQUEST_COMPLETED]
        assert sum(e.get("spec_drafted") or 0 for e in evs) == want_d
        assert sum(e.get("spec_accepted") or 0 for e in evs) == want_a
        # the `tpurun requests --events` aggregation must render the
        # exact live block (wasted derived, -1.0 on zero evidence)
        from dlrover_tpu.serving.cli import _spec_forensic
        assert _spec_forensic(recent_events()) == spec
        assert _spec_forensic([]) == {
            "drafted_tokens": 0, "accepted_tokens": 0,
            "wasted_tokens": 0, "accept_rate": -1.0}

    def test_releases_twin_cannot_double_charge(self):
        r = RequestRouter(lease_timeout_secs=0.01)
        rid = r.submit([1, 2], 4)
        r.lease(0, 1)
        time.sleep(0.05)
        assert r.scan_expired_once() == [rid]
        r.lease(1, 1)  # the re-leased twin
        assert r.complete(0, rid, [5], spec_drafted_tokens=6,
                          spec_accepted_tokens=3)
        # the twin's late completion is deduped: the ledger must not
        # double-count its drafts
        assert not r.complete(1, rid, [5], spec_drafted_tokens=6,
                              spec_accepted_tokens=3)
        spec = r.spec_summary()
        assert spec["drafted_tokens"] == 6
        assert spec["accepted_tokens"] == 3

    def test_negative_and_overshoot_reports_are_clamped(self):
        r = RequestRouter()
        rid = r.submit([1], 2)
        r.lease(0, 1)
        r.complete(0, rid, [9], spec_drafted_tokens=-5,
                   spec_accepted_tokens=12)
        spec = r.spec_summary()
        assert spec["drafted_tokens"] == 0
        assert spec["accepted_tokens"] == 0
        assert spec["accept_rate"] == -1.0  # no evidence, not 0/0

    def test_failed_verify_restores_draft_credit(self, plain_engine,
                                                 spec_engine):
        """A verify dispatch that raises must not charge the ledger
        (nothing committed) and must not kill serving: the batch falls
        back to one plain decode step, bitwise the same stream."""
        jobs = _jobs(2, max_new=8, seed0=160)
        expect = {rid: r["tokens"]
                  for rid, r in _serve(plain_engine, jobs)[0].items()}
        refs = {tuple(p): expect[rid] for rid, p, _ in jobs}
        spec_engine.cache = spec_engine.fresh_cache()
        ex = ServeExecutor(spec_engine, serve_window=1,
                           spec_proposer=lambda: _OracleProposer(refs))
        for rid, prompt, max_new in jobs:
            ex.submit(prompt, max_new_tokens=max_new, request_id=rid)
        program = spec_engine.program
        orig, calls = program.verify, []

        def flaky(*args):
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("injected verify failure")
            return orig(*args)

        program.verify = flaky
        try:
            got = {r["request_id"]: r for r in ex.serve()}
        finally:
            program.verify = orig
        for rid, _, _ in jobs:
            assert got[rid]["tokens"] == expect[rid], rid
        assert len(calls) >= 2  # failed once, then kept speculating
        drafted = sum(r["spec_drafted_tokens"] for r in got.values())
        accepted = sum(r["spec_accepted_tokens"] for r in got.values())
        # the oracle drafts ALWAYS land: with the failed step charged,
        # drafted would exceed accepted — credit restore keeps them
        # equal (and the recovered steps did speculate)
        assert drafted > 0 and accepted == drafted


# -- planner pricing: evidence-only -------------------------------------------


class TestSpecPlannerPricing:
    def test_zero_evidence_is_exactly_the_k0_estimate(self):
        m = planner.model_spec_from_llama(TINY, global_batch=1)
        base = planner.estimate_decode(m, 8, 4, 8, 64)
        noev = planner.estimate_decode(m, 8, 4, 8, 64,
                                       spec_draft_len=4,
                                       spec_accept_rate=-1.0)
        assert noev["tokens_per_s"] == base["tokens_per_s"]
        assert noev["step_s"] == base["step_s"]
        assert noev["breakdown"]["spec_expected_tokens_per_step"] == 1.0
        assert noev["breakdown"]["spec_accept_rate"] == -1.0

    def test_monotone_in_observed_rate(self):
        m = planner.model_spec_from_llama(TINY, global_batch=1)
        prev = None
        for rate in (0.0, 0.3, 0.6, 0.9):
            est = planner.estimate_decode(m, 8, 4, 8, 64,
                                          spec_draft_len=4,
                                          spec_accept_rate=rate)
            bd = est["breakdown"]
            assert bd["spec_expected_tokens_per_step"] \
                == pytest.approx(1.0 + rate * 4)
            if prev is not None:
                assert est["tokens_per_s"] > prev
            prev = est["tokens_per_s"]

    def test_zero_rate_never_beats_k0(self):
        # rate 0: every draft wasted — (K+1)x flops for 1 token/step
        m = planner.model_spec_from_llama(TINY, global_batch=1)
        base = planner.estimate_decode(m, 8, 4, 8, 64)
        zero = planner.estimate_decode(m, 8, 4, 8, 64,
                                       spec_draft_len=4,
                                       spec_accept_rate=0.0)
        assert zero["tokens_per_s"] <= base["tokens_per_s"]


# -- the optimizer knob family ------------------------------------------------


def _optimizer(publish=None):
    from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
    from dlrover_tpu.master.optimizer import RuntimeOptimizer

    opt = RuntimeOptimizer(NodeRuntimeStore(), publish=publish,
                           cooldown_secs=0.0)
    opt.update_model_info(comm.ModelInfo(
        num_params=7_000_000_000, hidden_size=8 * 128, num_layers=32,
        seq_len=128))
    return opt


def _serve_report(**kw):
    base = dict(node_id=0, world=8, serve_slots=4, prefill_chunk=16,
                kv_precision="bf16", max_seq=128, num_layers=32,
                kv_heads=8, head_dim=128, page_size=16)
    base.update(kw)
    return comm.ServeConfigReport(**base)


class TestSpecKnobFamily:
    def test_zero_evidence_never_turns_spec_on(self):
        published = []
        opt = _optimizer(publish=published.append)
        opt.update_serving_config(_serve_report(
            spec_draft_len=0, spec_accept_rate=-1.0))
        if published:
            # other knobs may move; spec must publish leave-unchanged
            assert published[-1].serve_spec_draft_len == -1

    def test_observed_acceptance_chooses_nonzero_k(self):
        published = []
        opt = _optimizer(publish=published.append)
        opt.update_serving_config(_serve_report(
            spec_draft_len=0, spec_accept_rate=0.7))
        dec = [d for d in opt.decisions()
               if d["trigger"].startswith("serve:")][-1]
        assert dec["outcome"] == "chosen"
        chosen = dec["chosen"]
        assert chosen["spec_draft_len"] > 0
        assert "|spec=" in chosen["key"]
        assert published[-1].serve_spec_draft_len \
            == chosen["spec_draft_len"]

    def test_master_switch_freezes_enumeration(self, monkeypatch):
        monkeypatch.setattr(get_context(), "serve_spec_enabled", False)
        opt = _optimizer()
        cands = opt._serve_candidates({
            "serve_slots": 4, "prefill_chunk": 8, "max_seq": 48,
            "kv_precision": "f32", "world": 8, "node_id": 0,
            "spec_draft_len": 0})
        assert all(c["spec_draft_len"] == 0 for c in cands)

    def test_enumeration_covers_the_k_ladder(self):
        opt = _optimizer()
        cands = opt._serve_candidates({
            "serve_slots": 4, "prefill_chunk": 8, "max_seq": 48,
            "kv_precision": "f32", "world": 8, "node_id": 0,
            "spec_draft_len": 0})
        assert {c["spec_draft_len"] for c in cands} == {0, 2, 4, 8}

    def test_engine_master_switch_pins_k_to_zero(self, monkeypatch):
        monkeypatch.setattr(get_context(), "serve_spec_enabled", False)
        eng = ServeEngine(
            TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                    rule_set="llama"),
            serve_slots=2, prefill_chunk=8, max_seq=48, page_size=8,
            spec_draft_len=4,
        )
        assert eng.spec_draft_len == 0  # no verify program will build


# -- the windowed acceptance gauge on the node series -------------------------


BOUNDS = [0.001, 0.005, 0.01, 0.05, 0.1, 1.0]


def _spec_node_report(node, steps, drafted, accepted):
    counts = [0] * (len(BOUNDS) + 1)
    counts[1] = steps
    return comm.NodeRuntimeReport(
        node_id=node, node_type="serve", timestamp=time.time(),
        step=int(steps), steps_total=float(steps), bounds=BOUNDS,
        step_time_counts=counts, serve_tokens_total=float(steps),
        serve_slots=4.0, rss_mb=1.0,
        serve_spec_drafted_total=float(drafted),
        serve_spec_accepted_total=float(accepted),
    )


class TestSpecNodeSeries:
    def test_windowed_rate_diffs_cumulative_totals(self):
        from dlrover_tpu.master.monitor.node_series import (
            NodeRuntimeStore,
        )

        process_registry().reset()
        store = NodeRuntimeStore()
        store.ingest(_spec_node_report(3, 10, drafted=40, accepted=30))
        reg = process_registry()
        labels = {"node": "3"}
        # one sample: no window yet — absent, not zero
        assert reg.get(tm.NODE_SERVE_SPEC_ACCEPT_RATE,
                       labels=labels) is None
        # window 2: +60 drafted, +15 accepted -> 0.25 (NOT the
        # lifetime 45/100 — a regression shows immediately)
        store.ingest(_spec_node_report(3, 20, drafted=100, accepted=45))
        g = reg.get(tm.NODE_SERVE_SPEC_ACCEPT_RATE, labels=labels)
        assert g is not None and g.value == pytest.approx(0.25)

    def test_non_spec_nodes_export_no_rate(self):
        from dlrover_tpu.master.monitor.node_series import (
            NodeRuntimeStore,
        )

        process_registry().reset()
        store = NodeRuntimeStore()
        store.ingest(_spec_node_report(4, 10, drafted=0, accepted=0))
        store.ingest(_spec_node_report(4, 20, drafted=0, accepted=0))
        assert process_registry().get(
            tm.NODE_SERVE_SPEC_ACCEPT_RATE,
            labels={"node": "4"}) is None
