"""Text reader + dynamic-shard batch source (FileReader parity)."""

import numpy as np
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.sharding_client import ShardingClient
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.trainer.text_reader import (
    ByteTokenizer,
    LineIndexedFile,
    ShardedTextBatches,
)


@pytest.fixture()
def corpus(tmp_path):
    path = tmp_path / "corpus.txt"
    lines = [f"line number {i} with some text" for i in range(100)]
    path.write_text("\n".join(lines) + "\n")
    return str(path), lines


class TestLineIndexedFile:
    def test_count_and_read(self, corpus):
        path, lines = corpus
        reader = LineIndexedFile(path)
        assert reader.count() == 100
        got = reader.read_range(10, 13)
        assert got == [lines[i].encode() for i in range(10, 13)]

    def test_no_trailing_newline(self, tmp_path):
        path = tmp_path / "no_nl.txt"
        path.write_bytes(b"alpha\nbeta\ngamma")  # no final newline
        reader = LineIndexedFile(path)
        assert reader.count() == 3
        assert reader.read_range(2, 3) == [b"gamma"]
        assert reader.read_range(0, 99) == [b"alpha", b"beta", b"gamma"]

    def test_crlf_stripped(self, tmp_path):
        path = tmp_path / "crlf.txt"
        path.write_bytes(b"one\r\ntwo\r\n")
        reader = LineIndexedFile(path)
        assert reader.read_range(0, 2) == [b"one", b"two"]

    def test_out_of_range_indices_warn(self, tmp_path):
        """A master/reader dataset_size mismatch drops records — the
        sharding protocol still credits them as consumed, so the drop
        must be VISIBLE (a silently shrinking epoch is undebuggable)."""
        import logging

        from dlrover_tpu.common.log import get_logger

        path = tmp_path / "three.txt"
        path.write_text("a\nb\nc\n")
        reader = LineIndexedFile(str(path))
        messages = []

        class _Capture(logging.Handler):
            def emit(self, record):
                messages.append(record.getMessage())

        target = get_logger("trainer.text")
        handler = _Capture(level=logging.WARNING)
        target.addHandler(handler)
        try:
            got = reader.read_indices([0, 5, 6, 2])
            assert got == [b"a", b"c"]
            assert any("out-of-range" in m for m in messages), messages
            # a contiguous run straddling the boundary drops only its
            # tail — and still warns
            messages.clear()
            got = reader.read_indices([1, 2, 3, 4])
            assert got == [b"b", b"c"]
            assert any("dropped 2 " in m for m in messages), messages
        finally:
            target.removeHandler(handler)


class TestByteTokenizer:
    def test_fixed_shape_bos_pad(self):
        tok = ByteTokenizer(seq_len=8)
        out = tok(b"hi")
        assert out.shape == (8,)
        assert out[0] == 1  # bos
        assert out[1] == ord("h") + 2 and out[2] == ord("i") + 2
        assert (out[3:] == 0).all()  # pad

    def test_truncates_long_records(self):
        tok = ByteTokenizer(seq_len=4)
        out = tok(b"abcdefgh")
        assert out.shape == (4,)
        assert (out[1:] == np.frombuffer(b"abc", np.uint8) + 2).all()


class TestHFTokenizerAdapter:
    def _tokenizer(self):
        # a real `tokenizers` tokenizer built in memory (no network)
        from tokenizers import Tokenizer, models
        from tokenizers.pre_tokenizers import Whitespace

        vocab = {"<pad>": 0, "<bos>": 1, "<unk>": 2}
        for i, w in enumerate(["line", "number", "with", "some", "text"]
                              + [str(n) for n in range(100)]):
            vocab[w] = i + 3
        t = Tokenizer(models.WordLevel(vocab, unk_token="<unk>"))
        t.pre_tokenizer = Whitespace()
        return t

    def test_padded_and_packed_modes(self, corpus):
        from dlrover_tpu.trainer.text_reader import HFTokenizerAdapter

        path, lines = corpus
        tok = HFTokenizerAdapter(self._tokenizer(), seq_len=16,
                                 pad_id=0, bos_id=1)
        assert tok.vocab_size == 108
        fixed = tok(lines[7].encode())
        assert fixed.shape == (16,) and fixed[0] == 1  # bos
        var = tok.encode(lines[7].encode())
        assert var.ndim == 1 and var[0] == 1 and len(var) <= 16

        master = start_local_master()
        try:
            reader = LineIndexedFile(path)
            client = MasterClient(master.addr, node_id=0)
            for name, pack in (("hf_pad", False), ("hf_pack", True)):
                sc = ShardingClient(
                    client, dataset_name=name, batch_size=4,
                    dataset_size=reader.count(), num_epochs=1,
                    num_minibatches_per_shard=2,
                )
                source = ShardedTextBatches(
                    sc, reader, batch_size=4, tokenizer=tok, seq_len=16,
                    pack=pack,
                )
                batches = list(source)
                assert batches, name
                for b in batches:
                    assert b["input_ids"].shape == (4, 16)
                    # pad ids never trained on
                    trained = b["labels"] != -100
                    assert (b["labels"][trained] != 0).all()
            client.close()
        finally:
            master.stop()


class TestPadLabelMasking:
    def test_interior_pad_id_tokens_keep_labels(self, tmp_path):
        """pad == eos convention: a REAL token sharing the pad id inside
        the sequence must keep its label — only the trailing pad run is
        masked (masking by id would silently untrain EOS everywhere)."""

        class IdTok:
            pad_id = 7
            vocab_size = 16
            seq_len = 8

            def __call__(self, record):
                # record "a b" -> [3, 7, 4] then padded with 7s: the
                # interior 7 is a REAL token (eos-like), trailing 7s pad
                ids = np.full((8,), 7, np.int32)
                ids[:3] = [3, 7, 4]
                return ids

        path = tmp_path / "one.txt"
        path.write_text("x\n" * 4)
        master = start_local_master()
        try:
            reader = LineIndexedFile(str(path))
            client = MasterClient(master.addr, node_id=0)
            sc = ShardingClient(
                client, dataset_name="padmask", batch_size=4,
                dataset_size=reader.count(), num_epochs=1,
                num_minibatches_per_shard=1,
            )
            source = ShardedTextBatches(sc, reader, batch_size=4,
                                        tokenizer=IdTok(), seq_len=8)
            batch = next(iter(source))
            labels = batch["labels"]
            # label[0] predicts ids[1] == 7 (the real interior token):
            # must be TRAINED; label[1] predicts ids[2] == 4: trained;
            # labels from position 2 on point into the trailing pad run
            assert (labels[:, 0] == 7).all()
            assert (labels[:, 1] == 4).all()
            assert (labels[:, 2:] == -100).all()
            client.close()
        finally:
            master.stop()

    def test_terminal_eos_target_survives_pad_eq_eos(self, tmp_path):
        """With a declared eos_id equal to pad_id, exactly one trailing
        token is the document's real terminal EOS: the label predicting
        it must be TRAINED, or the model never learns to stop. Without
        an eos_id the conservative mask stands (the documented
        residual)."""

        def make_tok(declare_eos):
            class IdTok:
                pad_id = 7
                eos_id = 7 if declare_eos else None
                vocab_size = 16
                seq_len = 8

                def __call__(self, record):
                    # doc [3, 4, 5] + terminal eos(7), then pad(7)s
                    ids = np.full((8,), 7, np.int32)
                    ids[:3] = [3, 4, 5]
                    return ids

            return IdTok()

        for declare_eos, eos_target_trained in ((True, True),
                                                (False, False)):
            path = tmp_path / f"eos_{declare_eos}.txt"
            path.write_text("x\n" * 4)
            master = start_local_master()
            try:
                reader = LineIndexedFile(str(path))
                client = MasterClient(master.addr, node_id=0)
                sc = ShardingClient(
                    client, dataset_name=f"eosmask{declare_eos}",
                    batch_size=4, dataset_size=reader.count(),
                    num_epochs=1, num_minibatches_per_shard=1,
                )
                source = ShardedTextBatches(
                    sc, reader, batch_size=4,
                    tokenizer=make_tok(declare_eos), seq_len=8)
                labels = next(iter(source))["labels"]
                assert (labels[:, 0] == 4).all()
                assert (labels[:, 1] == 5).all()
                if eos_target_trained:
                    # label[2] predicts ids[3] == 7, the terminal EOS
                    assert (labels[:, 2] == 7).all()
                    assert (labels[:, 3:] == -100).all()
                else:
                    assert (labels[:, 2:] == -100).all()
                client.close()
            finally:
                master.stop()

    def test_hf_adapter_appends_terminal_eos(self):
        from dlrover_tpu.trainer.text_reader import HFTokenizerAdapter

        class RawTok:  # minimal `tokenizers.Tokenizer`-shaped stub
            def encode(self, text):
                return [10, 11, 12]

            def get_vocab_size(self):
                return 32

        tok = HFTokenizerAdapter(RawTok(), seq_len=8, pad_id=0,
                                 bos_id=1, eos_id=2)
        assert tok.encode(b"abc").tolist() == [1, 10, 11, 12, 2]
        fixed = tok(b"abc")
        assert fixed.tolist() == [1, 10, 11, 12, 2, 0, 0, 0]


class TestPackedBatches:
    def test_packing_consumes_all_tokens_with_segments(self, corpus):
        path, lines = corpus
        master = start_local_master()
        try:
            reader = LineIndexedFile(path)
            client = MasterClient(master.addr, node_id=0)
            shard_client = ShardingClient(
                client, dataset_name="packed", batch_size=2,
                dataset_size=reader.count(), num_epochs=1,
                num_minibatches_per_shard=4,
            )
            tok = ByteTokenizer(48)
            source = ShardedTextBatches(
                shard_client, reader, batch_size=2, tokenizer=tok,
                seq_len=48, pack=True,
            )
            total_tokens = sum(
                len(tok.encode(line.encode())) for line in lines
            )
            seen_tokens = 0
            for batch in source:
                assert batch["input_ids"].shape == (2, 48)
                assert batch["segment_ids"].shape == (2, 48)
                seen_tokens += int((batch["segment_ids"] >= 0).sum())
                # labels never cross a segment boundary or land on pad
                segs, labels = batch["segment_ids"], batch["labels"]
                trained = labels != -100
                assert (segs[trained] >= 0).all()
                same_next = segs[:, :-1] == segs[:, 1:]
                assert (~trained[:, :-1] | same_next).all()
                assert not trained[:, -1].any()
            # every token packed exactly once, modulo the repeated last
            # row of the flush batch (allow overshoot, forbid loss)
            assert seen_tokens >= total_tokens
            client.close()
        finally:
            master.stop()


class TestPackedTaskAccounting:
    def test_completion_deferred_until_rows_yielded(self, corpus):
        """A shard whose tokens still sit in the packing buffer must stay
        in the master's 'doing' state — reporting it done at pack time
        would make a worker crash silently drop those records (the
        dead-worker recovery only re-queues incomplete tasks)."""
        path, _lines = corpus
        master = start_local_master()
        try:
            reader = LineIndexedFile(path)
            client = MasterClient(master.addr, node_id=0)
            shard_client = ShardingClient(
                client, dataset_name="defer", batch_size=4,
                dataset_size=reader.count(), num_epochs=1,
                num_minibatches_per_shard=1,
            )
            tok = ByteTokenizer(512)
            source = ShardedTextBatches(
                shard_client, reader, batch_size=4, tokenizer=tok,
                seq_len=512, pack=True,
            )
            dataset = master.task_manager.get_dataset("defer")

            # packed mode must NEVER credit record counts: the master
            # auto-completes a shard once credits reach its size, which
            # would pop it from 'doing' while tokens are still buffered
            def _forbidden(*_a, **_k):
                raise AssertionError(
                    "report_batch_done called in packed mode")

            shard_client.report_batch_done = _forbidden
            it = iter(source)
            next(it)  # one batch out; more shards were fetched than
            # fully emitted (512-token rows swallow many 30-byte lines)
            assert dataset.doing, (
                "every fetched shard already reported done while its "
                "tokens are still buffered"
            )
            # draining everything completes every task
            for _ in it:
                pass
            assert not dataset.doing
            client.close()
        finally:
            master.stop()


class TestShardedTextBatches:
    def test_consumes_corpus_exactly_once(self, corpus):
        path, lines = corpus
        master = start_local_master()
        try:
            reader = LineIndexedFile(path)
            client = MasterClient(master.addr, node_id=0)
            shard_client = ShardingClient(
                client, dataset_name="txt", batch_size=4,
                dataset_size=reader.count(), num_epochs=1,
                num_minibatches_per_shard=2,
            )
            source = ShardedTextBatches(
                shard_client, reader, batch_size=4, seq_len=64,
            )
            batches = list(source)
            # 100 records / (4*2) per shard = 12 full shards + tail 4
            assert all(b["input_ids"].shape == (4, 64) for b in batches)
            total = sum(b["input_ids"].shape[0] for b in batches)
            assert total >= 100  # tail batches pad by repeating
            # every batch trains next-token: labels are inputs shifted
            b0 = batches[0]
            row = b0["input_ids"][0]
            lab = b0["labels"][0]
            n = (row != 0).sum()
            np.testing.assert_array_equal(lab[: n - 1], row[1:n])
            assert (lab[n - 1:] == -100).all()
            client.close()
        finally:
            master.stop()
