"""Live elastic recovery (ISSUE 5): the in-process snapshot -> reshard
-> resume fast path, the warm program cache, recovery classification,
and the derived ``live_reshard`` MTTR scenario.

The chaos-parity headline: scaling 8 -> 4 devices via ``live_reshard``
must produce the SAME loss/param trajectory as a cold restart from the
same host-DRAM snapshot — optimizer state resharded correctly, no step
skipped or replayed.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.checkpoint import HostSnapshot
from dlrover_tpu.parallel.mesh import MeshPlan, topology_key
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor, TrainHook
from dlrover_tpu.trainer.failover import (
    RecoveryDecision,
    classify_recovery,
)
from dlrover_tpu.telemetry.names import EventKind

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_trainer(**kwargs):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)), "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": np.asarray(x),
             "y": np.asarray(x @ jax.random.normal(rngs[1], (4, 2)))}
    kwargs.setdefault("strategy", Strategy(mesh=MeshPlan(data=2, fsdp=4)))
    # adam: the optimizer STATE carries momentum arrays, so the parity
    # test can assert they reshard (sgd's state is empty)
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.adam(1e-2), batch, **kwargs
    )
    return trainer, batch


def _leaves_bitwise_equal(a, b):
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(la) == len(lb) and all(
        np.asarray(x).tobytes() == np.asarray(y).tobytes()
        for x, y in zip(la, lb)
    )


class TestLiveReshardParity:
    def test_scale_down_matches_cold_restart_from_same_snapshot(
        self, tmp_path, monkeypatch
    ):
        """The chaos-parity acceptance: 8 -> 4 via live reshard vs a
        cold restart (fresh trainer on 4 devices) resumed from the SAME
        host snapshot, stepped over the same batches with the same rng
        stream — bit-identical losses and params, every step present
        exactly once. Also the producer of the event timeline the MTTR
        derivation test below consumes."""
        events_file = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_file)

        trainer, batch = _make_trainer()
        state = trainer.prepare()
        for _ in range(5):
            state, _ = trainer.step(state, batch)
        snap = trainer.snapshot(state)
        assert snap.step == 5
        rng_at_reshard = trainer._rng

        # live path: reshard in place, then 5 more steps
        half = jax.devices()[:4]
        state_live = trainer.live_reshard(state, devices=half,
                                          snapshot=snap, reason="chaos")
        assert state_live.params["w"].sharding.mesh.devices.size == 4
        # optimizer state resharded onto the 4-device mesh too
        opt_leaves = [
            leaf for leaf in jax.tree.leaves(state_live.opt_state)
            if hasattr(leaf, "sharding")
        ]
        assert opt_leaves
        assert all(
            leaf.sharding.mesh.devices.size == 4 for leaf in opt_leaves
        )
        # params bit-identical to the drained snapshot
        assert _leaves_bitwise_equal(
            jax.device_get(state_live.params), snap.tree.params
        )
        live_losses = []
        for _ in range(5):
            state_live, m = trainer.step(state_live, batch)
            live_losses.append(float(m["loss"]))
        assert int(state_live.step) == 10  # no step skipped or replayed

        # cold path: a fresh trainer compiled directly for 4 devices
        # (the post-reshard strategy), state restored from the SAME
        # snapshot, rng realigned to the reshard point
        cold_trainer, _ = _make_trainer(
            strategy=trainer.accelerated.strategy, devices=half
        )
        cold_trainer.prepare()
        state_cold = snap.restore(
            cold_trainer.accelerated.state_sharding
        )
        cold_trainer._rng = rng_at_reshard
        cold_losses = []
        for _ in range(5):
            state_cold, m = cold_trainer.step(state_cold, batch)
            cold_losses.append(float(m["loss"]))
        assert cold_losses == live_losses
        assert _leaves_bitwise_equal(state_live.params, state_cold.params)

    def test_mttr_cli_derives_live_reshard_scenario(self, tmp_path,
                                                    monkeypatch):
        """``python -m dlrover_tpu.telemetry mttr`` must attribute the
        live-reshard incident from the chaos timeline — the same
        derivation pipeline the production events feed."""
        events_file = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_file)
        trainer, batch = _make_trainer()
        state = trainer.prepare()
        state, _ = trainer.step(state, batch)
        trainer.live_reshard(state, devices=jax.devices()[:4])

        from dlrover_tpu.telemetry.cli import main as telemetry_main

        out = str(tmp_path / "mttr.json")
        rc = telemetry_main(["mttr", "--events", events_file,
                             "--out", out])
        assert rc == 0
        with open(out) as fh:
            report = json.loads(fh.read())
        by_scenario = report["detail"]["by_scenario"]
        assert by_scenario["live_reshard"]["count"] >= 1
        assert report["detail"]["unrecovered"] == 0


class TestExecutorLiveReshard:
    def test_request_drains_window_and_resumes(self):
        """request_live_reshard at a dispatch boundary: the in-flight
        window drains, the world shrinks in place, and the loop runs to
        train_steps with every step materialized exactly once."""
        trainer, batch = _make_trainer()
        half = jax.devices()[:4]
        seen = []

        class Recorder(TrainHook):
            def after_step(self, step, metrics):
                seen.append(step)

        class ReshardAt(TrainHook):
            def __init__(self, box):
                self.box = box
                self.fired = False

            def before_step(self, step):
                if step == 5 and not self.fired:
                    self.fired = True
                    self.box[0].request_live_reshard(half)

        box = []
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 100,
            hooks=[Recorder(), ReshardAt(box)],
            conf=Configuration({"train_steps": 10, "log_every_steps": 0,
                                "train_window": 4}),
        )
        box.append(executor)
        out = executor.train_and_evaluate()
        assert out["step"] == 10
        assert seen == list(range(1, 11))
        assert trainer.accelerated.mesh.devices.size == 4
        assert executor.state.params["w"].sharding.mesh.devices.size == 4

    def test_request_without_new_world_is_skipped(self):
        """The failover monitor can re-fire while nodes wait at the
        rendezvous, but without renegotiated coordinates (no explicit
        devices, ambient world unchanged) a reshard would be churn onto
        the identical topology — the executor must skip it, not
        snapshot+device_put every poll."""
        from dlrover_tpu.telemetry import events as events_mod

        trainer, batch = _make_trainer()

        class ReshardAt(TrainHook):
            def __init__(self, box):
                self.box = box

            def before_step(self, step):
                if step == 3:
                    self.box[0].request_live_reshard(None)

        box = []
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 100,
            hooks=[ReshardAt(box)],
            conf=Configuration({"train_steps": 6, "log_every_steps": 0,
                                "train_window": 2}),
        )
        box.append(executor)
        events_mod.clear_ring()
        out = executor.train_and_evaluate()
        assert out["step"] == 6
        assert trainer.accelerated.mesh.devices.size == 8  # untouched
        assert trainer.compile_count == 1  # no rebuild happened
        kinds = {r["kind"] for r in events_mod.recent_events()}
        assert EventKind.LIVE_RESHARD_BEGIN not in kinds

    def test_failover_monitor_routes_survivable_change_to_reshard(self):
        """Nodes waiting at the rendezvous while this process is healthy
        = survivable: the monitor must fire on_reshard, not on_change."""
        import time

        from dlrover_tpu.trainer.failover import TrainingFailover

        class StubMaster:
            waiting = 0

            def query_ps_nodes(self):
                class _N:
                    nodes = []

                return _N()

            def num_nodes_waiting(self):
                return self.waiting

        master = StubMaster()
        fired = {"restart": 0, "reshard": 0}
        monitor = TrainingFailover(
            master,
            on_change=lambda: fired.__setitem__(
                "restart", fired["restart"] + 1),
            on_reshard=lambda: fired.__setitem__(
                "reshard", fired["reshard"] + 1),
            poll_interval=0.02,
        )
        monitor.start()
        master.waiting = 2
        time.sleep(0.3)
        monitor.stop()
        assert fired["reshard"] >= 1
        assert fired["restart"] == 0


class TestProgramCache:
    def test_same_topology_return_pays_zero_recompiles(self):
        """8 -> 4 -> 8: the return to the original topology must hit
        the in-process program cache — zero accelerate() compiles, and
        the previously-compiled executables are reused as-is."""
        trainer, batch = _make_trainer()
        state = trainer.prepare()
        state, _ = trainer.step(state, batch)
        full_result = trainer.accelerated
        exe_before = full_result.compiled_cache_size()
        assert trainer.compile_count == 1

        state = trainer.live_reshard(state, devices=jax.devices()[:4])
        assert trainer.compile_count == 2
        state, _ = trainer.step(state, batch)

        state = trainer.live_reshard(state, devices=None)
        assert trainer.compile_count == 2  # cache hit: no new compile
        assert trainer.accelerated is full_result
        state, m = trainer.step(state, batch)
        assert np.isfinite(float(m["loss"]))
        # the reused program did not retrace either
        assert full_result.compiled_cache_size() == exe_before

    def test_prewarm_compiles_standby_topology_once(self):
        trainer, batch = _make_trainer()
        trainer.prepare()
        half = jax.devices()[:4]
        assert trainer.prewarm(devices=half) is True
        count = trainer.compile_count
        assert trainer.prewarm(devices=half) is False  # already cached
        assert trainer.compile_count == count

    def test_topology_key_is_order_and_identity_sensitive(self):
        devs = jax.devices()
        assert topology_key(devs) != topology_key(devs[:4])
        assert topology_key(devs) == topology_key(list(devs))
        assert topology_key(devs[::-1]) != topology_key(devs)


class TestRecoveryClassification:
    def test_decision_tree(self):
        # survivable: a peer's failure / a scale plan, healthy self
        assert classify_recovery(
            EventKind.WORKER_FAILED
        ) == RecoveryDecision.LIVE_RESHARD
        assert classify_recovery(
            EventKind.SCALE_PLAN_APPLIED
        ) == RecoveryDecision.LIVE_RESHARD
        # own casualty: in-process recovery cannot help
        assert classify_recovery(
            EventKind.WORKER_FAILED, self_affected=True
        ) == RecoveryDecision.PROCESS_RESTART
        # no viable survivor world: nothing to reshard onto
        assert classify_recovery(
            EventKind.SCALE_PLAN_APPLIED, world_viable=False
        ) == RecoveryDecision.PROCESS_RESTART
        # sick host: escalate past the process
        assert classify_recovery(
            EventKind.WORKER_FAILED, host_healthy=False
        ) == RecoveryDecision.POD_RESTART
        # non-survivable kinds default to a restart
        assert classify_recovery(
            EventKind.NONFINITE_STEP
        ) == RecoveryDecision.PROCESS_RESTART

    def test_scale_plan_stamped_live_reshard(self):
        from dlrover_tpu.common.node import Node
        from dlrover_tpu.master.node.job_auto_scaler import JobAutoScaler
        from dlrover_tpu.master.scaler.base_scaler import ScalePlan

        plan = ScalePlan(launch_nodes=[Node("worker", 1)])
        assert plan.resizes_world_only()

        class StubJobManager:
            executed = None

            def execute_scale_plan(self, p):
                StubJobManager.executed = p

        class StubSpeed:
            def reset_running_speed_monitor(self):
                ...

        scaler = JobAutoScaler(StubJobManager(), None, StubSpeed())
        scaler.execute_job_optimization_plan(plan)
        assert plan.recovery == RecoveryDecision.LIVE_RESHARD
        assert plan.to_dict()["recovery"] == "live_reshard"

        # a PS-topology change is NOT a pure resize: never stamped live
        ps_plan = ScalePlan(ps_addrs=["a:1"])
        assert not ps_plan.resizes_world_only()
        scaler.execute_job_optimization_plan(ps_plan)
        assert ps_plan.recovery == ""

        # a group-resource-only plan could be a cpu/memory re-spec (pod
        # relaunch required) — indistinguishable from a count bump at
        # the plan level, so never stamped live
        from dlrover_tpu.common.node import NodeGroupResource, NodeResource

        respec = ScalePlan(node_group_resources={
            "worker": NodeGroupResource(
                count=4, node_resource=NodeResource(cpu=8, memory=1024)
            )
        })
        assert not respec.resizes_world_only()
        scaler.execute_job_optimization_plan(respec)
        assert respec.recovery == ""


class TestAgentDelegation:
    def _agent(self, live_recovery, grace=120.0):
        from dlrover_tpu.agent.training_agent import (
            AgentConfig,
            ElasticTrainingAgent,
        )

        agent = ElasticTrainingAgent.__new__(ElasticTrainingAgent)
        agent._config = AgentConfig(live_recovery=live_recovery,
                                    live_reshard_grace=grace)
        agent._reshard_deadline = None

        class StubGroup:
            restart_round = 0

        agent._worker_group = StubGroup()
        return agent

    def test_survivable_change_delegated_then_grace_fallback(self):
        import time

        agent = self._agent(live_recovery=True, grace=0.05)
        # first poll: delegate (skip the restart)
        assert agent._maybe_delegate_reshard() is True
        # inside the grace window: still delegated
        assert agent._maybe_delegate_reshard() is True
        time.sleep(0.06)
        # grace expired, change unabsorbed: fall back to restart
        assert agent._maybe_delegate_reshard() is False
        # the next event opens a fresh window
        assert agent._maybe_delegate_reshard() is True

    def test_knob_off_keeps_classic_restart(self):
        agent = self._agent(live_recovery=False)
        assert agent._maybe_delegate_reshard() is False


class TestKnobWiring:
    def test_tpurun_exposes_live_recovery_flag(self):
        from dlrover_tpu.trainer.run import build_parser

        args = build_parser().parse_args(["--live_recovery", "t.py"])
        assert args.live_recovery is True
        args = build_parser().parse_args(["t.py"])
        assert args.live_recovery is False

    def test_context_env_override(self, monkeypatch):
        from dlrover_tpu.common.config import Context

        assert Context().live_recovery is True  # default on
        monkeypatch.setenv("DLROVER_TPU_LIVE_RECOVERY", "0")
        assert Context().live_recovery is False

    def test_executor_knob_off_routes_to_restart(self):
        """live_recovery=False: the failover monitor gets NO on_reshard
        callback — every change takes the classic restart path."""
        trainer, batch = _make_trainer()

        class StubMaster:
            def num_nodes_waiting(self):
                return 0

            def query_ps_nodes(self):
                class _N:
                    nodes = []

                return _N()

        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch],
            master_client=StubMaster(),
            conf=Configuration({"live_recovery": False,
                                "log_every_steps": 0}),
        )
        assert executor._failover._on_reshard is None
        executor2 = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch],
            master_client=StubMaster(),
            conf=Configuration({"log_every_steps": 0}),
        )
        assert executor2._failover._on_reshard is not None


class TestRenegotiate:
    def test_live_round_tagged_in_timeline(self):
        from dlrover_tpu.agent.rendezvous import MasterRendezvousHandler
        from dlrover_tpu.telemetry import events as events_mod

        class StubClient:
            def report_rdzv_params(self, *a, **kw):
                ...

            def join_rendezvous(self, *a, **kw):
                ...

            def get_comm_world(self, name, rank):
                class _World:
                    round = 7
                    world = {0: 1}
                    coordinator_addr = "127.0.0.1:1"

                return _World()

        handler = MasterRendezvousHandler(
            StubClient(), node_rank=0, host_ip="127.0.0.1",
        )
        events_mod.clear_ring()
        info = handler.renegotiate(timeout=5.0)
        assert info.round == 7 and info.group_world_size == 1
        ring = events_mod.recent_events()
        joins = [r for r in ring if r["kind"] == EventKind.RDZV_JOIN]
        completes = [r for r in ring
                     if r["kind"] == EventKind.RDZV_COMPLETE]
        assert joins and joins[-1].get("live") is True
        assert completes and completes[-1].get("live") is True
        # an ordinary round is NOT tagged
        events_mod.clear_ring()
        handler.next_rendezvous(timeout=5.0)
        ring = events_mod.recent_events()
        joins = [r for r in ring if r["kind"] == EventKind.RDZV_JOIN]
        assert joins and "live" not in joins[-1]


class TestCompileCacheFingerprint:
    def test_topology_hint_keys_fingerprint(self, monkeypatch):
        from dlrover_tpu.utils import compile_cache as cc

        fp_here = cc.machine_fingerprint()
        assert len(fp_here) == 12
        int(fp_here, 16)
        assert fp_here == cc.machine_fingerprint()  # stable
        # a different topology (device count) must change the key
        monkeypatch.setenv(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=2")
        assert cc.machine_fingerprint() != fp_here
        # and a different process-count contract too
        monkeypatch.setenv("DLROVER_NUM_PROCESSES", "16")
        fp_multi = cc.machine_fingerprint()
        assert fp_multi != fp_here

    def test_cache_cli_reports_stats(self, tmp_path):
        from dlrover_tpu.telemetry.cli import main as telemetry_main

        root = str(tmp_path / "cc")
        rc = telemetry_main(["cache", "--dir", root])
        assert rc == 0
        # the stats are also reachable programmatically with the same
        # shape the CLI printed
        from dlrover_tpu.utils.compile_cache import cache_stats

        stats = cache_stats(root)
        assert stats["entries"] == 0
        assert stats["fingerprint"] == stats["dir"].rsplit("host-", 1)[1]
        assert {"hits", "misses", "requests"} <= set(stats)


@pytest.mark.usefixtures("tmp_path")
class TestWarmRestartZeroRecompiles:
    def test_same_topology_warm_restart_hits_persistent_cache(
        self, tmp_path
    ):
        """The warm-compile restart gate: two fresh processes compiling
        the same program against one cache root — the second must
        serve EVERY compile from the persistent cache (misses == 0).
        Single device: jax 0.4.37 cannot serialize multi-device SPMD
        executables, so 1 device is where the zero-recompile contract
        is enforceable (bench.py's warm restart leg matches)."""
        root = str(tmp_path / "cc")
        prog = (
            "import os, json\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from dlrover_tpu.utils.compile_cache import ("
            "enable_compile_cache, cache_stats)\n"
            f"enable_compile_cache({root!r})\n"
            "import jax.numpy as jnp\n"
            "x = jax.jit(lambda a: (a @ a).sum())"
            "(jnp.ones((64, 64), jnp.float32))\n"
            "jax.block_until_ready(x)\n"
            f"print('STATS ' + json.dumps(cache_stats({root!r})))\n"
        )
        from dlrover_tpu.utils.compile_cache import CPU_ISA_CAP_FLAG

        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["PYTHONPATH"] = REPO
        env["XLA_FLAGS"] = (
            "--xla_force_host_platform_device_count=1 " + CPU_ISA_CAP_FLAG
        )

        def run():
            out = subprocess.run(
                [sys.executable, "-c", prog], env=env,
                capture_output=True, text=True, timeout=300,
            )
            assert out.returncode == 0, out.stderr[-2000:]
            line = [ln for ln in out.stdout.splitlines()
                    if ln.startswith("STATS ")][-1]
            return json.loads(line[len("STATS "):])

        cold = run()
        assert cold["misses"] >= 1  # populated the cache
        assert cold["entries"] >= 1
        warm = run()
        assert warm["misses"] == 0, warm  # zero recompiles
        assert warm["hits"] >= 1, warm
