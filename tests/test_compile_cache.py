"""Persistent XLA compile cache: host-fingerprinted layout.

XLA:CPU AOT executables embed compile-time machine features; a cache
directory shared verbatim across hosts (image-baked ``~/.cache`` or
NFS) produces "machine features don't match … SIGILL" loader errors
when another host's entries are deserialized. The cache therefore keys
a per-host subdirectory off (arch, cpu flags, jaxlib version).
"""

import os
import subprocess
import sys

from dlrover_tpu.utils.compile_cache import (
    cache_entries,
    enable_compile_cache,
    machine_fingerprint,
)


def test_fingerprint_is_stable_and_cheap():
    fp1 = machine_fingerprint()
    fp2 = machine_fingerprint()
    assert fp1 == fp2
    assert len(fp1) == 12
    int(fp1, 16)  # hex


def test_enable_appends_host_subdir(tmp_path):
    root = str(tmp_path / "cc")
    active = enable_compile_cache(root)
    assert active == os.path.join(root, f"host-{machine_fingerprint()}")
    assert os.path.isdir(active)
    # idempotent: same resolved dir on re-enable
    assert enable_compile_cache(root) == active


def test_entries_land_in_host_subdir_and_reload(tmp_path):
    """A jitted program populates THIS host's subdir; a foreign host's
    entries at the root are never touched. Run in subprocesses: the
    cache config is process-global."""
    root = str(tmp_path / "cc")
    # plant a fake foreign-host entry at the root: the fingerprinted
    # layout must leave it alone and never try to load it
    os.makedirs(root, exist_ok=True)
    foreign = os.path.join(root, "jit_f-deadbeef-cache")
    with open(foreign, "wb") as f:
        f.write(b"not an executable")
    prog = (
        "import os\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "from dlrover_tpu.utils.compile_cache import enable_compile_cache\n"
        f"enable_compile_cache({root!r})\n"
        "import jax.numpy as jnp\n"
        "print(jax.jit(lambda x: x * 2 + 1)(jnp.arange(4.0))[3])\n"
    )
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    # the CPU-harness convention (conftest/dryrun/bench smoke): AVX2 cap
    # keeps cached CPU executables free of machine-feature mismatch
    flags = env.get("XLA_FLAGS", "")
    if "xla_cpu_max_isa" not in flags:
        env["XLA_FLAGS"] = (flags + " --xla_cpu_max_isa=AVX2").strip()
    out = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    assert "7.0" in out.stdout
    assert cache_entries(root) >= 1
    # the foreign entry is untouched and uncounted
    assert os.path.exists(foreign)
    host_dir = os.path.join(root, f"host-{machine_fingerprint()}")
    assert foreign not in [
        os.path.join(host_dir, n) for n in os.listdir(host_dir)
    ]
    # no cross-host loader noise on a warm re-run
    out2 = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True,
        text=True, timeout=300,
    )
    assert out2.returncode == 0, out2.stderr[-2000:]
    assert "machine features" not in out2.stderr.lower()
