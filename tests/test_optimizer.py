"""The runtime optimization loop (ISSUE 7): telemetry → planner →
live-reshard, closed.

Units: the proposal cooldown/dedup guard, planner breakdown
monotonicity (the perturbation pins the optimizer's candidate ranking
leans on), the predicted-vs-observed cost calibrator, the master-side
``RuntimeOptimizer`` decision logic, the verdict listeners and the
auto-scaler's immediate re-evaluation kick, the worker-side
``OptimizerPlanHook``, and the derived ``replan`` MTTR/goodput
scenario.

The acceptance wedge: a 30 ms/dispatch straggler (and, separately, a
world shrink) mid-run → the optimizer re-plans through the calibrated
cost model and the job converges LIVE — no process restart, zero
recompiles at the swap (the chosen program was prewarmed), the full
``OPTIMIZER_*`` decision trail under one trace id, and paired
post-convergence steps/sec ≥ 1.5× the degraded no-optimizer baseline.
"""

import bisect
import time

import jax
import jax.numpy as jnp
import optax
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.monitor.straggler import StragglerDetector
from dlrover_tpu.master.node.job_auto_scaler import JobAutoScaler
from dlrover_tpu.master.optimizer import (
    CostCalibrator,
    RuntimeOptimizer,
    decision_trail_from_events,
)
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.planner import (
    DeviceSpec,
    ModelSpec,
    estimate,
)
from dlrover_tpu.parallel.search import ProposalCooldown
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.telemetry import (
    EventKind,
    read_events,
    recent_events,
)
from dlrover_tpu.telemetry.events import clear_ring
from dlrover_tpu.telemetry.goodput import derive_goodput
from dlrover_tpu.telemetry.metrics import process_registry
from dlrover_tpu.telemetry.mttr import mttr_report
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import (
    NodeRuntimeReportHook,
    OptimizerPlanHook,
    TrainExecutor,
    TrainHook,
)


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


# -- cooldown / dedup guard ---------------------------------------------------


class TestProposalCooldown:
    def test_identical_proposal_within_cooldown_is_suppressed(self):
        cd = ProposalCooldown(cooldown_secs=60.0)
        assert cd.check("mesh=1.8.1.1.1|k=8", now=100.0)
        # the satellite pin: the IDENTICAL candidate proposed again
        # inside the window must be suppressed
        assert not cd.check("mesh=1.8.1.1.1|k=8", now=130.0)
        assert cd.seconds_remaining("mesh=1.8.1.1.1|k=8", now=130.0) \
            == pytest.approx(30.0)

    def test_different_candidate_is_never_suppressed(self):
        cd = ProposalCooldown(cooldown_secs=60.0)
        assert cd.check("a", now=0.0)
        assert cd.check("b", now=1.0)
        assert cd.check("c", now=2.0)

    def test_expiry_re_allows_and_rearms(self):
        cd = ProposalCooldown(cooldown_secs=60.0)
        assert cd.check("a", now=0.0)
        assert cd.check("a", now=61.0)
        # the allowed repeat re-armed the window
        assert not cd.check("a", now=90.0)

    def test_unknown_key_has_no_remaining(self):
        cd = ProposalCooldown(cooldown_secs=60.0)
        assert cd.seconds_remaining("never-seen", now=5.0) == 0.0


# -- planner breakdown monotonicity (perturbation pins) -----------------------


def _big_spec(batch=64):
    return ModelSpec(
        param_count=7_000_000_000, num_layers=32, hidden_size=4096,
        seq_len=4096, global_batch=batch, vocab_size=32000,
    )


class TestEstimateBreakdownMonotonicity:
    """The candidate ranking is only as sound as the cost terms it
    compares: pin the directions the optimizer's knobs move them, both
    ways (the PR 2 perturbation style)."""

    def test_dispatch_term_non_increasing_in_steps_per_call(self):
        dev = DeviceSpec(hbm_bytes=95e9)
        ks = (1, 2, 4, 8, 16)
        disp = [
            estimate(MeshPlan(fsdp=16, tensor=4), _big_spec(), dev,
                     steps_per_call=k).breakdown["dispatch_s"]
            for k in ks
        ]
        # growing K must never raise the per-step dispatch cost — and
        # for this amortized term it strictly shrinks
        for a, b in zip(disp, disp[1:]):
            assert b < a
        # the reverse direction: shrinking K must never lower it
        for a, b in zip(reversed(disp), list(reversed(disp))[1:]):
            assert b > a

    def test_collective_terms_non_increasing_when_slow_axis_shrinks(self):
        """A straggler-free submesh that shrinks the slow axis must
        never be priced MORE collective seconds on that axis — the
        property that makes 'drop the straggler's slice' a candidate
        the optimizer can ever prefer."""
        dev = DeviceSpec(hbm_bytes=95e9)
        spec = _big_spec()
        fsdp_terms = [
            estimate(MeshPlan(fsdp=f), spec, dev
                     ).breakdown["fsdp_comm_s"]
            for f in (32, 16, 8)
        ]
        for a, b in zip(fsdp_terms, fsdp_terms[1:]):
            assert b <= a
        tp_terms = [
            estimate(MeshPlan(fsdp=8, tensor=t), spec, dev
                     ).breakdown["tp_comm_s"]
            for t in (8, 4, 2)
        ]
        for a, b in zip(tp_terms, tp_terms[1:]):
            assert b <= a
        # and growing the axis back must never shrink the term
        for seq in (list(reversed(fsdp_terms)), list(reversed(tp_terms))):
            for a, b in zip(seq, seq[1:]):
                assert b >= a


# -- cost calibration ---------------------------------------------------------


def _tiny_spec(batch=16):
    return ModelSpec(
        param_count=10_000, num_layers=2, hidden_size=32, seq_len=16,
        global_batch=batch,
    )


class TestCostCalibrator:
    def test_one_pass_reproduces_the_measured_step_p50(self):
        """The acceptance pin: after ONE calibration pass against the
        current config, the calibrated prediction for that config is
        within 10% of the measured p50 (device-visible regime)."""
        cal = CostCalibrator(model=_big_spec(),
                             device=DeviceSpec(hbm_bytes=95e9))
        mesh = MeshPlan(fsdp=16, tensor=4)
        measured = 0.5
        cal.observe(mesh, steps_per_call=1, measured_step_p50=measured)
        predicted = cal.price(mesh, steps_per_call=1, train_window=4)
        assert predicted == pytest.approx(measured, rel=0.10)

    def test_dispatch_bound_regime_anchors_the_dispatch_factor(self):
        """A tiny model whose step time IS host dispatch: one pass with
        the measured per-call dispatch p50 reprices the current config
        to the measurement (within the 1% dispatch-bound residual)."""
        cal = CostCalibrator(model=_tiny_spec())
        mesh = MeshPlan(data=8)
        cal.observe(mesh, steps_per_call=1,
                    measured_step_p50=0.03, measured_dispatch_p50=0.03)
        predicted = cal.price(mesh, steps_per_call=1, train_window=4)
        assert predicted == pytest.approx(0.03, rel=0.15)
        # and the K=8 candidate amortizes it ~8x
        k8 = cal.price(mesh, steps_per_call=8, train_window=4)
        assert predicted / k8 > 4.0

    def test_factors_are_clamped_against_garbage_windows(self):
        cal = CostCalibrator(model=_tiny_spec())
        cal.observe(MeshPlan(data=8), steps_per_call=1,
                    measured_step_p50=1e9, measured_dispatch_p50=1e9)
        assert cal.corrections.dispatch <= 1e4
        assert cal.corrections.compute <= 1e4

    def test_dispatch_only_first_pass_does_not_dilute_compute(self):
        """A dispatch-only pass 1 must not make the compute family
        think it has been observed: pass 2's FIRST device-visible
        observation is adopted outright, not EMA-diluted against the
        1.0 prior (which would halve a true 10x correction right when
        the first replan decision is made)."""
        cal = CostCalibrator(model=_big_spec(),
                             device=DeviceSpec(hbm_bytes=95e9))
        mesh = MeshPlan(fsdp=16, tensor=4)
        cal.observe(mesh, steps_per_call=1, measured_step_p50=None,
                    measured_dispatch_p50=0.001)
        cal.observe(mesh, steps_per_call=1, measured_step_p50=0.5)
        predicted = cal.price(mesh, steps_per_call=1, train_window=4)
        assert predicted == pytest.approx(0.5, rel=0.10)

    def test_infeasible_plan_is_unpriceable(self):
        """A cheap-LOOKING mesh the planner judges infeasible (HBM
        overflow: 7B params fully replicated on 1 GB devices) must
        raise instead of returning a finite price — the corrections
        rescale breakdown terms that stay finite even for plans
        estimate() refused, and an infeasible candidate must never win
        the ranking. The current config (observably running) is exempt
        via require_fit=False."""
        cal = CostCalibrator(model=_big_spec(),
                             device=DeviceSpec(hbm_bytes=1e9))
        with pytest.raises(ValueError):
            cal.price(MeshPlan(data=8), steps_per_call=1)
        s = cal.price(MeshPlan(data=8), steps_per_call=1,
                      require_fit=False)
        assert 0 < s < float("inf")

    def test_ema_blends_subsequent_observations(self):
        cal = CostCalibrator(model=_big_spec(),
                             device=DeviceSpec(hbm_bytes=95e9), ema=0.5)
        mesh = MeshPlan(fsdp=16, tensor=4)
        cal.observe(mesh, steps_per_call=1, measured_step_p50=0.5)
        first = cal.corrections.compute
        cal.observe(mesh, steps_per_call=1, measured_step_p50=1.0)
        blended = cal.corrections.compute
        # the second (2x) observation moves the factor by the EMA
        # weight, not all the way
        assert first < blended < 2.05 * first


# -- the master-side optimizer ------------------------------------------------


class _Snap:
    def __init__(self, step_p50, dispatch_p50, ts=None):
        self.ts = ts if ts is not None else time.time()
        self.step_p50 = step_p50
        self.dispatch_p50 = dispatch_p50


class _Store:
    """Minimal NodeRuntimeStore stand-in: latest() per node."""

    def __init__(self, snaps=None):
        self.snaps = dict(snaps or {})

    def node_ids(self):
        return sorted(self.snaps)

    def latest(self, nid):
        return self.snaps.get(nid)


def _dispatch_bound_store(p50=0.03):
    return _Store({0: _Snap(0.002, 0.001), 1: _Snap(p50, p50)})


def _running_report(**kw):
    kw.setdefault("node_id", 0)
    kw.setdefault("world", 8)
    kw.setdefault("mesh_shape", {"pipe": 1, "data": 8, "fsdp": 1,
                                 "seq": 1, "tensor": 1})
    kw.setdefault("train_window", 4)
    kw.setdefault("steps_per_call", 1)
    kw.setdefault("global_batch", 16)
    return comm.TrainerConfigReport(**kw)


def _optimizer(store=None, **kw):
    kw.setdefault("min_speedup", 1.2)
    kw.setdefault("cooldown_secs", 60.0)
    kw.setdefault("enabled", True)
    published = []
    opt = RuntimeOptimizer(store or _dispatch_bound_store(),
                           publish=published.append, **kw)
    opt.update_model_info(comm.ModelInfo(
        num_params=10_000, hidden_size=32, num_layers=2, seq_len=16))
    return opt, published


class TestRuntimeOptimizer:
    def test_replan_without_running_config_is_a_noop(self):
        opt, published = _optimizer()
        assert opt.replan("straggler:1") is None
        assert published == []

    def test_dispatch_bound_job_chooses_a_bigger_k_and_publishes(self):
        clear_ring()
        opt, published = _optimizer()
        opt.update_running_config(_running_report())
        d = opt.replan("straggler:1")
        assert d.outcome == "chosen"
        assert d.chosen["steps_per_call"] > 1
        assert d.predicted_speedup >= 1.2
        assert d.plan_id and d.trace_id
        # the chosen plan went out on the ParallelConfig channel
        assert len(published) == 1
        cfg = published[0]
        assert cfg.plan_id == d.plan_id
        assert cfg.steps_per_call == d.chosen["steps_per_call"]
        assert cfg.prewarm
        assert opt.pending_plan() is cfg
        kinds = [r["kind"] for r in recent_events()]
        assert EventKind.OPTIMIZER_REPLAN in kinds
        assert EventKind.OPTIMIZER_PLAN_CHOSEN in kinds
        assert EventKind.OPTIMIZER_CALIBRATED in kinds

    def test_identical_replan_within_cooldown_is_suppressed(self):
        opt, published = _optimizer()
        opt.update_running_config(_running_report())
        assert opt.replan("straggler:1").outcome == "chosen"
        d2 = opt.replan("straggler:1")  # same trigger, same winner
        assert d2.outcome == "rejected"
        assert d2.reason.startswith("cooldown")
        assert len(published) == 1

    def test_hysteresis_rejects_marginal_wins(self):
        opt, published = _optimizer(min_speedup=1000.0)
        opt.update_running_config(_running_report())
        d = opt.replan("straggler:1")
        assert d.outcome == "rejected"
        assert d.reason.startswith("hysteresis")
        assert published == []

    def test_already_optimal_config_proposes_no_churn(self):
        # already at the best knobs the enumeration can offer
        # (mesh candidates off: a same-world refactorization pricing
        # epsilon lower would turn this into a hysteresis rejection)
        opt, published = _optimizer(mesh_candidates=False)
        opt.update_running_config(_running_report(steps_per_call=8))
        d = opt.replan("tick")
        assert d.outcome == "rejected"
        assert d.reason == "already_optimal"
        assert published == []

    def test_world_change_report_triggers_a_replan(self):
        opt, published = _optimizer()
        opt.update_running_config(_running_report(world=8))
        assert len(opt.decisions()) == 0
        opt.update_running_config(_running_report(
            world=4, mesh_shape={"pipe": 1, "data": 4, "fsdp": 1,
                                 "seq": 1, "tensor": 1}))
        trail = opt.decisions()
        assert trail and trail[-1]["trigger"] == "world_change:8->4"

    def test_verdict_listener_replans_on_flag_and_recovery(self):
        opt, _published = _optimizer()
        opt.update_running_config(_running_report())
        opt.on_verdict(2, "straggler")
        opt.on_verdict(2, "healthy")
        triggers = [d["trigger"] for d in opt.decisions()]
        assert "straggler:2" in triggers
        # the satellite: recovery replans IMMEDIATELY, its own decision
        assert "recovered:2" in triggers

    def test_apply_ack_records_the_realized_speedup(self):
        opt, published = _optimizer()
        opt.update_running_config(_running_report())
        d = opt.replan("straggler:1")
        assert d.outcome == "chosen"
        assert opt.pending_plan() is not None
        opt.update_running_config(_running_report(
            steps_per_call=d.chosen["steps_per_call"],
            plan_id=d.plan_id, realized_speedup=6.25))
        rec = [x for x in opt.decisions() if x["plan_id"] == d.plan_id]
        assert rec and rec[-1]["applied"]
        assert rec[-1]["realized_speedup"] == pytest.approx(6.25)
        # the consumed plan is retracted: a worker restarted later must
        # not replay it from the broadcast slot
        assert opt.pending_plan() is None

    def test_ack_retracts_the_published_broadcast(self):
        slot = {}
        published = []
        opt = RuntimeOptimizer(
            _dispatch_bound_store(),
            publish=lambda cfg: (published.append(cfg),
                                 slot.__setitem__(-1, cfg)),
            retract=lambda plan_id: (
                slot.pop(-1, None)
                if getattr(slot.get(-1), "plan_id", "") == plan_id
                else None),
            min_speedup=1.2, cooldown_secs=60.0, enabled=True,
        )
        opt.update_model_info(comm.ModelInfo(
            num_params=10_000, hidden_size=32, num_layers=2, seq_len=16))
        opt.update_running_config(_running_report())
        d = opt.replan("straggler:1")
        assert d.outcome == "chosen" and -1 in slot
        opt.update_running_config(_running_report(
            steps_per_call=d.chosen["steps_per_call"],
            plan_id=d.plan_id, realized_speedup=4.0))
        assert -1 not in slot

    def test_failed_apply_blacklists_the_knob_tuple(self):
        # cooldown 0: only the blacklist stands between a
        # deterministically-failing plan and an infinite
        # choose -> drain -> fail loop
        opt, published = _optimizer(cooldown_secs=0.0)
        opt.update_running_config(_running_report())
        d = opt.replan("straggler:1")
        assert d.outcome == "chosen"
        failed_tuple = dict(d.chosen)
        # the worker negative-acks: the rebuild failed on this tuple
        opt.update_running_config(_running_report(
            plan_id=d.plan_id, apply_failed=True))
        rec = [x for x in opt.decisions()
               if x["plan_id"] == d.plan_id][-1]
        assert rec["apply_failed"] and not rec["applied"]
        assert opt.pending_plan() is None  # retracted, not re-served
        d2 = opt.replan("straggler:1")
        assert d2 is not None
        if d2.outcome == "chosen":
            # a DIFFERENT tuple (next-best mesh/knobs) is fine; the
            # exact failed one must never be re-proposed
            assert d2.chosen != failed_tuple

    def test_disabled_optimizer_never_plans(self):
        opt, published = _optimizer(enabled=False)
        opt.update_running_config(_running_report())
        assert opt.replan("straggler:1") is None
        assert published == []

    def test_report_shape_for_the_plan_cli(self):
        opt, _published = _optimizer()
        opt.update_running_config(_running_report())
        opt.replan("straggler:1")
        report = opt.to_report(limit=1)
        assert report["running"]["world"] == 8
        assert report["corrections"]["samples"] >= 1
        assert report["pending_plan"]["plan_id"]
        assert len(report["decisions"]) == 1


# -- verdict listeners + the auto-scaler kick ---------------------------------


BOUNDS = [0.001, 0.005, 0.01, 0.05, 0.1, 1.0]


def _node_report(node, steps_total, counts, ts=None):
    return comm.NodeRuntimeReport(
        node_id=node, timestamp=ts or time.time(), step=int(steps_total),
        steps_total=float(steps_total), bounds=BOUNDS,
        step_time_counts=list(counts),
    )


def _counts_at(ms_per_step, steps):
    counts = [0] * (len(BOUNDS) + 1)
    idx = bisect.bisect_left(BOUNDS, ms_per_step / 1000.0)
    counts[min(idx, len(BOUNDS))] += steps
    return counts


class TestVerdictListeners:
    def _run_straggler(self, det, store, windows=3, recover=0):
        now = time.time()
        cum = {n: [0] * (len(BOUNDS) + 1) for n in (0, 1, 2)}
        steps = {n: 0 for n in (0, 1, 2)}

        def feed(node, ms, ts):
            cum[node] = [a + b for a, b in
                         zip(cum[node], _counts_at(ms, 8))]
            steps[node] += 8
            store.ingest(_node_report(node, steps[node], cum[node],
                                      ts=ts), now=ts)
            det.observe(node, now=ts)

        for w in range(windows):
            for node in (0, 1):
                feed(node, 5, now + w)
            feed(2, 50, now + w)
        for w in range(windows, windows + recover):
            for node in (0, 1, 2):
                feed(node, 5, now + w)

    def test_listener_fires_on_flag_and_on_recovery(self):
        store = NodeRuntimeStore()
        det = StragglerDetector(store, ratio=2.0, confirm_windows=3,
                                hang_secs=60.0)
        seen = []
        det.add_verdict_listener(lambda nid, v: seen.append((nid, v)))
        self._run_straggler(det, store, windows=3, recover=2)
        assert (2, "straggler") in seen
        assert (2, "healthy") in seen

    def test_broken_listener_does_not_kill_ingest(self):
        store = NodeRuntimeStore()
        det = StragglerDetector(store, ratio=2.0, confirm_windows=3,
                                hang_secs=60.0)

        def boom(nid, v):
            raise RuntimeError("listener bug")

        det.add_verdict_listener(boom)
        self._run_straggler(det, store, windows=3)
        assert det.stragglers() == [2]  # verdict still landed


class TestAutoScalerImmediateKick:
    def test_recovery_kick_beats_the_periodic_interval(self):
        """The satellite: request_immediate_evaluation must run
        optimize_once as soon as the loop services the wake event, not
        after the remaining scaler period."""
        scaler = JobAutoScaler(job_manager=None, job_optimizer=None,
                               speed_monitor=None, interval_secs=3600.0)
        ran = []
        evt = __import__("threading").Event()

        def fake_optimize():
            ran.append(time.monotonic())
            evt.set()

        scaler.optimize_once = fake_optimize
        scaler.start_auto_scaling()
        try:
            time.sleep(0.1)
            assert not ran  # parked on the hour-long interval
            t0 = time.monotonic()
            scaler.request_immediate_evaluation()
            assert evt.wait(2.0), "kick did not wake the scaler loop"
            assert ran[0] - t0 < 2.0
        finally:
            scaler.stop()

    def test_stop_unparks_a_waiting_loop(self):
        scaler = JobAutoScaler(job_manager=None, job_optimizer=None,
                               speed_monitor=None, interval_secs=3600.0)
        scaler.start_auto_scaling()
        t0 = time.monotonic()
        scaler.stop()
        scaler._thread.join(timeout=2.0)
        assert not scaler._thread.is_alive()
        assert time.monotonic() - t0 < 2.0


# -- the worker-side plan hook ------------------------------------------------


class _FakeExecutor:
    def __init__(self):
        self.retunes = []
        self.restarts = 0

    def request_retune(self, **kw):
        self.retunes.append(kw)

    def request_restart(self):
        self.restarts += 1


class _FakePlanClient:
    def __init__(self, cfg=None):
        self.cfg = cfg or comm.ParallelConfig()

    def get_parallel_config(self):
        return self.cfg


class TestOptimizerPlanHook:
    def test_plan_is_applied_once_per_plan_id(self):
        client = _FakePlanClient(comm.ParallelConfig(
            steps_per_call=8, train_window=4, plan_id="plan-7",
            trace_id="inc-1", predicted_speedup=3.0))
        hook = OptimizerPlanHook(client, poll_secs=0)
        ex = _FakeExecutor()
        hook._executor = ex
        hook.poll_once()
        hook.poll_once()  # same plan id: no re-apply
        assert len(ex.retunes) == 1
        req = ex.retunes[0]
        assert req["steps_per_call"] == 8
        assert req["train_window"] == 4
        assert req["plan_id"] == "plan-7"
        assert req["trace_id"] == "inc-1"

    def test_sentinel_values_leave_knobs_unchanged(self):
        client = _FakePlanClient(comm.ParallelConfig(
            steps_per_call=0, train_window=-1, plan_id="plan-8"))
        hook = OptimizerPlanHook(client, poll_secs=0)
        ex = _FakeExecutor()
        hook._executor = ex
        hook.poll_once()
        assert ex.retunes[0]["steps_per_call"] is None
        assert ex.retunes[0]["train_window"] is None

    def test_restart_flag_routes_to_request_restart(self):
        client = _FakePlanClient(comm.ParallelConfig(
            plan_id="plan-9", restart=True))
        hook = OptimizerPlanHook(client, poll_secs=0)
        ex = _FakeExecutor()
        hook._executor = ex
        hook.poll_once()
        assert ex.restarts == 1
        assert ex.retunes == []

    def test_autowires_with_a_master_client(self):
        class Client:
            node_id = 0

            def get_parallel_config(self):
                return comm.ParallelConfig()

        trainer, batch = _make_trainer()
        ex = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch],
            master_client=Client(),
            conf=Configuration({"plan_poll_secs": 30.0,
                                "runtime_report_steps": 0}),
        )
        assert any(isinstance(h, OptimizerPlanHook) for h in ex._hooks)
        ex0 = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch],
            master_client=Client(),
            conf=Configuration({"plan_poll_secs": 0,
                                "runtime_report_steps": 0}),
        )
        assert not any(isinstance(h, OptimizerPlanHook)
                       for h in ex0._hooks)


# -- the derived replan scenario + decision-trail forensics -------------------


def _apply_pair(begin_ts, seconds, pid=10, plan="plan-1"):
    return [
        {"kind": EventKind.OPTIMIZER_APPLY_BEGIN, "ts": begin_ts,
         "mono": begin_ts, "pid": pid, "plan_id": plan},
        {"kind": EventKind.OPTIMIZER_APPLY_DONE, "ts": begin_ts + seconds,
         "mono": begin_ts + seconds, "pid": pid, "plan_id": plan,
         "seconds": seconds},
    ]


class TestReplanScenarioDerived:
    def test_mttr_pairs_apply_begin_to_done_as_replan(self):
        events = _apply_pair(100.0, 2.5)
        rep = mttr_report(events)["detail"]
        assert rep["by_scenario"]["replan"]["count"] == 1
        assert rep["by_scenario"]["replan"]["max_s"] == pytest.approx(
            2.5, abs=0.01)

    def test_goodput_buckets_the_apply_as_replan_downtime(self):
        events = [
            {"kind": EventKind.TRAIN_START, "ts": 0.0, "pid": 10},
            *_apply_pair(40.0, 5.0),
            {"kind": EventKind.TRAIN_END, "ts": 100.0, "pid": 10},
        ]
        b = derive_goodput(events)["detail"]["buckets"]
        assert b["replan"]["seconds"] == pytest.approx(5.0, abs=0.01)
        assert b["productive_step"]["seconds"] == pytest.approx(
            95.0, abs=0.01)


class TestDecisionTrailForensics:
    def test_plans_join_choice_apply_and_measurement(self):
        events = [
            {"kind": EventKind.OPTIMIZER_REPLAN, "ts": 1.0,
             "trigger": "straggler:2"},
            {"kind": EventKind.OPTIMIZER_PLAN_CHOSEN, "ts": 1.0,
             "plan_id": "plan-1", "trigger": "straggler:2",
             "trace_id": "inc-9", "predicted_speedup": 4.0,
             "knob_steps_per_call": 8, "knob_train_window": 4},
            *_apply_pair(2.0, 0.4),
            {"kind": EventKind.OPTIMIZER_APPLIED, "ts": 9.0,
             "plan_id": "plan-1", "predicted_speedup": 4.0,
             "realized_speedup": 3.6},
            {"kind": "train_start", "ts": 0.0},  # non-optimizer noise
        ]
        trail = decision_trail_from_events(events)
        assert trail["events"] == 5
        assert len(trail["plans"]) == 1
        p = trail["plans"][0]
        assert p["plan_id"] == "plan-1"
        assert p["trigger"] == "straggler:2"
        assert p["predicted_speedup"] == 4.0
        assert p["realized_speedup"] == 3.6
        assert p["apply_seconds"] == pytest.approx(0.4)

    def test_failed_apply_carries_the_error_code(self):
        events = [
            {"kind": EventKind.OPTIMIZER_PLAN_CHOSEN, "ts": 1.0,
             "plan_id": "plan-1"},
            {"kind": EventKind.OPTIMIZER_APPLY_BEGIN, "ts": 2.0,
             "plan_id": "plan-1"},
            {"kind": EventKind.OPTIMIZER_APPLY_DONE, "ts": 2.5,
             "plan_id": "plan-1", "error_code": "APPLY_FAILED"},
        ]
        trail = decision_trail_from_events(events)
        assert trail["plans"][0]["apply_error"] == "APPLY_FAILED"


# -- the acceptance wedge -----------------------------------------------------


def _make_trainer(**kwargs):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)), "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (4, 2))}
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.sgd(0.1), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)), **kwargs,
    )
    return trainer, batch


def _slow_dispatch(trainer, seconds):
    """The injected straggler: every DISPATCH (one ``step`` /
    ``step_multi`` call) pays extra host latency — a degraded-but-alive
    host whose per-call cost a bigger ``steps_per_call`` amortizes.
    Wrapping the trainer methods (not a hook) makes the injection
    survive the live retune's program swap, so the post-plan speedup is
    real amortization, not the straggler conveniently vanishing."""
    orig_step, orig_multi = trainer.step, trainer.step_multi

    def step(state, batch):
        time.sleep(seconds)
        return orig_step(state, batch)

    def step_multi(state, group):
        time.sleep(seconds)
        return orig_multi(state, group)

    trainer.step, trainer.step_multi = step, step_multi


class _StepClock(TrainHook):
    """Wall timestamps per materialized step (steps/sec measurement)."""

    def __init__(self):
        self.at = {}

    def after_step(self, step, metrics):
        self.at[step] = time.monotonic()

    def rate(self, first, last):
        return (last - first) / (self.at[last] - self.at[first])


class _PollEvery(TrainHook):
    def __init__(self, plan_hook, every=6):
        self.plan_hook = plan_hook
        self.every = every

    def after_step(self, step, metrics):
        if step % self.every == 0:
            self.plan_hook.poll_once()


def _run_node(master, node_id, slow_s=0.0, steps=60, poll=False,
              reshard_at=None, conf_extra=None):
    """One in-process 'node' against the real master RPC (the
    test_diagnosis idiom), optionally polling for optimizer plans."""
    process_registry().reset()
    client = MasterClient(master.addr, node_id=node_id)
    trainer, batch = _make_trainer()
    if slow_s:
        _slow_dispatch(trainer, slow_s)
    clock = _StepClock()
    hooks = [NodeRuntimeReportHook(client, every_steps=6,
                                   min_interval_s=0), clock]
    conf = {
        "train_steps": steps, "log_every_steps": 0,
        "train_window": 2, "preemption_grace": False,
        "plan_measure_steps": 16, "plan_poll_secs": 0,
    }
    conf.update(conf_extra or {})
    ex = TrainExecutor(
        trainer, train_iter_fn=lambda: [batch] * steps, hooks=hooks,
        conf=Configuration(conf),
    )
    ex._master_client = client
    if poll:
        plan_hook = OptimizerPlanHook(client, poll_secs=0)
        plan_hook._executor = ex
        ex._hooks.append(_PollEvery(plan_hook))
    if reshard_at is not None:
        at, devices = reshard_at

        class _Shrink(TrainHook):
            fired = False

            def after_step(self, step, metrics):
                if step >= at and not self.fired:
                    _Shrink.fired = True
                    ex.request_live_reshard(devices=devices)

        ex._hooks.append(_Shrink())
    out = ex.train_and_evaluate()
    client.close()
    return ex, trainer, clock, out


class TestReplanWedge:
    def test_straggler_replan_converges_live(self, tmp_path, monkeypatch):
        """The acceptance wedge: a 30 ms/dispatch straggler → verdict →
        calibrated re-plan → live apply with ZERO recompiles at the
        swap → paired post-convergence steps/sec ≥ 1.5× the degraded
        no-optimizer baseline → decision trail merged under one trace
        id; live and forensic ``tpurun plan`` both render it."""
        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        ctx = get_context()
        monkeypatch.setattr(ctx, "diagnosis_confirm_windows", 3)
        monkeypatch.setattr(ctx, "diagnosis_straggler_ratio", 2.0)
        monkeypatch.setattr(ctx, "replan_min_speedup", 1.2)
        monkeypatch.setattr(ctx, "replan_cooldown_secs", 60.0)
        master = start_local_master()
        try:
            # fast peers anchor the straggler detector's peer median
            _run_node(master, 0)
            _run_node(master, 1)
            # the DEGRADED baseline: same straggler, optimizer off
            _bex, _btr, base_clock, _ = _run_node(
                master, 2, slow_s=0.03, steps=60, poll=False)
            degraded_rate = base_clock.rate(30, 60)

            # the optimizer leg: same straggler, loop closed
            ex, trainer, clock, _ = _run_node(
                master, 2, slow_s=0.03, steps=120, poll=True)

            # converged WITHOUT a restart: every step ran in this
            # process on this trainer, and the plan moved the knobs
            assert int(ex.state.step) == 120
            assert trainer.steps_per_call > 1
            opt = master.servicer.runtime_optimizer
            chosen = [d for d in opt.decisions()
                      if d["outcome"] == "chosen"]
            assert chosen, opt.decisions()
            decision = chosen[0]
            assert decision["trigger"] == "straggler:2"
            assert decision["applied"]
            assert decision["predicted_speedup"] >= 1.5
            # calibration pinned: the decision priced the CURRENT
            # config from the calibrated model — within 2x of the
            # measured (degraded) step p50 anchor
            assert decision["current_predicted_s"] == pytest.approx(
                0.03, rel=1.0)
            assert decision["corrections"]["dispatch"] > 10

            # predicted-vs-realized landed in OPTIMIZER_APPLIED and in
            # the master's decision record (the plan ack)
            records = read_events(events_path)
            applied = [r for r in records
                       if r["kind"] == EventKind.OPTIMIZER_APPLIED]
            assert applied
            assert applied[-1]["predicted_speedup"] >= 1.5
            assert applied[-1]["realized_speedup"] >= 1.5
            assert decision["realized_speedup"] >= 1.5

            # zero recompiles at the swap: the apply prewarmed the
            # chosen program, the retune hit the cache
            done = [r for r in records
                    if r["kind"] == EventKind.OPTIMIZER_APPLY_DONE]
            assert done and done[-1]["recompiled"] == 0
            assert done[-1]["prewarmed"]

            # the paired throughput gate: post-convergence vs degraded
            recovered_rate = clock.rate(90, 120)
            assert recovered_rate >= 1.5 * degraded_rate, (
                recovered_rate, degraded_rate)

            # one trace id stitches master decision + worker apply +
            # measurement into one incident trail
            tids = {r.get("trace_id") for r in records
                    if r["kind"] in (EventKind.OPTIMIZER_PLAN_CHOSEN,
                                     EventKind.OPTIMIZER_APPLY_BEGIN,
                                     EventKind.OPTIMIZER_APPLY_DONE,
                                     EventKind.OPTIMIZER_APPLIED)
                    and r.get("plan_id") == decision["plan_id"]}
            assert len(tids) == 1 and None not in tids
            # ...and it is the VERDICT's incident id: the diagnosis and
            # the decision it triggered merge into ONE `tpurun trace`
            # incident, not two
            verdict_tids = {r.get("trace_id") for r in records
                            if r["kind"] == EventKind.DIAG_STRAGGLER}
            assert tids <= verdict_tids, (tids, verdict_tids)

            # forensic + live plan views agree on the plan
            trail = decision_trail_from_events(records)
            assert trail["plans"]
            assert trail["plans"][0]["plan_id"] == decision["plan_id"]
            assert trail["plans"][0]["realized_speedup"] >= 1.5
            client = MasterClient(master.addr, node_id=0)
            live = client.get_plan()
            client.close()
            assert live["running"]["steps_per_call"] \
                == decision["chosen"]["steps_per_call"]
            assert live["decisions"]

            # the mttr/goodput satellites see the replan scenario
            rep = mttr_report(records)["detail"]
            assert rep["by_scenario"]["replan"]["count"] >= 1
            ledger = derive_goodput(records)
            assert ledger["detail"]["buckets"]["replan"]["seconds"] > 0

            # the CLI smoke gate: live + forensic
            from dlrover_tpu.trainer.run import main as tpurun

            assert tpurun(["plan", "--addr", master.addr]) == 0
            assert tpurun(["plan", "--events", events_path]) == 0
            assert tpurun(
                ["plan", "--events", events_path, "--json"]) == 0
        finally:
            master.stop()

    def test_world_shrink_triggers_a_replan_without_restart(
            self, tmp_path, monkeypatch):
        """The second trigger: a live world shrink (8 → 4 devices,
        PR 5's in-process reshard) reports the new running config and
        the optimizer re-plans for the survivor world — still no
        process restart."""
        events_path = str(tmp_path / "events2.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        ctx = get_context()
        monkeypatch.setattr(ctx, "replan_cooldown_secs", 60.0)
        master = start_local_master()
        try:
            half = jax.devices()[:4]
            ex, trainer, _clock, _ = _run_node(
                master, 0, steps=40, poll=True,
                reshard_at=(12, half))
            assert int(ex.state.step) == 40  # finished, no restart
            world = ex.state.params["w"].sharding.mesh.devices.size
            assert world == 4  # survivor mesh
            opt = master.servicer.runtime_optimizer
            triggers = [d["trigger"] for d in opt.decisions()]
            assert "world_change:8->4" in triggers, triggers
            # the master's running-config view tracks the shrink
            assert opt.to_report()["running"]["world"] == 4
        finally:
            master.stop()
