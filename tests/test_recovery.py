"""Fault-recovery e2e: the CPU-mesh recovery wedge.

``bench.py --mode recovery`` with BENCH_PLATFORM=cpu runs the three-way
wedge from docs/operations.md: in-process live reshard vs warm
(compile-cached) process restart vs cold process restart, on the same
tiny model (ISSUE 5 acceptance). This test runs it end-to-end and
asserts the wedge's own gates: live reshard >= 3x faster than a warm
restart (paired median), zero persistent-cache misses on the warm
same-topology restart legs, and post-reshard params bit-identical to
the drained snapshot. On real accelerators the same mode keeps the
kill-and-restore MTTR measurement against the BASELINE <90 s target.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_recovery_wedge_live_vs_restart(tmp_path):
    env = dict(os.environ)
    env.update(
        BENCH_PLATFORM="cpu",
        BENCH_WEDGE_PAIRS="3",
        BENCH_RECOVERY_DIR=str(tmp_path),
        BENCH_RECOVERY_TIMEOUT="240",
        BENCH_WEDGE_ARTIFACT=str(tmp_path / "BENCH_r07.json"),
        BENCH_WEDGE_MTTR=str(tmp_path / "MTTR_r02.json"),
        BENCH_PEER_ARTIFACT=str(tmp_path / "BENCH_r14.json"),
        JAX_PLATFORMS="cpu",
    )
    # the wedge pins its own XLA_FLAGS (8-device live mesh, 1-device
    # restart legs); a pytest-inherited 8-device flag is fine
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mode",
         "recovery"],
        env=env, capture_output=True, text=True, timeout=560, cwd=REPO,
    )
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no bench output; stderr tail: {proc.stderr[-2000:]}"
    by_metric = {json.loads(ln)["metric"]: json.loads(ln)
                 for ln in lines}
    rec = by_metric["live_reshard_speedup"]
    assert "error" not in rec, rec

    detail = rec["detail"]
    # the acceptance wedge: live reshard >= 3x a warm process restart
    assert rec["value"] >= 3.0, rec
    # zero recompiles on every warm same-topology restart leg
    assert detail["warm_zero_recompiles"] is True, detail
    assert all(m == 0 for m in detail["warm_cache_misses"]), detail
    # correctness: the resharded params ARE the drained snapshot
    assert detail["params_bit_identical"] is True, detail
    # every restart leg resumed from a committed checkpoint
    assert all(s >= 5 for s in detail["restored_from"]), detail
    # the warm compile cache also pays off for plain restarts
    assert detail["cold_restart_mttr_s"] > min(
        detail["warm_restart_mttr_s"]
    ), detail

    # artifacts: the wedge line and the DERIVED live_reshard MTTR report
    wedge = json.loads((tmp_path / "BENCH_r07.json").read_text())
    assert wedge["metric"] == "live_reshard_speedup"
    mttr = json.loads((tmp_path / "MTTR_r02.json").read_text())
    assert mttr["detail"]["by_scenario"]["live_reshard"]["count"] >= 1

    # the checkpoint-free peer-rebuild leg (ISSUE 15): MTTR breakdown
    # recorded, every byte came from peer DRAM, params bitwise
    peer = by_metric["peer_rebuild_mttr_s"]
    assert "error" not in peer, peer
    pd = peer["detail"]
    assert pd["params_bit_identical"] is True, pd
    assert pd["bytes_from_storage"] == 0, pd
    assert all(b > 0 for b in pd["bytes_from_peers"]), pd
    assert pd["drain_s"] >= 0 and pd["fetch_s"] and pd["device_put_s"]
    artifact = json.loads((tmp_path / "BENCH_r14.json").read_text())
    assert artifact["metric"] == "peer_rebuild_mttr_s"
