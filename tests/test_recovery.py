"""Fault-recovery (MTTR) e2e: kill a training worker, restart, measure.

The BASELINE.json target is <90 s restore after an injected host
preemption (reference rationale: ``docs/blogs/
stabilize_llm_training_cn.md:209-216`` — process restart beats job
restart). The bench driver (``bench.py --mode recovery``) SIGKILLs a
checkpointing worker and times kill → first completed post-restore step;
this test runs it end-to-end on CPU and asserts both correctness (the
restart resumed from a committed Orbax step, not from scratch) and the
bound. The persistent XLA compile cache is what keeps the warm boot
fast; the test asserts it actually collapsed the restart compile time.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.slow
def test_kill_and_restore_within_budget(tmp_path):
    env = dict(os.environ)
    env.update(
        BENCH_PLATFORM="cpu",
        BENCH_PRESET="tiny",
        BENCH_STEPS="500",  # plenty; the driver kills long before this
        BENCH_SAVE_EVERY="5",
        BENCH_RECOVERY_DIR=str(tmp_path),
        BENCH_RECOVERY_TIMEOUT="240",
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"), "--mode",
         "recovery"],
        env=env, capture_output=True, text=True, timeout=560, cwd=REPO,
    )
    lines = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
    assert lines, f"no bench output; stderr tail: {proc.stderr[-2000:]}"
    rec = json.loads(lines[-1])
    assert rec["metric"] == "recovery_mttr_s"
    assert "error" not in rec, rec

    detail = rec["detail"]
    # correctness: resumed from a committed checkpoint, stepped past it
    assert detail["restored_from_step"] >= 5
    assert detail["first_post_restore_step"] == (
        detail["restored_from_step"] + 1
    )
    assert detail["loss_after_restore"] == pytest.approx(
        detail["loss_after_restore"]
    )  # finite

    # the target bound (generous on a 1-core CPU; ~6 s typical)
    assert rec["value"] < 90.0, rec

    # the compile cache must have made the warm boot faster than cold
    assert detail["warm_boot_to_first_step_s"] < (
        detail["cold_boot_to_first_step_s"]
    ), detail

    # the cache is populated on disk
    cache = tmp_path / "xla_cache"
    assert cache.is_dir() and any(cache.iterdir())
