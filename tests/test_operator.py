"""Operator reconcilers against a fake API server (the reference tests
drive its Go reconcilers against canned objects the same way)."""

from dlrover_tpu.operator.controller import (
    ElasticJobReconciler,
    ScalePlanReconciler,
    build_master_pod,
    build_master_service,
    master_addr,
    master_pod_name,
    run_operator,
)
from dlrover_tpu.operator.types import (
    ElasticJob,
    JobPhase,
    ScalePlan,
    elastic_job_cr,
)
from dlrover_tpu.scheduler.kubernetes import (
    ELASTICJOB_PLURAL,
    SCALEPLAN_PLURAL,
    build_scale_plan_cr,
)


class FakeK8sClient:
    """Tiny in-memory API server: pods, services, custom resources."""

    def __init__(self):
        self.pods = {}
        self.services = {}
        self.crs = {ELASTICJOB_PLURAL: {}, SCALEPLAN_PLURAL: {}}

    # pod API
    def create_pod(self, pod):
        pod.setdefault("status", {"phase": "Pending"})
        self.pods[pod["metadata"]["name"]] = pod

    def delete_pod(self, name):
        self.pods.pop(name, None)

    def list_pods(self, label_selector=""):
        wants = dict(
            kv.split("=") for kv in label_selector.split(",") if "=" in kv
        )
        out = []
        for pod in self.pods.values():
            labels = pod["metadata"].get("labels", {})
            if all(labels.get(k) == v for k, v in wants.items()):
                out.append(pod)
        return out

    def create_service(self, service):
        self.services[service["metadata"]["name"]] = service

    # CR API
    def create_custom_resource(self, plural, body):
        self.crs[plural][body["metadata"]["name"]] = body

    def get_custom_resource(self, plural, name):
        return self.crs[plural].get(name)

    def list_custom_resources(self, plural):
        return list(self.crs[plural].values())

    def update_custom_resource_status(self, plural, name, body):
        self.crs[plural][name] = body

    # test helpers
    def set_pod_phase(self, name, phase):
        self.pods[name]["status"]["phase"] = phase


def _job_cr(name="job1"):
    return elastic_job_cr(
        name,
        replica_specs={
            "worker": {"replicas": 2, "resources": {"cpu": 4, "memory": 8192,
                                                    "tpu": 4}},
        },
    )


class TestTypes:
    def test_elastic_job_parses_spec(self):
        job = ElasticJob.from_dict(_job_cr())
        assert job.name == "job1"
        assert job.replica_specs["worker"].replicas == 2
        assert job.replica_specs["worker"].tpu_chips == 4
        assert job.phase == JobPhase.CREATED

    def test_scale_plan_parses(self):
        cr = build_scale_plan_cr(
            "job1", {"worker": {"replicas": 4}}, remove_pods=["worker-9"]
        )
        plan = ScalePlan.from_dict(cr)
        assert plan.owner_job == "job1"
        assert plan.replica_resource_specs["worker"]["replicas"] == 4
        assert plan.remove_pods == ["worker-9"]
        assert plan.phase == JobPhase.PENDING


class TestMasterBootstrap:
    def test_master_pod_and_service(self):
        job = ElasticJob.from_dict(_job_cr())
        pod = build_master_pod(job, "img:1")
        assert pod["metadata"]["name"] == master_pod_name("job1")
        cmd = pod["spec"]["containers"][0]["command"]
        assert "--platform" in cmd and "k8s" in cmd
        assert "--node_num" in cmd and "2" in cmd
        svc = build_master_service(job)
        assert svc["spec"]["selector"]["elasticjob-name"] == "job1"
        assert master_addr("job1", "default").endswith(":50001")


class TestElasticJobReconciler:
    def test_created_bootstraps_master_then_pending(self):
        client = FakeK8sClient()
        client.create_custom_resource(ELASTICJOB_PLURAL, _job_cr())
        rec = ElasticJobReconciler(client, "img:1")
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))
        assert master_pod_name("job1") in client.pods
        assert len(client.services) == 1
        cr = client.get_custom_resource(ELASTICJOB_PLURAL, "job1")
        assert cr["status"]["phase"] == JobPhase.PENDING

    def test_phase_follows_master_pod(self):
        client = FakeK8sClient()
        client.create_custom_resource(ELASTICJOB_PLURAL, _job_cr())
        rec = ElasticJobReconciler(client, "img:1")
        cr = client.get_custom_resource(ELASTICJOB_PLURAL, "job1")
        rec.reconcile(cr)  # Created -> Pending, master created
        client.set_pod_phase(master_pod_name("job1"), "Running")
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))
        assert client.get_custom_resource(ELASTICJOB_PLURAL, "job1")[
            "status"]["phase"] == JobPhase.RUNNING
        client.set_pod_phase(master_pod_name("job1"), "Succeeded")
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))
        assert client.get_custom_resource(ELASTICJOB_PLURAL, "job1")[
            "status"]["phase"] == JobPhase.SUCCEEDED

    def test_failed_master_is_relaunched(self):
        client = FakeK8sClient()
        client.create_custom_resource(ELASTICJOB_PLURAL, _job_cr())
        rec = ElasticJobReconciler(client, "img:1")
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))
        client.set_pod_phase(master_pod_name("job1"), "Failed")
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))
        # relaunched: pod exists again and is Pending
        assert client.pods[master_pod_name("job1")]["status"][
            "phase"] == "Pending"

    def test_terminal_job_stops_pods(self):
        client = FakeK8sClient()
        cr = _job_cr()
        cr["status"]["phase"] = JobPhase.FAILED
        client.create_custom_resource(ELASTICJOB_PLURAL, cr)
        # a leftover running worker pod
        client.create_pod({
            "metadata": {"name": "job1-worker-0",
                         "labels": {"elasticjob-name": "job1",
                                    "replica-type": "worker"}},
            "status": {"phase": "Running"},
        })
        ElasticJobReconciler(client).reconcile(cr)
        assert "job1-worker-0" not in client.pods

    def test_running_job_picks_up_user_scaleplan(self):
        # the natural flow: user applies a ScalePlan against a Running
        # job; the reconciler relays it and moves the job to Scaling,
        # then back to Running once the plan is terminal
        client = FakeK8sClient()
        client.create_custom_resource(ELASTICJOB_PLURAL, _job_cr())
        rec = ElasticJobReconciler(client, "img:1")
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))
        client.set_pod_phase(master_pod_name("job1"), "Running")
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))

        plan_cr = build_scale_plan_cr("job1", {"worker": {"replicas": 1}})
        plan_cr["status"] = {"phase": JobPhase.PENDING}
        client.create_custom_resource(SCALEPLAN_PLURAL, plan_cr)
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))
        name = plan_cr["metadata"]["name"]
        assert client.get_custom_resource(SCALEPLAN_PLURAL, name)[
            "status"]["phase"] == JobPhase.SCALING
        assert client.get_custom_resource(ELASTICJOB_PLURAL, "job1")[
            "status"]["phase"] == JobPhase.SCALING
        # plan succeeds -> job returns to Running
        client.get_custom_resource(SCALEPLAN_PLURAL, name)["status"][
            "phase"] = JobPhase.SUCCEEDED
        rec.reconcile(client.get_custom_resource(ELASTICJOB_PLURAL, "job1"))
        assert client.get_custom_resource(ELASTICJOB_PLURAL, "job1")[
            "status"]["phase"] == JobPhase.RUNNING

    def test_pending_scaleplan_relayed_when_scaling(self):
        client = FakeK8sClient()
        cr = _job_cr()
        cr["status"]["phase"] = JobPhase.SCALING
        client.create_custom_resource(ELASTICJOB_PLURAL, cr)
        plan_cr = build_scale_plan_cr("job1", {"worker": {"replicas": 4}})
        plan_cr["status"] = {"phase": JobPhase.PENDING}
        client.create_custom_resource(SCALEPLAN_PLURAL, plan_cr)
        ElasticJobReconciler(client).reconcile(cr)
        name = plan_cr["metadata"]["name"]
        assert client.get_custom_resource(SCALEPLAN_PLURAL, name)[
            "status"]["phase"] == JobPhase.SCALING


class TestScalePlanReconciler:
    def test_succeeds_when_replicas_match(self):
        client = FakeK8sClient()
        plan_cr = build_scale_plan_cr("job1", {"worker": {"replicas": 2}})
        plan_cr["status"] = {"phase": JobPhase.SCALING}
        client.create_custom_resource(SCALEPLAN_PLURAL, plan_cr)
        for i in range(2):
            client.create_pod({
                "metadata": {"name": f"job1-worker-{i}",
                             "labels": {"elasticjob-name": "job1",
                                        "replica-type": "worker"}},
                "status": {"phase": "Running"},
            })
        ScalePlanReconciler(client).reconcile(plan_cr)
        assert plan_cr["status"]["phase"] == JobPhase.SUCCEEDED

    def test_stays_scaling_until_pods_arrive(self):
        client = FakeK8sClient()
        plan_cr = build_scale_plan_cr("job1", {"worker": {"replicas": 2}})
        plan_cr["status"] = {"phase": JobPhase.SCALING}
        client.create_custom_resource(SCALEPLAN_PLURAL, plan_cr)
        ScalePlanReconciler(client).reconcile(plan_cr)
        assert plan_cr["status"]["phase"] == JobPhase.SCALING


class TestOperatorLoop:
    def test_end_to_end_rounds(self):
        client = FakeK8sClient()
        client.create_custom_resource(ELASTICJOB_PLURAL, _job_cr())
        run_operator(client, poll_interval=0, max_rounds=1)
        assert master_pod_name("job1") in client.pods
        client.set_pod_phase(master_pod_name("job1"), "Running")
        run_operator(client, poll_interval=0, max_rounds=1)
        assert client.get_custom_resource(ELASTICJOB_PLURAL, "job1")[
            "status"]["phase"] == JobPhase.RUNNING
