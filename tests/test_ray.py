"""Ray platform: job args, actor scaler, actor watcher, job submitter —
all against fakes (the reference tests monkey-patch RayClient the same
way; no Ray cluster required)."""

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.client.ray_job_submitter import RayJobSubmitter
from dlrover_tpu.master.scaler.actor_scaler import ActorScaler
from dlrover_tpu.master.scaler.base_scaler import ScalePlan
from dlrover_tpu.master.watcher.ray_watcher import (
    ActorWatcher,
    actor_state_to_status,
)
from dlrover_tpu.scheduler.ray import (
    ActorArgs,
    parse_type_id_from_actor_name,
    ray_job_args,
)


class FakeRayClient:
    """In-memory actor registry standing in for scheduler.ray.RayClient."""

    def __init__(self):
        self.actors = {}  # name -> state
        self.created = []
        self.deleted = []

    def create_actor(self, actor_args: ActorArgs):
        self.actors[actor_args.actor_name] = "ALIVE"
        self.created.append(actor_args)

    def delete_actor(self, name):
        self.deleted.append(name)
        return self.actors.pop(name, None) is not None

    def list_actors(self):
        return dict(self.actors)


class TestRayJobArgs:
    def test_conf_to_job_args(self):
        args = ray_job_args({
            "worker": {"count": 4, "cpu": 8, "memory": 16384, "chips": 4},
            "ps": {"count": 2, "cpu": 16, "memory": 32768},
            "distribution_strategy": "ps",
            "node_unit": 2,
        }, job_name="rj")
        assert args.platform == "ray"
        assert args.node_unit == 2
        worker = args.node_args[NodeType.WORKER].group_resource
        assert worker.count == 4
        assert worker.node_resource.accelerator.chips == 4
        assert args.node_args[NodeType.PS].group_resource.count == 2

    def test_actor_name_roundtrip(self):
        assert parse_type_id_from_actor_name("worker-3") == ("worker", 3)
        assert parse_type_id_from_actor_name("ps-10") == ("ps", 10)
        node = Node(node_type="worker", node_id=3)
        assert parse_type_id_from_actor_name(node.name) == ("worker", 3)


class TestActorScaler:
    def _scaler(self, client):
        return ActorScaler("rj", client, master_addr="127.0.0.1:1234")

    def test_scale_up_from_group_target(self):
        client = FakeRayClient()
        plan = ScalePlan()
        plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
            count=3, node_resource=NodeResource(cpu=2, memory=2048)
        )
        self._scaler(client).scale(plan)
        assert sorted(client.actors) == ["worker-0", "worker-1", "worker-2"]
        env = client.created[0].env
        assert env["DLROVER_MASTER_ADDR"] == "127.0.0.1:1234"
        assert env["NODE_TYPE"] == NodeType.WORKER

    def test_scale_down_removes_highest_ids(self):
        client = FakeRayClient()
        for i in range(4):
            client.actors[f"worker-{i}"] = "ALIVE"
        plan = ScalePlan()
        plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
            count=2, node_resource=NodeResource()
        )
        self._scaler(client).scale(plan)
        assert sorted(client.actors) == ["worker-0", "worker-1"]
        assert sorted(client.deleted) == ["worker-2", "worker-3"]

    def test_relaunch_concrete_node(self):
        client = FakeRayClient()
        plan = ScalePlan()
        plan.launch_nodes.append(Node(node_type="worker", node_id=7))
        plan.remove_nodes.append(Node(node_type="worker", node_id=2))
        client.actors["worker-2"] = "ALIVE"
        self._scaler(client).scale(plan)
        assert "worker-7" in client.actors
        assert "worker-2" not in client.actors

    def test_initial_plan_does_not_double_create(self):
        # the initial plan carries the same workers in launch_nodes AND
        # node_group_resources; only one actor per name must exist
        client = FakeRayClient()
        plan = ScalePlan()
        plan.launch_nodes = [Node(node_type="worker", node_id=i)
                             for i in range(2)]
        plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
            count=2, node_resource=NodeResource()
        )
        self._scaler(client).scale(plan)
        assert sorted(client.actors) == ["worker-0", "worker-1"]
        assert len(client.created) == 2

    def test_scale_up_skips_used_ids(self):
        client = FakeRayClient()
        client.actors["worker-0"] = "ALIVE"
        client.actors["worker-2"] = "ALIVE"
        plan = ScalePlan()
        plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
            count=3, node_resource=NodeResource()
        )
        self._scaler(client).scale(plan)
        # new actor takes a fresh id above the max, not the hole
        assert "worker-3" in client.actors


class TestRayWorker:
    def test_default_executor_resolves_to_actor_class(self):
        import importlib

        from dlrover_tpu.master.scaler.actor_scaler import DEFAULT_EXECUTOR

        module_name, _, attr = DEFAULT_EXECUTOR.partition(":")
        cls = getattr(importlib.import_module(module_name), attr)
        assert isinstance(cls, type)

    def test_worker_applies_env_and_runs(self, monkeypatch):
        import os

        from dlrover_tpu.scheduler.ray import RayWorker

        monkeypatch.delenv("RAY_TEST_KEY", raising=False)
        worker = RayWorker(env={"RAY_TEST_KEY": "42"})
        assert os.environ["RAY_TEST_KEY"] == "42"
        assert worker.ping() == "pong"
        assert worker.exec_func("math:sqrt", 9.0) == 3.0


class TestActorWatcher:
    def test_list_maps_states(self):
        client = FakeRayClient()
        client.actors = {"worker-0": "ALIVE", "worker-1": "PENDING_CREATION"}
        watcher = ActorWatcher("rj", client)
        nodes = {n.name: n for n in watcher.list()}
        assert nodes["worker-0"].status == NodeStatus.RUNNING
        assert nodes["worker-1"].status == NodeStatus.PENDING

    def test_watch_emits_transitions(self):
        client = FakeRayClient()
        client.actors = {"worker-0": "PENDING_CREATION"}
        watcher = ActorWatcher("rj", client, poll_interval=0.01)
        stream = watcher.watch()
        ev = next(stream)
        assert (ev.event_type, ev.node.name) == (NodeEventType.ADDED,
                                                "worker-0")
        client.actors["worker-0"] = "ALIVE"
        ev = next(stream)
        assert ev.event_type == NodeEventType.MODIFIED
        assert ev.node.status == NodeStatus.RUNNING
        del client.actors["worker-0"]
        ev = next(stream)
        assert ev.event_type == NodeEventType.DELETED
        watcher.stop()

    def test_state_mapping_unknown(self):
        assert actor_state_to_status("WEIRD") == NodeStatus.UNKNOWN


class FakeSubmissionClient:
    def __init__(self):
        self.jobs = {}

    def submit_job(self, entrypoint, runtime_env=None):
        job_id = f"raysubmit_{len(self.jobs)}"
        self.jobs[job_id] = {"entrypoint": entrypoint, "status": "RUNNING"}
        return job_id

    def get_job_status(self, job_id):
        return self.jobs[job_id]["status"]

    def stop_job(self, job_id):
        self.jobs[job_id]["status"] = "STOPPED"
        return True

    def get_job_info(self, job_id):
        return self.jobs[job_id]

    def get_job_logs(self, job_id):
        return ""


class TestRayJobSubmitter:
    def test_submit_and_wait(self):
        fake = FakeSubmissionClient()
        submitter = RayJobSubmitter(
            conf={"job_name": "rj", "worker": {"count": 2}}, client=fake
        )
        job_id = submitter.submit()
        entry = fake.jobs[job_id]["entrypoint"]
        assert "--platform ray" in entry and "rj" in entry
        fake.jobs[job_id]["status"] = "SUCCEEDED"
        assert submitter.wait_until_finish(job_id, timeout=1) == "SUCCEEDED"

    def test_stop(self):
        fake = FakeSubmissionClient()
        submitter = RayJobSubmitter(conf={"job_name": "rj"}, client=fake)
        job_id = submitter.submit()
        assert submitter.stop_job(job_id)
        assert submitter.get_status(job_id) == "STOPPED"
