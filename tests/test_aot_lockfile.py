"""libtpu lockfile serialization in the AOT prover.

libtpu holds ``/tmp/libtpu_lockfile`` for the holder's lifetime; a
SIGKILLed holder leaves it behind and every later init — including
deviceless compiles needing no tunnel — aborts. The helper
distinguishes a live sibling (flock held: wait within a TIME budget)
from a stale file (acquirable: unlink while holding the lock, inode-
checked) and passes through non-lockfile errors untouched.
"""

import fcntl
import os
import threading

import pytest

from dlrover_tpu.parallel import aot


class FakeTopologies:
    """Scripted get_topology_desc: fail N times, then succeed."""

    def __init__(self, failures, error):
        self.failures = failures
        self.error = error
        self.calls = 0

    def get_topology_desc(self, platform, topology_name):
        self.calls += 1
        if self.calls <= self.failures:
            raise RuntimeError(self.error)
        return f"topo:{topology_name}"


LOCK_ERR = ("ABORTED: Internal error when accessing libtpu "
            "multi-process lockfile.")


@pytest.fixture()
def lockfile(tmp_path, monkeypatch):
    path = str(tmp_path / "libtpu_lockfile")
    monkeypatch.setattr(aot, "_LIBTPU_LOCKFILE", path)
    return path


def test_non_lockfile_errors_pass_through(lockfile):
    fake = FakeTopologies(failures=99, error="some other compiler error")
    with pytest.raises(RuntimeError, match="other compiler"):
        aot._get_topology_desc_serialized(
            fake, "v5:2x2x4", wait_budget_s=1.0, poll_s=0.01,
        )
    assert fake.calls == 1  # no retry for unrelated failures


def test_stale_lockfile_is_removed_and_retried(lockfile):
    with open(lockfile, "w"):
        pass  # present, no holder: stale
    fake = FakeTopologies(failures=1, error=LOCK_ERR)
    out = aot._get_topology_desc_serialized(
        fake, "v5:2x2x4", wait_budget_s=5.0, poll_s=0.01,
    )
    assert out == "topo:v5:2x2x4"
    assert fake.calls == 2
    assert not os.path.exists(lockfile)  # the stale file was unlinked


def test_live_holder_is_waited_for_and_never_unlinked(lockfile):
    """While a sibling holds the flock the helper must wait and must
    NOT unlink the file; once the holder releases, the retry
    proceeds. The existence check runs INSIDE the holding window (the
    release callback, before unlocking), so a helper that wrongly
    unlinks under a live holder fails this test."""
    with open(lockfile, "w"):
        pass
    holder = open(lockfile)
    fcntl.flock(holder, fcntl.LOCK_EX)
    still_there_at_release = []
    released = threading.Event()

    class HeldTopologies:
        calls = 0

        def get_topology_desc(self, platform, topology_name):
            HeldTopologies.calls += 1
            if not released.is_set():
                # the sibling's init keeps failing while the lock is held
                raise RuntimeError(LOCK_ERR)
            return f"topo:{topology_name}"

    def release():
        # sampled while the hold is still in effect
        still_there_at_release.append(os.path.exists(lockfile))
        fcntl.flock(holder, fcntl.LOCK_UN)
        holder.close()
        released.set()

    timer = threading.Timer(0.4, release)
    timer.start()
    try:
        out = aot._get_topology_desc_serialized(
            HeldTopologies(), "v5:2x2x4", wait_budget_s=10.0,
            poll_s=0.1,
        )
        assert out == "topo:v5:2x2x4"
        assert HeldTopologies.calls >= 2
        assert still_there_at_release == [True], (
            "the lockfile was unlinked while a live holder held it"
        )
    finally:
        timer.cancel()


def test_gives_up_when_budget_exhausted(lockfile):
    with open(lockfile, "w"):
        pass
    fake = FakeTopologies(failures=99, error=LOCK_ERR)
    with pytest.raises(RuntimeError, match="lockfile"):
        aot._get_topology_desc_serialized(
            fake, "v5:2x2x4", wait_budget_s=0.3, poll_s=0.01,
        )
    assert fake.calls >= 2  # it did retry within the budget
