"""Unit tests for the common core: node model, state flow, codec, config."""

import os

import pytest

from dlrover_tpu.common import comm, serialize
from dlrover_tpu.common.config import Context
from dlrover_tpu.common.constants import (
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.node import AcceleratorResource, Node, NodeGroupResource, NodeResource
from dlrover_tpu.common.status_flow import get_node_state_flow


class TestStatusFlow:
    def test_allowed_transitions(self):
        flow = get_node_state_flow(NodeStatus.PENDING, NodeStatus.RUNNING)
        assert flow is not None and not flow.should_relaunch
        flow = get_node_state_flow(NodeStatus.RUNNING, NodeStatus.FAILED)
        assert flow is not None and flow.should_relaunch

    def test_same_status_ignored(self):
        assert get_node_state_flow(NodeStatus.RUNNING, NodeStatus.RUNNING) is None

    def test_deleted_from_anywhere(self):
        flow = get_node_state_flow(NodeStatus.BREAKDOWN, NodeStatus.DELETED)
        assert flow is not None and flow.should_relaunch
        flow = get_node_state_flow(NodeStatus.SUCCEEDED, NodeStatus.DELETED)
        assert flow is not None and not flow.should_relaunch

    def test_illegal_transition(self):
        assert get_node_state_flow(NodeStatus.FAILED, NodeStatus.RUNNING) is None


class TestNode:
    def test_lifecycle(self):
        node = Node(NodeType.WORKER, 3, max_relaunch_count=2)
        node.update_status(NodeStatus.RUNNING)
        assert node.start_time is not None
        assert not node.exited()
        node.update_status(NodeStatus.FAILED)
        assert node.exited()

    def test_unrecoverable(self):
        node = Node(NodeType.WORKER, 0, max_relaunch_count=1)
        assert not node.is_unrecoverable_failure()
        node.inc_relaunch_count()
        assert node.is_unrecoverable_failure()
        node2 = Node(NodeType.WORKER, 1)
        node2.exit_reason = NodeExitReason.FATAL_ERROR
        assert node2.is_unrecoverable_failure()

    def test_relaunch_clone(self):
        node = Node(NodeType.WORKER, 0, rank_index=7, slice_index=1)
        clone = node.get_relaunch_node(new_id=10)
        assert clone.id == 10
        assert clone.rank_index == 7
        assert clone.slice_index == 1
        assert clone.relaunch_count == 1

    def test_group_resource_update(self):
        group = NodeGroupResource(
            2, NodeResource(4.0, 8192, AcceleratorResource("tpu", 4, "2x2x1"))
        )
        group.update(count=4, memory=16384)
        assert group.count == 4
        assert group.node_resource.memory == 16384
        assert group.node_resource.cpu == 4.0


class TestSerialize:
    def test_roundtrip_nested(self):
        task = comm.Task(
            task_id=5,
            task_type="training",
            shard=comm.Shard(name="ds", start=100, end=200),
            epoch=2,
        )
        restored = serialize.loads(serialize.dumps(task))
        assert restored == task
        assert restored.shard.end == 200

    def test_roundtrip_int_keyed_world(self):
        world = comm.CommWorld(round=3, world={0: 4, 2: 4, 5: 4})
        restored = serialize.loads(serialize.dumps(world))
        assert restored.world == {0: 4, 2: 4, 5: 4}
        assert all(isinstance(k, int) for k in restored.world)

    def test_response_with_payload(self):
        resp = comm.Response(data=comm.KVStoreValue(key="k", value="v", found=True))
        restored = serialize.loads(serialize.dumps(resp))
        assert restored.data.found

    def test_unknown_type_rejected(self):
        with pytest.raises(ValueError):
            serialize.loads(b'{"__type__": "Evil", "x": 1}')


class TestContext:
    def test_env_override(self, monkeypatch):
        monkeypatch.setenv("DLROVER_TPU_RDZV_TIMEOUT_SECS", "42")
        monkeypatch.setenv("DLROVER_TPU_AUTO_SCALE_ENABLED", "false")
        ctx = Context()
        assert ctx.rdzv_timeout_secs == 42
        assert ctx.auto_scale_enabled is False

    def test_runtime_override(self):
        ctx = Context()
        ctx.set_params({"hang_detection_secs": 60, "_private": 1, "nope": 2})
        assert ctx.hang_detection_secs == 60
        assert not hasattr(ctx, "nope")
