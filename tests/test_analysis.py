"""Static-analysis subsystem: AST rule units (one firing + one clean
case per rule id), SPMD graph-lint fixtures, the four-dispatch MoE
collective audit, and the cost-model perturbation regression."""

import os
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.analysis import graph_lint
from dlrover_tpu.analysis.ast_rules import lint_source
from dlrover_tpu.analysis.findings import Baseline, Finding
from dlrover_tpu.parallel.mesh import MeshPlan


def rules_of(findings):
    return sorted({f.rule_id for f in findings})


def lint_snip(code):
    return lint_source(textwrap.dedent(code), "snippet.py")


# -- AST rules --------------------------------------------------------------


class TestDLR001GrpcTimeout:
    def test_fires_on_stub_call_without_timeout(self):
        findings = lint_snip("""
            import grpc

            class C:
                def __init__(self, channel):
                    self._get = channel.unary_unary("/svc/get")

                def get(self, msg):
                    return self._get(msg)
        """)
        assert rules_of(findings) == ["DLR001"]
        assert findings[0].scope == "C.get"

    def test_clean_with_timeout(self):
        findings = lint_snip("""
            import grpc

            class C:
                def __init__(self, channel):
                    self._get = channel.unary_unary("/svc/get")

                def get(self, msg):
                    return self._get(msg, timeout=30.0)
        """)
        assert findings == []

    def test_fires_on_future_fanout_without_timeout(self):
        findings = lint_snip("""
            import grpc

            def fanout(stub, frames):
                return [stub.future(f) for f in frames]
        """)
        assert rules_of(findings) == ["DLR001"]

    def test_no_grpc_import_no_rule(self):
        # .future() on arbitrary objects outside grpc modules is not ours
        findings = lint_snip("""
            def fanout(stub, frames):
                return [stub.future(f) for f in frames]
        """)
        assert findings == []


class TestDLR002SwallowedException:
    def test_fires_on_silent_pass(self):
        findings = lint_snip("""
            def poll(client):
                try:
                    return client.num_nodes_waiting()
                except Exception:
                    return 0
        """)
        assert rules_of(findings) == ["DLR002"]

    def test_clean_when_logged(self):
        findings = lint_snip("""
            def poll(client, logger):
                try:
                    return client.num_nodes_waiting()
                except Exception as e:
                    logger.warning("poll failed: %s", e)
                    return 0
        """)
        assert findings == []

    def test_clean_when_reraised_or_narrow(self):
        findings = lint_snip("""
            def a(x):
                try:
                    return int(x)
                except ValueError:
                    return 0

            def b(x):
                try:
                    return int(x)
                except Exception:
                    raise
        """)
        assert findings == []


class TestDLR003ThreadDaemon:
    def test_fires_without_daemon(self):
        findings = lint_snip("""
            import threading

            def start(fn):
                t = threading.Thread(target=fn)
                t.start()
        """)
        assert rules_of(findings) == ["DLR003"]

    def test_clean_with_daemon(self):
        findings = lint_snip("""
            import threading

            def start(fn):
                t = threading.Thread(target=fn, daemon=True)
                t.start()
        """)
        assert findings == []


class TestDLR004ImpureInJit:
    def test_fires_on_time_in_jitted_fn(self):
        findings = lint_snip("""
            import time
            import jax

            @jax.jit
            def step(x):
                t0 = time.time()
                return x * t0
        """)
        assert rules_of(findings) == ["DLR004"]

    def test_fires_on_np_random_under_partial_jit(self):
        findings = lint_snip("""
            import functools
            import jax
            import numpy as np

            @functools.partial(jax.jit, static_argnums=0)
            def step(n, x):
                return x + np.random.uniform()
        """)
        assert rules_of(findings) == ["DLR004"]

    def test_clean_outside_jit_and_with_jax_random(self):
        findings = lint_snip("""
            import time
            import jax

            def host_loop(x):
                return time.time()

            @jax.jit
            def step(x, key):
                return x + jax.random.normal(key, x.shape)
        """)
        assert findings == []


class TestDLR005MutableDefault:
    def test_fires_on_function_default(self):
        findings = lint_snip("""
            def merge(extra={}):
                return dict(extra)
        """)
        assert rules_of(findings) == ["DLR005"]

    def test_fires_on_annotated_class_attr(self):
        findings = lint_snip("""
            from typing import Dict, List

            class RegistryConf:
                entries: List[str] = []
        """)
        assert rules_of(findings) == ["DLR005"]
        assert findings[0].scope == "RegistryConf"

    def test_clean_with_classvar_none_or_factory(self):
        findings = lint_snip("""
            from dataclasses import dataclass, field
            from typing import ClassVar, Dict, List, Optional

            class Registry:
                entries: ClassVar[List[str]] = []

            @dataclass
            class Conf:
                tags: List[str] = field(default_factory=list)

            def merge(extra=None):
                return dict(extra or {})
        """)
        assert findings == []


class TestDLR006HostSyncOnMetrics:
    def test_fires_on_float_item_asarray(self):
        findings = lint_snip("""
            import numpy as np

            def after_step(step, metrics):
                loss = float(metrics["loss"])
                gn = metrics["grad_norm"].item()
                arr = np.asarray(metrics.get("aux"))
                return loss, gn, arr
        """)
        assert rules_of(findings) == ["DLR006"]
        assert len(findings) == 3
        assert findings[0].scope == "after_step"

    def test_fires_on_device_get_of_self_metrics(self):
        findings = lint_snip("""
            import jax

            class Loop:
                def log(self):
                    return jax.device_get(self.step_metrics)
        """)
        assert rules_of(findings) == ["DLR006"]

    def test_clean_on_non_metric_values(self):
        findings = lint_snip("""
            import numpy as np

            def report(v, config):
                rate = float(v)
                lim = config.limit.item()
                return np.asarray([rate, lim])
        """)
        assert findings == []


class TestDLR007UnregisteredMetricName:
    def test_fires_on_literal_names(self):
        findings = lint_snip("""
            from dlrover_tpu.telemetry import emit_event, get_registry

            def instrument(reg):
                c = reg.counter("my_adhoc_total")
                g = get_registry().gauge(name="my_gauge")
                emit_event("my_event", step=1)
                return c, g
        """)
        assert rules_of(findings) == ["DLR007"]
        assert len(findings) == 3

    def test_clean_with_names_constants(self):
        findings = lint_snip("""
            from dlrover_tpu.telemetry import (
                emit_event, get_registry, names as tm,
            )

            def instrument(reg):
                c = reg.counter(tm.TRAIN_STEPS)
                emit_event(tm.EventKind.TRAIN_START, step=1)
                return c
        """)
        assert findings == []

    def test_telemetry_package_itself_is_exempt(self):
        from dlrover_tpu.analysis.ast_rules import lint_source

        findings = lint_source(
            'def counter(name):\n    return counter("literal")\n',
            "dlrover_tpu/telemetry/metrics.py",
        )
        assert findings == []

    def test_unrelated_counter_class_is_not_matched(self):
        # collections.Counter / .count() must not trip the rule
        findings = lint_snip("""
            from collections import Counter

            def tally(words):
                c = Counter("abc")
                return c, words.count("x")
        """)
        assert findings == []


class TestDLR008FailureEventErrorCode:
    def test_fires_on_missing_or_empty_code(self):
        findings = lint_snip("""
            from dlrover_tpu.telemetry import EventKind, emit_event

            def report(rank):
                emit_event(EventKind.WORKER_FAILED, local_rank=rank)
                emit_event(EventKind.HANG_DETECTED, error_code="")
        """)
        assert rules_of(findings) == ["DLR008"]
        assert len(findings) == 2

    def test_fires_on_string_literal_kind(self):
        # inside the telemetry package a literal kind is DLR007-exempt,
        # but the failure-class code requirement still applies
        findings = lint_source(
            "from dlrover_tpu.telemetry import emit_event\n"
            "def f():\n"
            "    emit_event('diag_straggler', diag_node=2)\n",
            "dlrover_tpu/telemetry/whatever.py",
        )
        assert rules_of(findings) == ["DLR008"]

    def test_clean_with_codes_and_on_non_failure_kinds(self):
        findings = lint_snip("""
            from dlrover_tpu.telemetry import EventKind, emit_event

            def report(rc, reason):
                emit_event(EventKind.WORKER_FAILED,
                           error_code=f"EXIT_{rc}")
                emit_event(EventKind.ERROR_REPORT, error_code=reason)
                emit_event(EventKind.TRAIN_START, step=0)
                emit_event(EventKind.WORKERS_STARTED, round=1)
        """)
        assert findings == []

    def test_telemetry_package_is_not_exempt(self):
        # unlike DLR007, a failure emit inside the telemetry package
        # itself must still carry a code
        findings = lint_source(
            "from dlrover_tpu.telemetry import EventKind, emit_event\n"
            "def f():\n"
            "    emit_event(EventKind.NONFINITE_STEP, step=1)\n",
            "dlrover_tpu/telemetry/whatever.py",
        )
        assert rules_of(findings) == ["DLR008"]


class TestBaseline:
    def test_filter_allows_counts_and_reports_stale(self):
        f1 = Finding("DLR002", "a.py", 10, "m", scope="A.f")
        f2 = Finding("DLR002", "a.py", 20, "m", scope="A.f")
        base = Baseline.from_findings([f1, f2])
        # both findings covered
        new, stale = base.filter([f1, f2])
        assert new == [] and stale == []
        # a third in the same scope is NEW
        f3 = Finding("DLR002", "a.py", 30, "m", scope="A.f")
        new, _ = base.filter([f1, f2, f3])
        assert len(new) == 1
        # fixing one leaves a stale count so the ratchet shrinks
        new, stale = base.filter([f1])
        assert new == [] and stale == [f1.baseline_key]

    def test_round_trip(self, tmp_path):
        base = Baseline.from_findings(
            [Finding("DLR001", "b.py", 1, "m", scope="g")]
        )
        path = str(tmp_path / "baseline.json")
        base.save(path)
        assert Baseline.load(path).entries == base.entries

    def test_missing_file_is_empty(self, tmp_path):
        assert Baseline.load(str(tmp_path / "nope.json")).entries == {}


# -- graph lint: per-rule fixtures ------------------------------------------


class TestGraphRuleFixtures:
    def test_g102_fires_on_debug_callback(self):
        def f(x):
            jax.debug.print("x sum {}", x.sum())
            return x * 2

        low = jax.jit(f).lower(jnp.ones((4,)))
        findings = graph_lint.check_host_callbacks(low.as_text())
        assert rules_of(findings) == ["G102"]

    def test_g102_clean_without_callback(self):
        low = jax.jit(lambda x: x * 2).lower(jnp.ones((4,)))
        assert graph_lint.check_host_callbacks(low.as_text()) == []

    def test_g103_fires_on_python_scalar_arg(self):
        low = jax.jit(lambda x, s: x * s).lower(jnp.ones((4,)), 0.5)
        findings = graph_lint.check_weak_type_inputs(
            getattr(low, "args_info", None)
        )
        assert rules_of(findings) == ["G103"]

    def test_g103_clean_with_strong_scalar(self):
        low = jax.jit(lambda x, s: x * s).lower(
            jnp.ones((4,)), jnp.float32(0.5)
        )
        assert graph_lint.check_weak_type_inputs(
            getattr(low, "args_info", None)
        ) == []

    def test_g104_fires_on_f32_dots_under_bf16_policy(self):
        low = jax.jit(lambda a, b: a @ b).lower(
            jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32)
        )
        findings = graph_lint.check_dtype_drift(low.as_text(), "bfloat16")
        assert rules_of(findings) == ["G104"]

    def test_g104_clean_on_bf16_dots(self):
        low = jax.jit(lambda a, b: a @ b).lower(
            jnp.ones((8, 8), jnp.bfloat16), jnp.ones((8, 8), jnp.bfloat16)
        )
        assert graph_lint.check_dtype_drift(low.as_text(), "bfloat16") == []

    def test_g104_not_applicable_to_f32_policy(self):
        low = jax.jit(lambda a, b: a @ b).lower(
            jnp.ones((8, 8), jnp.float32), jnp.ones((8, 8), jnp.float32)
        )
        assert graph_lint.check_dtype_drift(low.as_text(), "float32") == []

    def test_g105_donation_detected_and_missed(self):
        state = {"w": jnp.ones((16, 16)), "m": jnp.ones((16, 16))}
        step = lambda s: jax.tree.map(lambda x: x + 1.0, s)  # noqa: E731
        donated = jax.jit(step, donate_argnums=(0,)).lower(state).compile()
        plain = jax.jit(step).lower(state).compile()
        assert graph_lint.check_donation(donated.as_text(), 2) == []
        findings = graph_lint.check_donation(plain.as_text(), 2)
        assert rules_of(findings) == ["G105"]

    def test_g101_replicated_param_under_sharded_strategy(self):
        from types import SimpleNamespace

        from jax.sharding import Mesh, NamedSharding, PartitionSpec

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("fsdp",))
        big = jax.ShapeDtypeStruct((128, 128), jnp.float32)
        plan = MeshPlan(data=1, fsdp=8)
        replicated = SimpleNamespace(
            params={"w": NamedSharding(mesh, PartitionSpec())}
        )
        sharded = SimpleNamespace(
            params={"w": NamedSharding(mesh, PartitionSpec("fsdp", None))}
        )
        abstract = SimpleNamespace(params={"w": big})
        assert rules_of(graph_lint.check_param_shardings(
            replicated, abstract, plan)) == ["G101"]
        assert graph_lint.check_param_shardings(
            sharded, abstract, plan) == []
        # pure-DP strategies replicate by design: not a finding
        assert graph_lint.check_param_shardings(
            replicated, abstract, MeshPlan(data=8, fsdp=1)) == []
        # deliberately-replicated SMALL tensors (norm scales, biases —
        # under rel_frac of total param bytes) are fine
        small = jax.ShapeDtypeStruct((64,), jnp.float32)
        mixed_shard = SimpleNamespace(params={
            "w": NamedSharding(mesh, PartitionSpec("fsdp", None)),
            "scale": NamedSharding(mesh, PartitionSpec()),
        })
        mixed_abs = SimpleNamespace(params={"w": big, "scale": small})
        assert graph_lint.check_param_shardings(
            mixed_shard, mixed_abs, plan) == []

    def test_g101_full_param_gather_text_fixture(self):
        total = 1024 * 256 * 4
        hoisted = ("  %ag = f32[1024,256]{1,0} all-gather("
                   "f32[128,256]{1,0} %p), dimensions={0}\n")
        per_layer = ("  %ag = f32[64,256]{1,0} all-gather("
                     "f32[8,256]{1,0} %p), dimensions={0}\n")
        assert rules_of(graph_lint.check_full_param_gather(
            hoisted, total)) == ["G101"]
        assert graph_lint.check_full_param_gather(per_layer, total) == []
        # bigger-than-the-param-set gathers are activation movement
        # (capacity-MoE one-hots) — G106's domain, not G101's
        assert graph_lint.check_full_param_gather(
            hoisted, total // 2) == []

    def test_g106_audit_both_directions(self):
        assert graph_lint.collective_audit(1e6, 1e6) == []
        assert rules_of(
            graph_lint.collective_audit(100e6, 1e6)) == ["G106"]
        assert rules_of(
            graph_lint.collective_audit(1e6, 100e6)) == ["G106"]
        # sub-KiB predictions (single-chip meshes) skip the ratio
        assert graph_lint.collective_audit(1e6, 0.0) == []


# -- graph lint: end-to-end over the real train step ------------------------


@pytest.fixture(scope="module")
def dense_report():
    return graph_lint.lint_train_step()


@pytest.fixture(scope="module")
def moe_reports():
    return graph_lint.moe_dispatch_audit()


class TestGraphLintEndToEnd:
    def test_head_train_step_is_clean(self, dense_report):
        assert dense_report.findings == []

    def test_measures_every_collective_family_planner_prices(
            self, dense_report):
        # data x fsdp x tensor mesh: gathers + reduces must both appear
        kinds = set(dense_report.measured_bytes)
        assert "all-gather" in kinds and "all-reduce" in kinds
        assert dense_report.predicted_total > 0

    def test_moe_audit_clean_for_all_four_dispatches(self, moe_reports):
        assert [r.label for r in moe_reports] == [
            "llama_tiny_moe[gather]", "llama_tiny_moe[einsum]",
            "llama_tiny_moe[grouped]", "llama_tiny_moe[grouped_ep]",
        ]
        for rep in moe_reports:
            assert rep.findings == [], (
                rep.label, [f.render() for f in rep.findings]
            )

    def test_grouped_ep_prediction_includes_dispatch_bytes(
            self, moe_reports):
        by_label = {r.label: r for r in moe_reports}
        ep = by_label["llama_tiny_moe[grouped_ep]"]
        assert ep.predicted_bytes["moe_dispatch"] > 0
        # capacity dispatches price the overhead as compute, not comm
        assert by_label["llama_tiny_moe[gather]"].predicted_bytes[
            "moe_dispatch"] == 0

    def test_perturbed_cost_term_fails_the_audit(self, moe_reports):
        """The cost-model-rot regression (ISSUE 2 satellite): corrupting
        one planner term must trip G106 against the UNCHANGED compiled
        measurement. Inflation uses 10000x: the einsum dispatch already
        sits ~16.7x above its prediction (GSPMD realizes the one-hot
        capacity movement as per-layer gathers the model prices as
        compute), so a single-term inflation must clear tol * that
        headroom — with margin — before the symmetric band flags it."""
        for rep in moe_reports:
            perturbed = dict(rep.predicted_bytes)
            perturbed["moe_dispatch"] = (
                perturbed["moe_dispatch"] or perturbed["fsdp"]) * 10_000
            findings = graph_lint.collective_audit(
                rep.measured_total, sum(perturbed.values()),
                path=rep.label,
            )
            assert rules_of(findings) == ["G106"], rep.label
            shrunk = {k: v / 100 for k, v in rep.predicted_bytes.items()}
            findings = graph_lint.collective_audit(
                rep.measured_total, sum(shrunk.values()), path=rep.label,
            )
            assert rules_of(findings) == ["G106"], rep.label

    def test_multi_step_scan_passes_g105_and_g106(self):
        """The steps_per_call=8 fused program (the lax.scan multi-step
        of ISSUE 3): donation must survive the outer scan (G105 clean),
        and the G106 audit must hold with the measured bytes K-weighted
        by the scan's known_trip_count against a K-scaled prediction.
        K=1 is the dense_report fixture; this pins K=8."""
        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel import planner

        rep = graph_lint.lint_train_step(
            steps_per_call=8, rules={"G105", "G106"},
        )
        assert rep.findings == [], [f.render() for f in rep.findings]
        assert rep.measured_total > 0
        # prediction scaled by exactly K (same per-step formulas)
        config = llama.llama_tiny(
            param_dtype=jnp.bfloat16, compute_dtype=jnp.bfloat16
        )
        base = planner.predicted_collective_bytes(
            MeshPlan(data=2, fsdp=2, tensor=2),
            planner.model_spec_from_llama(config, 8),
            planner.TPU_SPECS["v5e"],
        )
        assert rep.predicted_total == pytest.approx(
            8 * sum(base.values()))

    def test_seeded_callback_violation_end_to_end(self):
        """A debug print smuggled into the loss must trip G102 through
        the same accelerate -> lower -> lint_artifacts path the CLI
        runs (lower only, no compile: the check reads StableHLO)."""
        import optax

        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.strategy import Strategy

        config = llama.llama_tiny()
        base_loss = llama.make_loss_fn(config)

        def noisy_loss(params, batch, rng):
            jax.debug.print("step!")
            return base_loss(params, batch, rng)

        rng = np.random.RandomState(0)
        ids = rng.randint(0, config.vocab_size,
                          size=(4, config.max_seq_len + 1))
        batch = {"input_ids": jnp.asarray(ids[:, :-1]),
                 "labels": jnp.asarray(ids[:, 1:])}
        result = accelerate(
            llama.make_init_fn(config), noisy_loss, optax.sgd(1e-3),
            batch,
            strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                              rule_set="llama"),
        )
        abstract_state = jax.eval_shape(
            result.init_fn, jax.random.PRNGKey(0))
        abstract_batch = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), batch)
        lowered = result.train_step.lower(
            abstract_state, abstract_batch,
            jax.ShapeDtypeStruct((2,), jnp.uint32))
        rep = graph_lint.lint_artifacts(
            stablehlo=lowered.as_text(), rules={"G102"}, label="seeded")
        assert rules_of(rep.findings) == ["G102"]


class TestAotLintSurface:
    def test_report_json_carries_findings_only_when_lint_ran(self):
        from dlrover_tpu.parallel.aot import AotReport

        kwargs = dict(
            model="m", topology="t", n_devices=8, mesh={}, params=1,
            global_batch=8, seq_len=128, fits=True,
            hbm_per_device_bytes=1e9, hbm_capacity_bytes=9e9,
            flops_per_step=1e12, predicted_step_time_s=0.1,
            predicted_mfu=0.5, compile_time_s=1.0,
        )
        assert "lint_findings" not in AotReport(**kwargs).to_json()
        ran = AotReport(**kwargs, lint_findings=[
            Finding("G106", "m@t", 0, "drift")
        ]).to_json()
        assert '"lint_findings"' in ran and "G106" in ran


# -- planner byte/second consistency ----------------------------------------


class TestPlannerBytesConsistency:
    def test_estimate_and_bytes_share_formulas(self):
        from dlrover_tpu.parallel import planner

        model = planner.ModelSpec(
            param_count=7_000_000_000, num_layers=32, hidden_size=4096,
            seq_len=4096, global_batch=64, num_heads=32, kv_heads=8,
        )
        dev = planner.TPU_SPECS["v5p"]
        plan = MeshPlan(data=2, fsdp=4, seq=2, tensor=2)
        score = planner.estimate(plan, model, dev)
        pred = planner.predicted_collective_bytes(plan, model, dev)
        assert score.breakdown["tp_comm_s"] == pytest.approx(
            pred["tp"] / dev.ici_bw)
        assert score.breakdown["fsdp_comm_s"] == pytest.approx(
            pred["fsdp"] / dev.ici_bw)
        assert score.breakdown["dp_comm_s"] == pytest.approx(
            pred["dp"] / dev.ici_bw)
        assert score.breakdown["seq_comm_s"] == pytest.approx(
            pred["seq"] / dev.ici_bw)

    def test_moe_dispatch_bytes_match_breakdown(self):
        from dlrover_tpu.parallel import planner

        model = planner.ModelSpec(
            param_count=1_000_000_000, num_layers=8, hidden_size=2048,
            seq_len=2048, global_batch=32, num_experts=8,
            moe_dispatch="grouped_ep",
        )
        dev = planner.TPU_SPECS["v5e"]
        plan = MeshPlan(data=2, fsdp=4)
        score = planner.estimate(plan, model, dev)
        pred = planner.predicted_collective_bytes(plan, model, dev)
        assert pred["moe_dispatch"] > 0
        assert score.breakdown["moe_disp_comm_s"] == pytest.approx(
            pred["moe_dispatch"] / dev.ici_bw)


# -- CLI: concurrency pass + suppression plumbing ---------------------------


class TestCliConcurrencySurface:
    FIXTURE = textwrap.dedent("""
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def run(self):
                with self._lock:
                    time.sleep(1.0){note}
    """)

    def test_concurrency_finding_flows_through_cli(self, tmp_path,
                                                   capsys):
        from dlrover_tpu.analysis import cli

        bad = tmp_path / "locked_sleep.py"
        bad.write_text(self.FIXTURE.format(note=""))
        rc = cli.main([str(bad), "--ast-only",
                       "--baseline", str(tmp_path / "nb.json")])
        assert rc == 1
        assert "DLR009" in capsys.readouterr().out

    def test_suppressed_counts_in_text_summary(self, tmp_path, capsys):
        from dlrover_tpu.analysis import cli

        ok = tmp_path / "suppressed.py"
        ok.write_text(self.FIXTURE.format(
            note="  # dlrlint: disable=DLR009 paced by master"))
        rc = cli.main([str(ok), "--ast-only",
                       "--baseline", str(tmp_path / "nb.json")])
        out = capsys.readouterr().out
        assert rc == 0
        assert "1 inline-suppressed (DLR009" in out

    def test_suppressed_counts_in_json_output(self, tmp_path, capsys):
        import json as _json

        from dlrover_tpu.analysis import cli

        ok = tmp_path / "suppressed.py"
        ok.write_text(self.FIXTURE.format(
            note="  # dlrlint: disable=DLR009 paced by master"))
        rc = cli.main([str(ok), "--ast-only", "--json",
                       "--baseline", str(tmp_path / "nb.json")])
        data = _json.loads(capsys.readouterr().out)
        assert rc == 0
        assert data["suppressed"] == {"DLR009": 1}

    def test_changed_with_unresolvable_ref_exits_2(self, capsys):
        from dlrover_tpu.analysis import cli

        rc = cli.main(["--changed=no-such-ref-zzz", "--ast-only"])
        assert rc == 2
        assert "git could not resolve" in capsys.readouterr().err

    def test_changed_scopes_to_the_package(self, monkeypatch, capsys):
        # a diff touching only tests/ must not make the incremental
        # loop stricter than the full gate (which lints the package)
        import dlrover_tpu
        from dlrover_tpu.analysis import cli

        root = os.path.dirname(os.path.dirname(
            os.path.abspath(dlrover_tpu.__file__)))
        monkeypatch.setattr(
            cli, "_changed_files",
            lambda _root, _ref: [os.path.join(root, "tests",
                                              "test_aot.py")])
        rc = cli.main(["--changed=HEAD", "--ast-only"])
        assert rc == 0
        assert "0 changed .py files" in capsys.readouterr().out
