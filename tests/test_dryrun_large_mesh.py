"""The >=16-device mesh compositions, actually executed.

``__graft_entry__.dryrun_multichip`` defines factorizations for
n=16/32/64; the 8-device row is exercised by the driver, but the
larger rows were dead code (round-3 verdict #3). These tests run the
REAL driver entry point in a subprocess pinned to 16 (and 32) virtual
CPU devices and require every pass — the 4-axis dp x fsdp x sp x tp
mesh, interleaved pipeline parallelism, MoE expert parallelism, and
packed segments — to execute to a finite loss.

Subprocesses because the virtual device count is fixed at backend init;
the in-process test mesh is pinned to 8 (conftest).

Reference bar: mixed nested process groups at scale,
``atorch/atorch/distributed/distributed.py:318-339``.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_dryrun(n_devices, timeout=1500):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never touch the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}"
    )
    env["PYTHONPATH"] = REPO
    return subprocess.run(
        [sys.executable, "-c",
         f"from __graft_entry__ import dryrun_multichip; "
         f"dryrun_multichip({n_devices})"],
        env=env, capture_output=True, text=True, timeout=timeout,
        cwd=REPO,
    )


@pytest.mark.slow
@pytest.mark.parametrize("n,tensor", [(16, 2), (32, 4)])
def test_dryrun_multichip_large(n, tensor):
    proc = _run_dryrun(n)
    assert proc.returncode == 0, (
        f"dryrun_multichip({n}) failed:\n{proc.stderr[-3000:]}"
    )
    out = proc.stdout
    # all five passes ran at this device count
    assert f"dryrun_multichip({n}): mesh=" in out, out
    assert f"dryrun_multichip({n}): interleaved-pp" in out, out
    assert f"dryrun_multichip({n}): moe" in out, out
    assert f"dryrun_multichip({n}): packed segments" in out, out
    assert (
        f"dryrun_multichip({n}): elastic shrink {n}->{n // 2}" in out
    ), out
    assert "(continuity ok)" in out, out
    # the factor row actually used all four axes at n>=16
    mesh_line = next(
        ln for ln in out.splitlines()
        if ln.startswith(f"dryrun_multichip({n}): mesh=")
    )
    for axis in ("'data': 2", "'fsdp': 2", "'seq': 2",
                 f"'tensor': {tensor}"):
        assert axis in mesh_line, mesh_line
    assert "loss=" in mesh_line
