"""Node lifecycle, scalers, watchers, and resource optimization.

Mirrors the reference's test strategy (SURVEY §4): pure-logic managers
driven in-memory, platform clients faked, and one end-to-end run of the
distributed master over real local subprocesses.
"""

import queue
import sys
import time

import pytest

from dlrover_tpu.common.constants import (
    NodeEventType,
    NodeExitReason,
    NodeStatus,
    NodeType,
)
from dlrover_tpu.common.node import Node, NodeGroupResource, NodeResource
from dlrover_tpu.master.node.job_manager import DistributedJobManager
from dlrover_tpu.master.node.ps import ParameterServerManager
from dlrover_tpu.master.node.training_node import TrainingNodeManager
from dlrover_tpu.master.node.worker import WorkerManager
from dlrover_tpu.master.resource.local_optimizer import (
    PSLocalOptimizer,
    SpmdLocalOptimizer,
)
from dlrover_tpu.master.scaler.base_scaler import ScalePlan, Scaler
from dlrover_tpu.master.scaler.pod_scaler import PodScaler
from dlrover_tpu.master.stats.reporter import StatsReporter
from dlrover_tpu.master.stats.training_metrics import RuntimeMetric
from dlrover_tpu.master.watcher.base_watcher import NodeEvent, NodeWatcher
from dlrover_tpu.master.watcher.k8s_watcher import (
    ScalePlanWatcher,
    get_pod_exit_reason,
    pod_to_node,
)
from dlrover_tpu.scheduler.job import local_job_args


def make_nodes(n, node_type=NodeType.WORKER):
    return {
        i: Node(node_type=node_type, node_id=i, rank_index=i,
                status=NodeStatus.RUNNING)
        for i in range(n)
    }


class TestTrainingNodeManager:
    def test_scale_up_assigns_fresh_ranks(self):
        mgr = TrainingNodeManager(make_nodes(2))
        plan = mgr.adjust_node(
            NodeGroupResource(4, NodeResource(cpu=1)), NodeType.WORKER
        )
        assert len(plan.launch_nodes) == 2
        assert sorted(n.rank_index for n in plan.launch_nodes) == [2, 3]

    def test_scale_down_removes_highest_ranks(self):
        mgr = TrainingNodeManager(make_nodes(4))
        plan = mgr.adjust_node(
            NodeGroupResource(2, NodeResource()), NodeType.WORKER
        )
        assert sorted(n.rank_index for n in plan.remove_nodes) == [2, 3]

    def test_relaunch_preserves_rank(self):
        nodes = make_nodes(2)
        mgr = TrainingNodeManager(nodes)
        dead = nodes[1]
        plan = mgr.relaunch_node(dead)
        assert plan.launch_nodes[0].rank_index == 1
        assert plan.launch_nodes[0].id == 2
        assert plan.remove_nodes == [dead]


class TestWorkerManager:
    def test_node_unit_rounding(self):
        mgr = WorkerManager(make_nodes(4), node_unit=4)
        plan = mgr.adjust_worker(NodeGroupResource(6, NodeResource()))
        # 6 rounds down to 4: no new nodes.
        assert plan.node_group_resources[NodeType.WORKER].count == 4
        assert not plan.launch_nodes

        plan = mgr.adjust_worker(NodeGroupResource(9, NodeResource()))
        assert plan.node_group_resources[NodeType.WORKER].count == 8
        assert len(plan.launch_nodes) == 4

    def test_remove_not_joined(self):
        mgr = WorkerManager(make_nodes(3))
        plan = mgr.remove_not_joined_rdzv_workers([2])
        assert [n.rank_index for n in plan.remove_nodes] == [2]


class TestPSManager:
    def test_next_cluster_waits_for_running(self):
        nodes = make_nodes(2, NodeType.PS)
        mgr = ParameterServerManager(nodes)
        plan = mgr.adjust_ps(NodeGroupResource(3, NodeResource(cpu=2)))
        assert len(plan.launch_nodes) == 1
        new_ps = plan.launch_nodes[0]
        # New PS still INITIAL: next cluster == current cluster (2 PSs).
        assert len(mgr.get_next_training_ps_cluster()) == 2
        new_ps.update_status(NodeStatus.PENDING)
        new_ps.update_status(NodeStatus.RUNNING)
        assert len(mgr.get_next_training_ps_cluster()) == 3

    def test_migration_releases_old_after_new_runs(self):
        nodes = make_nodes(2, NodeType.PS)
        for n in nodes.values():
            n.name = f"ps-{n.id}"
        mgr = ParameterServerManager(nodes)
        plan = mgr.migrate_parameter_servers(
            {"ps-0": NodeResource(cpu=16, memory=32768)}
        )
        assert len(plan.launch_nodes) == 1
        replacement = plan.launch_nodes[0]
        assert not nodes[0].is_released
        replacement.update_status(NodeStatus.RUNNING)
        cluster = mgr.get_next_training_ps_cluster()
        assert nodes[0].is_released
        assert replacement in cluster


class RecordingScaler(Scaler):
    def __init__(self):
        super().__init__("test")
        self.plans = []

    def scale(self, plan: ScalePlan):
        self.plans.append(plan)


class QueueWatcher(NodeWatcher):
    """Feeds canned NodeEvents to the job manager's monitor thread."""

    def __init__(self):
        self.events = queue.Queue()
        self._stopped = False

    def watch(self):
        while not self._stopped:
            try:
                yield self.events.get(timeout=0.1)
            except queue.Empty:
                continue

    def list(self):
        return []

    def stop(self):
        self._stopped = True


def make_job_manager(node_num=2, node_unit=1):
    args = local_job_args("jmtest", node_num=node_num, node_unit=node_unit)
    scaler = RecordingScaler()
    watcher = QueueWatcher()
    mgr = DistributedJobManager(args, scaler, watcher)
    mgr._init_nodes()
    mgr._init_managers()
    return mgr, scaler, watcher


class TestDistributedJobManager:
    def test_failure_triggers_relaunch(self):
        mgr, scaler, _ = make_job_manager()
        node = mgr.get_job_nodes(NodeType.WORKER)[0]
        evt_node = Node(NodeType.WORKER, 0, status=NodeStatus.RUNNING)
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt_node))
        assert node.status == NodeStatus.RUNNING
        evt_node = Node(NodeType.WORKER, 0, status=NodeStatus.FAILED)
        evt_node.exit_reason = NodeExitReason.KILLED
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt_node))
        assert len(scaler.plans) == 1
        launched = scaler.plans[0].launch_nodes[0]
        assert launched.rank_index == 0
        assert launched.relaunch_count == 1

    def test_fatal_error_not_relaunched(self):
        mgr, scaler, _ = make_job_manager()
        evt_node = Node(NodeType.WORKER, 0, status=NodeStatus.FAILED)
        evt_node.exit_reason = NodeExitReason.FATAL_ERROR
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt_node))
        assert not scaler.plans

    def test_oom_doubles_memory(self):
        mgr, scaler, _ = make_job_manager()
        node = mgr.get_job_nodes(NodeType.WORKER)[0]
        node.config_resource.memory = 1024
        evt_node = Node(NodeType.WORKER, 0, status=NodeStatus.FAILED)
        evt_node.exit_reason = NodeExitReason.OOM
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt_node))
        assert node.config_resource.memory == 2048
        assert scaler.plans[0].launch_nodes[0].config_resource.memory == 2048

    def test_relaunch_budget_exhausted(self):
        mgr, scaler, _ = make_job_manager()
        node = mgr.get_job_nodes(NodeType.WORKER)[0]
        node.relaunch_count = node.max_relaunch_count
        evt_node = Node(NodeType.WORKER, 0, status=NodeStatus.FAILED)
        evt_node.exit_reason = NodeExitReason.KILLED
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt_node))
        assert not scaler.plans

    def test_oom_bump_does_not_alias_group_resource(self):
        mgr, _, _ = make_job_manager(node_num=2)
        nodes = mgr.get_job_nodes(NodeType.WORKER)
        base_mem = nodes[1].config_resource.memory
        evt = Node(NodeType.WORKER, 0, status=NodeStatus.FAILED)
        evt.exit_reason = NodeExitReason.OOM
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt))
        # Only the OOMed node's resource doubled.
        assert nodes[0].config_resource.memory == base_mem * 2
        assert nodes[1].config_resource.memory == base_mem

    def test_agent_classification_survives_watcher_exit_code(self):
        # Agent reports an OOM traceback; process then exits 1 and the
        # watcher would classify FATAL. The specific reason must win.
        mgr, scaler, _ = make_job_manager()
        node = mgr.get_job_nodes(NodeType.WORKER)[0]
        base_mem = node.config_resource.memory
        mgr.handle_training_failure(
            0, 0, "RESOURCE_EXHAUSTED: HBM OOM while allocating", "process"
        )
        assert node.exit_reason == NodeExitReason.OOM
        evt = Node(NodeType.WORKER, 0, status=NodeStatus.FAILED)
        evt.exit_reason = NodeExitReason.FATAL_ERROR
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt))
        assert node.exit_reason == NodeExitReason.OOM
        assert scaler.plans  # relaunched with the memory bump
        assert node.config_resource.memory == base_mem * 2

    def test_scale_plan_inherits_node_resource(self):
        # Optimizer plans carry only a count; launched nodes must still
        # request the job's per-node resource (chips/cpu/memory).
        mgr, scaler, _ = make_job_manager(node_num=2)
        plan = ScalePlan()
        plan.node_group_resources[NodeType.WORKER] = NodeGroupResource(
            count=4, node_resource=NodeResource()
        )
        mgr.execute_scale_plan(plan)
        launched = scaler.plans[-1].launch_nodes
        assert len(launched) == 2
        assert all(n.config_resource.cpu > 0 for n in launched)

    def test_hot_ps_migration_reaches_scaler(self):
        mgr, scaler, _ = make_job_manager()
        ps_nodes = {
            0: Node(NodeType.PS, 0, name="jmtest-ps-0",
                    status=NodeStatus.RUNNING)
        }
        mgr._job_nodes[NodeType.PS] = ps_nodes
        from dlrover_tpu.master.node.ps import ParameterServerManager
        mgr._ps_manager = ParameterServerManager(ps_nodes)
        plan = ScalePlan()
        plan.migrate_nodes["jmtest-ps-0"] = NodeResource(cpu=16, memory=32768)
        mgr.execute_scale_plan(plan)
        launched = scaler.plans[-1].launch_nodes
        assert len(launched) == 1
        assert launched[0].config_resource.cpu == 16

    def test_breakdown_report_relaunches_node(self):
        # An ICI network-check failure arrives as an agent report, not a
        # watcher event: the process is alive but the chip/link is bad.
        mgr, scaler, _ = make_job_manager()
        node = mgr.get_job_nodes(NodeType.WORKER)[0]
        evt = Node(NodeType.WORKER, 0, status=NodeStatus.RUNNING)
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt))
        mgr.update_node_reported_status(
            NodeType.WORKER, 0, NodeStatus.BREAKDOWN
        )
        assert node.exit_reason == NodeExitReason.HARDWARE_ERROR
        assert len(scaler.plans) == 1
        assert scaler.plans[0].launch_nodes[0].rank_index == 0

    def test_slice_cordon_stops_relaunch(self):
        mgr, scaler, _ = make_job_manager()
        mgr._slice_relaunches[0] = mgr.max_relaunch_count
        evt = Node(NodeType.WORKER, 0, status=NodeStatus.FAILED)
        evt.exit_reason = NodeExitReason.KILLED
        mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt))
        assert not scaler.plans

    def test_pending_early_stop(self):
        mgr, _, _ = make_job_manager()
        ctx = mgr._ctx
        old_timeout = ctx.seconds_to_wait_pending_pod
        ctx.seconds_to_wait_pending_pod = 0.01
        try:
            for node in mgr.get_job_nodes(NodeType.WORKER).values():
                node.update_status(NodeStatus.PENDING)
                node.create_time = time.time() - 1
            assert mgr.should_early_stop()
            # One running node suppresses early stop.
            mgr.get_job_nodes(NodeType.WORKER)[0].update_status(
                NodeStatus.RUNNING
            )
            assert not mgr.should_early_stop()
        finally:
            ctx.seconds_to_wait_pending_pod = old_timeout

    def test_all_workers_exited(self):
        mgr, _, _ = make_job_manager(node_num=2)
        nodes = mgr.get_job_nodes(NodeType.WORKER)
        assert not mgr.all_workers_exited()
        for nid in nodes:
            evt = Node(NodeType.WORKER, nid, status=NodeStatus.SUCCEEDED)
            mgr._process_event(NodeEvent(NodeEventType.MODIFIED, evt))
        assert mgr.all_workers_exited()
        assert mgr.all_workers_succeeded()


class TestK8sWatcherParsing:
    def make_pod(self, phase="Running", reason="", exit_code=0):
        pod = {
            "metadata": {
                "name": "job-worker-0",
                "labels": {"replica-type": "worker", "rank-index": "0"},
                "annotations": {"node-id": "0"},
            },
            "status": {"phase": phase, "containerStatuses": []},
        }
        if reason or exit_code:
            pod["status"]["containerStatuses"] = [
                {"state": {"terminated": {"reason": reason,
                                          "exitCode": exit_code}}}
            ]
        return pod

    def test_pod_to_node(self):
        node = pod_to_node(self.make_pod())
        assert node.type == NodeType.WORKER
        assert node.status == NodeStatus.RUNNING

    def test_oom_reason(self):
        pod = self.make_pod("Failed", reason="OOMKilled", exit_code=137)
        assert get_pod_exit_reason(pod) == NodeExitReason.OOM

    def test_fatal_exit_code(self):
        pod = self.make_pod("Failed", exit_code=1)
        assert get_pod_exit_reason(pod) == NodeExitReason.FATAL_ERROR

    def test_scale_plan_cr_parsing(self):
        cr = {
            "metadata": {"name": "sp-1"},
            "spec": {
                "replicaResourceSpecs": {
                    "worker": {"replicas": 8,
                               "resource": {"cpu": "4", "memory": "8192Mi"}},
                    "ps": {"replicas": 2,
                           "resource": {"cpu": "8", "memory": "2Gi"}},
                },
                "psHosts": ["ps-0:2222"],
            },
        }
        plan = ScalePlanWatcher.to_scale_plan(cr)
        group = plan.node_group_resources["worker"]
        assert group.count == 8
        assert group.node_resource.memory == 8192
        assert plan.node_group_resources["ps"].node_resource.memory == 2048
        assert plan.ps_addrs == ["ps-0:2222"]


class FakeK8sClient:
    def __init__(self):
        self.pods = []
        self.deleted = []

    def create_pod(self, pod):
        self.pods.append(pod)
        return pod

    def delete_pod(self, name):
        self.deleted.append(name)
        return True

    def list_pods(self, label_selector=""):
        return list(self.pods)


class TestPodScaler:
    def test_launch_builds_tpu_pod(self):
        client = FakeK8sClient()
        scaler = PodScaler(
            "job", client, "10.0.0.1:50051", tpu_topology="2x2x4",
            tpu_accelerator="tpu-v5p-slice",
        )
        node = Node(NodeType.WORKER, 0, config_resource=NodeResource(
            cpu=4, memory=8192))
        node.config_resource.accelerator.chips = 4
        plan = ScalePlan(launch_nodes=[node])
        scaler.scale(plan)
        scaler._create_pod(scaler._create_queue.get())
        pod = client.pods[0]
        spec = pod["spec"]["containers"][0]
        assert spec["resources"]["requests"]["google.com/tpu"] == "4"
        assert pod["spec"]["nodeSelector"][
            "cloud.google.com/gke-tpu-topology"] == "2x2x4"
        envs = {e["name"]: e["value"] for e in spec["env"]}
        assert envs["DLROVER_TPU_MASTER_ADDR"] == "10.0.0.1:50051"


def push_runtime_samples(job_name, specs):
    """specs: list of dicts with speed, workers, ps (list of (cpu, used))."""
    reporter = StatsReporter.new_stats_reporter(job_name)
    reporter.runtime_stats.clear()
    for i, s in enumerate(specs):
        metric = RuntimeMetric(timestamp=float(i), speed=s.get("speed", 1.0))
        metric.running_nodes[NodeType.WORKER] = [
            {"id": w, "cpu": 4, "used_cpu": 2, "memory": 8192}
            for w in range(s.get("workers", 1))
        ]
        if "ps" in s:
            metric.running_nodes[NodeType.PS] = [
                {"id": j, "cpu": cpu, "used_cpu": used, "memory": 16384}
                for j, (cpu, used) in enumerate(s["ps"])
            ]
        reporter.runtime_stats.append(metric)
    return reporter


class TestLocalOptimizers:
    def test_ps_headroom_grows_workers(self):
        push_runtime_samples(
            "opt1", [{"workers": 2, "ps": [(8, 3.2)]}] * 4
        )
        opt = PSLocalOptimizer("opt1")
        plan = opt.generate_worker_resource()
        group = plan.node_group_resources[NodeType.WORKER]
        # util 0.4, threshold 0.8 → target capped at 2× current.
        assert group.count == 4

    def test_saturated_ps_blocks_growth(self):
        push_runtime_samples(
            "opt2", [{"workers": 2, "ps": [(8, 7.5)]}] * 4
        )
        opt = PSLocalOptimizer("opt2")
        assert not opt.generate_worker_resource().node_group_resources

    def test_hot_ps_migration(self):
        push_runtime_samples("opt3", [{"workers": 2, "ps": [(8, 7.8)]}] * 4)
        opt = PSLocalOptimizer("opt3")
        plan = opt.generate_hot_ps_migration()
        assert plan.node_resources["ps-0"].cpu == 16

    def test_spmd_grows_while_efficient(self):
        # Speed scales with workers: efficiency flat → keep growing.
        specs = [{"workers": 4, "speed": 4.0}] * 6 + [
            {"workers": 4, "speed": 4.0}] * 6
        push_runtime_samples("opt4", specs)
        opt = SpmdLocalOptimizer("opt4", node_unit=4)
        plan = opt.generate_opt_plan()
        assert plan.node_group_resources[NodeType.WORKER].count == 8

    def test_spmd_stops_on_efficiency_drop(self):
        specs = [{"workers": 4, "speed": 4.0}] * 6 + [
            {"workers": 8, "speed": 4.4}] * 6
        push_runtime_samples("opt5", specs)
        opt = SpmdLocalOptimizer("opt5", node_unit=4)
        plan = opt.generate_opt_plan()
        assert not plan.node_group_resources


class TestDistMasterEndToEnd:
    def test_workers_run_to_completion(self):
        from dlrover_tpu.master.dist_master import DistributedJobMaster
        from dlrover_tpu.master.scaler.process_scaler import LocalProcessScaler
        from dlrover_tpu.master.watcher.process_watcher import LocalProcessWatcher
        from dlrover_tpu.scheduler.local import LocalProcessBackend

        backend = LocalProcessBackend()
        args = local_job_args("e2e-nodes", node_num=2)
        scaler = LocalProcessScaler(
            "e2e-nodes", backend, "",
            command_factory=lambda node: [
                sys.executable, "-c", "import time; time.sleep(0.3)",
            ],
        )
        master = DistributedJobMaster(
            job_args=args,
            scaler=scaler,
            watcher=LocalProcessWatcher(backend, poll_secs=0.1),
        )
        master._ctx.seconds_interval_to_report = 0.2
        master.prepare()
        try:
            rc = master.run()
            assert rc == 0
        finally:
            master.stop()

    def test_failing_worker_relaunched_then_succeeds(self, tmp_path):
        from dlrover_tpu.master.dist_master import DistributedJobMaster
        from dlrover_tpu.master.scaler.process_scaler import LocalProcessScaler
        from dlrover_tpu.master.watcher.process_watcher import LocalProcessWatcher
        from dlrover_tpu.scheduler.local import LocalProcessBackend

        marker = tmp_path / "failed_once"
        script = (
            "import os, sys, time\n"
            f"m = {str(marker)!r}\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').close()\n"
            "    sys.exit(1)\n"
            "time.sleep(0.2)\n"
        )
        backend = LocalProcessBackend()
        args = local_job_args("e2e-relaunch", node_num=1)
        scaler = LocalProcessScaler(
            "e2e-relaunch", backend, "",
            command_factory=lambda node: [sys.executable, "-c", script],
        )
        master = DistributedJobMaster(
            job_args=args,
            scaler=scaler,
            watcher=LocalProcessWatcher(backend, poll_secs=0.1),
        )
        master._ctx.seconds_interval_to_report = 0.2
        master.prepare()
        try:
            rc = master.run()
            assert rc == 0
            workers = master.job_manager.get_job_nodes(NodeType.WORKER)
            assert len(workers) == 2  # original + relaunch
        finally:
            master.stop()
