"""The repo must be lint-clean: the full AST pass over ``dlrover_tpu/``
yields no findings outside the checked-in baseline, and the baseline
carries no stale (already-fixed) entries. This is the tier-1 CI gate of
ISSUE 2 — a new RPC without a deadline, a new silent ``except
Exception`` on a failover path, or a new shared mutable default fails
this test, not a code review."""

import os
import textwrap

import dlrover_tpu
from dlrover_tpu.analysis import cli
from dlrover_tpu.analysis.ast_rules import lint_paths
from dlrover_tpu.analysis.concurrency import lint_paths_concurrency
from dlrover_tpu.analysis.findings import Baseline

PKG_DIR = os.path.dirname(os.path.abspath(dlrover_tpu.__file__))
ROOT = os.path.dirname(PKG_DIR)
BASELINE = os.path.join(PKG_DIR, "analysis", "baseline.json")


class TestRepoLintClean:
    def test_no_findings_outside_baseline_and_no_stale_entries(self):
        findings = lint_paths([PKG_DIR], root=ROOT)
        findings.extend(lint_paths_concurrency([PKG_DIR], root=ROOT))
        baseline = Baseline.load(BASELINE)
        new, stale = baseline.filter(findings)
        assert new == [], "new lint findings (fix or baseline them):\n" \
            + "\n".join(f.render() for f in new)
        assert stale == [], (
            "baseline entries whose sites were fixed — ratchet them out "
            "of dlrover_tpu/analysis/baseline.json: " + ", ".join(stale)
        )

    def test_cli_ast_pass_exits_zero_at_head(self, capsys):
        assert cli.main(["--ast-only"]) == 0
        assert "0 findings" in capsys.readouterr().out

    def test_cli_exits_nonzero_on_seeded_violation(self, tmp_path,
                                                   capsys):
        bad = tmp_path / "seeded.py"
        bad.write_text(textwrap.dedent("""
            def poll(client):
                try:
                    return client.ask()
                except Exception:
                    return None
        """))
        rc = cli.main([
            str(bad), "--ast-only",
            "--baseline", str(tmp_path / "empty_baseline.json"),
        ])
        assert rc == 1
        assert "DLR002" in capsys.readouterr().out

    def test_write_baseline_guards_against_partial_regeneration(
            self, tmp_path, capsys):
        # any of: a rule subset, an explicit path subset, or --graph-only
        # would rewrite the full allowlist from partial findings
        some = str(tmp_path / "f.py")
        open(some, "w").write("x = 1\n")
        for argv in (
            ["--ast-only", "--write-baseline", "--rules", "DLR002"],
            ["--ast-only", "--write-baseline", some],
            ["--graph-only", "--write-baseline"],
        ):
            assert cli.main(argv) == 2, argv
        capsys.readouterr()

    def test_partial_scope_does_not_trip_the_stale_ratchet(self, capsys):
        # linting one subtree leaves the rest of the baseline unconsumed;
        # that must not read as "stale" (pre-submit single-file runs)
        rc = cli.main(["--ast-only", os.path.join(PKG_DIR, "trainer")])
        out = capsys.readouterr().out
        assert rc == 0 and "stale" not in out

    def test_rules_subset_skips_the_other_pass(self, capsys):
        # DLR-only rule selection must not compile the graph models:
        # against the checked-in baseline this is clean AND emits no
        # graph report lines
        rc = cli.main(["--rules", "DLR002"])
        out = capsys.readouterr().out
        assert rc == 0 and "graph " not in out

    def test_baseline_is_sorted_and_versioned(self):
        # a deterministic file keeps diffs reviewable
        import json

        with open(BASELINE) as fh:
            data = json.load(fh)
        keys = list(data["entries"])
        assert keys == sorted(keys)
        assert data["version"] == 1
