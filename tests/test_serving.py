"""The elastic serving tier (ISSUE 13): continuous-batching decode on
the training runtime.

Tier-1 core: router unit semantics (lease/complete/expiry,
conservation), KV-cache geometry + int8 storage + rule composition,
decode numerics (prefill+decode == the one-shot training forward —
EXACT for f32 pools on this backend; prefill_sequence bitwise),
checkpoint->serving promotion, the continuous-vs-static batching gate
(>= 1.3x tokens/sec on the tiny-model wedge), and THE acceptance
wedge: a real router + two serve workers over RPC, a live 8->4 resize
under in-flight traffic -> zero dropped requests, held leases
complete, unaffected continuations bitwise-identical, zero recompiles
on the prewarmed survivor topology. The full bench wedge and the
closed-loop serve replan ride slow-marked."""

import json
import math

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.models import llama
from dlrover_tpu.parallel import planner
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.serving.engine import ServeEngine, ServeExecutor
from dlrover_tpu.serving.kv_cache import (
    KVCacheSpec,
    init_kv_cache,
    kv_cache_rules,
    migrate_slots_host,
    resolve_kv_precision,
)
from dlrover_tpu.serving.router import RequestRouter
from dlrover_tpu.telemetry import EventKind, read_events, recent_events
from dlrover_tpu.telemetry.events import clear_ring


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


TINY = llama.llama_tiny()


@pytest.fixture(scope="module")
def tiny_params():
    return llama.init(jax.random.PRNGKey(0), TINY)


@pytest.fixture(scope="module")
def engine(tiny_params):
    eng = ServeEngine(
        TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                rule_set="llama"),
        serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
    )
    eng.prepare(tiny_params)
    return eng


def _prompt(n=6, seed=0):
    rng = np.random.RandomState(seed)
    return [int(t) for t in rng.randint(0, TINY.vocab_size, size=(n,))]


# -- the request router -------------------------------------------------------


class TestRequestRouter:
    def test_lease_complete_lifecycle_and_accounting(self):
        r = RequestRouter(lease_timeout_secs=120.0)
        rids = [r.submit([1, 2, 3], 4) for _ in range(3)]
        assert len(set(rids)) == 3
        leased = r.lease(node_id=0, max_requests=2)
        assert [q["request_id"] for q in leased] == rids[:2]
        assert r.complete(0, rids[0], [7, 8], ttft_s=0.1, e2e_s=0.2)
        rep = r.report()
        age = rep["requests"].pop("oldest_lease_age_s")
        assert age >= 0.0  # one lease still open
        assert rep["requests"] == {
            "queued": 1, "leased": 1, "done": 1, "submitted": 3,
            "completed": 1, "dropped": 0, "leases_expired": 0,
            "evicted": 0,
        }
        assert rep["latency"]["ttft_p50_s"] is not None
        assert rep["nodes"]["0"]["done"] == 1

    def test_resubmit_is_idempotent(self):
        r = RequestRouter()
        assert r.submit([1], 2, request_id="x") == "x"
        assert r.submit([9, 9], 5, request_id="x") == "x"
        assert r.report()["requests"]["submitted"] == 1

    def test_expired_lease_requeues_with_event_then_dedups_late_completion(
            self):
        clear_ring()
        r = RequestRouter(lease_timeout_secs=0.01)
        rid = r.submit([1, 2], 4)
        assert r.lease(0, 1)
        import time as _t

        _t.sleep(0.05)
        assert r.scan_expired_once() == [rid]
        evs = [e for e in recent_events()
               if e["kind"] == EventKind.SERVE_LEASE_EXPIRED]
        assert evs and evs[-1]["error_code"] == "SERVE_LEASE_EXPIRED"
        # the re-queued request leases to a LIVE worker...
        again = r.lease(1, 1)
        assert again and again[0]["request_id"] == rid
        # ...and the ORIGINAL worker's late completion is accepted
        # once, the twin's is a no-op: never a duplicate, never a drop
        assert r.complete(0, rid, [5])
        assert not r.complete(1, rid, [5])
        rep = r.report()["requests"]
        assert rep["completed"] == 1 and rep["dropped"] == 0
        assert rep["leases_expired"] == 1

    def test_completion_of_requeued_request_pulls_it_from_queue(self):
        r = RequestRouter(lease_timeout_secs=0.01)
        rid = r.submit([1], 4)
        r.lease(0, 1)
        import time as _t

        _t.sleep(0.05)
        r.scan_expired_once()
        # original worker finishes while the request sits re-queued
        assert r.complete(0, rid, [3])
        assert r.lease(1, 4) == []  # nothing left to hand out
        assert r.report()["requests"]["dropped"] == 0


# -- KV cache -----------------------------------------------------------------


class TestKVCache:
    def test_spec_geometry_page_aligned_and_one_byte_formula(self):
        spec = KVCacheSpec.from_model(TINY, num_slots=4, max_seq=30,
                                      page_size=8)
        assert spec.max_seq == 32  # rounded UP to whole pages
        assert spec.pages_per_slot == 4
        # bytes_per_slot and the planner's decode pricing share ONE
        # formula (kv_bytes_per_elem) — pinned so they cannot drift
        m = planner.model_spec_from_llama(TINY, global_batch=1)
        for precision in ("f32", "bf16", "int8"):
            s = KVCacheSpec.from_model(
                TINY, num_slots=4, max_seq=32, page_size=8,
                precision=precision)
            assert s.total_bytes() == pytest.approx(
                planner.serve_cache_bytes(m, 4, 32, precision))

    def test_int8_round_trip_bounded_by_block_scale(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(3, 2, 16).astype(np.float32))
        from dlrover_tpu.serving.kv_cache import decode_kv, encode_kv

        spec = KVCacheSpec.from_model(TINY, num_slots=1,
                                      precision="int8")
        v, s = encode_kv(x, spec)
        assert v.dtype == jnp.int8
        back = decode_kv(v, s, spec)
        # error bounded by half a quantization step of the BLOCK max
        err = np.abs(np.asarray(back) - np.asarray(x))
        assert err.max() <= float(np.abs(x).max()) / 127.0

    def test_precision_resolution_and_probe_fallback(self, monkeypatch):
        assert resolve_kv_precision("bf16") == "bf16"
        with pytest.raises(ValueError):
            resolve_kv_precision("fp4")
        import dlrover_tpu.serving.kv_cache as kvmod

        monkeypatch.setattr(kvmod, "int8_kv_supported", lambda: False)
        assert kvmod.resolve_kv_precision("int8") == "f32"

    def test_rules_compose_with_training_rules(self):
        rules = kv_cache_rules("llama")
        sizes = {"pipe": 1, "data": 2, "fsdp": 2, "seq": 1, "tensor": 2}
        # pool payload: slots on (data, fsdp), heads on tensor
        assert rules.spec_for("cache/k", (2, 4, 32, 2, 16), sizes) == \
            (None, ("data", "fsdp"), None, "tensor", None)
        assert rules.spec_for("cache/length", (4,), sizes) == \
            (("data", "fsdp"),)
        # params fall THROUGH to the unchanged training rules — what
        # makes promotion a pure device_put
        from dlrover_tpu.parallel.sharding_rules import llama_rules

        path = "params/layers/q_proj/kernel"
        shape = (2, 64, 64)
        assert rules.spec_for(path, shape, sizes) == \
            llama_rules().spec_for(path, shape, sizes)

    def test_migrate_slots_host_remaps_live_slots(self):
        spec4 = KVCacheSpec.from_model(TINY, num_slots=4, max_seq=16,
                                       page_size=8)
        spec2 = spec4.with_slots(2)
        host = {k: np.array(v)
                for k, v in init_kv_cache(spec4).items()}
        host["k"][:, 3] = 7.0
        host["length"][3] = 9
        out = migrate_slots_host(host, spec4, spec2, {3: 0})
        assert out["k"].shape[1] == 2
        assert (out["k"][:, 0] == 7.0).all()
        assert out["length"][0] == 9 and out["length"][1] == 0


# -- decode numerics ----------------------------------------------------------


class TestDecodeNumerics:
    def _reference(self, seq):
        logits, _aux = llama.apply(TINY, jnp.asarray(seq)[None], TINY) \
            if False else llama.apply(
                llama.init(jax.random.PRNGKey(0), TINY),
                jnp.asarray(seq)[None], TINY)
        return np.asarray(logits[0])

    def test_prefill_plus_decode_matches_one_shot_forward(
            self, tiny_params):
        """The decode-parity satellite: chunked prefill + teacher-
        forced single-token decode reproduces the one-shot training
        forward PER POSITION — exactly (f32 pool, this backend's
        kernels; the attention read mirrors mha_reference's f32
        logits/softmax conventions)."""
        p_len, new = 10, 5
        rng = np.random.RandomState(1)
        seq = rng.randint(0, TINY.vocab_size, size=(p_len + new,))
        ref, _ = llama.apply(tiny_params, jnp.asarray(seq)[None], TINY)
        ref = np.asarray(ref[0])
        spec = KVCacheSpec.from_model(TINY, num_slots=2, max_seq=32,
                                      page_size=8)
        cache = init_kv_cache(spec)
        c, start = 4, 0
        for i in range(math.ceil(p_len / c)):
            chunk = seq[:p_len][i * c:(i + 1) * c]
            padded = np.zeros((c,), np.int32)
            padded[:len(chunk)] = chunk
            cache, last = llama.prefill_chunk(
                tiny_params, cache, jnp.asarray(padded), jnp.int32(0),
                jnp.int32(start), jnp.int32(len(chunk)), TINY, spec)
            start += len(chunk)
        np.testing.assert_array_equal(np.asarray(last),
                                      ref[p_len - 1])
        active = jnp.asarray([True, False])
        dec = jax.jit(lambda cch, t: llama.decode_step(
            tiny_params, cch, t, active, TINY, spec))
        for j in range(new - 1):
            tokens = jnp.asarray([seq[p_len + j], 0], jnp.int32)
            _nt, logits, cache = dec(cache, tokens)
            np.testing.assert_array_equal(
                np.asarray(logits)[0], ref[p_len + j])

    def test_prefill_sequence_is_bitwise_the_training_forward(
            self, tiny_params):
        """``prefill_sequence`` routes the prompt through
        ``_attention_block`` itself (ring/flash included for big
        configs), so its last-token logits are BITWISE ``apply``'s —
        the first generated token of a promoted checkpoint is exactly
        what the trainer would predict."""
        seq = _prompt(9, seed=3)
        ref, _ = llama.apply(tiny_params, jnp.asarray(seq)[None], TINY)
        spec = KVCacheSpec.from_model(TINY, num_slots=2, max_seq=16,
                                      page_size=8)
        cache = init_kv_cache(spec)
        cache, last = llama.prefill_sequence(
            tiny_params, cache, jnp.asarray(seq), jnp.int32(1), TINY,
            spec)
        np.testing.assert_array_equal(np.asarray(last),
                                      np.asarray(ref[0, -1]))
        assert int(cache["length"][1]) == len(seq)

    def test_int8_pool_within_documented_tolerance(self, tiny_params):
        """int8 KV pages drift at the quantization level (the G109
        "kv" family ratchets the loss-level number; this pins the
        logit-level bound)."""
        p_len, new = 8, 4
        rng = np.random.RandomState(2)
        seq = rng.randint(0, TINY.vocab_size, size=(p_len + new,))
        ref, _ = llama.apply(tiny_params, jnp.asarray(seq)[None], TINY)
        ref = np.asarray(ref[0])
        spec = KVCacheSpec.from_model(TINY, num_slots=1, max_seq=16,
                                      page_size=8, precision="int8")
        cache = init_kv_cache(spec)
        cache, last = llama.prefill_chunk(
            tiny_params, cache, jnp.asarray(seq[:p_len], jnp.int32),
            jnp.int32(0), jnp.int32(0), jnp.int32(p_len), TINY, spec)
        worst = np.abs(np.asarray(last) - ref[p_len - 1]).max()
        active = jnp.asarray([True])
        for j in range(new - 1):
            tokens = jnp.asarray([seq[p_len + j]], jnp.int32)
            _nt, logits, cache = llama.decode_step(
                tiny_params, cache, tokens, active, TINY, spec)
            worst = max(worst, np.abs(
                np.asarray(logits)[0] - ref[p_len + j]).max())
        assert worst < 0.25, worst  # documented: ~6e-2 observed


# -- promotion ----------------------------------------------------------------


class TestPromotion:
    def _trained_state(self, steps=3, lr=1e-2):
        from dlrover_tpu.parallel.accelerate import TrainState

        loss_fn = llama.make_loss_fn(TINY)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, TINY.vocab_size, size=(2, 17))
        batch = {"input_ids": jnp.asarray(ids[:, :-1]),
                 "labels": jnp.asarray(ids[:, 1:])}
        opt = optax.sgd(lr)
        params = llama.init(jax.random.PRNGKey(0), TINY)
        opt_state = opt.init(params)
        grad = jax.jit(jax.grad(
            lambda p: loss_fn(p, batch, jax.random.PRNGKey(1))[0]))
        for _ in range(steps):
            g = grad(params)
            updates, opt_state = opt.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
        return TrainState(step=jnp.asarray(steps, jnp.int32),
                          params=params, opt_state=opt_state), opt

    def test_snapshot_and_checkpoint_promote_with_exact_logits(
            self, engine, tmp_path):
        """Train a few steps -> promote (live HostSnapshot AND a saved
        training checkpoint restored against the SERVING shardings) ->
        the served first-token logits are bitwise a fresh forward's on
        the trained weights: no cold start, no numerics gap."""
        from dlrover_tpu.checkpoint import (
            ElasticCheckpointManager,
            HostSnapshot,
        )

        state, opt = self._trained_state()
        seq = _prompt(7, seed=5)
        ref, _ = llama.apply(state.params, jnp.asarray(seq)[None], TINY)
        ref_last = np.asarray(ref[0, -1])

        # live trainer -> serving (train+serve colocation)
        snap = HostSnapshot.take(state)
        engine.load_from_snapshot(snap)
        cache = engine.fresh_cache()
        cache, last = llama.prefill_sequence(
            engine.params, cache, jnp.asarray(seq), jnp.int32(0), TINY,
            engine.program.spec)
        np.testing.assert_array_equal(np.asarray(last), ref_last)

        # training checkpoint -> serving (restore against the serving
        # shardings directly)
        mgr = ElasticCheckpointManager(str(tmp_path / "ckpt"))
        mgr.save(int(state.step), state, force=True)
        mgr.wait()
        mgr.close()
        engine.load_from_snapshot(
            HostSnapshot.take(jax.tree.map(np.zeros_like, state)))
        step = engine.load_from_checkpoint(
            str(tmp_path / "ckpt"),
            init_fn=llama.make_init_fn(TINY), optimizer=opt)
        assert step == int(state.step)
        cache = engine.fresh_cache()
        cache, last = llama.prefill_sequence(
            engine.params, cache, jnp.asarray(seq), jnp.int32(0), TINY,
            engine.program.spec)
        np.testing.assert_array_equal(np.asarray(last), ref_last)
        # leave the module engine with its canonical weights
        engine.load_from_snapshot(HostSnapshot.take(
            llama.init(jax.random.PRNGKey(0), TINY)))


# -- continuous batching ------------------------------------------------------


class TestContinuousBatching:
    def test_beats_static_batching_on_mixed_lengths(self, engine):
        """The tier-1 gate: admission churn (slot reuse as short
        requests finish) must buy >= 1.3x tokens/sec over static
        batching on the same mixed-length workload — and the whole
        paired run must not recompile anything."""
        import bench

        workload = bench._serve_workload(requests=16)
        bench._serve_leg(engine, "continuous",
                         bench._serve_workload(requests=2))
        bench._serve_leg(engine, "static",
                         bench._serve_workload(requests=2))
        compiles = engine.compile_count
        cache_size = engine.program.compiled_cache_size()
        static = bench._serve_leg(engine, "static", workload)
        cont = bench._serve_leg(engine, "continuous", workload)
        assert static["completed"] == cont["completed"] == 16
        ratio = cont["tokens_per_s"] / static["tokens_per_s"]
        step_ratio = static["decode_steps"] / cont["decode_steps"]
        assert step_ratio >= 1.3, (static, cont)
        assert ratio >= 1.3, (ratio, static, cont)
        assert engine.compile_count == compiles
        assert engine.program.compiled_cache_size() == cache_size

    def test_prefill_chunk_fits_the_pool_and_long_prompts_survive(
            self, engine, tiny_params):
        """Regression: a requested chunk whose padded write window
        could cross the pool end (T=48, chunk 32, a 40-token prompt —
        ``dynamic_update_slice`` would CLAMP the start and silently
        destroy earlier pages) is normalized to the largest divisor of
        the pool depth, and the long prompt decodes identically to a
        small-chunk serve (the module engine, chunk 8)."""
        from dlrover_tpu.serving.engine import _fit_prefill_chunk

        assert _fit_prefill_chunk(32, 48) == 24
        assert _fit_prefill_chunk(8, 48) == 8
        assert _fit_prefill_chunk(500, 48) == 48

        prompt = _prompt(40, seed=9)
        engine.cache = engine.fresh_cache()
        ref = ServeExecutor(engine, serve_window=1)
        ref.submit(prompt, max_new_tokens=4, request_id="long")
        expect = {r["request_id"]: r["tokens"] for r in ref.serve()}

        eng_big = ServeEngine(
            TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                    rule_set="llama"),
            serve_slots=2, prefill_chunk=32, max_seq=44, page_size=8)
        assert eng_big.prefill_chunk == 24  # normalized, pool depth 48
        eng_big.prepare(tiny_params)
        ex = ServeExecutor(eng_big, serve_window=1)
        ex.submit(prompt, max_new_tokens=4, request_id="long")
        got = {r["request_id"]: r["tokens"] for r in ex.serve()}
        assert got == expect
        assert len(got["long"]) == 4

    def test_oversized_request_evicts_with_error_code(self, engine):
        clear_ring()
        engine.cache = engine.fresh_cache()
        ex = ServeExecutor(engine, serve_window=1)
        ex.submit(_prompt(6), max_new_tokens=500, request_id="huge")
        ex.submit(_prompt(6, seed=7), max_new_tokens=3, request_id="ok")
        done = ex.serve()
        by = {r["request_id"]: r for r in done}
        assert by["huge"]["error_code"] == "SERVE_REQUEST_EVICTED"
        assert by["ok"]["error_code"] == ""
        assert len(by["ok"]["tokens"]) == 3
        evs = [e for e in recent_events()
               if e["kind"] == EventKind.SERVE_REQUEST_EVICTED]
        assert evs and evs[-1]["error_code"] == "SERVE_REQUEST_EVICTED"

    def test_retune_repacks_live_slots(self, engine, tiny_params):
        """An optimizer serve plan (slot-width change) applies at a
        drained boundary with live requests repacked host-side — no
        request lost, tokens unchanged."""
        engine.cache = engine.fresh_cache()
        baseline = ServeExecutor(engine, serve_window=1)
        for i in range(3):
            baseline.submit(_prompt(5, seed=10 + i), max_new_tokens=5,
                            request_id=f"b{i}")
        expect = {r["request_id"]: r["tokens"]
                  for r in baseline.serve()}
        engine.cache = engine.fresh_cache()
        ex = ServeExecutor(engine, serve_window=1)
        for i in range(3):
            ex.submit(_prompt(5, seed=10 + i), max_new_tokens=5,
                      request_id=f"b{i}")
        ex.serve(max_steps=2, until_idle=False)
        ex.request_retune(serve_slots=8)
        done = ex.serve()
        assert engine.program.spec.num_slots == 8
        got = {r["request_id"]: r["tokens"] for r in done}
        assert got == expect
        # restore the module engine's canonical knobs
        ex.request_retune(serve_slots=4)
        ex._drain_window()
        ex._apply_retune()
        assert engine.program.spec.num_slots == 4

    def test_chunk_only_retune_leaves_live_slots_in_place(self, engine):
        """A prefill_chunk-only plan swaps the program WITHOUT moving
        slots: the engine migrates no pages, so the executor must not
        compact its bookkeeping either — regression for the
        slot-map/page divergence that garbled every in-flight
        continuation."""
        engine.cache = engine.fresh_cache()
        baseline = ServeExecutor(engine, serve_window=1)
        for i in range(3):
            baseline.submit(_prompt(5, seed=30 + i), max_new_tokens=6,
                            request_id=f"c{i}")
        expect = {r["request_id"]: r["tokens"]
                  for r in baseline.serve()}
        engine.cache = engine.fresh_cache()
        ex = ServeExecutor(engine, serve_window=1)
        for i in range(3):
            ex.submit(_prompt(5, seed=30 + i), max_new_tokens=6,
                      request_id=f"c{i}")
        ex.serve(max_steps=2, until_idle=False)
        assert any(ex._active_host)
        ex.request_retune(prefill_chunk=4)
        done = ex.serve()
        assert engine.program.prefill_chunk == 4
        assert engine.program.spec.num_slots == 4  # unchanged
        got = {r["request_id"]: r["tokens"] for r in done}
        assert got == expect
        ex.request_retune(prefill_chunk=8)  # restore module knobs
        ex._drain_window()
        ex._apply_retune()
        assert engine.program.prefill_chunk == 8

    def test_chunk_retune_mid_prefill_restarts_the_prompt(self, engine):
        """Regression: a chunk change invalidates in-flight prefill
        cursors (old-chunk-multiple starts + a grown chunk = the
        window-clamp hazard) — those prompts restart from 0 and still
        decode correctly."""
        engine.cache = engine.fresh_cache()
        baseline = ServeExecutor(engine, serve_window=1)
        baseline.submit(_prompt(20, seed=33), max_new_tokens=4,
                        request_id="mid")
        expect = {r["request_id"]: r["tokens"]
                  for r in baseline.serve()}
        engine.cache = engine.fresh_cache()
        ex = ServeExecutor(engine, serve_window=1)
        ex.submit(_prompt(20, seed=33), max_new_tokens=4,
                  request_id="mid")
        ex._ensure_prepared()
        ex._admit()
        ex._prefill_tick()  # one 8-token chunk in: cursor=8, inactive
        state = next(s for s in ex._slots if s is not None)
        assert 0 < state.cursor < len(state.prompt)
        ex.request_retune(prefill_chunk=16)
        ex._apply_retune()
        assert state.cursor == 0  # restarted under the new chunk
        got = {r["request_id"]: r["tokens"] for r in ex.serve()}
        assert got == expect
        ex.request_retune(prefill_chunk=8)  # restore module knobs
        ex._apply_retune()

    def test_unachievable_chunk_plan_negative_acks(self, engine):
        """A plan whose chunk does not divide the pool depth (48) is
        negative-acked BEFORE any state change — the PR 11 phantom-
        apply guard — and the optimizer never enumerates such chunks
        in the first place."""
        class AckSpy:
            acks = []

            def report_serve_config(self, **kw):
                self.acks.append(kw)

            def get_parallel_config(self):  # plan-poll interface
                return comm.ParallelConfig()

        engine.cache = engine.fresh_cache()
        spy = AckSpy()
        ex = ServeExecutor(engine, router_client=spy,
                           serve_window=1, plan_poll_secs=0)
        ex._ensure_prepared()
        before = engine.prefill_chunk
        ex.request_retune(prefill_chunk=9, plan_id="bad-chunk")
        ex._apply_retune()
        assert engine.prefill_chunk == before  # nothing applied
        nack = [a for a in spy.acks if a.get("plan_id") == "bad-chunk"]
        assert nack and nack[-1]["apply_failed"] is True
        # master side: candidates are divisor-only
        opt = _optimizer()
        opts = opt._serve_candidates({
            "serve_slots": 4, "prefill_chunk": 8, "max_seq": 48,
            "kv_precision": "f32", "world": 8, "node_id": 0})
        assert all(48 % c["prefill_chunk"] == 0 for c in opts)


# -- THE acceptance wedge -----------------------------------------------------


class TestServeResizeWedge:
    def test_live_resize_under_traffic_zero_drops_bitwise_continuations(
            self, tmp_path, monkeypatch):
        """Real router + two serve workers over RPC; worker 0 resizes
        8 -> 4 LIVE with leased requests mid-decode. Pinned: zero
        dropped requests, zero expired leases (held, not dropped),
        every request completes, continuations bitwise-identical to a
        resize-free serve of the same workload, zero recompiles on the
        prewarmed survivor topology, and the mttr/goodput derivations
        see the serving_resize scenario. The workers run the PREFIX
        POOL (shared 16-token head across the workload) against a
        pool-FREE baseline — the bitwise gate then also pins reuse ==
        full prefill across the live resize, and the prefix columns
        must agree live-vs-forensic."""
        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        shared = _prompt(16, seed=7)
        prompts = {f"r{i}": shared + _prompt(4, seed=20 + i)
                   for i in range(10)}

        def build_worker(pool_pages=8):
            eng = ServeEngine(
                TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                        rule_set="llama"),
                serve_slots=4, prefill_chunk=4, max_seq=32,
                page_size=8, prefix_pool_pages=pool_pages,
            )
            eng.prepare(llama.init(jax.random.PRNGKey(0), TINY))
            return eng

        # resize-free pool-FREE baseline (local queue): ground truth
        base_eng = build_worker(pool_pages=0)
        base = ServeExecutor(base_eng, serve_window=1)
        for rid, p in prompts.items():
            base.submit(p, max_new_tokens=6, request_id=rid)
        expect = {r["request_id"]: r["tokens"] for r in base.serve()}

        master = start_local_master()
        try:
            sub = MasterClient(master.addr, node_id=99)
            for rid, p in prompts.items():
                assert sub.submit_serve_request(
                    p, max_new_tokens=6, request_id=rid) == rid

            eng_a = build_worker()
            worker_a = ServeExecutor(
                eng_a, router_client=MasterClient(master.addr,
                                                  node_id=0),
                serve_window=1, plan_poll_secs=0)
            eng_b = build_worker()
            worker_b = ServeExecutor(
                eng_b, router_client=MasterClient(master.addr,
                                                  node_id=1),
                serve_window=1, plan_poll_secs=0)

            # worker 0 leases a slot-batch and decodes PARTWAY —
            # in-flight traffic
            worker_a.serve(max_steps=3, until_idle=False)
            assert any(worker_a._active_host), "no in-flight traffic"
            # worker 1 serves a share of the queue over the same RPC
            # router (>= 2 real workers)
            worker_b.serve()
            assert worker_b.completed

            # live 8 -> 4 on the prewarmed survivor topology, leases
            # held across it
            survivors = jax.devices()[:4]
            eng_a.prewarm(devices=survivors)
            compiles = eng_a.compile_count
            worker_a.request_resize(survivors)
            worker_a.serve()
            assert eng_a.compile_count == compiles, \
                "resize recompiled on a prewarmed survivor topology"
            assert eng_a.program.mesh.devices.size == 4

            report = sub.get_serve_report()
            req = report["requests"]
            assert req["submitted"] == 10
            assert req["completed"] == 10, report
            assert req["dropped"] == 0
            assert req["leases_expired"] == 0  # held, never re-leased
            assert req["queued"] == 0 and req["leased"] == 0

            # continuations bitwise-identical to the resize-free serve
            got = {r["request_id"]: r["tokens"]
                   for r in worker_a.completed + worker_b.completed}
            assert set(got) == set(expect)
            for rid in expect:
                assert got[rid] == expect[rid], rid

            # both workers' rows in the ledger
            assert set(report["nodes"]) == {"0", "1"}

            # the CLI views agree (live vs forensic)
            import io
            import sys as _sys

            from dlrover_tpu.trainer.run import main as tpurun

            buf, prev = io.StringIO(), _sys.stdout
            _sys.stdout = buf
            try:
                rc = tpurun(["requests", "--addr", master.addr,
                             "--json"])
            finally:
                _sys.stdout = prev
            assert rc == 0
            live = json.loads(buf.getvalue())
            assert live["requests"]["completed"] == 10

            # the prefix columns: the shared head hits once each
            # worker's first completion publishes it, and the hit
            # totals survive the live resize
            live_prefix = live.get("prefix") or {}
            assert live_prefix.get("hits", 0) >= 1, live
            assert live_prefix["saved_prefill_tokens"] \
                == 16 * live_prefix["hits"]

            records = read_events(events_path)
            begun = [r for r in records
                     if r["kind"] == EventKind.SERVE_RESIZE_BEGIN]
            done_ev = [r for r in records
                       if r["kind"] == EventKind.SERVE_RESIZE_DONE]
            assert begun and done_ev
            assert done_ev[-1]["world_from"] == 8
            assert done_ev[-1]["world_to"] == 4
            assert done_ev[-1]["recompiled"] == 0

            buf, prev = io.StringIO(), _sys.stdout
            _sys.stdout = buf
            try:
                rc = tpurun(["requests", "--events", events_path,
                             "--json"])
            finally:
                _sys.stdout = prev
            assert rc == 0
            forensic = json.loads(buf.getvalue())
            assert forensic["resizes"][-1]["world_to"] == 4
            assert forensic["leases_expired"] == 0
            # live-vs-forensic agreement extends to the prefix
            # columns: router-ledger hits == worker HIT edges
            assert forensic["prefix"]["hits"] == live_prefix["hits"]
            assert forensic["prefix"]["saved_prefill_tokens"] \
                == live_prefix["saved_prefill_tokens"]

            # mttr derives the serving_resize scenario from the same
            # timeline; goodput books it as reshard-class downtime
            from dlrover_tpu.telemetry.goodput import derive_goodput
            from dlrover_tpu.telemetry.mttr import derive_incidents

            incidents = [i for i in derive_incidents(records)
                         if i["scenario"] == "serving_resize"]
            assert incidents
            assert incidents[-1]["recovery_seconds"] is not None
            ledger = derive_goodput(records)
            buckets = ledger["detail"]["buckets"]
            assert buckets.get("reshard", {}).get("seconds", 0.0) >= 0.0
        finally:
            master.stop()


# -- the serve knob family (runtime optimizer) --------------------------------


def _serve_report(**kw):
    base = dict(node_id=0, world=8, serve_slots=4, prefill_chunk=8,
                kv_precision="f32", max_seq=64)
    base.update(kw)
    return comm.ServeConfigReport(**base)


def _optimizer(publish=None):
    from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
    from dlrover_tpu.master.optimizer import RuntimeOptimizer

    return RuntimeOptimizer(NodeRuntimeStore(), publish=publish,
                            cooldown_secs=0.0)

class TestServeKnobFamily:
    def test_serve_config_triggers_replan_and_publishes_sentinels(self):
        published = []
        opt = _optimizer(publish=published.append)
        opt.update_model_info(comm.ModelInfo(
            num_params=10_000, hidden_size=64, num_layers=2,
            seq_len=128))
        opt.update_serving_config(_serve_report())
        serve_dec = [d for d in opt.decisions()
                     if d["trigger"].startswith("serve:")]
        assert serve_dec, opt.decisions()
        last = serve_dec[-1]
        assert last["outcome"] == "chosen"
        assert published
        cfg = published[-1]
        # more slots amortize the weight read: slots grow, chunk is a
        # tie broken toward NO change (sentinel 0)
        assert cfg.serve_slots > 4
        assert cfg.serve_prefill_chunk == 0
        assert cfg.plan_id == last["plan_id"]

    def test_hbm_gate_refuses_pools_that_cannot_fit(self, monkeypatch):
        monkeypatch.setattr(get_context(), "device_hbm_budget_bytes",
                            1.0)
        opt = _optimizer()
        opt.update_serving_config(_serve_report())
        last = [d for d in opt.decisions()
                if d["trigger"].startswith("serve:")][-1]
        assert last["outcome"] == "rejected"
        assert last["reason"] == "serve:no_feasible_candidate"
        assert last["memory_rejected"]
        worst = last["memory_rejected"][0]
        assert worst["predicted_hbm_bytes"] > worst["budget_bytes"]

    def test_failed_apply_blacklists_the_serve_knob_tuple(self):
        published = []
        opt = _optimizer(publish=published.append)
        opt.update_model_info(comm.ModelInfo(
            num_params=10_000, hidden_size=64, num_layers=2,
            seq_len=128))
        opt.update_serving_config(_serve_report())
        plan_id = published[-1].plan_id
        chosen_key = [d for d in opt.decisions()
                      if d.get("plan_id") == plan_id][-1]["chosen"]["key"]
        # negative ack: worker could not apply (e.g. live > new slots)
        opt.update_serving_config(_serve_report(
            plan_id=plan_id, apply_failed=True))
        assert chosen_key in opt._failed_keys
        # the same tuple is never re-chosen
        opt.replan_serving("again")
        latest = [d for d in opt.decisions()
                  if d["trigger"].startswith("serve:")][-1]
        assert (latest.get("chosen") or {}).get("key") != chosen_key

    def test_stale_laggard_report_neither_rewinds_nor_replans(self):
        """Two serve workers around an 8->4 resize: the survivor's
        world=4 report retriggers planning, but a laggard peer's
        queued PRE-resize report (world=8, no per-node change) must
        neither rewind the serving view to the dead world nor fire a
        replan priced for it — the update_running_config discipline."""
        opt = _optimizer()
        opt.update_serving_config(_serve_report(node_id=0, world=8))
        opt.update_serving_config(_serve_report(node_id=1, world=8))
        # node 0 resized: per-node change -> adopted
        opt.update_serving_config(_serve_report(node_id=0, world=4))
        assert opt.serving_config()["world"] == 4
        n = len(opt.decisions())
        # node 1's stale queued report: same world it last reported,
        # a minority view of a dead world — ignored entirely
        opt.update_serving_config(_serve_report(node_id=1, world=8))
        assert opt.serving_config()["world"] == 4
        assert len(opt.decisions()) == n

    def test_ack_marks_decision_applied_without_replan_chase(self):
        published = []
        opt = _optimizer(publish=published.append)
        opt.update_model_info(comm.ModelInfo(
            num_params=10_000, hidden_size=64, num_layers=2,
            seq_len=128))
        opt.update_serving_config(_serve_report())
        n_before = len(opt.decisions())
        plan = published[-1]
        # the worker applies and acks with its NEW config: the echo
        # must not trigger another serve replan (tail chasing)
        opt.update_serving_config(_serve_report(
            serve_slots=plan.serve_slots or 4,
            plan_id=plan.plan_id))
        assert len(opt.decisions()) == n_before
        applied = [d for d in opt.decisions()
                   if d.get("plan_id") == plan.plan_id][-1]
        assert applied["applied"] is True


class TestKvDriftFamily:
    @pytest.mark.slow  # the clean judgement ALSO runs tier-1 inside
    # test_lint_clean's full tpulint pass (which executes the kv
    # probe); this standalone copy rides slow
    def test_clean_against_the_committed_ratchet(self):
        """The G109 "kv" family: the teacher-forced prefill+decode
        probe reproduces the committed baseline (fire/clean judged
        like every other family)."""
        from dlrover_tpu.analysis import graph_lint

        report = graph_lint.quantization_drift_audit(
            family="kv", precision="int8")
        assert not report.findings, [f.message for f in report.findings]

    def test_fires_when_drift_regresses_past_the_ratchet(
            self, tmp_path, monkeypatch):
        from dlrover_tpu.analysis import graph_lint

        label = "llama_tiny[kv,int8]@cpu"
        baseline = tmp_path / "quant_baseline.json"
        baseline.write_text(json.dumps(
            {"version": 1, "entries": {label: {"drift": 1e-6}}}))
        monkeypatch.setattr(
            graph_lint, "measure_quantization_drift",
            lambda *a, **k: (1.0e-3, label))
        report = graph_lint.quantization_drift_audit(
            family="kv", precision="int8",
            baseline_path=str(baseline))
        assert report.findings
        assert report.findings[0].rule_id == "G109"


class TestPlannerDecodeTerm:
    def test_tokens_per_s_monotone_in_slots(self):
        m = planner.model_spec_from_llama(TINY, global_batch=1)
        prev = 0.0
        for slots in (1, 2, 4, 8, 16):
            est = planner.estimate_decode(m, 8, slots, 8, 64)
            assert est["tokens_per_s"] > prev
            prev = est["tokens_per_s"]

    def test_kv_precision_orders_bytes_and_step_time(self):
        m = planner.model_spec_from_llama(TINY, global_batch=1)
        by = {p: planner.estimate_decode(m, 8, 8, 8, 64, p)
              for p in ("f32", "bf16", "int8")}
        assert by["int8"]["cache_bytes"] < by["bf16"]["cache_bytes"] \
            < by["f32"]["cache_bytes"]
        assert by["int8"]["breakdown"]["kv_read_s"] \
            < by["f32"]["breakdown"]["kv_read_s"]

    def test_step_floors_at_host_dispatch(self):
        m = planner.model_spec_from_llama(TINY, global_batch=1)
        est = planner.estimate_decode(m, 8, 4, 8, 64)
        assert est["step_s"] >= planner.HOST_DISPATCH_OVERHEAD_S
        for key in ("kv_read_s", "weight_read_s", "flops_s",
                    "dispatch_s", "prefill_amort_s"):
            assert key in est["breakdown"]


# -- slow: the full bench wedge + the closed loop over RPC --------------------


@pytest.mark.slow
class TestServeBenchWedge:
    def test_bench_serve_mode_writes_r12_and_passes_gates(
            self, tmp_path, monkeypatch):
        import bench

        artifact = tmp_path / "BENCH_r12.json"
        monkeypatch.setenv("BENCH_SERVE_ARTIFACT", str(artifact))
        result = bench.serve_result()
        assert "error" not in result, result
        assert result["tokens_per_s_ratio_median"] >= 1.3
        assert result["resize"]["dropped"] == 0
        assert result["resize"]["recompiled"] == 0
        assert result["zero_recompiles_in_timed_legs"]


@pytest.mark.slow
class TestServeReplanE2E:
    def test_closed_loop_retunes_serve_slots_live(self, tiny_params):
        """Serve config report -> optimizer prices the decode term ->
        publishes a serve plan -> the worker polls, retunes through
        the prewarmed program cache, and acks — the serving twin of
        the PR 7 replan wedge, over real RPC."""
        master = start_local_master()
        try:
            sub = MasterClient(master.addr, node_id=99)
            sub.report_model_info(comm.ModelInfo(
                num_params=100_000, hidden_size=64, num_layers=2,
                seq_len=128))
            for i in range(12):
                sub.submit_serve_request(_prompt(5, seed=40 + i),
                                         max_new_tokens=6,
                                         request_id=f"e{i}")
            eng = ServeEngine(
                TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                        rule_set="llama"),
                serve_slots=4, prefill_chunk=4, max_seq=32,
                page_size=8)
            eng.prepare(tiny_params)
            ex = ServeExecutor(
                eng, router_client=MasterClient(master.addr,
                                                node_id=0),
                serve_window=1, plan_poll_secs=0.01)
            done = ex.serve()
            assert len(done) == 12
            # the optimizer chose a wider slot batch and the worker
            # applied it live, acking the plan
            assert eng.program.spec.num_slots > 4
            serve_dec = [
                d for d in master.servicer.runtime_optimizer.decisions()
                if d["trigger"].startswith("serve:")
                and d["outcome"] == "chosen"]
            assert serve_dec and serve_dec[0]["applied"]
        finally:
            master.stop()

# -- the shared prefix pool (ISSUE 16) ----------------------------------------


from dlrover_tpu.serving.prefix_index import PrefixIndex  # noqa: E402


class TestPrefixIndex:
    """Host-side radix-index semantics: exact-token matching, LRU
    eviction that never touches a pinned chain, full-pool degradation
    to miss-and-prefill, idempotent release across flush."""

    def test_match_is_exact_tokens_and_page_grain(self):
        ix = PrefixIndex(page_size=4, num_pages=8)
        ix.publish(list(range(12)))  # 3 pages
        assert ix.used_pages == 3
        # full-page exact match only: 11 tokens -> 2 whole pages
        h = ix.match(list(range(11)))
        assert h.tokens == 8 and len(h.pages) == 2
        ix.release(h)
        # one differing token inside the first page -> no hash
        # shortcut, the walk misses at the literal comparison
        assert ix.match([0, 1, 2, 99, 4, 5, 6, 7]) is None
        assert ix.misses == 1

    def test_match_caps_and_aligns_before_pinning(self):
        ix = PrefixIndex(page_size=4, num_pages=8)
        ix.publish(list(range(16)))  # 4 pages
        h = ix.match(list(range(16)), max_pages=3, align_pages=2)
        # capped to 3 then aligned DOWN to 2 pages; only those pinned
        assert len(h.pages) == 2
        assert all(n.refcount == 1 for n in h.nodes)
        unpinned = ix.match(list(range(16)))  # pins all 4
        assert [n.refcount for n in unpinned.nodes] == [2, 2, 1, 1]
        ix.release(h)
        ix.release(unpinned)

    def test_pinned_chains_never_evicted_lru_picks_oldest(self):
        ix = PrefixIndex(page_size=2, num_pages=2)
        ix.publish([1, 1])
        ix.publish([2, 2])
        pin = ix.match([1, 1])  # pins page for [1,1]
        # pool full; publishing a third chunk must evict [2,2] (the
        # only refcount-0 leaf), never the pinned [1,1]
        out = ix.publish([3, 3])
        assert len(out) == 1
        assert ix.evictions == 1
        assert ix.match([2, 2]) is None  # evicted -> exact miss
        got = ix.match([1, 1])
        assert got is not None  # pinned chain survived
        ix.release(pin)
        ix.release(got)

    def test_evicted_page_reuse_cannot_stale_match(self):
        """The page id freed by eviction is re-published under NEW
        tokens; a request for the OLD tokens misses (trie removal
        precedes reuse) and re-verifies by publishing afresh."""
        ix = PrefixIndex(page_size=2, num_pages=1)
        ix.publish([7, 7])
        assert ix.publish([8, 8])  # evicts [7,7], reuses its page
        assert ix.match([7, 7]) is None  # never a stale hit
        again = ix.publish([7, 7])  # the next miss re-publishes
        assert len(again) == 1
        assert ix.match([8, 8]) is None  # and [8,8] was the victim

    def test_full_pool_of_pinned_pages_degrades_never_raises(self):
        ix = PrefixIndex(page_size=2, num_pages=2)
        ix.publish([1, 1, 2, 2])
        pin = ix.match([1, 1, 2, 2])
        # every page pinned: publish skips, counted, no exception
        assert ix.publish([3, 3, 4, 4]) == []
        assert ix.publish_skipped == 1
        ix.release(pin)

    def test_interior_node_with_children_is_not_a_victim(self):
        ix = PrefixIndex(page_size=2, num_pages=2)
        ix.publish([1, 1, 2, 2])  # chain: [1,1] -> [2,2]
        # only the CHILDLESS tail is evictable — evicting the parent
        # would orphan the child and break "whole chain present"
        out = ix.publish([3, 3])
        assert len(out) == 1
        assert ix.match([1, 1]) is not None  # parent survived

    def test_release_is_idempotent_and_survives_flush(self):
        ix = PrefixIndex(page_size=2, num_pages=4)
        ix.publish([1, 1])
        h = ix.match([1, 1])
        ix.flush()
        assert ix.used_pages == 0
        ix.publish([9, 9])
        fresh = ix.match([9, 9])
        ix.release(h)  # orphaned nodes absorb it
        ix.release(h)  # idempotent
        assert fresh.nodes[0].refcount == 1  # fresh pin untouched
        ix.release(fresh)
        # stats survive the flush (they describe the process)
        assert ix.hits == 2 and ix.published == 2


@pytest.fixture(scope="module")
def prefix_engine(tiny_params):
    eng = ServeEngine(
        TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                rule_set="llama"),
        serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
        prefix_pool_pages=12,
    )
    eng.prepare(tiny_params)
    return eng


def _serve_locally(eng, jobs, tag):
    """Serve ``jobs`` ([(rid, prompt, max_new)]) on a fresh slot pool
    (the prefix pool is NOT reset — legs seed it deliberately)."""
    eng.cache = eng.fresh_cache()
    ex = ServeExecutor(eng, serve_window=1)
    for rid, prompt, max_new in jobs:
        ex.submit(prompt, max_new_tokens=max_new,
                  request_id=f"{tag}-{rid}")
    return {r["request_id"].split("-", 1)[1]: r for r in ex.serve()}


class TestPrefixReuseBitwise:
    """THE tentpole oracle: a prefix-reused continuation is BITWISE
    equal to the full prefill on the f32 pool, at every hit-length
    class — 0, partial-chunk, chunk-exact, and full-prompt (capped
    strictly below the prompt so the final chunk still seeds the
    first token)."""

    def test_bitwise_at_every_hit_length(self, engine, prefix_engine,
                                         tiny_params):
        seed_prompt = _prompt(40, seed=77)
        # hit-length cases against a pool seeded with seed_prompt:
        #  q0:  shares <1 page            -> hit 0
        #  q16: shares 20 tokens          -> partial page rounds DOWN to 16
        #  q24: shares 24 (3 exact pages) -> hit 24
        #  qfp: the seed prompt itself    -> hit 32 (cap < len(prompt))
        cases = {
            "q0": (seed_prompt[:4] + _prompt(8, seed=78), 0),
            "q16": (seed_prompt[:20] + _prompt(4, seed=79), 16),
            "q24": (seed_prompt[:24] + _prompt(8, seed=80), 24),
            "qfp": (list(seed_prompt), 32),
        }
        # seed the pool (published at the final prefill chunk)
        _serve_locally(prefix_engine, [("seed", seed_prompt, 2)], "s")
        assert prefix_engine.prefix_index.used_pages == 5

        jobs = [(rid, p, 4) for rid, (p, _) in cases.items()]
        on = _serve_locally(prefix_engine, jobs, "on")
        off = _serve_locally(engine, jobs, "off")
        for rid, (_, want_hit) in cases.items():
            assert on[rid]["prefix_hit_tokens"] == want_hit, rid
            assert on[rid]["tokens"] == off[rid]["tokens"], rid
        assert all(off[r]["prefix_hit_tokens"] == 0 for r in off)

    def test_int8_pool_reuse_token_identical_admission(self,
                                                      tiny_params):
        """int8 pools: the pool stores the QUANTIZED page bytes +
        scales the publishing slot computed, and admission copies them
        back verbatim — so the reused continuation sees bit-identical
        cache state to a same-engine full prefill. (Cross-engine
        logits may differ at quantization boundaries; the documented
        int8 caveat in docs/serving.md. Here both legs run one
        engine.)"""
        eng = ServeEngine(
            TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                    rule_set="llama"),
            serve_slots=2, prefill_chunk=8, max_seq=48, page_size=8,
            kv_precision="int8", prefix_pool_pages=8,
        )
        eng.prepare(tiny_params)
        seed_prompt = _prompt(32, seed=81)
        # leg 1: pool empty -> full prefill (and it publishes)
        first = _serve_locally(eng, [("a", seed_prompt, 4)], "l1")
        assert first["a"]["prefix_hit_tokens"] == 0
        # leg 2: same prompt -> 24-token hit, quantized pages copied
        second = _serve_locally(eng, [("a", seed_prompt, 4)], "l2")
        assert second["a"]["prefix_hit_tokens"] == 24
        assert second["a"]["tokens"] == first["a"]["tokens"]


class TestPrefixPoolLifecycle:
    """Retune/resize discipline: slot-only retunes carry the pool,
    chunk changes flush the index (page bytes depend on the chunk
    windows), pool-width changes rebuild, and eviction pressure under
    a tiny pool stays a logged degradation."""

    def test_retune_carry_flush_rebuild(self, tiny_params):
        eng = ServeEngine(
            TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                    rule_set="llama"),
            serve_slots=4, prefill_chunk=8, max_seq=48, page_size=8,
            prefix_pool_pages=8,
        )
        eng.prepare(tiny_params)
        p = _prompt(24, seed=90)
        _serve_locally(eng, [("seed", p, 2)], "s")
        assert eng.prefix_index.used_pages == 3

        # slot-only retune: pool and index carry (no slot dimension)
        eng.retune(serve_slots=6, slot_map={})
        got, h = eng.prefix_match(p + _prompt(8, seed=91))
        assert got == 24 and h is not None
        eng.prefix_release(h)

        # chunk change: index flushed (stats survive), pool pages
        # unreachable; a released pre-flush handle dangles nothing
        hits_before = eng.prefix_index.hits
        eng.retune(prefill_chunk=4)
        assert eng.prefix_index.used_pages == 0
        assert eng.prefix_index.hits == hits_before
        eng.prefix_release(h)  # idempotent, post-flush

        # pool-width change: rebuilt empty at the new capacity
        eng.retune(prefix_pool_pages=4)
        assert eng.prefix_index.capacity == 4
        assert eng.prefix_index.used_pages == 0
        # pool off: the engine reports disabled and matches miss
        eng.retune(prefix_pool_pages=0)
        assert not eng.prefix_enabled()
        assert eng.prefix_match(p) == (0, None)

    def test_eviction_pressure_end_to_end(self, tiny_params):
        """A pool smaller than the working set: victims are LRU,
        every re-use after eviction is a clean miss-and-prefill, and
        completions stay bitwise against a pool-free engine."""
        eng = ServeEngine(
            TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                    rule_set="llama"),
            serve_slots=2, prefill_chunk=8, max_seq=48, page_size=8,
            prefix_pool_pages=3,
        )
        eng.prepare(tiny_params)
        off = ServeEngine(
            TINY, strategy=Strategy(mesh=MeshPlan(data=-1),
                                    rule_set="llama"),
            serve_slots=2, prefill_chunk=8, max_seq=48, page_size=8,
        )
        off.prepare(tiny_params)
        # three distinct 24-token prompts = 9 pages over a 3-page pool
        prompts = {f"p{i}": _prompt(24, seed=95 + i) for i in range(3)}
        jobs = [(rid, p, 3) for rid, p in prompts.items()]
        a = _serve_locally(eng, jobs, "w1")
        b = _serve_locally(eng, jobs, "w2")
        want = _serve_locally(off, jobs, "off")
        for rid in prompts:
            assert a[rid]["tokens"] == want[rid]["tokens"], rid
            assert b[rid]["tokens"] == want[rid]["tokens"], rid
        st = eng.prefix_stats()
        assert st["evictions"] > 0
        assert st["used_pages"] <= 3


class TestPrefixRouterAffinity:
    def test_soft_affinity_homes_without_starvation(self):
        r = RequestRouter(lease_timeout_secs=120.0)
        shared = list(range(100, 116))  # >= the 16-token prefix key
        a_ids = [r.submit(shared + [i], 4, request_id=f"a{i}")
                 for i in range(4)]
        b_ids = [r.submit(list(range(200, 216)) + [i], 4,
                          request_id=f"b{i}") for i in range(2)]
        # node 0 leases first: claims the shared-prefix home
        first = [q["request_id"] for q in r.lease(0, 2)]
        assert first == a_ids[:2]
        # node 1: pass 1 skips node-0-homed requests, claims the B
        # prefix; pass 2 fills spare capacity FIFO (no starvation)
        second = [q["request_id"] for q in r.lease(1, 3)]
        assert second[:2] == b_ids
        assert second[2] == a_ids[2]  # capacity steal, FIFO
        # node 0 returns: the remaining A request is homed here
        third = [q["request_id"] for q in r.lease(0, 4)]
        assert third == [a_ids[3]]
        summary = r.prefix_summary()
        assert summary["affinity_routed"] >= 1
        # hit accounting rides complete(); conservation holds
        for n, rid in [(0, a_ids[0]), (0, a_ids[1]), (1, b_ids[0]),
                       (1, b_ids[1]), (1, a_ids[2]), (0, a_ids[3])]:
            r.complete(n, rid, [1, 2], ttft_s=0.1, e2e_s=0.2,
                       prefix_hit_tokens=16 if rid[0] == "a" else 0)
        summary = r.prefix_summary()
        assert summary["hits"] == 4
        assert summary["saved_prefill_tokens"] == 64
        assert summary["hit_rate"] == pytest.approx(4 / 6, abs=1e-3)
        rep = r.report()["requests"]
        assert rep["completed"] == 6 and rep["leased"] == 0

    def test_affinity_disabled_keeps_pure_fifo(self, monkeypatch):
        monkeypatch.setattr(get_context(), "serve_prefix_affinity",
                            False)
        r = RequestRouter()
        shared = list(range(16))
        rids = [r.submit(shared + [i], 2) for i in range(3)]
        assert [q["request_id"] for q in r.lease(1, 1)] == rids[:1]
        assert [q["request_id"] for q in r.lease(0, 2)] == rids[1:]


class TestPrefixPlannerPricing:
    SPEC = planner.ModelSpec(
        param_count=7e9, num_layers=8, hidden_size=64, seq_len=128,
        global_batch=1, num_heads=4, kv_heads=2)

    def test_hit_rate_discount_raises_tokens_per_s(self):
        off = planner.estimate_decode(self.SPEC, 8, 16, 8, 64)
        on = planner.estimate_decode(
            self.SPEC, 8, 16, 8, 64, prefix_pool_pages=16,
            page_size=8, prefix_hit_rate=0.8)
        assert on["tokens_per_s"] > off["tokens_per_s"]
        assert on["breakdown"]["prefix_hit_rate"] == 0.8
        # zero observed/expected hits -> the pool is pure cost, the
        # throughput term must NOT move (the optimizer's churn
        # tie-break then keeps the knob off)
        cold = planner.estimate_decode(
            self.SPEC, 8, 16, 8, 64, prefix_pool_pages=16,
            page_size=8, prefix_hit_rate=0.0)
        assert cold["tokens_per_s"] == off["tokens_per_s"]

    def test_discount_capped_by_pool_token_coverage(self):
        small = planner.estimate_decode(
            self.SPEC, 8, 16, 8, 64, prefix_pool_pages=1,
            page_size=8, prefix_hit_rate=1.0)
        big = planner.estimate_decode(
            self.SPEC, 8, 16, 8, 64, prefix_pool_pages=16,
            page_size=8, prefix_hit_rate=1.0)
        assert big["tokens_per_s"] > small["tokens_per_s"]

    def test_pool_bytes_charged_undivided_per_device(self):
        est = planner.estimate_decode(
            self.SPEC, 8, 16, 8, 64, prefix_pool_pages=16,
            page_size=8, prefix_hit_rate=0.5)
        pool = planner.serve_prefix_pool_bytes(self.SPEC, 16, 8)
        assert pool > 0
        assert est["breakdown"]["prefix_pool_bytes"] == pool
        assert est["cache_bytes_per_device"] == pytest.approx(
            est["cache_bytes"] / 8 + pool)
        # the same byte formula as the device-side spec
        spec = KVCacheSpec(num_layers=8, num_kv_heads=2, head_dim=16,
                           num_slots=16, page_size=8,
                           prefix_pool_pages=16)
        assert pool == spec.prefix_pool_bytes()


class TestPrefixKnobFamily:
    def test_optimizer_chooses_pool_with_prior_and_geometry(
            self, monkeypatch):
        monkeypatch.setattr(get_context(),
                            "serve_prefix_expected_hit_rate", 0.8)
        published = []
        opt = _optimizer(publish=published.append)
        opt.update_model_info(comm.ModelInfo(
            num_params=7_000_000_000, hidden_size=64, num_layers=2,
            seq_len=128))
        opt.update_serving_config(_serve_report(
            num_layers=2, kv_heads=2, head_dim=16,
            prefix_pool_pages=0, page_size=8))
        assert published
        cfg = published[-1]
        assert cfg.serve_prefix_pool_pages > 0
        last = [d for d in opt.decisions()
                if d["trigger"].startswith("serve:")][-1]
        assert last["chosen"]["prefix_pool_pages"] \
            == cfg.serve_prefix_pool_pages
        assert "|ppp=" in last["chosen"]["key"]

    def test_without_evidence_pool_stays_off(self, monkeypatch):
        monkeypatch.setattr(get_context(),
                            "serve_prefix_expected_hit_rate", 0.0)
        published = []
        opt = _optimizer(publish=published.append)
        opt.update_model_info(comm.ModelInfo(
            num_params=7_000_000_000, hidden_size=64, num_layers=2,
            seq_len=128))
        opt.update_serving_config(_serve_report(
            num_layers=2, kv_heads=2, head_dim=16,
            prefix_pool_pages=0, page_size=8))
        # whatever else the plan tunes, the pool knob is the
        # leave-unchanged sentinel: no evidence, no pool
        assert all(p.serve_prefix_pool_pages == -1 for p in published)

    def test_observed_hit_rate_overrides_the_prior(self, monkeypatch):
        """A worker reporting hit_rate=0 beats an optimistic prior:
        with zero observed benefit every pool width ties and the churn
        tie-break refuses to GROW the pool — the plan leaves the knob
        at its unchanged sentinel."""
        monkeypatch.setattr(get_context(),
                            "serve_prefix_expected_hit_rate", 0.9)
        published = []
        opt = _optimizer(publish=published.append)
        opt.update_model_info(comm.ModelInfo(
            num_params=7_000_000_000, hidden_size=64, num_layers=2,
            seq_len=128))
        opt.update_serving_config(_serve_report(
            num_layers=2, kv_heads=2, head_dim=16,
            prefix_pool_pages=24, page_size=8, prefix_hit_rate=0.0))
        assert all(p.serve_prefix_pool_pages == -1 for p in published)

    def test_hbm_gate_charges_pool_undivided(self, monkeypatch):
        """A budget that fits every slot pool (divided by world) but
        not the UNDIVIDED prefix pool: pool candidates are memory-
        rejected with their page count on the decision trail."""
        spec = planner.ModelSpec(
            param_count=10_000, num_layers=2, hidden_size=64,
            seq_len=128, global_batch=1, num_heads=4, kv_heads=2)
        slot_worst = planner.serve_cache_bytes(spec, 16, 64) / 8
        budget = slot_worst * 1.5
        monkeypatch.setattr(get_context(), "device_hbm_budget_bytes",
                            budget)
        monkeypatch.setattr(get_context(),
                            "serve_prefix_expected_hit_rate", 0.8)
        opt = _optimizer()
        opt.update_model_info(comm.ModelInfo(
            num_params=10_000, hidden_size=64, num_layers=2,
            seq_len=128))
        opt.update_serving_config(_serve_report(
            num_layers=2, kv_heads=2, head_dim=16,
            prefix_pool_pages=0, page_size=8))
        last = [d for d in opt.decisions()
                if d["trigger"].startswith("serve:")][-1]
        rejected = last["memory_rejected"]
        assert any(r.get("prefix_pool_pages", 0) > 0
                   for r in rejected)
        # and anything chosen fits WITH its pool charge
        chosen = last.get("chosen")
        if chosen:
            pool = planner.serve_prefix_pool_bytes(
                spec, chosen["prefix_pool_pages"], 8)
            slot = planner.serve_cache_bytes(
                spec, chosen["serve_slots"], 64) / 8
            assert slot + pool <= budget
