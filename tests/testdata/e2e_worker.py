"""E2E test worker: bootstrap distributed JAX from the agent env contract,
run a real cross-process collective, and consume dynamic shards."""

import sys

from dlrover_tpu.trainer.bootstrap import init_worker


def main() -> int:
    ctx = init_worker(platform="cpu")
    import jax
    import jax.numpy as jnp

    if ctx.num_processes > 1:
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(
            jnp.ones(1) * ctx.process_id
        )
        assert gathered.shape[0] == ctx.num_processes, gathered.shape
        assert float(gathered.sum()) == sum(range(ctx.num_processes))

    client = ctx.master_client
    if client is not None:
        from dlrover_tpu.agent.sharding_client import ShardingClient

        sharding = ShardingClient(
            client, "e2e_ds", batch_size=2, dataset_size=8, num_epochs=1,
            num_minibatches_per_shard=2,
        )
        consumed = 0
        if ctx.is_chief:  # chief consumes; others train on broadcast data
            while True:
                shard = sharding.fetch_shard()
                if shard is None:
                    break
                consumed += shard.end - shard.start
                sharding.report_batch_done(
                    (shard.end - shard.start) // 2
                )
            assert consumed == 8, consumed
            client.report_global_step(consumed // 2)
    print(f"worker {ctx.process_id}/{ctx.num_processes} done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
