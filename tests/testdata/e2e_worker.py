"""E2E test worker: bootstrap distributed JAX from the agent env contract,
run a real cross-process collective, and consume dynamic shards."""

import sys

from dlrover_tpu.trainer.bootstrap import init_worker


def main() -> int:
    ctx = init_worker(platform="cpu")
    import jax
    import jax.numpy as jnp

    if ctx.num_processes > 1:
        from jax.experimental import multihost_utils

        gathered = multihost_utils.process_allgather(
            jnp.ones(1) * ctx.process_id
        )
        assert gathered.shape[0] == ctx.num_processes, gathered.shape
        assert float(gathered.sum()) == sum(range(ctx.num_processes))

        # multi-host GSPMD data plane: ONE jitted train step over the
        # GLOBAL mesh spanning every process's devices. Each process
        # contributes only its PROCESS-LOCAL batch rows
        # (shard_batch -> make_array_from_process_local_data); the
        # gradient allreduce crosses the process boundary — the
        # DCN-equivalent collective the reference reaches via NCCL.
        import numpy as np
        import optax

        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.mesh import MeshPlan
        from dlrover_tpu.parallel.strategy import Strategy

        cfg = llama.llama_tiny(max_seq_len=32)
        # one batch row per device: local rows follow however many
        # local devices the environment forces (1 bare, 8 under the
        # test conftest's xla_force_host_platform_device_count)
        local_rows = jax.local_device_count()
        rng_np = np.random.RandomState(ctx.process_id)
        local_batch = {
            "input_ids": rng_np.randint(
                0, cfg.vocab_size, (local_rows, 16)).astype(np.int32),
            "labels": rng_np.randint(
                0, cfg.vocab_size, (local_rows, 16)).astype(np.int32),
        }
        # tracing example with the GLOBAL batch dimension
        example = jax.tree.map(
            lambda x: np.concatenate([x] * ctx.num_processes, axis=0),
            local_batch,
        )
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.adam(1e-2), example,
            strategy=Strategy(mesh=MeshPlan(data=-1, fsdp=1)),
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(local_batch)
        losses = []
        for i in range(2):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(jax.device_get(metrics["loss"])))
        assert all(np.isfinite(v) for v in losses), losses
        assert losses[1] < losses[0], losses

        # DevicePreloader's multi-host branch: local rows in, global
        # pre-sharded batch out, consumable by the same train step
        from dlrover_tpu.trainer.data import DevicePreloader

        (preloaded,) = list(
            DevicePreloader([local_batch], sharding=result.batch_spec)
        )
        state, metrics = result.train_step(
            state, preloaded, jax.random.PRNGKey(9)
        )
        assert np.isfinite(float(jax.device_get(metrics["loss"])))
        print(f"worker {ctx.process_id}: global-mesh train step ok "
              f"losses={losses}", flush=True)

    client = ctx.master_client
    if client is not None:
        from dlrover_tpu.agent.sharding_client import ShardingClient

        sharding = ShardingClient(
            client, "e2e_ds", batch_size=2, dataset_size=8, num_epochs=1,
            num_minibatches_per_shard=2,
        )
        consumed = 0
        if ctx.is_chief:  # chief consumes; others train on broadcast data
            while True:
                shard = sharding.fetch_shard()
                if shard is None:
                    break
                consumed += shard.end - shard.start
                sharding.report_batch_done(
                    (shard.end - shard.start) // 2
                )
            assert consumed == 8, consumed
            client.report_global_step(consumed // 2)
    print(f"worker {ctx.process_id}/{ctx.num_processes} done", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
