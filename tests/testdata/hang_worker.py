"""E2E chaos worker: heartbeats a few steps, then freezes (simulating a
collective blocked on a dead peer — process alive, step loop stuck). The
restarted round finishes cleanly."""

import os
import sys
import time

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.diagnosis.hang_detector import touch_heartbeat

restart_round = int(os.environ.get(NodeEnv.RESTART_ROUND, "0"))
if restart_round == 0:
    for _ in range(3):
        touch_heartbeat()
        time.sleep(0.1)
    print("hang worker: freezing now (no more heartbeats)", flush=True)
    time.sleep(120)  # the agent must kill us long before this returns
    sys.exit(0)
touch_heartbeat()
print(f"hang worker: round {restart_round} finishing", flush=True)
sys.exit(0)
