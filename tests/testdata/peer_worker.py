"""Checkpoint-free recovery chaos worker.

Round 0 (under the agent): trains a tiny deterministic model with the
peer-replication plane on (env-configured Context knobs) and NO
checkpoint directory — the only recovery source is the surviving
peer's DRAM. Steps are slowed so the test can SIGKILL it after a
replica committed. The relaunched round peer-restores through
``ElasticTrainer.prepare`` and finishes exactly 3 steps past the
resumed step, writing a bitwise param digest.

PEER_REFERENCE=1: the uninterrupted control — same model, same rng
stream, same constant batch, trained 0 -> PEER_TOTAL_STEPS in one run,
writing the digest the recovered run must match bitwise.
"""

import hashlib
import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.common.constants import NodeEnv
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor, TrainHook

STATUS = os.environ["PEER_STATUS"]
REFERENCE = os.environ.get("PEER_REFERENCE", "") == "1"
RESTART_ROUND = int(os.environ.get(NodeEnv.RESTART_ROUND, "0"))


def emit(record):
    with open(STATUS, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
        os.fsync(f.fileno())


def build_trainer():
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)),
                "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (4, 2))}
    client = None
    if not REFERENCE:
        from dlrover_tpu.agent.master_client import build_master_client

        client = build_master_client()
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.adam(0.1), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)),
        master_client=client,
    )
    return trainer, batch, client


class _StatusHook(TrainHook):
    def __init__(self, slow_s=0.0):
        self.slow_s = slow_s

    def after_step(self, step, metrics):
        emit({"event": "step", "step": step, "round": RESTART_ROUND})
        if self.slow_s:
            time.sleep(self.slow_s)


def digest_of(state):
    h = hashlib.sha256()
    for leaf in jax.tree.leaves(jax.device_get(state)):
        h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
    return h.hexdigest()


def main():
    trainer, batch, client = build_trainer()
    state = trainer.prepare()
    resumed = int(trainer._host_step)
    emit({"event": "begin", "round": RESTART_ROUND,
          "reference": REFERENCE, "resumed_step": resumed})
    if REFERENCE:
        total = int(os.environ["PEER_TOTAL_STEPS"])
        slow = 0.0
    elif RESTART_ROUND == 0:
        total = 5000  # killed long before this
        slow = 0.05
    else:
        total = resumed + 3
        slow = 0.0
    executor = TrainExecutor(
        trainer,
        train_iter_fn=lambda: iter(lambda: batch, None),
        hooks=[_StatusHook(slow_s=slow)],
        master_client=client,
        conf=Configuration({
            "train_steps": total, "log_every_steps": 0,
            "train_window": 2, "preemption_grace": False,
            "plan_poll_secs": 0, "runtime_report_steps": 0,
        }),
    )
    executor.state = state
    executor.train_and_evaluate()
    emit({"event": "end", "round": RESTART_ROUND,
          "final_step": int(executor.state.step),
          "resumed_step": resumed,
          "digest": digest_of(executor.state)})
    return 0


if __name__ == "__main__":
    sys.exit(main())
