"""Soak worker: every round runs long enough to be SIGKILLed from
outside; a round that survives ~3 s undisturbed exits cleanly."""

import sys
import time

for _ in range(15):
    time.sleep(0.2)
print("soak worker: survived undisturbed, exiting cleanly", flush=True)
sys.exit(0)
