"""Preemption-grace chaos worker: trains with NO periodic checkpoint
cadence, so the ONLY restore point a SIGTERM leaves behind is the
executor's emergency save. Emits one status line per step; on restart
(a checkpoint exists) it logs the resumed step and exits.

Env: PREEMPT_CKPT_DIR (checkpoint root), PREEMPT_STATUS (jsonl path).

PREEMPT_SLOW_AFTER=N (>0): slow-step mode — step N blocks the loop for
PREEMPT_SLOW_SECS (default 300) INSIDE the step path, before the
executor can reach its preemption-flag check, emitting a "slow" event
first. This emulates a wedged/ tens-of-seconds device step on real TPU:
the first SIGTERM is flagged-and-swallowed (the loop never returns to
check it), and only the second-SIGTERM escape hatch — the handler
re-arms the default disposition after the first notice — can end the
process. Slow mode also saves a checkpoint EVERY step (steps=1) so the
kill lands with a staged/committed save chain to corrupt-or-not.
"""

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from dlrover_tpu.checkpoint.manager import CheckpointInterval
from dlrover_tpu.models import llama
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import TrainExecutor, TrainHook

CKPT = os.environ["PREEMPT_CKPT_DIR"]
STATUS = os.environ["PREEMPT_STATUS"]
TOTAL_STEPS = int(os.environ.get("PREEMPT_TOTAL_STEPS", "200"))


def emit(record):
    with open(STATUS, "a") as f:
        f.write(json.dumps(record) + "\n")
        f.flush()
        os.fsync(f.fileno())


# PREEMPT_PIPELINE=1: run the PIPELINED path on the 8-device mesh so
# the emergency save flushes pipe-sharded state (stage-stacked layer
# params on "pipe") rather than the single-device layout
PIPELINED = os.environ.get("PREEMPT_PIPELINE", "") == "1"
SLOW_AFTER = int(os.environ.get("PREEMPT_SLOW_AFTER", "0"))
SLOW_SECS = float(os.environ.get("PREEMPT_SLOW_SECS", "300"))
# PREEMPT_WINDOW=W (>0): run the ASYNC dispatch-pipelined loop with W
# step calls in flight — the mid-window preemption chaos test. Default
# 0 keeps the legacy synchronous timing these tests' kill windows and
# per-step save assertions were written against.
WINDOW = int(os.environ.get("PREEMPT_WINDOW", "0"))

cfg = llama.llama_tiny(num_layers=4 if PIPELINED else 2,
                       max_seq_len=64, use_flash=False)
rng = np.random.RandomState(0)
rows = 8 if PIPELINED else 4
ids = rng.randint(0, cfg.vocab_size, size=(rows, 65))
batch = {
    "input_ids": jnp.asarray(ids[:, :-1]),
    "labels": jnp.asarray(ids[:, 1:]),
}

if PIPELINED:
    from dlrover_tpu.models.losses import masked_lm_loss

    def loss_fn(params, b, rng_key):
        logits, _ = llama.apply_pipelined(
            params, b["input_ids"], cfg,
            num_stages=2, num_microbatches=2, rng=rng_key,
        )
        return masked_lm_loss(logits, b["labels"]), {}

    strategy = Strategy(mesh=MeshPlan(pipe=2, data=2, tensor=2),
                        rule_set="llama_pp")
else:
    loss_fn = llama.make_loss_fn(cfg)
    strategy = Strategy(mesh=MeshPlan(data=1, fsdp=1))

trainer = ElasticTrainer(
    llama.make_init_fn(cfg),
    loss_fn,
    optax.adamw(1e-3),
    batch,
    strategy=strategy,
    ckpt_dir=CKPT,
    # default: no periodic cadence (steps=0/secs=0 never fires), so
    # only the preemption path can produce a checkpoint. Slow-step mode
    # saves EVERY step instead: the hard kill must leave the committed
    # chain restorable.
    ckpt_interval=(CheckpointInterval(steps=1, secs=0.0) if SLOW_AFTER
                   else CheckpointInterval(steps=0, secs=0.0)),
)


class StatusHook(TrainHook):
    def begin(self, executor):
        emit({"event": "begin",
              "resumed_step": int(executor.state.step)})

    def after_step(self, step, metrics):
        emit({"event": "step", "step": step,
              "loss": float(metrics["loss"])})
        if SLOW_AFTER and step == SLOW_AFTER:
            # block INSIDE the step path, before the executor's
            # preempted-flag check: a SIGTERM arriving now is flagged
            # but never acted on (PEP 475 resumes the sleep after the
            # handler returns) — only a second SIGTERM, restored to the
            # default disposition by the first, can end the process
            emit({"event": "slow", "step": step})
            time.sleep(SLOW_SECS)
        time.sleep(0.2)  # widen the kill window


def batches():
    for _ in range(TOTAL_STEPS):
        yield batch


executor = TrainExecutor(
    trainer,
    train_iter_fn=batches,
    hooks=[StatusHook()],
    conf=Configuration({"train_steps": TOTAL_STEPS,
                        "log_every_steps": 0,
                        "train_window": WINDOW}),
)
result = executor.train_and_evaluate()
emit({"event": "end", "preempted": bool(result.get("preempted")),
      "final_step": int(executor.state.step)})
trainer.finalize()
sys.exit(0)
