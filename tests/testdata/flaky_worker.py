"""E2E test worker that fails on its first launch and succeeds after the
agent restarts it (exercises the failure -> report -> re-rendezvous path)."""

import os
import sys

from dlrover_tpu.common.constants import NodeEnv

restart_round = int(os.environ.get(NodeEnv.RESTART_ROUND, "0"))
if restart_round == 0:
    print("flaky worker: failing on purpose (round 0)", flush=True)
    sys.exit(3)
print(f"flaky worker: succeeding on round {restart_round}", flush=True)
sys.exit(0)
