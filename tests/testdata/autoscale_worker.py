"""Auto-scale e2e worker.

World of 1: reports a steadily advancing global step so the master's
SpeedMonitor sees healthy speed (the auto-scaler's input signal).
After the scale-up the agent restarts it into a >= 2-process world; it
then writes the marker file and exits 0, letting the whole job finish.
"""

import os
import sys
import time

from dlrover_tpu.trainer.bootstrap import init_worker


def main() -> int:
    ctx = init_worker(platform="cpu")
    marker = os.environ.get("AUTOSCALE_MARKER", "")

    if ctx.num_processes >= 2:
        if ctx.is_chief and marker:
            with open(marker, "w") as f:
                f.write(str(ctx.num_processes))
        print(
            f"worker {ctx.process_id}: scaled world of "
            f"{ctx.num_processes} reached", flush=True,
        )
        return 0

    client = ctx.master_client
    step = 0
    deadline = time.time() + float(
        os.environ.get("AUTOSCALE_WORKER_TIMEOUT", "120")
    )
    while time.time() < deadline:
        step += 1
        if client is not None and ctx.is_chief:
            client.report_global_step(step)
        time.sleep(0.1)
    print("worker: never restarted into a bigger world", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
