"""E2E chaos worker: runs long enough to be SIGKILLed from outside on its
first launch; finishes quickly after the agent restarts it."""

import os
import sys
import time

from dlrover_tpu.common.constants import NodeEnv

restart_round = int(os.environ.get(NodeEnv.RESTART_ROUND, "0"))
if restart_round == 0:
    print("chaos worker: round 0, running slow (kill me)", flush=True)
    for _ in range(100):  # ~20 s — the test kills us long before
        time.sleep(0.2)
    sys.exit(0)
# the relaunched round emits its lifecycle edges into the shared
# timeline: with the agent's incident trace id riding the worker env,
# these records correlate the WORKER side of the recovery
from dlrover_tpu.telemetry import EventKind, emit_event  # noqa: E402

emit_event(EventKind.TRAIN_START, step=0)
print(f"chaos worker: round {restart_round}, finishing", flush=True)
emit_event(EventKind.TRAIN_END, step=0)
sys.exit(0)
