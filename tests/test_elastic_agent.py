"""End-to-end agent tests: real master, real agents, real worker
subprocesses running distributed JAX on the CPU backend.

Mirrors the reference pattern (``test_elastic_training_agent.py``): a live
in-process master + agents driven through the real RPC/spawn path.
"""

import os
import threading

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.agent.training_agent import (
    AgentConfig,
    ElasticTrainingAgent,
)
from dlrover_tpu.agent.worker_group import WorkerSpec
from dlrover_tpu.master.local_master import start_local_master

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER_ENV = {
    "PYTHONPATH": REPO_ROOT + os.pathsep + os.environ.get("PYTHONPATH", ""),
}


@pytest.fixture()
def master():
    m = start_local_master()
    yield m
    m.stop()


def _agent(master, node_rank, entrypoint, *, nnodes=(1, 1), nproc=1,
           max_restarts=1, monitor_interval=0.3):
    client = MasterClient(master.addr, node_id=node_rank)
    config = AgentConfig(
        node_rank=node_rank,
        node_id=node_rank,
        nproc_per_node=nproc,
        min_nodes=nnodes[0],
        max_nodes=nnodes[1],
        max_restarts=max_restarts,
        monitor_interval=monitor_interval,
        rdzv_waiting_timeout=5.0,
    )
    spec = WorkerSpec(
        entrypoint=entrypoint, nproc_per_node=nproc, env=dict(WORKER_ENV)
    )
    return ElasticTrainingAgent(config, spec, client, host_ip="127.0.0.1")


def test_single_node_end_to_end(master):
    agent = _agent(master, 0, os.path.join(TESTDATA, "e2e_worker.py"))
    rc = agent.run()
    assert rc == 0
    # the chief consumed all 8 records => 4 global steps reported
    assert master.speed_monitor.completed_global_step == 4


@pytest.mark.slow
def test_two_node_world_with_collectives(master):
    """Two agents rendezvous into one world; their worker processes form a
    2-process JAX world and run a real allgather."""
    agents = [
        _agent(master, rank, os.path.join(TESTDATA, "e2e_worker.py"),
               nnodes=(2, 2))
        for rank in range(2)
    ]
    results = {}

    def run(rank):
        results[rank] = agents[rank].run()

    threads = [
        threading.Thread(target=run, args=(r,), daemon=True)
        for r in range(2)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert results == {0: 0, 1: 0}
    # ranks 0/1 mapped to contiguous process ids
    assert agents[0].last_rdzv.process_id_base == 0
    assert agents[1].last_rdzv.process_id_base == 1
    assert agents[0].last_rdzv.num_processes == 2


def test_worker_failure_triggers_restart(master):
    agent = _agent(master, 0, os.path.join(TESTDATA, "flaky_worker.py"),
                   max_restarts=2)
    rc = agent.run()
    assert rc == 0
    assert agent._worker_group.restart_round == 1


def test_restart_budget_exhausted_fails(master):
    agent = _agent(master, 0, os.path.join(TESTDATA, "flaky_worker.py"),
                   max_restarts=0)
    rc = agent.run()
    assert rc == 1
