"""Integration: LocalJobMaster + MasterClient over real gRPC.

Mirrors the reference's pattern of booting a real in-process master and
driving it with real clients (``test_elastic_training_agent.py:33-35``).
"""

import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common.constants import RendezvousName
from dlrover_tpu.master.local_master import start_local_master


@pytest.fixture(scope="module")
def master():
    m = start_local_master()
    yield m
    m.stop()


@pytest.fixture()
def client(master):
    c = MasterClient(master.addr, node_id=0)
    yield c
    c.close()


def test_dataset_flow_over_rpc(master, client):
    client.report_dataset_shard_params(
        dataset_name="rpc_ds", dataset_size=12, batch_size=3,
        num_epochs=1, num_minibatches_per_shard=2,
    )
    task = client.get_task("rpc_ds")
    assert task.task_id >= 0
    assert task.shard.end - task.shard.start == 6
    client.report_task_result("rpc_ds", task.task_id)
    task2 = client.get_task("rpc_ds")
    client.report_batch_done("rpc_ds", 6)
    task3 = client.get_task("rpc_ds")
    assert task3.task_id < 0  # exhausted


def test_rendezvous_flow_over_rpc(master):
    clients = [MasterClient(master.addr, node_id=i) for i in range(2)]
    try:
        clients[0].report_rdzv_params(
            min_nodes=2, max_nodes=2, waiting_timeout=30.0, node_unit=1,
            rdzv_name=RendezvousName.TRAINING,
        )
        for i, c in enumerate(clients):
            c.join_rendezvous(i, 4, addr=f"host{i}:2222")
        world = clients[1].get_comm_world(node_rank=1)
        assert world.world == {0: 4, 1: 4}
        assert world.coordinator_addr == "host0:2222"
        assert clients[0].num_nodes_waiting() == 0
    finally:
        for c in clients:
            c.close()


def test_kv_and_sync_over_rpc(master, client):
    client.kv_store_set("ckpt_step", "100")
    assert client.kv_store_get("ckpt_step") == "100"
    assert client.kv_store_get("missing") is None
    assert client.kv_store_add("counter", 5) == 5
    assert client.kv_store_add("counter", 2) == 7

    master.sync_service.set_expected_count(1)
    assert client.join_sync("epoch-end", 0)
    assert client.sync_finished("epoch-end")
    assert not client.barrier("b1")
    client.barrier("b1", notify=True)
    assert client.barrier("b1")


def test_monitor_reports_over_rpc(master, client):
    client.report_global_step(10)
    client.report_global_step(20)
    assert master.speed_monitor.completed_global_step == 20
    client.report_resource(cpu_percent=50.0, memory_mb=1024)
    client.report_heartbeat()  # no job manager on local master: must not fail


def test_cluster_version_over_rpc(master, client):
    assert client.get_cluster_version("global", "worker", 0) == 0
    client.update_cluster_version("global", 3, "worker", 0)
    assert client.get_cluster_version("global", "worker", 0) == 3
    client.update_cluster_version("local", 2, "worker", 1)
    assert client.get_cluster_version("local", "worker", 1) == 2


def test_cluster_version_cas_over_rpc(master, client):
    # compare-and-set: only applies while current == expected, so two
    # workers racing the 0->1 startup bump cannot clobber each other
    cur = client.get_cluster_version("global", "worker", 0)
    stale = client.update_cluster_version(
        "global", 99, "worker", 0, expected=cur + 7
    )
    assert not stale.success
    assert client.get_cluster_version("global", "worker", 0) == cur
    ok = client.update_cluster_version(
        "global", cur + 1, "worker", 0, expected=cur
    )
    assert ok.success
    assert client.get_cluster_version("global", "worker", 0) == cur + 1


def test_job_exit_over_rpc(master, client):
    assert not master.servicer.job_exit_requested
    client.report_job_exit(success=True, reason="all done")
    assert master.servicer.job_exit_requested
    assert master.servicer.job_success
