"""The cluster diagnosis plane (ISSUE 6): per-node runtime series,
straggler/hang verdicts, the goodput ledger, trace-id correlation, and
the end-to-end wedge — a chaos run with one deliberately slow worker
must produce a ``DIAG_STRAGGLER`` verdict naming that node, a goodput
ledger covering ≥99% of job wall-time, and working ``tpurun diagnose``
/ ``tpurun goodput`` CLIs — with node-runtime reporting overhead gated
at ≤5% (paired-run median-ratio methodology from PR 4)."""

import json
import time

import jax
import jax.numpy as jnp
import optax
import pytest

from dlrover_tpu.agent.master_client import MasterClient
from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.master.local_master import start_local_master
from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor
from dlrover_tpu.master.monitor.straggler import StragglerDetector
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.telemetry import (
    EventKind,
    names as tm,
    read_events,
    recent_events,
)
from dlrover_tpu.telemetry.events import clear_ring
from dlrover_tpu.telemetry.goodput import derive_goodput
from dlrover_tpu.telemetry.metrics import process_registry
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import (
    NodeRuntimeReportHook,
    TrainExecutor,
    TrainHook,
)

BOUNDS = [0.001, 0.005, 0.01, 0.05, 0.1, 1.0]


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


def _report(node, steps_total, counts, ts=None, **kw):
    return comm.NodeRuntimeReport(
        node_id=node, timestamp=ts or time.time(), step=int(steps_total),
        steps_total=float(steps_total), bounds=BOUNDS,
        step_time_counts=list(counts), **kw,
    )


def _counts_at(ms_per_step, steps):
    """Cumulative counts with ``steps`` observations at ``ms_per_step``."""
    import bisect

    counts = [0] * (len(BOUNDS) + 1)
    idx = bisect.bisect_left(BOUNDS, ms_per_step / 1000.0)
    counts[min(idx, len(BOUNDS))] += steps
    return counts


# -- node series store -------------------------------------------------------


class TestNodeRuntimeStore:
    def test_cumulative_reports_diff_into_windows(self):
        store = NodeRuntimeStore()
        c1 = _counts_at(5, 10)
        store.ingest(_report(0, 10, c1))
        # second window: 10 more steps, now slow (60ms)
        c2 = [a + b for a, b in zip(c1, _counts_at(60, 10))]
        sample = store.ingest(_report(0, 20, c2))
        assert sample.window_steps == 10
        # the WINDOW p50 reflects only the new (slow) observations
        assert sample.step_p50 is not None and sample.step_p50 > 0.05
        # lifetime-cumulative would have blended the fast history
        first = store.series(0)[0]
        assert first.step_p50 is not None and first.step_p50 <= 0.005

    def test_worker_restart_resets_the_diff(self):
        store = NodeRuntimeStore()
        store.ingest(_report(0, 100, _counts_at(5, 100)))
        # restarted worker: counters began again from zero
        sample = store.ingest(_report(0, 4, _counts_at(5, 4)))
        assert sample.window_steps == 4

    def test_series_is_bounded_and_summary_reports_age(self):
        store = NodeRuntimeStore(max_samples=8)
        for i in range(1, 20):
            store.ingest(_report(3, i, _counts_at(5, i)))
        assert len(store.series(3)) == 8
        summary = store.summary()
        assert 3 in summary
        assert summary[3]["report_age_s"] < 5
        assert store.last_report_age(99) is None

    def test_latest_sample_exports_labeled_gauges(self):
        process_registry().reset()
        store = NodeRuntimeStore()
        store.ingest(_report(7, 10, _counts_at(5, 10), rss_mb=123.0,
                             window_occupancy=3))
        g = process_registry().get(tm.NODE_STEP_P50,
                                   labels={"node": "7"})
        assert g is not None and g.value > 0
        text = process_registry().render_prometheus()
        assert 'dlrover_node_rss_mb{node="7"} 123' in text
        assert 'dlrover_node_dispatch_window_occupancy{node="7"} 3' in text


# -- straggler / hang detector ----------------------------------------------


def _detector(store, speed_monitor=None, **kw):
    kw.setdefault("ratio", 2.0)
    kw.setdefault("confirm_windows", 3)
    kw.setdefault("hang_secs", 60.0)
    return StragglerDetector(store, speed_monitor=speed_monitor, **kw)


def _feed(store, det, node, ms, window, steps=8, ts=None):
    cum = getattr(_feed, "_cum", {}).setdefault(node, {
        "counts": [0] * (len(BOUNDS) + 1), "steps": 0})
    cum["counts"] = [a + b for a, b in
                     zip(cum["counts"], _counts_at(ms, steps))]
    cum["steps"] += steps
    # ingest stamps the MASTER clock; synthetic time rides `now`
    store.ingest(_report(node, cum["steps"], cum["counts"], ts=ts),
                 now=ts)
    det.observe(node, now=ts)


@pytest.fixture(autouse=True)
def _fresh_feed_state():
    _feed._cum = {}
    yield
    _feed._cum = {}


class TestStragglerDetector:
    def test_confirmation_window_rides_out_one_spike(self):
        store = NodeRuntimeStore()
        det = _detector(store)
        now = time.time()
        for w in range(3):
            for node in (0, 1):
                _feed(store, det, node, 5, w, ts=now + w)
            # node 2: ONE slow window, then fast again
            _feed(store, det, 2, 50 if w == 0 else 5, w, ts=now + w)
        assert det.stragglers() == []
        assert det.verdicts().get(2, {}).get("verdict", "healthy") \
            == "healthy"

    def test_three_consecutive_windows_confirm_with_evidence(self):
        clear_ring()
        store = NodeRuntimeStore()
        monitor = SpeedMonitor()
        det = _detector(store, speed_monitor=monitor)
        now = time.time()
        for w in range(3):
            for node in (0, 1):
                _feed(store, det, node, 5, w, ts=now + w)
            _feed(store, det, 2, 50, w, ts=now + w)
        assert det.stragglers() == [2]
        v = det.verdicts()[2]
        assert v["verdict"] == "straggler"
        assert v["trace_id"].startswith("inc-")
        ev = v["evidence"]
        assert ev["ratio"] >= 2.0 and ev["confirm_windows"] == 3
        assert ev["peer_median_p50_s"] < ev["step_p50_s"]
        # the verdict reached the speed monitor (the auto-scaler input)
        assert monitor.straggler_nodes == [2]
        assert monitor.unhealthy_nodes == [2]
        # and the evidence-carrying event reached the timeline
        diag = [r for r in recent_events()
                if r["kind"] == EventKind.DIAG_STRAGGLER]
        assert diag and diag[-1]["diag_node"] == 2
        assert diag[-1]["error_code"] == "STRAGGLER"

    def test_ratio_just_below_threshold_never_flags(self):
        store = NodeRuntimeStore()
        det = _detector(store, ratio=12.0)  # 50/5 = 10x < 12x
        now = time.time()
        for w in range(5):
            for node in (0, 1):
                _feed(store, det, node, 5, w, ts=now + w)
            _feed(store, det, 2, 50, w, ts=now + w)
        assert det.stragglers() == []

    def test_recovery_clears_the_verdict(self):
        store = NodeRuntimeStore()
        monitor = SpeedMonitor()
        det = _detector(store, speed_monitor=monitor)
        now = time.time()
        for w in range(3):
            for node in (0, 1):
                _feed(store, det, node, 5, w, ts=now + w)
            _feed(store, det, 2, 50, w, ts=now + w)
        assert det.stragglers() == [2]
        for w in range(3, 5):
            for node in (0, 1, 2):
                _feed(store, det, node, 5, w, ts=now + w)
        assert det.stragglers() == []
        assert monitor.straggler_nodes == []

    def test_two_node_cluster_flags_only_the_slow_one(self):
        store = NodeRuntimeStore()
        det = _detector(store)
        now = time.time()
        for w in range(4):
            _feed(store, det, 0, 5, w, ts=now + w)
            _feed(store, det, 1, 50, w, ts=now + w)
        assert det.stragglers() == [1]

    def test_silent_node_is_diagnosed_hung_and_recovers(self):
        clear_ring()
        store = NodeRuntimeStore()
        det = _detector(store, hang_secs=30.0)
        now = time.time()
        _feed(store, det, 0, 5, 0, ts=now)
        _feed(store, det, 1, 5, 0, ts=now)
        # node 1 goes silent; node 0 keeps reporting 40s later
        _feed(store, det, 0, 5, 1, ts=now + 40)
        assert det.hung_nodes() == [1]
        hang = [r for r in recent_events()
                if r["kind"] == EventKind.DIAG_NODE_HANG]
        assert hang and hang[-1]["diag_node"] == 1
        assert hang[-1]["error_code"] == "NODE_HANG"
        # node 1 reports again: the hang verdict clears
        _feed(store, det, 1, 5, 1, ts=now + 41)
        assert det.hung_nodes() == []

    def test_all_nodes_silent_is_not_a_per_node_hang(self):
        store = NodeRuntimeStore()
        det = _detector(store, hang_secs=30.0)
        now = time.time()
        _feed(store, det, 0, 5, 0, ts=now)
        _feed(store, det, 1, 5, 0, ts=now)
        det.scan_hangs(now=now + 500)  # job ended / master partitioned
        assert det.hung_nodes() == []

    def test_skewed_worker_clock_cannot_forge_a_hang(self):
        # the worker stamps its report 10 minutes in the past (clock
        # skew); the MASTER's receive clock decides the age, so the
        # node is fresh, not hung
        store = NodeRuntimeStore()
        det = _detector(store, hang_secs=30.0)
        now = time.time()
        store.ingest(_report(0, 8, _counts_at(5, 8), ts=now - 600),
                     now=now)
        store.ingest(_report(1, 8, _counts_at(5, 8), ts=now), now=now)
        det.scan_hangs(now=now + 1)
        assert det.hung_nodes() == []

    def test_departed_node_stops_pinning_the_verdict(self):
        """A node diagnosed hung that NEVER returns (deleted pod) must
        not keep the auto-scaler disabled forever: past the departed
        window its verdict and series are dropped."""
        store = NodeRuntimeStore()
        monitor = SpeedMonitor()
        det = _detector(store, speed_monitor=monitor, hang_secs=30.0)
        now = time.time()
        _feed(store, det, 0, 5, 0, ts=now)
        _feed(store, det, 1, 5, 0, ts=now)
        _feed(store, det, 0, 5, 1, ts=now + 40)
        assert det.hung_nodes() == [1]
        assert monitor.unhealthy_nodes == [1]
        # 4*hang_secs floor is 300s: at +400s node 1 has departed
        _feed(store, det, 0, 5, 2, ts=now + 400)
        assert det.hung_nodes() == []
        assert monitor.unhealthy_nodes == []
        assert store.node_ids() == [0]

    def test_straggler_verdict_clears_when_all_peers_vanish(self):
        store = NodeRuntimeStore()
        det = _detector(store, hang_secs=0)  # isolate the peer logic
        now = time.time()
        for w in range(3):
            for node in (0, 1):
                _feed(store, det, node, 5, w, ts=now + w)
            _feed(store, det, 2, 50, w, ts=now + w)
        assert det.stragglers() == [2]
        store.forget(0)
        store.forget(1)
        # no fresh peers: the comparison that produced the verdict is
        # gone, so the verdict must not outlive it
        _feed(store, det, 2, 50, 3, ts=now + 3)
        assert det.stragglers() == []


# -- speed monitor reset + auto-scaler gating --------------------------------


class TestSpeedMonitorReset:
    def test_reset_step_unpins_the_monotone_max(self):
        m = SpeedMonitor()
        m.collect_global_step(100, timestamp=time.time())
        m.collect_global_step(120, timestamp=time.time())
        assert m.completed_global_step == 120
        # a rollback rewound the truth to 80: max() alone would ignore
        m.collect_global_step(80, timestamp=time.time())
        assert m.completed_global_step == 120  # the monotone default
        m.reset_step(80)
        assert m.completed_global_step == 80
        # the speed window restarted from the reset point
        assert m.running_speed() == 0.0
        m.collect_global_step(90, timestamp=time.time() + 10)
        assert m.completed_global_step == 90

    def test_servicer_routes_reset_reports(self):
        from dlrover_tpu.master.servicer import MasterServicer

        monitor = SpeedMonitor()
        servicer = MasterServicer(speed_monitor=monitor)
        servicer.report(comm.GlobalStep(step=50, timestamp=time.time()))
        assert monitor.completed_global_step == 50
        servicer.report(comm.GlobalStep(step=20, timestamp=time.time(),
                                        reset=True))
        assert monitor.completed_global_step == 20

    def test_auto_scaler_defers_to_active_verdicts(self):
        from dlrover_tpu.master.node.job_auto_scaler import JobAutoScaler

        calls = []

        class Optimizer:
            def get_job_resource_plan(self):
                calls.append(1)
                return None

        monitor = SpeedMonitor()
        scaler = JobAutoScaler(job_manager=None, job_optimizer=Optimizer(),
                               speed_monitor=monitor)
        monitor._worker_adjust_time = 0.0  # long-stable membership
        monitor.update_node_verdict(2, "straggler")
        scaler.optimize_once()
        assert calls == []  # incident active: recovery owns the world
        monitor.update_node_verdict(2, "healthy")
        scaler.optimize_once()
        assert calls == [1]


# -- goodput ledger ----------------------------------------------------------


def _ev(kind, ts, pid=1, **kw):
    return {"kind": kind, "ts": ts, "mono": ts, "pid": pid, "node": "0",
            **kw}


class TestGoodputLedger:
    def test_buckets_partition_the_wall_clock(self):
        events = [
            _ev(EventKind.RDZV_JOIN, 0.0),
            _ev(EventKind.RDZV_COMPLETE, 3.0, wait_seconds=3.0),
            _ev(EventKind.TRAIN_START, 4.0, pid=2),
            _ev(EventKind.COMPILE_FIRST_STEP, 9.0, pid=2, seconds=5.0),
            _ev(EventKind.CKPT_SAVE, 20.0, pid=2, stage_seconds=1.0),
            _ev(EventKind.WORKER_FAILED, 30.0, error_code="EXIT_137"),
            _ev(EventKind.WORKERS_STARTED, 40.0),
            _ev(EventKind.TRAIN_START, 41.0, pid=3),
            _ev(EventKind.TRAIN_END, 100.0, pid=3),
        ]
        rep = derive_goodput(events)
        b = rep["detail"]["buckets"]
        assert rep["detail"]["coverage"] >= 0.99
        assert b["restart"]["seconds"] == pytest.approx(10.0, abs=0.01)
        assert b["rendezvous"]["seconds"] == pytest.approx(3.0, abs=0.01)
        assert b["compile"]["seconds"] == pytest.approx(5.0, abs=0.01)
        assert b["checkpoint"]["seconds"] == pytest.approx(1.0, abs=0.01)
        # productive: (9..20)+(21..30) from span 1 + (41..100) span 2
        assert b["productive_step"]["seconds"] == pytest.approx(
            79.0, abs=0.01)
        assert rep["value"] == pytest.approx(0.79, abs=0.001)

    def test_downtime_wins_over_a_bracketing_train_span(self):
        events = [
            _ev(EventKind.TRAIN_START, 0.0, pid=2),
            _ev(EventKind.NONFINITE_STEP, 10.0, pid=2,
                error_code="NONFINITE"),
            _ev(EventKind.ROLLBACK_RESTORED, 14.0, pid=2),
            _ev(EventKind.TRAIN_END, 20.0, pid=2),
        ]
        b = derive_goodput(events)["detail"]["buckets"]
        assert b["rollback"]["seconds"] == pytest.approx(4.0, abs=0.01)
        assert b["productive_step"]["seconds"] == pytest.approx(
            16.0, abs=0.01)

    def test_unclosed_train_span_ends_at_the_failure_edge(self):
        events = [
            _ev(EventKind.TRAIN_START, 0.0, pid=2),
            # the worker died silently; the agent noticed at 30
            _ev(EventKind.WORKER_FAILED, 30.0, error_code="EXIT_137"),
            _ev(EventKind.WORKERS_STARTED, 35.0),
            _ev(EventKind.TRAIN_END, 50.0, pid=3),
        ]
        b = derive_goodput(events)["detail"]["buckets"]
        # 0..30 productive (span clipped at the failure edge),
        # 30..35 restart, 35..50 idle (no open train span for pid 3)
        assert b["productive_step"]["seconds"] == pytest.approx(
            30.0, abs=0.01)
        assert b["restart"]["seconds"] == pytest.approx(5.0, abs=0.01)
        assert b["idle"]["seconds"] == pytest.approx(15.0, abs=0.01)

    def test_too_short_timeline_reports_an_error(self):
        rep = derive_goodput([_ev(EventKind.TRAIN_START, 1.0)])
        assert "error" in rep

    def test_pid_collision_across_nodes_does_not_cross_close_spans(self):
        # containerized workers on two hosts both run as pid 1: node
        # B's TRAIN_END must not close node A's span
        events = [
            {"kind": EventKind.TRAIN_START, "ts": 0.0, "pid": 1,
             "node": "A"},
            {"kind": EventKind.TRAIN_START, "ts": 0.0, "pid": 1,
             "node": "B"},
            {"kind": EventKind.TRAIN_END, "ts": 10.0, "pid": 1,
             "node": "B"},
            {"kind": EventKind.TRAIN_END, "ts": 40.0, "pid": 1,
             "node": "A"},
        ]
        b = derive_goodput(events)["detail"]["buckets"]
        # node A trained the full 40s; keyed by pid alone, its span
        # would have closed at 10s and 10..40 read as idle
        assert b["productive_step"]["seconds"] == pytest.approx(
            40.0, abs=0.01)
        assert b["idle"]["seconds"] == pytest.approx(0.0, abs=0.01)


# -- the end-to-end wedge ----------------------------------------------------


def _make_trainer(**kwargs):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)), "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (4, 2))}
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.sgd(0.1), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)), **kwargs,
    )
    return trainer, batch


class _SlowStep(TrainHook):
    """The injected straggler: every step pays extra host latency
    (reusing the slow-step chaos idiom — the device step itself is
    unchanged, the node is just slower)."""

    def __init__(self, seconds):
        self.seconds = seconds

    def before_step(self, step):
        time.sleep(self.seconds)


def _run_node(trainer, batch, master, node_id, slow_s=0.0, steps=36,
              report_every=6):
    """One 'node': a real executor + the real NodeRuntimeReportHook
    against the real master RPC. The process registry is reset first so
    this node's instruments carry only its own observations (three
    nodes share one test process)."""
    process_registry().reset()
    client = MasterClient(master.addr, node_id=node_id)
    # min_interval_s=0: the wedge wants one report per step-cadence (a
    # real job paces reports by wall time; tier-1 runs are seconds long)
    hooks = [NodeRuntimeReportHook(client, every_steps=report_every,
                                   min_interval_s=0)]
    if slow_s:
        hooks.insert(0, _SlowStep(slow_s))
    executor = TrainExecutor(
        trainer, train_iter_fn=lambda: [batch] * steps,
        hooks=hooks,
        conf=Configuration({
            "train_steps": steps, "log_every_steps": 0,
            "train_window": 2, "preemption_grace": False,
        }),
    )
    out = executor.train_and_evaluate()
    client.close()
    return out


class TestDiagnosisWedge:
    def test_slow_worker_is_named_with_evidence_and_ledger_covers(
            self, tmp_path, monkeypatch):
        """The acceptance wedge: one deliberately slow node out of
        three → (a) a DIAG_STRAGGLER event naming that node with
        evidence, (b) a goodput ledger covering ≥99% of wall-time, and
        (c) live + forensic diagnosis CLIs agreeing on the verdict."""
        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        ctx = get_context()
        monkeypatch.setattr(ctx, "diagnosis_confirm_windows", 3)
        monkeypatch.setattr(ctx, "diagnosis_straggler_ratio", 2.0)
        master = start_local_master()
        try:
            trainer, batch = _make_trainer()
            # fast peers first (their series anchor the median), then
            # the slow node — per-step sleep makes its p50 ~10x theirs
            _run_node(trainer, batch, master, node_id=0)
            _run_node(trainer, batch, master, node_id=1)
            _run_node(trainer, batch, master, node_id=2, slow_s=0.03)

            det = master.servicer.straggler_detector
            assert det.stragglers() == [2], det.verdicts()
            verdict = det.verdicts()[2]
            ev = verdict["evidence"]
            assert ev["ratio"] >= 2.0
            assert ev["step_p50_s"] > ev["peer_median_p50_s"]
            # the verdict fed the speed monitor (auto-scaler input)
            assert master.speed_monitor.straggler_nodes == [2]

            # (a) the event timeline carries the verdict + evidence
            records = read_events(events_path)
            diag = [r for r in records
                    if r["kind"] == EventKind.DIAG_STRAGGLER]
            assert diag and diag[-1]["diag_node"] == 2
            assert diag[-1]["trace_id"].startswith("inc-")
            assert diag[-1]["ratio"] >= 2.0

            # the master's /metrics view has per-node labeled series
            # (in-process simulation shares ONE registry, and each
            # node's run resets it — only the last node's series
            # survive here; a real master keeps all of them, pinned by
            # TestNodeRuntimeStore.test_latest_sample_exports_...)
            text = process_registry().render_prometheus()
            assert 'dlrover_node_step_time_p50_seconds{node="2"}' in text
            assert 'dlrover_node_steps_total{node="2"} 36' in text

            # (b) goodput ledger over the same timeline
            ledger = derive_goodput(records)
            assert ledger["detail"]["coverage"] >= 0.99, ledger
            assert ledger["detail"]["buckets"]["productive_step"][
                "seconds"] > 0

            # (c) live CLI (master RPC) and forensic CLI (events file)
            # agree on the verdict
            client = MasterClient(master.addr, node_id=0)
            live = client.get_diagnosis()
            client.close()
            assert live["stragglers"] == [2]
            assert live["nodes"]["2"]["step_p50"] is not None

            from dlrover_tpu.trainer.run import main as tpurun

            assert tpurun(["diagnose", "--addr", master.addr]) == 0
            assert tpurun(["diagnose", "--events", events_path]) == 0
            assert tpurun(["goodput", "--events", events_path]) == 0
        finally:
            master.stop()

    def test_runtime_hook_autowires_with_a_master_client(self):
        class Client:
            node_id = 0

            def report_node_runtime(self, **kw):
                pass

        trainer, batch = _make_trainer()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch],
            master_client=Client(),
            conf=Configuration({"runtime_report_steps": 4}),
        )
        assert any(isinstance(h, NodeRuntimeReportHook)
                   for h in executor._hooks)
        # knob 0 opts out
        executor2 = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch],
            master_client=Client(),
            conf=Configuration({"runtime_report_steps": 0}),
        )
        assert not any(isinstance(h, NodeRuntimeReportHook)
                       for h in executor2._hooks)


# -- reporting overhead gate -------------------------------------------------


class _TimedRegion(TrainHook):
    def __init__(self, warmup):
        self.warmup = warmup
        self.t0 = None

    def before_step(self, step):
        if step == self.warmup + 1 and self.t0 is None:
            self.t0 = time.perf_counter()


class TestReportingOverheadGate:
    def test_node_reporting_overhead_within_budget(self):
        """Reporting must stay observation-only: ≤5% step-loop overhead
        with the runtime-report hook at its PRODUCTION pacing (step
        cadence + the seconds_interval_to_report wall-time floor)
        pushing to a REAL master, measured as the median of
        back-to-back paired ratios (run drift on a shared 1-core box
        dwarfs the real cost). The wall-time floor is the load-bearing
        design here: per-report CPU is ~2ms, so a sub-ms-step job
        reporting every N STEPS would tax itself double digits — pacing
        by wall time makes the cost step-speed-invariant."""
        steps, warmup = 280, 8
        master = start_local_master()
        client = MasterClient(master.addr, node_id=0)
        trainer, batch = _make_trainer()

        def run(report):
            timer = _TimedRegion(warmup)
            hooks = [timer]
            if report:
                hooks.append(NodeRuntimeReportHook(client, every_steps=8,
                                                   min_interval_s=1.0))
            executor = TrainExecutor(
                trainer, train_iter_fn=lambda: [batch] * (warmup + steps),
                hooks=hooks,
                conf=Configuration({
                    "train_steps": warmup + steps, "log_every_steps": 0,
                    "train_window": 4, "preemption_grace": False,
                }),
            )
            executor.train_and_evaluate()
            return time.perf_counter() - timer.t0

        def leg(report, best_of):
            # best_of > 1: MIN over repeats — floors out the one-off
            # scheduler stalls that are this box's residual flake
            return min(run(report) for _ in range(best_of))

        def paired_median(pairs=3, best_of=1):
            ratios = []
            for i in range(pairs):
                if i % 2 == 0:
                    dt_b = leg(False, best_of)
                    dt_r = leg(True, best_of)
                else:
                    dt_r = leg(True, best_of)
                    dt_b = leg(False, best_of)
                ratios.append(dt_r / dt_b)
            return sorted(ratios)[len(ratios) // 2]

        try:
            # De-flake (ISSUE 9 satellite): one attempt's median still
            # failed ~1/3 of clean runs to box noise. Up to 3 attempts,
            # gate on the minimum of the attempt medians, stopping
            # early on the first pass. Min-selection is deliberately
            # biased low (noise can deflate a baseline leg too): the
            # accepted trade — the gate trips on LARGE regressions
            # (every attempt fails) while a clean tree stops failing
            # one run in three. See test_telemetry.py for the full
            # rationale.
            # retry attempts escalate to BEST-OF-2 legs (ISSUE 15
            # satellite; rationale in test_telemetry.py): the common
            # case stays one attempt of single-run pairs, while a
            # retry filters single-run stalls on either side
            medians = [paired_median()]
            while medians[-1] - 1.0 > 0.05 and len(medians) < 3:
                medians.append(paired_median(best_of=2))
            overhead = min(medians) - 1.0
            assert overhead <= 0.05, (
                f"node-runtime reporting overhead {overhead:.1%} above "
                f"the 5% budget (attempt medians "
                f"{[round(m, 3) for m in medians]})"
            )
            # the reports genuinely flowed (not a null comparison)
            assert master.servicer.node_runtime_store.node_ids() == [0]
        finally:
            client.close()
            master.stop()
