"""Trainer executor: conf system, hooks, train_and_evaluate loop,
failover version handshake + restart path."""

import jax
import jax.numpy as jnp
import optax
import pytest

from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.conf import (
    Configuration,
    ConfigurationManager,
    ConfigurationManagerMeta,
    build_configuration,
)
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import (
    ElasticDataShardReportHook,
    ReportModelInfoHook,
    TrainExecutor,
    TrainHook,
)
from dlrover_tpu.trainer.failover import (
    FailoverClient,
    TrainingFailover,
    VersionType,
)


class TestConfiguration:
    def test_class_merge_subclass_wins(self):
        class Base:
            lr = 0.1
            batch_size = 32
            data = {"path": "/a", "format": "tfrecord"}

        class Override(Base):
            lr = 0.01
            data = {"path": "/b"}

        conf = Configuration.from_class(Override)
        assert conf.lr == 0.01
        assert conf.batch_size == 32
        # note: class-attr merge replaces dicts (python semantics); deep
        # merge applies across build_configuration sources
        assert conf.data.path == "/b"

    def test_build_configuration_deep_merge(self):
        conf = build_configuration(
            {"train": {"steps": 100, "lr": 0.1}},
            {"train": {"lr": 0.01}},
            overrides={"eval_every_steps": 10},
        )
        assert conf.train.steps == 100
        assert conf.train.lr == 0.01
        assert conf.eval_every_steps == 10

    def test_manager_registry(self):
        ConfigurationManagerMeta.clear()

        class DataConf(ConfigurationManager):
            dataset = "mnist"

        class TrainConf(ConfigurationManager):
            lr = 0.05

        merged = ConfigurationManager.merged_configuration()
        assert merged.dataset == "mnist"
        assert merged.lr == 0.05
        ConfigurationManagerMeta.clear()


class StubMasterClient:
    """Minimal master for failover tests."""

    def __init__(self):
        self.versions = {}
        self.waiting = 0
        self.global_steps = []
        self.model_infos = []

    def get_cluster_version(self, version_type, task_type, task_id):
        return self.versions.get(version_type, 0)

    def update_cluster_version(self, version_type, version, task_type,
                               task_id, expected=-1):
        if expected >= 0 and self.versions.get(version_type, 0) != expected:
            return
        self.versions[version_type] = version

    def query_ps_nodes(self):
        class _PsNodes:
            nodes = []

        return _PsNodes()

    def num_nodes_waiting(self):
        return self.waiting

    def report_global_step(self, step, **kw):
        self.global_steps.append(step)

    def report_model_info(self, info):
        self.model_infos.append(info)

    def report_failure(self, node_rank, restart_count, error_data, level):
        if not hasattr(self, "failures"):
            self.failures = []
        self.failures.append({
            "node_rank": node_rank, "restart_count": restart_count,
            "error_data": error_data, "level": level,
        })


class TestFailoverClient:
    def test_version_handshake(self):
        client = FailoverClient(StubMasterClient())
        client.init_version()
        assert client.get_version(VersionType.GLOBAL) == 1
        assert client.get_version(VersionType.LOCAL) == 1
        assert not client.ps_cluster_changed()
        client.set_version(VersionType.GLOBAL, 2)
        assert client.ps_cluster_changed()
        client.sync_to_global()
        assert not client.ps_cluster_changed()

    def test_monitor_fires_on_waiting_nodes(self):
        master = StubMasterClient()
        fired = []
        monitor = TrainingFailover(
            master, lambda: fired.append(1), poll_interval=0.02
        )
        monitor.start()
        import time

        master.waiting = 2
        time.sleep(0.2)
        monitor.stop()
        assert fired


def _make_trainer(**kwargs):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (4, 2)), "b": jnp.zeros((2,))}

    def loss_fn(params, batch, rng):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (16, 4))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (4, 2))}
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.sgd(0.1), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)), **kwargs,
    )
    return trainer, batch


class CountingHook(TrainHook):
    def __init__(self):
        self.begins = self.steps = self.evals = self.ends = 0

    def begin(self, executor):
        self.begins += 1

    def after_step(self, step, metrics):
        self.steps += 1

    def after_evaluate(self, step, metrics):
        self.evals += 1

    def end(self, executor):
        self.ends += 1


class TestTrainExecutor:
    def test_train_and_evaluate_runs_hooks_and_eval(self):
        trainer, batch = _make_trainer()
        hook = CountingHook()

        def eval_fn(state):
            return {"eval_loss": jnp.asarray(0.5)}

        executor = TrainExecutor(
            trainer,
            train_iter_fn=lambda: [batch] * 100,
            eval_fn=eval_fn,
            hooks=[hook],
            conf=Configuration({"train_steps": 7, "eval_every_steps": 3,
                                "log_every_steps": 2}),
        )
        out = executor.train_and_evaluate()
        assert out["step"] == 7
        assert hook.begins == 1 and hook.ends == 1
        assert hook.steps == 7
        # evals at steps 3, 6 + final
        assert hook.evals == 3
        assert float(out["eval_loss"]) == 0.5

    def test_restart_rebuilds_and_continues(self):
        trainer, batch = _make_trainer()

        class RestartOnce(TrainHook):
            def __init__(self, executor_box):
                self.box = executor_box
                self.done = False

            def after_step(self, step, metrics):
                if step == 3 and not self.done:
                    self.done = True
                    self.box[0].request_restart()

        box = []
        hook = RestartOnce(box)
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 100,
            hooks=[hook],
            conf=Configuration({"train_steps": 6, "log_every_steps": 0}),
        )
        box.append(executor)
        out = executor.train_and_evaluate()
        assert out["step"] == 6
        assert hook.done

    def test_data_exhaustion_finishes(self):
        trainer, batch = _make_trainer()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 4,
            conf=Configuration({"log_every_steps": 0}),
        )
        out = executor.train_and_evaluate()
        assert out["step"] == 4

    def test_nonfinite_halt_reports_failure_and_raises(self):
        """Round-2 verdict missing #1: a NaN step must reach
        report_failure (level=process) instead of dissolving into a log
        line."""
        import pytest

        from dlrover_tpu.trainer.executor import NonFiniteLossError

        master = StubMasterClient()
        trainer, batch = _make_trainer()
        nan_batch = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
        executor = TrainExecutor(
            trainer,
            train_iter_fn=lambda: [batch, batch, nan_batch, batch],
            conf=Configuration({
                "train_steps": 10, "log_every_steps": 0,
                "check_finite_every_steps": 1, "on_nonfinite": "halt",
            }),
            master_client=master,
        )
        with pytest.raises(NonFiniteLossError):
            executor.train_and_evaluate()
        assert master.failures, "non-finite step never reported"
        report = master.failures[0]
        assert report["level"] == "process"
        assert "non-finite" in report["error_data"]

    def test_nonfinite_rollback_restores_and_continues(self):
        import tempfile

        from dlrover_tpu.checkpoint import CheckpointInterval

        master = StubMasterClient()
        with tempfile.TemporaryDirectory() as ckpt_dir:
            # save every 2 steps so a REAL checkpoint (step 2) exists
            # before the NaN at step 4 — rollback must restore it, not
            # silently reinit (the guard raises if nothing was saved)
            trainer, batch = _make_trainer(
                ckpt_dir=ckpt_dir,
                ckpt_interval=CheckpointInterval(steps=2),
            )
            nan_batch = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
            poisoned = {"armed": True}

            def batches():
                # NaN exactly once: after rollback the stream is clean
                for i in range(100):
                    if i == 3 and poisoned["armed"]:
                        poisoned["armed"] = False
                        yield nan_batch
                    else:
                        yield batch

            executor = TrainExecutor(
                trainer, train_iter_fn=batches,
                conf=Configuration({
                    "train_steps": 6, "log_every_steps": 0,
                    "check_finite_every_steps": 1,
                    "on_nonfinite": "rollback",
                }),
                master_client=master,
            )
            out = executor.train_and_evaluate()
        assert out["step"] >= 6
        assert master.failures  # reported before rolling back
        # the final state is finite: rollback discarded the NaN params
        final_loss = float(executor._trainer.accelerated.eval_step(
            executor.state, executor._trainer.accelerated.shard_batch(batch)
        )["loss"])
        assert final_loss == final_loss  # not NaN

    def test_nonfinite_final_step_off_cadence_still_fails(self):
        """A NaN landing between check cadences on the LAST step must not
        exit 0 as a success (review finding: _finish swallowed it)."""
        import pytest

        from dlrover_tpu.trainer.executor import NonFiniteLossError

        master = StubMasterClient()
        trainer, batch = _make_trainer()
        nan_batch = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
        executor = TrainExecutor(
            trainer,
            train_iter_fn=lambda: [batch, batch, batch, nan_batch],
            conf=Configuration({
                "train_steps": 4, "log_every_steps": 0,
                "check_finite_every_steps": 10,  # never fires mid-loop
                "on_nonfinite": "halt",
            }),
            master_client=master,
        )
        with pytest.raises(NonFiniteLossError, match="final step"):
            executor.train_and_evaluate()
        assert master.failures

    def test_nonfinite_rollback_without_ckpt_escalates_to_halt(self):
        import pytest

        from dlrover_tpu.trainer.executor import NonFiniteLossError

        trainer, batch = _make_trainer()  # no ckpt_dir
        nan_batch = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [nan_batch] * 4,
            conf=Configuration({
                "train_steps": 4, "log_every_steps": 0,
                "check_finite_every_steps": 1,
                "on_nonfinite": "rollback",
            }),
        )
        with pytest.raises(NonFiniteLossError, match="no.*checkpoint"):
            executor.train_and_evaluate()

    def test_nonfinite_persistent_rollback_budget_halts(self):
        import tempfile

        import pytest

        from dlrover_tpu.trainer.executor import NonFiniteLossError

        from dlrover_tpu.checkpoint import CheckpointInterval

        with tempfile.TemporaryDirectory() as ckpt_dir:
            trainer, batch = _make_trainer(
                ckpt_dir=ckpt_dir,
                ckpt_interval=CheckpointInterval(steps=1),
            )
            nan_batch = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
            executor = TrainExecutor(
                trainer,
                # every stream poisoned: rollback can never recover
                train_iter_fn=lambda: [batch, nan_batch] * 4,
                conf=Configuration({
                    "train_steps": 100, "log_every_steps": 0,
                    "check_finite_every_steps": 1,
                    "on_nonfinite": "rollback",
                    "max_nonfinite_rollbacks": 2,
                }),
            )
            with pytest.raises(NonFiniteLossError, match="rollbacks"):
                executor.train_and_evaluate()

    def test_report_hooks(self):
        master = StubMasterClient()
        trainer, batch = _make_trainer()

        class FakeShardingClient:
            def __init__(self):
                self.batches = 0

            def report_batch_done(self, n):
                self.batches += n

        shard_client = FakeShardingClient()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 10,
            hooks=[
                ElasticDataShardReportHook(shard_client, batch_size=16),
                ReportModelInfoHook(master, param_count=10,
                                    every_steps=2),
            ],
            conf=Configuration({"train_steps": 4, "log_every_steps": 0}),
        )
        executor.train_and_evaluate()
        # one BATCH credit per materialized step (the client converts
        # to records itself — crediting batch_size per step would
        # over-complete shards batch_size-fold on the master)
        assert shard_client.batches == 4
        assert master.global_steps == [2, 4]
        assert len(master.model_infos) == 1


class LossRecorderHook(TrainHook):
    """step -> bit-exact loss, recorded at (lagged) materialization."""

    def __init__(self):
        self.losses = {}

    def after_step(self, step, metrics):
        self.losses[step] = float(metrics["loss"])


class TestDispatchWindow:
    """The async dispatch pipeline: bounded in-flight window + lax.scan
    multi-step fusion (ISSUE 3). Parity, lagged non-finite rollback at
    an in-window offset, and preemption draining the window."""

    def _run(self, window, steps_per_call=1, train_steps=16, hooks=None,
             **trainer_kwargs):
        trainer, batch = _make_trainer(
            steps_per_call=steps_per_call, **trainer_kwargs
        )
        recorder = LossRecorderHook()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 200,
            hooks=[recorder] + list(hooks or []),
            conf=Configuration({
                "train_steps": train_steps, "log_every_steps": 0,
                "train_window": window,
            }),
        )
        out = executor.train_and_evaluate()
        return out, executor, recorder

    def test_window_and_scan_bitwise_parity_with_sync(self):
        import numpy as np

        out0, ex0, rec0 = self._run(window=0)
        out1, ex1, rec1 = self._run(window=4)
        out2, ex2, rec2 = self._run(window=4, steps_per_call=8)
        assert out0["step"] == out1["step"] == out2["step"] == 16
        # every per-step loss identical (the lagged ring reorders WHEN
        # metrics are read, never WHAT was computed)
        assert rec0.losses == rec1.losses == rec2.losses
        for a, b in ((ex1, ex0), (ex2, ex0)):
            for la, lb in zip(jax.tree.leaves(a.state.params),
                              jax.tree.leaves(b.state.params)):
                assert np.asarray(la).tobytes() == np.asarray(lb).tobytes()

    def test_partial_tail_group_dispatches_single_steps(self):
        # train_steps not divisible by steps_per_call: the remainder
        # runs through the single-step program (no recompile of the
        # scanned one), and the step count is exact
        out, ex, rec = self._run(window=2, steps_per_call=8,
                                 train_steps=13)
        assert out["step"] == 13
        assert sorted(rec.losses) == list(range(1, 14))

    @pytest.mark.parametrize("offset", [0, 2])
    def test_nan_at_in_window_offset_rolls_back_and_continues(
            self, tmp_path, offset):
        """A NaN landing ``offset`` dispatches deep inside the in-flight
        window is detected up to W steps LATE, rolls back through the
        existing checkpoint path, and training continues (acceptance:
        chaos-NaN at an arbitrary in-window offset)."""
        from dlrover_tpu.checkpoint import CheckpointInterval

        master = StubMasterClient()
        trainer, batch = _make_trainer(
            ckpt_dir=str(tmp_path / "ckpt"),
            ckpt_interval=CheckpointInterval(steps=2),
        )
        nan_batch = {"x": batch["x"] * jnp.nan, "y": batch["y"]}
        poisoned = {"armed": True}
        nan_step = 5 + offset  # window=4: NaN sits mid-window when seen

        def batches():
            for i in range(100):
                if i == nan_step - 1 and poisoned["armed"]:
                    poisoned["armed"] = False
                    yield nan_batch
                else:
                    yield batch

        executor = TrainExecutor(
            trainer, train_iter_fn=batches,
            conf=Configuration({
                "train_steps": 12, "log_every_steps": 0,
                "check_finite_every_steps": 1,
                "on_nonfinite": "rollback",
                "train_window": 4,
            }),
            master_client=master,
        )
        out = executor.train_and_evaluate()
        assert out["step"] >= 12
        assert master.failures  # lagged detection still reported
        final_loss = float(executor._trainer.accelerated.eval_step(
            executor.state,
            executor._trainer.accelerated.shard_batch(batch),
        )["loss"])
        assert final_loss == final_loss  # not NaN

    def test_preemption_drains_window_saves_materialized_step(
            self, tmp_path):
        """A preemption notice with W calls in flight drains the window
        first: the emergency checkpoint lands at the last materialized
        (= last dispatched, post-drain) step, and a resumed run replays
        the remaining steps with EXACT loss parity vs the synchronous
        loop over the same batch stream."""
        import signal

        # the reference run: synchronous, uninterrupted
        _, ex_sync, rec_sync = self._run(window=0, train_steps=20)

        class PreemptAt(TrainHook):
            def __init__(self, box, at_step):
                self.box, self.at = box, at_step

            def before_step(self, step):
                if step == self.at:  # dispatch-time, window non-empty
                    self.box[0]._preempted = signal.SIGTERM

        box = []
        hook = PreemptAt(box, at_step=11)
        trainer, batch = _make_trainer(
            ckpt_dir=str(tmp_path / "ckpt"), steps_per_call=1,
        )
        recorder = LossRecorderHook()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * 200,
            hooks=[recorder, hook],
            conf=Configuration({"train_steps": 20, "log_every_steps": 0,
                                "train_window": 4}),
        )
        box.append(executor)
        out = executor.train_and_evaluate()
        assert out["preempted"] is True
        killed_step = out["step"]
        assert killed_step >= 11
        # drained: every dispatched step was materialized before the save
        assert sorted(recorder.losses) == list(range(1, killed_step + 1))
        saved = trainer.latest_checkpoint_step()
        assert saved == killed_step, (saved, killed_step)

        # resume: a fresh trainer restores the emergency save and the
        # remaining steps' losses match the sync run bit-for-bit.
        # The rng stream advances one split per step from PRNGKey(0);
        # replaying the restored step count realigns it exactly.
        trainer2, _ = _make_trainer(ckpt_dir=str(tmp_path / "ckpt"))
        for _ in range(killed_step):
            trainer2._rng, _drop = jax.random.split(trainer2._rng)
        recorder2 = LossRecorderHook()
        executor2 = TrainExecutor(
            trainer2, train_iter_fn=lambda: [batch] * 200,
            hooks=[recorder2],
            conf=Configuration({"train_steps": 20, "log_every_steps": 0,
                                "train_window": 4}),
        )
        out2 = executor2.train_and_evaluate()
        assert out2["step"] == 20
        for s in range(killed_step + 1, 21):
            assert recorder2.losses[s] == rec_sync.losses[s], s

    def test_tpurun_parser_exposes_dispatch_knobs(self):
        from dlrover_tpu.trainer.run import build_parser

        args = build_parser().parse_args(
            ["--train_window", "2", "--steps_per_call", "8", "t.py"]
        )
        assert args.train_window == 2 and args.steps_per_call == 8

    def test_context_env_overrides(self, monkeypatch):
        from dlrover_tpu.common.config import Context

        monkeypatch.setenv("DLROVER_TPU_TRAIN_WINDOW", "7")
        monkeypatch.setenv("DLROVER_TPU_STEPS_PER_CALL", "3")
        ctx = Context()
        assert ctx.train_window == 7
        assert ctx.steps_per_call == 3

    def test_report_hooks_identical_across_window_settings(self):
        # the lagged ring changes WHEN report hooks fire, never WHAT
        # they report: sync (0) and windowed (4) runs must produce the
        # same shard counts and global-step reports
        results = {}
        for window in (0, 4):
            master = StubMasterClient()
            trainer, batch = _make_trainer()

            class FakeShardingClient:
                def __init__(self):
                    self.batches = 0

                def report_batch_done(self, n):
                    self.batches += n

            shard_client = FakeShardingClient()
            executor = TrainExecutor(
                trainer, train_iter_fn=lambda: [batch] * 10,
                hooks=[
                    ElasticDataShardReportHook(shard_client,
                                               batch_size=16),
                    ReportModelInfoHook(master, param_count=10,
                                        every_steps=2),
                ],
                conf=Configuration({"train_steps": 4,
                                    "log_every_steps": 0,
                                    "train_window": window}),
            )
            executor.train_and_evaluate()
            results[window] = (shard_client.batches,
                               master.global_steps,
                               len(master.model_infos))
        assert results[0] == results[4] == (4, [2, 4], 1)
