"""The closed auto-scaling loop, end to end (round-2 verdict #5).

Reference path (SURVEY §3.4, ``dlrover/python/master/node/
job_auto_scaler.py:154``): worker global-step reports -> SpeedMonitor ->
runtime stats -> resource optimizer plan -> ScalePlan -> scaler launches
a node -> the new agent joins the rendezvous -> the existing agent
restarts its workers into the bigger world.

Everything here is real: a live DistributedJobMaster with its gRPC
servicer, a LocalProcessScaler spawning REAL tpurun agent subprocesses,
real worker subprocesses reporting steps over the wire, and a real
second rendezvous at world size 2.
"""

import os
import sys
import threading
import time

import pytest

from dlrover_tpu.common.config import get_context
from dlrover_tpu.common.constants import NodeType

TESTDATA = os.path.join(os.path.dirname(__file__), "testdata")
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def fast_ctx():
    """Shrink the control-loop cadences; restore after the test."""
    ctx = get_context()
    saved = {
        k: getattr(ctx, k)
        for k in (
            "seconds_interval_to_report",
            "seconds_for_stable_worker_count",
            "seconds_interval_to_optimize",
            "seconds_between_scale_plans",
            "auto_scale_enabled",
        )
    }
    ctx.seconds_interval_to_report = 0.3
    ctx.seconds_for_stable_worker_count = 1.0
    ctx.seconds_interval_to_optimize = 0.5
    ctx.seconds_between_scale_plans = 30
    ctx.auto_scale_enabled = True
    yield ctx
    for k, v in saved.items():
        setattr(ctx, k, v)


@pytest.mark.slow
def test_speed_to_plan_to_scaler_to_new_rendezvous(fast_ctx, tmp_path):
    from dlrover_tpu.master.dist_master import DistributedJobMaster
    from dlrover_tpu.master.scaler.process_scaler import LocalProcessScaler
    from dlrover_tpu.master.watcher.process_watcher import (
        LocalProcessWatcher,
    )
    from dlrover_tpu.scheduler.job import local_job_args
    from dlrover_tpu.scheduler.local import LocalProcessBackend

    marker = tmp_path / "scaled_world"
    worker_script = os.path.join(TESTDATA, "autoscale_worker.py")

    def agent_command(node):
        # a REAL tpurun agent per node: master addr + node rank arrive
        # via the scaler's NodeEnv contract
        return [
            sys.executable, "-m", "dlrover_tpu.trainer.run",
            "--nnodes", "1:4",
            "--rdzv_waiting_timeout", "2.0",
            "--monitor_interval", "0.3",
            "--max_restarts", "3",
            worker_script,
        ]

    backend = LocalProcessBackend()
    args = local_job_args("autoscale-e2e", node_num=1)
    scaler = LocalProcessScaler(
        "autoscale-e2e", backend, "",
        command_factory=agent_command,
        extra_env={
            "PYTHONPATH": REPO_ROOT + os.pathsep
            + os.environ.get("PYTHONPATH", ""),
            "AUTOSCALE_MARKER": str(marker),
            "JAX_PLATFORMS": "cpu",
        },
    )
    master = DistributedJobMaster(
        job_args=args,
        scaler=scaler,
        watcher=LocalProcessWatcher(backend, poll_secs=0.1),
    )
    master.prepare()
    rc_box = {}

    def run_master():
        rc_box["rc"] = master.run()

    thread = threading.Thread(target=run_master, daemon=True)
    thread.start()
    try:
        deadline = time.time() + 120
        while time.time() < deadline and not marker.exists():
            time.sleep(0.5)
        assert marker.exists(), (
            "auto-scaling loop never produced a 2-node world "
            f"(auto_scaler started={master.job_auto_scaler.started}, "
            f"samples={master.speed_monitor.sample_count})"
        )
        assert marker.read_text().strip() == "2"
        # the loop actually flowed through the scaler: two worker nodes
        # exist in the job manager (original + scale-up)
        workers = master.job_manager.get_job_nodes(NodeType.WORKER)
        assert len(workers) >= 2
        # and the rendezvous re-formed at world size 2
        rdzv = master.rdzv_managers["elastic-training"]
        assert len(rdzv.world_dict()) == 2
        # job runs to completion after the scaled workers exit 0
        thread.join(timeout=60)
        assert rc_box.get("rc") == 0
    finally:
        master.stop()
        backend.stop_all()
