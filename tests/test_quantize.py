"""Low-precision everything (ISSUE 11): block-scaled fp8 MoE dispatch +
wire-compressed collectives, priced by the planner and audited by the
lint.

Pins, per the acceptance criteria:

  * fp8 ``grouped_ep`` matches the quantize→dequant reference oracle
    ("fp8_qdq" — identical math, full-precision wire) EXACTLY fwd+bwd
    on the 4-way CPU mesh, ``dropped_frac == 0``, zero recompiles;
  * ``grouped_matmul_quantized`` (dequant-in-kernel) is bitwise equal
    to dequantize-then-``grouped_matmul``, forward and dw;
  * quantize/dequant round-trip properties: block-scale shapes, zero
    blocks, denormals, error bounds;
  * the precision knob resolves config > Context(env) > default, keys
    the program cache, prewarm+retunes with ZERO recompiles, and the
    optimizer's candidate key / churn / blacklist carry it;
  * ``planner.estimate`` carries ``moe_disp_comm_bf16_s`` twins with
    quantized <= bf16 pinned both directions, and
    ``predicted_collective_bytes`` matches the wire-bytes formula the
    G106 audit is compared against;
  * the e2e replan wedge: the optimizer prices the precision family,
    chooses fp8 for a comm-bound MoE job, and the worker applies it
    live through the prewarmed program cache with zero recompiles;
  * G109 fires on a drifting fixture and is clean on HEAD against the
    committed ``quant_baseline.json``.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.models import llama
from dlrover_tpu.ops.moe import (
    MoEConfig,
    init_moe_params,
    moe_ffn,
    resolve_moe_precision,
)
from dlrover_tpu.ops.quantize import (
    FP8_MAX,
    dequantize_block_scaled,
    quantize_block_scaled,
    resolve_quant_block,
)
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.planner import (
    DeviceSpec,
    ModelSpec,
    estimate,
    model_spec_from_llama,
    predicted_collective_bytes,
)
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.trainer.elastic import ElasticTrainer


@pytest.fixture(autouse=True)
def _telemetry_on():
    ctx = get_context()
    prev = ctx.telemetry_enabled
    ctx.telemetry_enabled = True
    yield
    ctx.telemetry_enabled = prev


# -- quantize/dequant round-trip properties -----------------------------------


class TestQuantizeRoundTrip:
    def test_block_scale_shapes(self):
        x = jnp.asarray(np.random.RandomState(0).randn(5, 7, 64),
                        jnp.float32)
        v, s = quantize_block_scaled(x)
        assert v.shape == x.shape and v.dtype == jnp.float8_e4m3fn
        assert s.shape == (5, 7, 64 // resolve_quant_block(64))
        assert s.dtype == jnp.float32

    def test_resolve_quant_block_divides(self):
        assert resolve_quant_block(64) == 32
        assert resolve_quant_block(16) == 16
        assert resolve_quant_block(48) == 24  # largest divisor <= 32
        assert resolve_quant_block(7) == 7
        assert 96 % resolve_quant_block(96) == 0

    def test_indivisible_block_raises(self):
        x = jnp.zeros((2, 10), jnp.float32)
        with pytest.raises(ValueError, match="does not divide"):
            quantize_block_scaled(x, block=4)  # 10 % 4 != 0

    def test_zero_blocks_encode_to_exact_zeros(self):
        """An all-zero block must not divide by zero: the scale clamps
        to 1.0 and the rows decode to exact zeros — the property the
        dispatch's zero-sentinel pad rows rely on."""
        x = jnp.zeros((4, 64), jnp.float32)
        v, s = quantize_block_scaled(x)
        assert np.all(np.asarray(s) == 1.0)
        assert np.all(np.asarray(dequantize_block_scaled(v, s)) == 0.0)

    def test_denormal_blocks_rescale_into_range(self):
        """Values far below e4m3's smallest normal up-scale into range
        (scale = amax/448): a uniform tiny block round-trips exactly
        (its max lands on the representable 448), random tiny blocks
        keep e4m3 relative precision instead of flushing to zero."""
        tiny = jnp.full((2, 64), 1e-20, jnp.float32)
        v, s = quantize_block_scaled(tiny)
        np.testing.assert_array_equal(
            np.asarray(dequantize_block_scaled(v, s)), np.asarray(tiny))
        rnd = jnp.asarray(
            np.random.RandomState(0).randn(4, 64) * 1e-18, jnp.float32)
        back = np.asarray(dequantize_block_scaled(
            *quantize_block_scaled(rnd)))
        assert np.all(back[np.asarray(rnd) != 0] != 0)

    def test_deep_denormal_scale_floors_instead_of_minting_nan(self):
        """A block whose max is nonzero but so small that amax/448
        underflows must NOT divide by a flushed-to-zero scale (inf ->
        NaN in e4m3): the scale floors at the smallest normal f32 and
        the block encodes to finite values (zeros — below fp8's
        resolution). Guards the flush-to-zero (TPU) backend contract."""
        x = jnp.full((2, 64), 1e-43, jnp.float32)  # subnormal f32
        v, s = quantize_block_scaled(x)
        assert np.all(np.asarray(s) >= np.finfo(np.float32).tiny)
        back = np.asarray(dequantize_block_scaled(v, s))
        assert np.all(np.isfinite(back))

    def test_error_bound_relative_to_block_max(self):
        """The block-scaled contract: every element's round-trip error
        is bounded by its BLOCK's max (e4m3's 3 mantissa bits: half an
        ulp at the top of the range = amax * 2^-4) — per-element
        relative error is unbounded for tiny values sharing a block
        with a large one, which is exactly the trade the 32-channel
        neighborhood keeps local."""
        x = np.random.RandomState(1).randn(64, 64).astype(np.float32) * 10
        v, s = quantize_block_scaled(jnp.asarray(x))
        back = np.asarray(dequantize_block_scaled(v, s))
        amax = np.abs(x.reshape(64, 2, 32)).max(axis=-1)  # per block
        err = np.abs(back - x).reshape(64, 2, 32)
        assert np.all(err <= amax[:, :, None] * 2.0 ** -4 + 1e-7)
        # and the block max is representable at the top of the range
        assert float(jnp.max(jnp.abs(v.astype(jnp.float32)))) \
            == pytest.approx(FP8_MAX)


# -- the dequant-in-kernel grouped matmul -------------------------------------


class TestGroupedMatmulQuantized:
    def _case(self):
        from dlrover_tpu.ops.grouped_matmul import (
            grouped_matmul,
            grouped_matmul_quantized,
        )

        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(256, 64), jnp.float32)
        w = jnp.asarray(rng.randn(4, 64, 96), jnp.float32)
        te = jnp.asarray([0, 1, 2, 3], jnp.int32)  # block_t=64 tiles
        v, s = quantize_block_scaled(x)
        xd = dequantize_block_scaled(v, s)
        return grouped_matmul, grouped_matmul_quantized, v, s, xd, w, te

    def test_fwd_bitwise_equals_dequant_reference(self):
        """The oracle contract: dequant IN KERNEL == dequant outside
        then the plain kernel, bit for bit (the multiply runs in f32 at
        the same point of the computation either way)."""
        gm, gmq, v, s, xd, w, te = self._case()
        y_ref = gm(xd, w, te, 64, 512, True)
        y_q = gmq(v, s, w, te, 64, 512, True)
        assert np.asarray(y_q).tobytes() == np.asarray(y_ref).tobytes()

    def test_dw_bitwise_equals_dequant_reference(self):
        gm, gmq, v, s, xd, w, te = self._case()
        g_ref = jax.grad(
            lambda w_: (gm(xd, w_, te, 64, 512, True) ** 2).sum())(w)
        g_q = jax.grad(
            lambda w_: (gmq(v, s, w_, te, 64, 512, True) ** 2).sum())(w)
        assert np.asarray(g_q).tobytes() == np.asarray(g_ref).tobytes()


# -- fp8 grouped_ep vs the quantize→dequant oracle (4-way CPU mesh) -----------


class TestFp8GroupedEp:
    E = 8
    P = 4  # the 4-way expert submesh the acceptance names

    def _mesh(self):
        from jax.sharding import Mesh

        return Mesh(np.array(jax.devices()[:self.P]), ("expert",))

    def _params_x(self, d=16, f=32, b=2, s=16):
        rng = np.random.RandomState(0)
        params = init_moe_params(jax.random.PRNGKey(0), d, f, self.E)
        x = jnp.asarray(rng.randn(b, s, d), jnp.float32)
        return params, x

    def _cfg(self, precision, chunks=1):
        return MoEConfig(num_experts=self.E, top_k=2,
                         dispatch="grouped_ep", ep_axes=("expert",),
                         mesh=self._mesh(), dispatch_chunks=chunks,
                         precision=precision)

    def _grad_fn(self, cfg):
        def loss(p, x):
            o, a, m = moe_ffn(p, x, cfg, train=False)
            return (o.astype(jnp.float32) ** 2).sum() + a, m

        # jit: interpret-mode kernels trace once instead of re-running
        # op by op (the PR 10 lesson)
        return jax.jit(jax.value_and_grad(
            loss, argnums=(0, 1), has_aux=True))

    def test_fp8_matches_qdq_oracle_bitwise_fwd_bwd(self):
        """The acceptance pin: the fp8 wire (quantized exchange,
        dequant-in-kernel, quantized backward cotangents) is BITWISE
        equal to the quantize→dequant reference with a full-precision
        wire — fwd and bwd, at C in {1, 2} — and nothing is dropped.
        Quantization commutes with the row permutation; any deviation
        means the wire changed the math."""
        params, x = self._params_x()
        for chunks in (1, 2):
            (l_q, m_q), g_q = self._grad_fn(
                self._cfg("fp8", chunks))(params, x)
            (l_r, _), g_r = self._grad_fn(
                self._cfg("fp8_qdq", chunks))(params, x)
            assert float(l_q) == float(l_r), f"loss differs at C={chunks}"
            assert float(m_q["dropped_frac"]) == 0.0
            for a, b in zip(jax.tree.leaves(g_q), jax.tree.leaves(g_r)):
                assert np.asarray(a).tobytes() == np.asarray(b).tobytes(), \
                    f"grad differs at C={chunks}"

    # NOTE: "fp8 stays close to bf16" is covered by the G109 drift
    # audit below (quantization_drift_audit measures exactly that on
    # the llama twin pair) — no separate micro test, the tier-1 budget
    # is a first-class constraint on this 1-core box.

    def test_zero_recompiles_across_steps_fp8(self):
        params, x0 = self._params_x()
        cfg = self._cfg("fp8", chunks=2)

        @jax.jit
        def step(p, x):
            o, a, m = moe_ffn(p, x, cfg, train=False)
            return o.sum() + a, m["dropped_frac"]

        rs = np.random.RandomState(7)
        for i in range(3):
            if i == 2:  # adversarial: skew all tokens onto one expert
                p = dict(params)
                p["router"]["kernel"] = (
                    params["router"]["kernel"].at[:, 0].add(50.0)
                )
                _, dropped = step(p, jnp.asarray(
                    rs.randn(*x0.shape), jnp.float32))
                assert float(dropped) == 0.0
            else:
                step(params, jnp.asarray(
                    rs.randn(*x0.shape), jnp.float32))
        assert step._cache_size() == 1

    def test_probe_failure_degrades_to_bf16(self, monkeypatch):
        from dlrover_tpu.ops import shard_compat

        monkeypatch.setattr(shard_compat, "_FP8_WIRE_SUPPORTED", False)
        assert resolve_moe_precision(
            MoEConfig(num_experts=4, precision="fp8")) == "bf16"


# -- knob resolution order: config > env(Context) > default -------------------


class TestPrecisionKnobResolution:
    def test_explicit_config_wins(self, monkeypatch):
        monkeypatch.setattr(get_context(), "moe_precision", "bf16")
        assert resolve_moe_precision(
            MoEConfig(num_experts=4, precision="fp8")) == "fp8"

    def test_empty_config_resolves_context(self, monkeypatch):
        monkeypatch.setattr(get_context(), "moe_precision", "fp8")
        assert resolve_moe_precision(MoEConfig(num_experts=4)) == "fp8"

    def test_default_is_bf16(self, monkeypatch):
        monkeypatch.setattr(get_context(), "moe_precision", "bf16")
        assert resolve_moe_precision(MoEConfig(num_experts=4)) == "bf16"

    def test_unknown_precision_raises(self):
        with pytest.raises(ValueError, match="unknown MoE precision"):
            resolve_moe_precision(
                MoEConfig(num_experts=4, precision="int3"))

    def test_llama_spec_resolves_context_precision(self, monkeypatch):
        cfg = llama.llama_tiny(num_experts=8,
                               moe_dispatch="grouped_ep")
        monkeypatch.setattr(get_context(), "moe_precision", "fp8")
        assert model_spec_from_llama(cfg, 8).moe_precision == "fp8"
        cfg2 = llama.llama_tiny(num_experts=8,
                                moe_dispatch="grouped_ep",
                                moe_precision="bf16")
        assert model_spec_from_llama(cfg2, 8).moe_precision == "bf16"


# -- planner: dtype-aware bytes + breakdown twins -----------------------------


def _moe_spec(precision="bf16", **over):
    base = dict(
        param_count=25_000_000_000, num_layers=32, hidden_size=4096,
        seq_len=8192, global_batch=64, num_experts=64, moe_top_k=2,
        moe_dispatch="grouped_ep", moe_precision=precision,
    )
    base.update(over)
    return ModelSpec(**base)


class TestPlannerPrecision:
    DEV = DeviceSpec(hbm_bytes=95e9)
    MESH = MeshPlan(data=4, fsdp=16)

    def test_wire_bytes_formula(self):
        """The ONE formula the pricing, the audit and the bench read:
        fp8 = 1 byte of values + 4/block bytes of scale side-band per
        element; bf16 = dtype_bytes."""
        spec = _moe_spec("fp8")
        assert spec.moe_wire_bytes_per_elem() == 1.0 + 4.0 / 32.0
        assert _moe_spec("bf16").moe_wire_bytes_per_elem() == 2.0

    def test_predicted_bytes_match_the_audit_source_formula(self):
        b_bf = predicted_collective_bytes(
            self.MESH, _moe_spec("bf16"), self.DEV)
        b_q = predicted_collective_bytes(
            self.MESH, _moe_spec("fp8"), self.DEV)
        ratio = b_q["moe_dispatch"] / b_bf["moe_dispatch"]
        assert ratio == pytest.approx((1.0 + 4.0 / 32.0) / 2.0)
        # only the dispatch family changes: the other wires are
        # untouched by the MoE precision knob
        for k in ("tp", "fsdp", "dp", "seq", "pipe"):
            assert b_q[k] == b_bf[k]

    def test_breakdown_twins_and_monotonicity_both_directions(self):
        """The acceptance pin: quantized comm seconds <= bf16, checked
        both directions, with the bf16 twin invariant (it is the same
        exchange priced at the compute dtype)."""
        bf = estimate(self.MESH, _moe_spec("bf16"), self.DEV).breakdown
        q = estimate(self.MESH, _moe_spec("fp8"), self.DEV).breakdown
        assert bf["moe_disp_comm_s"] == bf["moe_disp_comm_bf16_s"]
        assert q["moe_disp_comm_s"] <= q["moe_disp_comm_bf16_s"]
        assert q["moe_disp_comm_bf16_s"] == bf["moe_disp_comm_s"]
        assert q["moe_disp_comm_s"] < bf["moe_disp_comm_s"]
        # and back: pricing the quantized spec at bf16 recovers the
        # serial figure exactly
        assert q["moe_disp_comm_bf16_serial_s"] \
            == bf["moe_disp_comm_serial_s"]

    def test_step_time_non_increasing_under_fp8(self):
        bf = estimate(self.MESH, _moe_spec("bf16"), self.DEV)
        q = estimate(self.MESH, _moe_spec("fp8"), self.DEV)
        assert q.step_time_s <= bf.step_time_s

    def test_qdq_reference_prices_its_actual_f32_wire(self):
        """The oracle exchanges DEQUANTIZED f32 rows (that is its
        point): it prices at 4 bytes/elem — never at bytes it does not
        save, so it can never win a ranking."""
        ref = _moe_spec("fp8_qdq")
        assert ref.moe_wire_bytes_per_elem() == 4.0

    def test_precision_composes_with_chunks(self):
        """The two knobs are orthogonal: chunking reshapes the exposed
        share, precision reshapes the bytes — fp8+C=4 is <= each alone."""
        both = estimate(self.MESH,
                        _moe_spec("fp8", moe_dispatch_chunks=4),
                        self.DEV).breakdown
        only_c = estimate(self.MESH,
                          _moe_spec("bf16", moe_dispatch_chunks=4),
                          self.DEV).breakdown
        only_p = estimate(self.MESH, _moe_spec("fp8"),
                          self.DEV).breakdown
        assert both["moe_disp_comm_s"] <= only_c["moe_disp_comm_s"]
        assert both["moe_disp_comm_s"] <= only_p["moe_disp_comm_s"]


# -- the optimizer's precision knob family ------------------------------------


class _Store:
    def __init__(self):
        self._s = {}

    def node_ids(self):
        return list(self._s)

    def latest(self, nid):
        return self._s.get(nid)


class _Snap:
    def __init__(self, step_p50):
        import time

        self.ts = time.time()
        self.step_p50 = step_p50
        self.dispatch_p50 = None
        self.exposed_comm_frac = None
        self.input_wait_frac = None


def _moe_model_info():
    return comm.ModelInfo(
        num_params=25_000_000_000, hidden_size=4096, num_layers=32,
        seq_len=8192, num_experts=64, moe_top_k=2, ffn_mult=2.7,
    )


def _running_report(moe_dispatch="grouped_ep", precision="bf16"):
    return comm.TrainerConfigReport(
        node_id=0, world=64, mesh_shape={"data": 4, "fsdp": 16},
        train_window=4, steps_per_call=1, moe_dispatch=moe_dispatch,
        dispatch_chunks=1, moe_precision=precision, global_batch=64,
    )


class TestOptimizerPrecisionKnob:
    def _opt(self, store, published):
        from dlrover_tpu.master.optimizer import RuntimeOptimizer

        return RuntimeOptimizer(
            store, publish=published.append, mesh_candidates=False,
            device=DeviceSpec(hbm_bytes=95e9), min_speedup=1.02,
        )

    def test_precision_family_enumerated_only_for_grouped_ep(self):
        store = _Store()
        store._s[0] = _Snap(16.6)
        opt = self._opt(store, [])
        opt.update_model_info(_moe_model_info())
        opt.update_running_config(_running_report("gather"))
        *_, precision_opts, _fsdp_opts = opt._knob_options(opt._running)
        assert precision_opts == ["bf16"]  # parked off grouped_ep
        opt.update_running_config(_running_report("grouped_ep"))
        *_, precision_opts, _fsdp_opts = opt._knob_options(opt._running)
        assert precision_opts == ["bf16", "fp8"]

    def test_replan_chooses_and_publishes_a_precision_plan(self):
        """Comm-bound grouped_ep spec → the fp8 wire wins (alone or
        composed with chunking); unchanged knobs publish as sentinels."""
        store = _Store()
        store._s[0] = _Snap(16.6)
        published = []
        opt = self._opt(store, published)
        opt.update_model_info(_moe_model_info())
        opt.update_running_config(_running_report())
        d = opt.replan("test")
        assert d.outcome == "chosen"
        assert d.chosen["moe_precision"] == "fp8"
        cfg = published[0]
        assert cfg.moe_precision == "fp8"
        assert cfg.steps_per_call == 0  # sentinel: unchanged
        assert cfg.mesh_shape is None
        assert cfg.moe_dispatch == ""

    def test_candidate_key_carries_precision(self):
        """The cooldown/blacklist identity must distinguish precisions
        or a failed fp8 apply would blacklist the bf16 twin too."""
        from dlrover_tpu.master.optimizer.runtime_optimizer import (
            CandidateScore,
        )

        a = CandidateScore(mesh=MeshPlan(data=8), steps_per_call=1,
                           train_window=4, moe_dispatch="grouped_ep",
                           moe_precision="bf16")
        b = CandidateScore(mesh=MeshPlan(data=8), steps_per_call=1,
                           train_window=4, moe_dispatch="grouped_ep",
                           moe_precision="fp8")
        assert a.key != b.key
        assert "|p=fp8" in b.key

    def test_failed_apply_blacklists_the_precision_tuple(self):
        store = _Store()
        store._s[0] = _Snap(16.6)
        opt = self._opt(store, [])
        opt.update_model_info(_moe_model_info())
        opt.update_running_config(_running_report())
        d = opt.replan("test")
        assert d.outcome == "chosen"
        key = d.chosen_key
        assert "|p=fp8" in key
        opt.update_running_config(comm.TrainerConfigReport(
            node_id=0, world=64, mesh_shape={"data": 4, "fsdp": 16},
            train_window=4, steps_per_call=1,
            moe_dispatch="grouped_ep", dispatch_chunks=1,
            moe_precision="bf16", global_batch=64,
            plan_id=d.plan_id, apply_failed=True,
        ))
        assert key in opt._failed_keys
        # the blacklisted tuple never re-publishes
        d2 = opt.replan("retry")
        assert d2 is None or (d2.chosen or {}).get("key") != key
        if d2 is not None and d2.outcome == "chosen":
            assert d2.chosen_key != key


# -- live apply: retune/prewarm through the program cache ---------------------


def _moe_trainer(precision="bf16", **kwargs):
    cfg = llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep")
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, size=(8, 17))
    batch = {
        "input_ids": jnp.asarray(ids[:, :-1]),
        "labels": jnp.asarray(ids[:, 1:]),
    }
    trainer = ElasticTrainer(
        llama.make_init_fn(cfg),
        llama.make_loss_fn(cfg),
        optax.adafactor(1e-3),
        batch,
        strategy=Strategy(mesh=MeshPlan(data=2, fsdp=2, tensor=2),
                          rule_set="moe_ep"),
        moe_precision=precision,
        model_spec=model_spec_from_llama(
            llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep",
                             moe_precision=precision or "bf16"), 8),
        **kwargs,
    )
    return trainer, batch


class TestRetunePrecisionZeroRecompile:
    # the ~16 s retune e2e is slow-marked per the ISSUE 12 tier-1
    # triage: the prewarm→retune→program-cache mechanics are
    # knob-agnostic and stay tier-1 via PR 7's test_optimizer e2e
    # wedges plus the newest family's gate (test_fsdp_wire
    # TestRetuneFsdpPrecisionZeroRecompile); the precision knob's OWN
    # identity keeps its cheap tier-1 pins (program key, plan-hook
    # routing) below
    @pytest.mark.slow
    def test_prewarmed_precision_retune_swaps_with_zero_recompiles(self):
        """The acceptance gate: retune() across precisions through the
        program cache — a prewarmed fp8 wire applies with ZERO
        recompiles, and retuning BACK hits the original program."""
        trainer, batch = _moe_trainer()
        state = trainer.prepare()
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])
        assert trainer.moe_precision == "bf16"

        compiled = trainer.prewarm(moe_precision="fp8")
        assert compiled  # fp8 is a new program
        assert trainer.moe_precision == "bf16"  # prewarm must not switch
        assert get_context().moe_precision == "bf16"

        before = trainer.compile_count
        state = trainer.retune(state, moe_precision="fp8")
        assert trainer.compile_count == before  # ZERO recompiles
        assert trainer.moe_precision == "fp8"
        assert get_context().moe_precision == "fp8"  # trace knob pinned
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])

        # back to bf16: the startup program is still in the cache
        before = trainer.compile_count
        state = trainer.retune(state, moe_precision="bf16")
        assert trainer.compile_count == before
        assert trainer.moe_precision == "bf16"
        state, m = trainer.step(state, batch)
        assert bool(m["finite"])

    def test_program_key_distinguishes_precisions(self):
        trainer, _ = _moe_trainer()
        strategy = trainer._resolved_strategy(8)
        k_bf = trainer._program_key(jax.devices(), strategy)
        trainer.moe_precision = "fp8"
        k_q = trainer._program_key(jax.devices(), strategy)
        assert k_bf != k_q and "|p=fp8" in k_q


class TestPlanHookRoutesPrecision:
    def test_precision_plan_reaches_request_retune(self):
        from dlrover_tpu.trainer.executor import OptimizerPlanHook

        class _Ex:
            def __init__(self):
                self.retunes = []

            def request_retune(self, **kw):
                self.retunes.append(kw)

        class _Client:
            def get_parallel_config(self):
                return comm.ParallelConfig(
                    moe_precision="fp8", plan_id="plan-p8",
                    trace_id="inc-p", predicted_speedup=1.4)

        hook = OptimizerPlanHook(_Client(), poll_secs=0)
        ex = _Ex()
        hook._executor = ex
        hook.poll_once()
        assert ex.retunes[0]["moe_precision"] == "fp8"
        assert ex.retunes[0]["steps_per_call"] is None
        assert ex.retunes[0]["dispatch_chunks"] is None
        assert ex.retunes[0]["plan_id"] == "plan-p8"


# -- the replan e2e wedge: master → RPC → live fp8 apply ----------------------


def _small_moe_model_info():
    """Fits the 8-device CPU mesh under the v5e-ish memory gate while
    staying dispatch-comm-bound, so the precision family wins the
    wedge's ranking honestly (the chunk-wedge spec, reused)."""
    return comm.ModelInfo(
        num_params=200_000_000, hidden_size=2048, num_layers=16,
        seq_len=4096, num_experts=32, moe_top_k=2, ffn_mult=2.7,
    )


@pytest.mark.slow
class TestPrecisionReplanWedge:
    """Slow-marked (~90 s): the full master→RPC→live-apply loop is
    tier-1-covered by PR 7's e2e wedges (test_optimizer) and the
    precision-specific guarantees by TestRetunePrecisionZeroRecompile
    + the optimizer/plan-hook unit tests above — the tier-1 budget on
    this 1-core box (870 s for the whole suite) cannot carry a second
    ~90 s wedge per knob family."""

    def test_optimizer_selects_fp8_and_worker_applies_live(
            self, tmp_path, monkeypatch):
        """The acceptance wedge: a comm-bound MoE job reports its
        config → the master's optimizer prices the precision family,
        chooses the fp8 wire, publishes → the worker's plan hook
        drains and applies it through the prewarmed program cache with
        ZERO recompiles at the swap → the ack marks the decision
        applied."""
        from dlrover_tpu.agent.master_client import MasterClient
        from dlrover_tpu.master.local_master import start_local_master
        from dlrover_tpu.telemetry import EventKind, read_events
        from dlrover_tpu.trainer.conf import Configuration
        from dlrover_tpu.trainer.executor import (
            NodeRuntimeReportHook,
            OptimizerPlanHook,
            TrainExecutor,
            TrainHook,
        )

        events_path = str(tmp_path / "events.jsonl")
        monkeypatch.setenv("DLROVER_TPU_EVENTS_FILE", events_path)
        ctx = get_context()
        monkeypatch.setattr(ctx, "replan_min_speedup", 1.02)
        # the live apply pins the chosen knobs into the Context (the
        # trace-time contract) — register restores so the chosen
        # chunks/precision don't leak into later tests' trace-time
        # resolution
        monkeypatch.setattr(ctx, "dispatch_chunks", ctx.dispatch_chunks)
        monkeypatch.setattr(ctx, "moe_precision", ctx.moe_precision)
        master = start_local_master()
        opt = master.servicer.runtime_optimizer
        opt._mesh_candidates = False
        opt._device = DeviceSpec(hbm_bytes=95e9)
        try:
            client = MasterClient(master.addr, node_id=0)
            client.report_model_info(_small_moe_model_info())
            trainer, batch = _moe_trainer()
            steps = 24
            ex = TrainExecutor(
                trainer, train_iter_fn=lambda: [batch] * steps,
                hooks=[NodeRuntimeReportHook(client, every_steps=4,
                                             min_interval_s=0)],
                conf=Configuration({
                    "train_steps": steps, "log_every_steps": 0,
                    "train_window": 2, "preemption_grace": False,
                    "plan_poll_secs": 0, "runtime_report_steps": 0,
                }),
            )
            ex._master_client = client
            plan_hook = OptimizerPlanHook(client, poll_secs=0)
            plan_hook._executor = ex

            class _Drive(TrainHook):
                fired = False

                def after_step(self, step, metrics):
                    if step >= 8 and not _Drive.fired:
                        _Drive.fired = True
                        opt.replan("wedge")
                    if step >= 10 and step % 4 == 2:
                        plan_hook.poll_once()

            ex._hooks.append(_Drive())
            ex.train_and_evaluate()
            client.close()

            decisions = opt.decisions()
            chosen = [d for d in decisions if d["outcome"] == "chosen"]
            assert chosen, decisions
            d = chosen[-1]
            assert d["chosen"]["moe_precision"] == "fp8"
            assert d["applied"], d
            assert trainer.moe_precision == "fp8"
            done = [r for r in read_events(events_path)
                    if r.get("kind") == EventKind.OPTIMIZER_APPLY_DONE
                    and r.get("plan_id") == d["plan_id"]]
            assert done and done[-1]["recompiled"] == 0, done
            assert done[-1]["moe_precision"] == "fp8"
        finally:
            master.stop()


# -- lint: the G106 audit of the quantized program + G109 ---------------------


class TestFp8GraphLint:
    # slow-marked per the ISSUE 12 tier-1 triage (~13 s, two full
    # accelerate+compiles): the G106-on-a-quantized-program coverage
    # stays tier-1 via test_fsdp_wire's dense-wire audit (same audit
    # machinery, same dtype-aware prediction path), the moe wire ratio
    # via the planner formula pins; the moe compile re-proof rides
    # tpulint / the slow lane
    @pytest.mark.slow
    def test_quantized_program_passes_the_audit_with_halved_row_bytes(
            self):
        """The acceptance pin: G106 audits the fp8 program's
        collective bytes against the dtype-aware prediction within the
        existing tolerance AND the measured all-to-all row bytes come
        out well under the bf16 twin's (values + scales both counted
        on both sides) — the halving is verified on the COMPILED HLO,
        not asserted from the formula."""
        from dlrover_tpu.analysis.graph_lint import lint_train_step

        # chunks pinned to 1 explicitly: at C>1 the rows ride the
        # ppermute ring ("collective-permute"), and this test's point
        # is the all-to-all comparison (a leaked Context chunk knob
        # from an earlier live apply must not reroute it)
        rep_q = lint_train_step(
            llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep",
                             moe_precision="fp8",
                             moe_dispatch_chunks=1),
            label="llama_tiny_moe[grouped_ep,fp8]",
        )
        assert rep_q.findings == [], [
            f.render() for f in rep_q.findings]
        rep_b = lint_train_step(
            llama.llama_tiny(num_experts=8, moe_dispatch="grouped_ep",
                             moe_precision="bf16",
                             moe_dispatch_chunks=1),
            label="llama_tiny_moe[grouped_ep,bf16]",
        )
        assert rep_b.findings == [], [
            f.render() for f in rep_b.findings]
        a2a_q = rep_q.measured_bytes.get("all-to-all", 0)
        a2a_b = rep_b.measured_bytes.get("all-to-all", 0)
        assert a2a_q > 0 and a2a_b > 0
        # f32 tokens on this config: 4-byte rows drop to 1.125 -> well
        # under 0.8 even with the int32 count exchange riding along
        assert a2a_q / a2a_b < 0.8, (a2a_q, a2a_b)
        # and the prediction the audit compared against used the
        # dtype-aware formula
        assert rep_q.predicted_bytes["moe_dispatch"] \
            < rep_b.predicted_bytes["moe_dispatch"]


class TestG109QuantizationDrift:
    def test_fires_on_a_drifting_fixture(self):
        from dlrover_tpu.analysis.graph_lint import (
            check_quantization_drift,
        )

        findings = check_quantization_drift(0.5, 9e-5)
        assert len(findings) == 1
        assert findings[0].rule_id == "G109"
        assert "regressed" in findings[0].message

    def test_clean_inside_the_ratchet_and_default_tolerance(self):
        from dlrover_tpu.analysis.graph_lint import (
            check_quantization_drift,
        )

        assert check_quantization_drift(2e-4, 9e-5) == []  # < 4x
        assert check_quantization_drift(0.01, None) == []  # default tol
        assert check_quantization_drift(0.5, None)  # over default

    def test_floor_protects_near_zero_baselines(self):
        from dlrover_tpu.analysis.graph_lint import (
            check_quantization_drift,
        )

        # baseline ~0: reassociation noise must not fire
        assert check_quantization_drift(5e-6, 1e-9) == []

    def test_clean_on_head_against_the_committed_baseline(self):
        """The acceptance pin: the HEAD fp8 program's drift sits inside
        the committed quant_baseline.json ratchet."""
        from dlrover_tpu.analysis.graph_lint import (
            quantization_drift_audit,
        )

        rep = quantization_drift_audit()
        assert rep.findings == [], [f.render() for f in rep.findings]

    def test_wired_into_the_rule_set_and_baseline_is_versioned(self):
        import json

        from dlrover_tpu.analysis.graph_lint import (
            ALL_GRAPH_RULES,
            GRAPH_RULE_DOCS,
            quantization_drift_baseline_path,
        )

        assert "G109" in ALL_GRAPH_RULES
        assert "G109" in GRAPH_RULE_DOCS
        with open(quantization_drift_baseline_path()) as fh:
            data = json.load(fh)
        assert data["version"] == 1
        # entries are keyed per EXECUTING backend (@cpu here): a
        # baseline ratcheted on one backend's kernels must not judge
        # another's
        assert any(k.startswith("llama_tiny_moe[grouped_ep,fp8]@")
                   for k in data["entries"])


# -- the precision bench wedge ------------------------------------------------


@pytest.mark.slow
class TestPrecisionBenchWedge:
    """Slow-marked: three executor legs (~1 min) on top of the e2e
    wedge above, and everything it gates beyond the bench plumbing —
    dequant-exact parity, recompiles, wire-bytes accounting — is
    already pinned tier-1 by the tests above; the tier-1 budget on
    this 1-core box is a first-class constraint."""

    def test_paired_legs_parity_recompiles_and_wire_bytes(self):
        """The CPU-mesh precision wedge, in-process (tier-1): paired
        bf16 vs fp8 legs through the real executor — dequant-exact
        parity (fp8 bitwise == the qdq reference leg), zero recompiles
        after warmup, and the wire-bytes ratio from the G106 counter
        recorded beside the planner prediction. The speed RATIO is
        recorded, not gated: on the CPU mesh exchanges are memcpys, so
        the fp8 win is a hardware row pending the tunnel."""
        import bench

        env_keys = {"BENCH_PRECISION_STEPS": "8",
                    "BENCH_PRECISION_PAIRS": "1"}
        saved = {k: os.environ.get(k) for k in env_keys}
        os.environ.update(env_keys)
        try:
            rec = bench.precision_result()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        assert rec["metric"] == "moe_wire_precision_ratio"
        assert "error" not in rec, rec
        detail = rec["detail"]
        assert detail["params_parity"] is True
        assert detail["recompiles_after_warmup"] == 0
        assert rec["pending_hardware"] is True
        wb = detail["wire_bytes"]
        assert wb["predicted_ratio"] == pytest.approx(0.5625)
        assert wb["measured_ratio"] is not None
        assert wb["measured_ratio"] < 0.8
