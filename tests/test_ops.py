"""Ops: flash attention (Pallas, interpret mode on CPU), ring attention
over a seq mesh axis, MoE routing/dispatch, remat policies."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dlrover_tpu.ops.attention_ref import mha_reference
from dlrover_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_lse,
)
from dlrover_tpu.ops.moe import (
    MoEConfig,
    init_moe_params,
    moe_ffn,
    router_dispatch,
)
from dlrover_tpu.ops.remat import apply_remat
from dlrover_tpu.ops.ring_attention import ring_attention
from dlrover_tpu.parallel.mesh import MeshPlan


def _qkv(b=2, h=2, s=256, d=64, dtype=jnp.float32, seed=0):
    keys = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(
        jax.random.normal(k, (b, h, s, d), dtype) for k in keys
    )


class TestFlashAttention:
    def test_matches_reference_causal(self):
        q, k, v = _qkv()
        out = flash_attention(q, k, v, causal=True)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_matches_reference_non_causal(self):
        q, k, v = _qkv(s=128)
        out = flash_attention(q, k, v, causal=False)
        ref = mha_reference(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gradients_match_reference(self):
        q, k, v = _qkv(b=1, h=1, s=128)
        gf = jax.grad(lambda *a: flash_attention(*a).sum(), argnums=(0, 1, 2))(
            q, k, v
        )
        gr = jax.grad(
            lambda *a: mha_reference(*a, causal=True).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)

    def test_indivisible_seq_falls_back_to_fitting_blocks(self):
        q, k, v = _qkv(s=192)  # 192 % 128 != 0: blocks auto-shrink to 96
        out = flash_attention(q, k, v, True, None, 128, 128)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_rejects_tpu_illegal_tiling(self):
        # 1000's best divisor under 512 is 500 (not a multiple of 8):
        # explicit error instead of a Mosaic lowering failure later
        q, k, v = _qkv(b=1, h=1, s=1000, d=64)
        with pytest.raises(ValueError, match="multiple of 8"):
            flash_attention(q, k, v, True, None, 512, 512)

    def test_multi_block_grid_forward_and_grad(self):
        # explicit small blocks force a 4x4 grid so the scratch-carry
        # accumulation, re-init boundaries, and causal block-skip paths
        # in both backward kernels are exercised
        q, k, v = _qkv(b=1, h=2, s=256, d=64)

        def f(*a):
            return flash_attention(*a, True, None, 64, 64).sum()

        def r(*a):
            return mha_reference(*a, causal=True).sum()

        np.testing.assert_allclose(
            flash_attention(q, k, v, True, None, 64, 64),
            mha_reference(q, k, v, causal=True), atol=2e-5, rtol=2e-5,
        )
        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)

    def test_bf16_inputs(self):
        q, k, v = _qkv(s=128, dtype=jnp.bfloat16)
        out = flash_attention(q, k, v)
        ref = mha_reference(q, k, v)
        assert out.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            out.astype(jnp.float32), ref.astype(jnp.float32),
            atol=3e-2, rtol=3e-2,
        )

    def test_gqa_matches_reference(self):
        # 4 query heads sharing 2 kv heads, no repeat materialized
        q, _, _ = _qkv(b=2, h=4, s=128, d=32)
        _, k, v = _qkv(b=2, h=2, s=128, d=32, seed=1)
        for causal in (True, False):
            out = flash_attention(q, k, v, causal)
            ref = mha_reference(q, k, v, causal=causal)
            np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_asymmetric_bwd_tiles_match_reference(self):
        """block_q_bwd/block_k_bwd tile the backward independently of
        the forward (the long-context VMEM lever): gradients must be
        identical for any legal tiling."""
        q, _, _ = _qkv(b=1, h=4, s=256, d=32)
        _, k, v = _qkv(b=1, h=2, s=256, d=32, seed=3)

        def f(*a):
            return flash_attention(
                *a, True, None, 128, 128, None, 64, 32
            ).sum()

        def r(*a):
            return mha_reference(*a, causal=True).sum()

        # forward unaffected by bwd tiles
        out = flash_attention(q, k, v, True, None, 128, 128, None, 64, 32)
        np.testing.assert_allclose(
            out, mha_reference(q, k, v, causal=True),
            atol=2e-5, rtol=2e-5,
        )
        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)

    def test_gqa_gradients_match_reference(self):
        # dk/dv must sum over the query-head group (the 5D dKV grid)
        q, _, _ = _qkv(b=1, h=4, s=128, d=32)
        _, k, v = _qkv(b=1, h=2, s=128, d=32, seed=3)

        def f(*a):
            return flash_attention(*a, True, None, 64, 64).sum()

        def r(*a):
            return mha_reference(*a, causal=True).sum()

        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        assert gf[1].shape == k.shape and gf[2].shape == v.shape
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)

    def test_lse_matches_reference_and_is_differentiable(self):
        q, k, v = _qkv(b=1, h=2, s=128, d=32)
        scale = 1.0 / (32 ** 0.5)
        _, lse = flash_attention_lse(q, k, v, True)
        logits = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
        mask = jnp.tril(jnp.ones((128, 128), bool))
        logits = jnp.where(mask, logits, -jnp.inf)
        ref_lse = jax.scipy.special.logsumexp(logits, axis=-1)
        np.testing.assert_allclose(lse, ref_lse, atol=2e-5, rtol=2e-5)

        # gradient THROUGH the lse output (the ring merge path)
        def f(q, k, v):
            out, lse = flash_attention_lse(q, k, v, True)
            return (out * jnp.exp(lse)[..., None]).sum()

        def r(q, k, v):
            out = mha_reference(q, k, v, causal=True)
            lg = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
            lg = jnp.where(mask, lg, -jnp.inf)
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            return (out * jnp.exp(lse)[..., None]).sum()

        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=2e-3, rtol=2e-3)


def _segment_bias(segment_ids):
    """[B, S] -> additive bias [B, 1, S, S] for the reference path."""
    same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
    return jnp.where(same, 0.0, jnp.finfo(jnp.float32).min)


class TestFlashAttentionSegmented:
    """Packed-sequence masking fused into the Pallas tiles."""

    def _packed(self, b=2, s=128):
        q, k, v = _qkv(b=b, s=s)
        # uneven document boundaries per row
        seg = np.zeros((b, s), np.int32)
        seg[0, int(s * 0.3):] = 1
        if b > 1:
            seg[1, int(s * 0.2):int(s * 0.8)] = 1
            seg[1, int(s * 0.8):] = 2
        return q, k, v, jnp.asarray(seg)

    def test_matches_reference_causal(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_segmented

        q, k, v, seg = self._packed()
        out = flash_attention_segmented(q, k, v, seg, causal=True)
        ref = mha_reference(q, k, v, causal=True, bias=_segment_bias(seg))
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_matches_reference_non_causal(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_segmented

        q, k, v, seg = self._packed()
        out = flash_attention_segmented(q, k, v, seg, causal=False)
        ref = mha_reference(q, k, v, causal=False, bias=_segment_bias(seg))
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_small_blocks_fully_masked_tiles_no_nan(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_segmented

        # block_k 8 with a 32-token leading segment: queries of segment 1
        # visit 4 fully-masked k tiles first — the running-max clamp must
        # keep the accumulator finite
        q, k, v = _qkv(b=1, s=64)
        seg = jnp.asarray(
            np.concatenate([np.zeros(32, np.int32), np.ones(32, np.int32)])
        )[None, :]
        out = flash_attention_segmented(q, k, v, seg, causal=True,
                                        block_q=8, block_k=8)
        assert np.isfinite(np.asarray(out)).all()
        ref = mha_reference(q, k, v, causal=True, bias=_segment_bias(seg))
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gradients_match_reference(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_segmented

        q, k, v, seg = self._packed(b=1, s=64)

        def f_flash(q, k, v):
            return flash_attention_segmented(q, k, v, seg).sum()

        def f_ref(q, k, v):
            return mha_reference(
                q, k, v, causal=True, bias=_segment_bias(seg)
            ).sum()

        gf = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)

    def test_gqa_segmented(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_segmented

        keys = jax.random.split(jax.random.PRNGKey(3), 3)
        b, s, d = 2, 64, 32
        q = jax.random.normal(keys[0], (b, 4, s, d))
        k = jax.random.normal(keys[1], (b, 2, s, d))
        v = jax.random.normal(keys[2], (b, 2, s, d))
        seg = jnp.asarray(np.repeat([[0, 1]], s // 2, axis=1
                                    ).reshape(1, s).repeat(b, 0))
        seg = jnp.sort(seg, axis=1)  # contiguous halves
        out = flash_attention_segmented(q, k, v, seg, causal=True)
        ref = mha_reference(q, k, v, causal=True, bias=_segment_bias(seg))
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_packed_equals_separate_documents(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_segmented

        # the semantic contract: packing two docs into one row computes
        # EXACTLY what two padded rows would
        q, k, v = _qkv(b=1, s=128)
        seg = jnp.asarray(
            np.concatenate([np.zeros(48, np.int32),
                            np.ones(80, np.int32)]))[None, :]
        packed = flash_attention_segmented(q, k, v, seg, causal=True)
        doc0 = flash_attention(q[:, :, :48], k[:, :, :48], v[:, :, :48],
                               causal=True)
        doc1 = flash_attention(q[:, :, 48:], k[:, :, 48:], v[:, :, 48:],
                               causal=True)
        np.testing.assert_allclose(packed[:, :, :48], doc0,
                                   atol=2e-5, rtol=2e-5)
        np.testing.assert_allclose(packed[:, :, 48:], doc1,
                                   atol=2e-5, rtol=2e-5)


class TestFlashAttentionPrefix:
    """Prefix-LM (GLM) masking fused into the Pallas tiles."""

    def _ref(self, q, k, v, prefix):
        s = q.shape[2]
        i = jnp.arange(s)[:, None]
        j = jnp.arange(s)[None, :]
        allowed = jnp.logical_or(j <= i,
                                 j[None] < prefix[:, None, None])
        bias = jnp.where(allowed, 0.0, jnp.finfo(jnp.float32).min)
        return mha_reference(q, k, v, causal=False, bias=bias[:, None])

    def test_matches_reference(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_prefix

        q, k, v = _qkv(b=2, s=128)
        prefix = jnp.asarray([40, 0])  # one prefix row, one pure-causal
        out = flash_attention_prefix(q, k, v, prefix)
        ref = self._ref(q, k, v, prefix)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_small_blocks_no_nan(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_prefix

        # early q rows visit prefix-needed blocks fully beyond both
        # their diagonal and the prefix — the clamp must hold
        q, k, v = _qkv(b=1, s=64)
        prefix = jnp.asarray([24])
        out = flash_attention_prefix(q, k, v, prefix, block_q=8,
                                     block_k=8)
        assert np.isfinite(np.asarray(out)).all()
        np.testing.assert_allclose(out, self._ref(q, k, v, prefix),
                                   atol=2e-5, rtol=2e-5)

    def test_gradients_match_reference(self):
        from dlrover_tpu.ops.flash_attention import flash_attention_prefix

        q, k, v = _qkv(b=1, s=64)
        prefix = jnp.asarray([20])
        gf = jax.grad(
            lambda *a: flash_attention_prefix(*a, prefix).sum(),
            argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(
            lambda *a: self._ref(*a, prefix).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)

    def test_glm_flash_matches_bias_path(self):
        from dlrover_tpu.models import glm

        cfg_flash = glm.glm_tiny(use_flash=True, flash_interpret=True)
        cfg_bias = glm.glm_tiny(use_flash=False)
        params = glm.init(jax.random.PRNGKey(0), cfg_flash)
        ids = jnp.asarray(
            np.random.RandomState(0).randint(0, 256, (2, 32)))
        prefix = jnp.asarray([10, 0])
        out_f = glm.apply(params, ids, cfg_flash, prefix_len=prefix)
        out_b = glm.apply(params, ids, cfg_bias, prefix_len=prefix)
        np.testing.assert_allclose(out_f, out_b, atol=3e-5, rtol=3e-5)


class TestRingAttentionPacked:
    """Packed documents under sequence parallelism: segment ids rotate
    with the KV shards; documents may span ring shards."""

    def _case(self, b=2, s=128):
        q, k, v = _qkv(b=b, s=s, h=2, d=32)
        seg = np.zeros((b, s), np.int32)
        # boundaries deliberately NOT aligned to the 4-way seq shards
        seg[0, int(s * 0.4):] = 1
        if b > 1:
            seg[1, int(s * 0.16):int(s * 0.7)] = 1
            seg[1, int(s * 0.7):] = 2
        return q, k, v, jnp.asarray(seg)

    def test_matches_reference_over_seq_axis(self):
        mesh = MeshPlan(data=2, seq=4).build()
        q, k, v, seg = self._case()
        out = ring_attention(q, k, v, mesh, causal=True, head_axis=None,
                             segment_ids=seg)
        ref = mha_reference(q, k, v, causal=True, bias=_segment_bias(seg))
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )

    def test_non_causal(self):
        mesh = MeshPlan(data=2, seq=4).build()
        q, k, v, seg = self._case()
        out = ring_attention(q, k, v, mesh, causal=False, head_axis=None,
                             segment_ids=seg)
        ref = mha_reference(q, k, v, causal=False,
                            bias=_segment_bias(seg))
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )

    # budget triage (PR 16): packed-ring bwd stays pinned tier-1 by
    # test_pallas_kernel_inside_packed_ring and the model-level
    # packed-segments parities; the standalone grad check rides slow
    @pytest.mark.slow
    def test_differentiable(self):
        mesh = MeshPlan(data=2, seq=4).build()
        q, k, v, seg = self._case(b=2, s=64)

        def f_ring(q, k, v):
            return ring_attention(q, k, v, mesh, causal=True,
                                  head_axis=None,
                                  segment_ids=seg).sum()

        def f_ref(q, k, v):
            return mha_reference(q, k, v, causal=True,
                                 bias=_segment_bias(seg)).sum()

        gr = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
        gf = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gf):
            np.testing.assert_allclose(
                jax.device_get(a), jax.device_get(b),
                atol=5e-5, rtol=5e-5)

    def test_gqa_packed_ring_matches_reference(self):
        # GQA (2 kv heads under 4 q heads) composing with segments and
        # the ring: only the kv heads + ids rotate, masking stays exact
        mesh = MeshPlan(data=2, seq=4).build()
        keys = jax.random.split(jax.random.PRNGKey(5), 3)
        b, s, d = 2, 128, 32
        q = jax.random.normal(keys[0], (b, 4, s, d))
        k = jax.random.normal(keys[1], (b, 2, s, d))
        v = jax.random.normal(keys[2], (b, 2, s, d))
        seg = jnp.asarray(np.sort(
            np.random.RandomState(2).randint(0, 3, (b, s)), axis=1))
        out = ring_attention(q, k, v, mesh, causal=True, head_axis=None,
                             segment_ids=seg)
        ref = mha_reference(q, k, v, causal=True, bias=_segment_bias(seg))
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )

    def test_pallas_kernel_inside_packed_ring(self):
        # the TPU path: each ring step runs the segmented PAIR kernel
        # (independent q-side/kv-side ids; interpret mode here)
        mesh = MeshPlan(seq=2).build()
        q, k, v, seg = self._case(b=1, s=128)
        out = ring_attention(q, k, v, mesh, causal=True, head_axis=None,
                             batch_axes=None, impl="pallas_interpret",
                             block_q=64, block_k=64, segment_ids=seg)
        ref = mha_reference(q, k, v, causal=True, bias=_segment_bias(seg))
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )

    def test_llama_seq_parallel_packed_matches_dense(self):
        """The whole model: packed llama under a (data x seq) mesh equals
        the dense packed path."""
        from dlrover_tpu.models import llama

        mesh = MeshPlan(data=2, seq=4).build()
        cfg_ring = llama.llama_tiny(remat_policy="none", seq_axis="seq",
                                    mesh=mesh)
        cfg_dense = llama.llama_tiny(remat_policy="none")
        params = llama.init(jax.random.PRNGKey(0), cfg_ring)
        rng = np.random.RandomState(0)
        ids = jnp.asarray(rng.randint(0, cfg_ring.vocab_size, (2, 64)))
        seg = jnp.asarray(
            np.sort(rng.randint(0, 3, (2, 64)), axis=1))
        out_ring, _ = llama.apply(params, ids, cfg_ring,
                                  segment_ids=seg)
        out_dense, _ = llama.apply(params, ids, cfg_dense,
                                   segment_ids=seg)
        np.testing.assert_allclose(np.asarray(out_ring),
                                   np.asarray(out_dense),
                                   atol=3e-5, rtol=3e-5)


class TestRingAttention:
    def test_matches_reference_over_seq_axis(self):
        mesh = MeshPlan(data=2, seq=4).build()
        q, k, v = _qkv(b=2, h=2, s=128, d=32)
        out = ring_attention(q, k, v, mesh, causal=True, head_axis=None)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )

    def test_non_causal(self):
        mesh = MeshPlan(seq=8).build()
        q, k, v = _qkv(b=1, h=2, s=64, d=32)
        out = ring_attention(q, k, v, mesh, causal=False, head_axis=None,
                             batch_axes=None)
        ref = mha_reference(q, k, v, causal=False)
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )

    # budget triage (PR 16): ring grads stay pinned tier-1 by
    # test_gqa_ring_gradients_match_reference and
    # test_ring_bwd_tiles_reach_the_kernel; this one rides slow
    @pytest.mark.slow
    def test_differentiable(self):
        mesh = MeshPlan(seq=4).build()
        q, k, v = _qkv(b=1, h=1, s=64, d=32)

        def loss(q, k, v):
            return ring_attention(q, k, v, mesh, head_axis=None,
                                  batch_axes=None).sum()

        def ref_loss(q, k, v):
            return mha_reference(q, k, v, causal=True).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(
                jax.device_get(a), jax.device_get(b), atol=5e-5, rtol=5e-5
            )

    def test_gqa_ring_gradients_match_reference(self):
        # the training path: grad flows through the lse merge, the
        # lax.cond skip, the ppermute rotation, and the GQA group map
        mesh = MeshPlan(seq=4).build()
        q, _, _ = _qkv(b=1, h=4, s=128, d=32)
        _, k, v = _qkv(b=1, h=2, s=128, d=32, seed=9)
        w = jax.random.normal(jax.random.PRNGKey(13), (1, 4, 128, 32))

        def loss(q, k, v):
            out = ring_attention(q, k, v, mesh, causal=True,
                                 head_axis=None, batch_axes=None)
            return (out * w).sum()

        def ref_loss(q, k, v):
            return (mha_reference(q, k, v, causal=True) * w).sum()

        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        assert g[1].shape == k.shape  # kv grads at kv head count
        for a, b in zip(g, gr):
            np.testing.assert_allclose(
                jax.device_get(a), jax.device_get(b), atol=5e-5, rtol=5e-5
            )

    def test_xla_attend_pads_indivisible_kv_len(self):
        from dlrover_tpu.ops.ring_attention import _xla_attend_lse

        # s_k=509 is prime: the fallback must pad, not degrade to bk=1
        q, _, _ = _qkv(b=1, h=2, s=64, d=32)
        _, k, v = _qkv(b=1, h=2, s=509, d=32, seed=15)
        out, lse = _xla_attend_lse(q, k, v, causal=False,
                                   scale=1.0 / (32 ** 0.5), block_k=128)
        ref = mha_reference(q, k, v, causal=False)
        np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)

    def test_gqa_ring_matches_reference_and_rotates_only_kv_heads(self):
        mesh = MeshPlan(seq=4).build()
        q, _, _ = _qkv(b=1, h=4, s=128, d=32)
        _, k, v = _qkv(b=1, h=2, s=128, d=32, seed=5)
        out = ring_attention(q, k, v, mesh, causal=True, head_axis=None,
                             batch_axes=None)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )
        # structural ICI check: every ppermute operand carries the KV
        # head count (2), not the query head count (4) — ring bytes are
        # kv/h of the MHA equivalent
        jaxpr = jax.make_jaxpr(
            lambda q, k, v: ring_attention(
                q, k, v, mesh, causal=True, head_axis=None,
                batch_axes=None,
            )
        )(q, k, v)
        perm_shapes = []

        def walk(jp):
            for eqn in jp.eqns:
                if eqn.primitive.name == "ppermute":
                    perm_shapes.extend(x.aval.shape for x in eqn.invars)
                for sub in eqn.params.values():
                    subs = sub if isinstance(sub, (list, tuple)) else [sub]
                    for s in subs:
                        while hasattr(s, "jaxpr"):  # ClosedJaxpr
                            s = s.jaxpr
                        if hasattr(s, "eqns"):
                            walk(s)

        walk(jaxpr.jaxpr)
        assert perm_shapes, "ring must rotate via ppermute"
        for shape in perm_shapes:
            assert shape[1] == 2, f"rotated {shape}, expected kv heads=2"

    def test_indivisible_kv_heads_warns_and_stays_correct(self):
        """Round-2 verdict #9: the kv-repeat fallback must not be a
        silent bandwidth cliff — it logs the repeat factor (the planner
        prices the same factor via ring_kv_repeat) and stays exact."""
        import logging

        from dlrover_tpu.common.log import get_logger

        records = []

        class _Capture(logging.Handler):
            def emit(self, record):
                records.append(record.getMessage())

        handler = _Capture(level=logging.WARNING)
        target = get_logger("ops.ring_attention")
        target.addHandler(handler)
        try:
            mesh = MeshPlan(seq=2, tensor=4).build()
            # 8 query heads, 2 kv heads: 2 % 4 != 0 -> repeat x2
            q, _, _ = _qkv(b=1, h=8, s=64, d=32)
            _, k, v = _qkv(b=1, h=2, s=64, d=32, seed=5)
            out = ring_attention(q, k, v, mesh, causal=True,
                                 head_axis="tensor", batch_axes=None)
        finally:
            target.removeHandler(handler)
        assert any("repeating kv" in m for m in records), records
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )
        # the runtime's minimal repeat equals what the planner prices
        from dlrover_tpu.parallel.planner import ring_kv_repeat

        assert ring_kv_repeat(2, 8, 4) == 2

    def test_pallas_kernel_inside_ring(self):
        # the TPU path: each ring step invokes the flash kernel
        # (interpret mode here); parity against the dense reference
        mesh = MeshPlan(seq=2).build()
        q, _, _ = _qkv(b=1, h=2, s=128, d=32)
        _, k, v = _qkv(b=1, h=1, s=128, d=32, seed=7)
        out = ring_attention(q, k, v, mesh, causal=True, head_axis=None,
                             batch_axes=None, impl="pallas_interpret",
                             block_q=64, block_k=64)
        ref = mha_reference(q, k, v, causal=True)
        np.testing.assert_allclose(
            jax.device_get(out), jax.device_get(ref), atol=2e-5, rtol=2e-5
        )

    # budget triage (PR 16): the model-level GLM gate
    # test_prefix_lm_seq_parallel_ring_matches_dense stays tier-1;
    # the op-level decomposition check rides slow
    @pytest.mark.slow
    def test_prefix_lm_ring_matches_dense_reference(self):
        """GLM's prefix-LM mask decomposed over the ring: past shards
        fully visible, diagonal runs the locally-shifted prefix
        kernel, future shards contribute only prompt columns. Prefixes
        deliberately straddle shard boundaries. Both impls, plus
        gradients through the Pallas path."""
        mesh = MeshPlan(seq=4).build()
        q, k, v = _qkv(b=2, h=2, s=128, d=32)
        prefix = jnp.asarray([37, 100], jnp.int32)  # shard size is 32

        i = jnp.arange(128)
        allowed = (i[None, :] <= i[:, None])[None] | (
            i[None, None, :] < prefix[:, None, None])
        bias = jnp.where(allowed, 0.0,
                         jnp.finfo(jnp.float32).min)[:, None]
        ref = mha_reference(q, k, v, causal=False, bias=bias)

        for impl in ("xla", "pallas_interpret"):
            out = ring_attention(
                q, k, v, mesh, causal=True, head_axis=None,
                batch_axes=None, impl=impl, block_q=32, block_k=32,
                prefix_len=prefix,
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=3e-5, rtol=3e-5
            )

        def f_ring(q, k, v):
            return ring_attention(
                q, k, v, mesh, causal=True, head_axis=None,
                batch_axes=None, impl="pallas_interpret", block_q=32,
                block_k=32, prefix_len=prefix,
            ).sum()

        def f_ref(q, k, v):
            return mha_reference(q, k, v, causal=False,
                                 bias=bias).sum()

        gr = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
        gd = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gd):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)

    def test_prefix_ring_rejects_packed_and_noncausal(self):
        from dlrover_tpu.ops.ring_attention import ring_attention_local

        try:
            from jax import shard_map  # jax >= 0.5
        except ImportError:
            from jax.experimental.shard_map import shard_map

        mesh = MeshPlan(seq=2).build()
        q, k, v = _qkv(b=1, h=2, s=64, d=32)
        prefix = jnp.asarray([10], jnp.int32)
        seg = jnp.zeros((1, 64), jnp.int32)
        with pytest.raises(ValueError, match="mutually exclusive"):
            ring_attention(q, k, v, mesh, causal=True, head_axis=None,
                           batch_axes=None, prefix_len=prefix,
                           segment_ids=seg)
        with pytest.raises(ValueError, match="causal"):
            jax.jit(
                lambda q, k, v: shard_map(
                    lambda ql, kl, vl: ring_attention_local(
                        ql, kl, vl, causal=False, prefix_len=prefix,
                        impl="xla",
                    ),
                    mesh=mesh,
                    in_specs=(jax.sharding.PartitionSpec(
                        None, None, "seq", None),) * 3,
                    out_specs=jax.sharding.PartitionSpec(
                        None, None, "seq", None),
                )(q, k, v)
            )(q, k, v)

    def test_ring_bwd_tiles_reach_the_kernel(self):
        """block_q_bwd/block_k_bwd plumb through the ring (the
        long-context path the knob documents): gradients with
        asymmetric backward tiles equal the XLA-ring gradients."""
        mesh = MeshPlan(seq=2).build()
        q, _, _ = _qkv(b=1, h=2, s=128, d=32)
        _, k, v = _qkv(b=1, h=1, s=128, d=32, seed=7)

        def f(q, k, v):
            return ring_attention(
                q, k, v, mesh, causal=True, head_axis=None,
                batch_axes=None, impl="pallas_interpret",
                block_q=64, block_k=64, block_q_bwd=32, block_k_bwd=32,
            ).sum()

        def r(q, k, v):
            return ring_attention(
                q, k, v, mesh, causal=True, head_axis=None,
                batch_axes=None, impl="xla",
            ).sum()

        gf = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(a, b, atol=5e-5, rtol=5e-5)


@pytest.mark.slow
class TestRingAttentionLongContext:
    def test_16k_tokens_on_8_device_mesh(self):
        """16k-token causal ring on the 8-device CPU mesh.

        Full dense parity would need a 16k x 16k tile (the very thing
        the ring avoids), so correctness uses the causal prefix
        property: rows < 2048 attend only to keys < 2048, so they must
        equal plain attention on the first shard.
        """
        if len(jax.devices()) < 8:
            pytest.skip("needs 8 devices")
        mesh = MeshPlan(seq=8).build()
        s, d = 16384, 64
        q, _, _ = _qkv(b=1, h=2, s=s, d=d, dtype=jnp.bfloat16)
        _, k, v = _qkv(b=1, h=1, s=s, d=d, dtype=jnp.bfloat16, seed=11)

        fn = jax.jit(
            lambda q, k, v: ring_attention(
                q, k, v, mesh, causal=True, head_axis=None,
                batch_axes=None,
            )
        )
        out = jax.device_get(fn(q, k, v))
        assert out.shape == (1, 2, s, d)
        assert np.isfinite(out.astype(np.float32)).all()

        prefix = 2048  # = S_local: exactly the first shard
        ref = mha_reference(
            q[:, :, :prefix], k[:, :, :prefix], v[:, :, :prefix],
            causal=True,
        )
        np.testing.assert_allclose(
            out[:, :, :prefix].astype(np.float32),
            jax.device_get(ref).astype(np.float32),
            atol=3e-2, rtol=3e-2,
        )


class TestMoE:
    def test_router_dispatch_respects_capacity(self):
        rng = jax.random.PRNGKey(0)
        logits = jax.random.normal(rng, (16, 4))
        dispatch, combine, aux = router_dispatch(logits, capacity=2)
        # per-expert token counts never exceed capacity
        per_expert = dispatch.sum(axis=(0, 2))
        assert (per_expert <= 2 * 1.0 + 1e-6).all()
        # each slot holds at most one token
        per_slot = dispatch.sum(axis=0)
        assert (per_slot <= 1.0 + 1e-6).all()
        assert float(aux) > 0

    def test_top2_routing(self):
        logits = jax.random.normal(jax.random.PRNGKey(1), (32, 4))
        dispatch, combine, aux = router_dispatch(logits, capacity=16, top_k=2)
        # most tokens dispatched twice at generous capacity
        sends = dispatch.sum(axis=(1, 2))
        assert float(sends.mean()) > 1.5

    def test_moe_ffn_forward_and_grad(self):
        cfg = MoEConfig(num_experts=4, capacity_factor=2.0)
        params = init_moe_params(jax.random.PRNGKey(0), d_model=16, d_ff=32,
                                 num_experts=4)
        x = jax.random.normal(jax.random.PRNGKey(1), (2, 8, 16))
        out, aux, metrics = moe_ffn(params, x, cfg)
        assert out.shape == x.shape
        assert metrics["expert_load"].shape == (4,)

        def loss(params):
            o, a, _ = moe_ffn(params, x, cfg)
            return (o ** 2).mean() + 0.01 * a

        grads = jax.grad(loss)(params)
        gnorm = jnp.sqrt(sum(
            (g ** 2).sum() for g in jax.tree.leaves(grads)
        ))
        assert float(gnorm) > 0

    @pytest.mark.parametrize("top_k", [1, 2])
    @pytest.mark.parametrize("capacity_factor", [0.5, 1.25])
    def test_gather_matches_einsum_reference(self, top_k, capacity_factor):
        """The fast slot-gather dispatch is numerically the einsum
        oracle — including under capacity overflow (dropped tokens) and
        top-2 round-by-round queue filling."""
        e = 4
        params = init_moe_params(jax.random.PRNGKey(2), d_model=16,
                                 d_ff=32, num_experts=e)
        x = jax.random.normal(jax.random.PRNGKey(3), (2, 24, 16))
        outs, auxs, grads = {}, {}, {}
        for dispatch in ("einsum", "gather"):
            cfg = MoEConfig(num_experts=e, capacity_factor=capacity_factor,
                            top_k=top_k, dispatch=dispatch)

            def loss(p):
                o, a, _ = moe_ffn(p, x, cfg)
                return (o ** 2).mean() + 0.01 * a

            outs[dispatch], auxs[dispatch], _ = moe_ffn(params, x, cfg)
            grads[dispatch] = jax.grad(loss)(params)
        np.testing.assert_allclose(outs["gather"], outs["einsum"],
                                   atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(auxs["gather"], auxs["einsum"],
                                   atol=1e-6, rtol=1e-6)
        for ga, ge in zip(jax.tree.leaves(grads["gather"]),
                          jax.tree.leaves(grads["einsum"])):
            np.testing.assert_allclose(ga, ge, atol=1e-5, rtol=1e-4)

    def test_skewed_tokens_load_metrics(self):
        """Under a skewed routing distribution, top-2 + tight capacity
        must report the overflow: dropped_frac > 0 and expert_load
        concentrated on the hot expert (switch_gating.py:24-195 parity:
        capacity-overflow accounting surfaced, not silently dropped)."""
        e, t = 4, 64
        params = init_moe_params(jax.random.PRNGKey(4), d_model=16,
                                 d_ff=32, num_experts=e)
        # bias the router so ~all tokens prefer experts 0 then 1
        params["router"]["kernel"] = params["router"]["kernel"] * 0.0 + \
            jnp.array([[8.0, 4.0, 0.0, -4.0]] * 16)
        # positive features: every token's logit ordering follows the
        # biased router columns (a negative feature-sum would flip it)
        x = jnp.abs(jax.random.normal(jax.random.PRNGKey(5), (1, t, 16)))
        cfg = MoEConfig(num_experts=e, capacity_factor=1.0, top_k=2)
        out, aux, metrics = moe_ffn(params, x, cfg)
        load = np.asarray(metrics["expert_load"])
        # every token's round-0 pick is expert 0, round-1 pick expert 1
        assert load[0] == pytest.approx(0.5, abs=1e-6)
        assert load[1] == pytest.approx(0.5, abs=1e-6)
        # gshard capacity = t*k*1.0/e = 32 slots/expert; 2*64
        # assignments all want experts 0/1 but only 64 slots exist
        # there -> 50% dropped
        assert float(metrics["dropped_frac"]) == pytest.approx(0.5,
                                                               abs=1e-6)
        # the aux loss sees the imbalance: >> 1 (balanced value is 1.0)
        assert float(aux) > 1.5

    def test_dropped_tokens_get_zero_combine(self):
        # capacity 1 with all tokens preferring expert 0: overflow dropped
        logits = jnp.tile(jnp.array([[10.0, 0.0]]), (8, 1))
        dispatch, combine, _ = router_dispatch(logits, capacity=1)
        assert float(dispatch[:, 0, :].sum()) == 1.0
        assert float(combine.sum(axis=(1, 2))[1:].max()) == 0.0


class TestRemat:
    def test_policies_apply(self):
        def f(x):
            return jnp.sin(x @ x).sum()

        for policy in ["full", "dots_saveable", "nothing_saveable", "none",
                       "dots_and_attn_saveable", "attn_saveable"]:
            g = jax.grad(apply_remat(f, policy))(jnp.eye(8))
            assert g.shape == (8, 8)

    def test_unknown_policy_raises(self):
        with pytest.raises(ValueError):
            apply_remat(lambda x: x, "bogus")(jnp.ones(1))


class TestGroupedMatmul:
    """ops.grouped_matmul: the dropless-MoE Pallas kernel (interpret
    mode on CPU; Mosaic lowering proven hermetically in test_aot)."""

    def _setup(self, tiles_per, d=16, f=48, bt=8):
        rng = np.random.RandomState(0)
        tp = sum(tiles_per) * bt
        x = jnp.asarray(rng.randn(tp, d), jnp.float32)
        w = jnp.asarray(rng.randn(len(tiles_per), d, f) * 0.1, jnp.float32)
        tile_expert = jnp.asarray(
            sum([[e] * n for e, n in enumerate(tiles_per)], []), jnp.int32
        )
        row_e = np.repeat(np.asarray(tile_expert), bt)
        return x, w, tile_expert, row_e, bt

    def test_forward_matches_per_row_reference(self):
        from dlrover_tpu.ops.grouped_matmul import grouped_matmul

        x, w, te, row_e, bt = self._setup([2, 1, 3])
        y = grouped_matmul(x, w, te, bt, 16)
        ref = np.stack([
            np.asarray(x)[i] @ np.asarray(w)[row_e[i]]
            for i in range(x.shape[0])
        ])
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4)

    def test_grads_match_reference(self):
        from dlrover_tpu.ops.grouped_matmul import grouped_matmul

        x, w, te, row_e, bt = self._setup([1, 2, 1])

        def loss(x, w):
            return (grouped_matmul(x, w, te, bt, 16) ** 2).sum()

        def ref_loss(x, w):
            y = jnp.stack([x[i] @ w[int(row_e[i])]
                           for i in range(x.shape[0])])
            return (y ** 2).sum()

        gx, gw = jax.grad(loss, argnums=(0, 1))(x, w)
        rgx, rgw = jax.grad(ref_loss, argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rgx),
                                   rtol=1e-3, atol=1e-3)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rgw),
                                   rtol=1e-3, atol=1e-3)

    def test_block_f_that_does_not_divide_is_repicked(self):
        from dlrover_tpu.ops.grouped_matmul import grouped_matmul

        # f=48 with block_f=32: picker falls back to a divisor
        x, w, te, row_e, bt = self._setup([1, 1], f=48)
        y = grouped_matmul(x, w, te, bt, 32)
        ref = np.stack([
            np.asarray(x)[i] @ np.asarray(w)[row_e[i]]
            for i in range(x.shape[0])
        ])
        np.testing.assert_allclose(np.asarray(y), ref, atol=1e-4)


class TestMoEGroupedDispatch:
    """The DROPLESS "grouped" dispatch: megablocks-style expert compute
    with no capacity and no dropped tokens."""

    def _params_x(self, d=32, f=64, e=4, b=2, s=64):
        rng = np.random.RandomState(0)
        params = init_moe_params(jax.random.PRNGKey(0), d, f, e)
        x = jnp.asarray(rng.randn(b, s, d), jnp.float32)
        return params, x, e

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_no_drop_einsum_oracle(self, top_k):
        params, x, e = self._params_x()
        # an einsum config with capacity == T serves every token too
        cfg_oracle = MoEConfig(num_experts=e, top_k=top_k,
                               capacity_factor=float(e),
                               eval_capacity_factor=float(e),
                               dispatch="einsum")
        cfg_grouped = MoEConfig(num_experts=e, top_k=top_k,
                                dispatch="grouped")
        out_o, aux_o, _ = moe_ffn(params, x, cfg_oracle, train=False)
        out_g, aux_g, m = moe_ffn(params, x, cfg_grouped, train=False)
        np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_o),
                                   rtol=1e-4, atol=1e-4)
        assert float(aux_g) == pytest.approx(float(aux_o))
        assert float(m["dropped_frac"]) == 0.0

    def test_dropless_under_skew(self):
        """Tokens that overflow a tight capacity are DROPPED by the
        capacity paths but served by the grouped path."""
        params, x, e = self._params_x()
        params["router"]["kernel"] = (
            params["router"]["kernel"].at[:, 0].add(10.0)
        )
        cfg_tight = MoEConfig(num_experts=e, capacity_factor=1.0,
                              dispatch="gather")
        cfg_grouped = MoEConfig(num_experts=e, dispatch="grouped")
        out_t, _, m_t = moe_ffn(params, x, cfg_tight, train=True)
        out_g, _, m_g = moe_ffn(params, x, cfg_grouped, train=True)
        assert float(m_t["dropped_frac"]) > 0.1
        assert float(m_g["dropped_frac"]) == 0.0
        assert not np.allclose(np.asarray(out_t), np.asarray(out_g),
                               atol=1e-5)

    def test_grads_flow_through_router_and_experts(self):
        params, x, e = self._params_x()
        cfg = MoEConfig(num_experts=e, top_k=2, dispatch="grouped")

        def loss(p):
            out, aux, _ = moe_ffn(p, x, cfg, train=False)
            return (out ** 2).sum() + aux

        g = jax.grad(loss)(params)
        for leaf in jax.tree.leaves(g):
            assert np.isfinite(np.asarray(leaf)).all()
        assert float(jnp.abs(g["router"]["kernel"]).sum()) > 0
        assert float(jnp.abs(g["experts"]["up"]["kernel"]).sum()) > 0

    def test_llama_grouped_moe_trains(self):
        """moe_dispatch="grouped" flows through the model config into a
        full train step (dropless expert FFN inside the decoder)."""
        import optax

        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel.accelerate import accelerate

        cfg = llama.llama_tiny(num_experts=4, moe_dispatch="grouped")

        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, cfg.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
            ),
        }
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.adam(1e-2), batch,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for i in range(3):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
            assert float(metrics["moe_dropped_frac"]) == 0.0
        assert losses[-1] < losses[0]

    def test_zero_token_expert_gets_zero_grad(self):
        """An expert with NO routed tokens still owns one (sentinel)
        tile, so its dw block is INITIALIZED to zero by the kernel —
        an unvisited output block would be garbage on real TPU."""
        params, x, e = self._params_x()
        # an all-zero router ties every token's logits; argmax breaks
        # ties to expert 0, so experts 1..e-1 get ZERO tokens
        params["router"]["kernel"] = jnp.zeros_like(
            params["router"]["kernel"]
        )
        cfg = MoEConfig(num_experts=e, dispatch="grouped")

        def loss(p):
            out, aux, _ = moe_ffn(p, x, cfg, train=False)
            return (out ** 2).sum()

        g = jax.grad(loss)(params)
        up = np.asarray(g["experts"]["up"]["kernel"])
        down = np.asarray(g["experts"]["down"]["kernel"])
        assert np.abs(up[0]).sum() > 0  # the busy expert learns
        for i in range(1, e):
            assert np.abs(up[i]).sum() == 0.0, i
            assert np.abs(down[i]).sum() == 0.0, i

    def test_unknown_dispatch_raises(self):
        params, x, e = self._params_x()
        with pytest.raises(ValueError, match="unknown MoE dispatch"):
            moe_ffn(params, x, MoEConfig(num_experts=e,
                                         dispatch="groupd"))


class TestGroupedMatmulContract:
    """The debug-mode tile_expert contract checks: violations are
    SILENT garbage on real TPU (interpret mode zero-fills), so concrete
    calls validate loudly (``grouped_matmul._check_tile_expert``)."""

    def _xw(self, tiles, d=16, f=32, bt=8, e=3):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(len(tiles) * bt, d), jnp.float32)
        w = jnp.asarray(rng.randn(e, d, f) * 0.1, jnp.float32)
        return x, w, jnp.asarray(tiles, jnp.int32), bt

    def test_missing_expert_raises(self):
        from dlrover_tpu.ops.grouped_matmul import grouped_matmul

        x, w, te, bt = self._xw([0, 0, 2])  # expert 1 owns no tile
        with pytest.raises(ValueError, match="absent from"):
            grouped_matmul(x, w, te, bt, 16)

    def test_decreasing_tile_expert_raises(self):
        from dlrover_tpu.ops.grouped_matmul import grouped_matmul

        x, w, te, bt = self._xw([0, 2, 1])  # expert 1 revisited later
        with pytest.raises(ValueError, match="NON-DECREASING"):
            grouped_matmul(x, w, te, bt, 16)

    def test_valid_concrete_call_unaffected(self):
        from dlrover_tpu.ops.grouped_matmul import grouped_matmul

        x, w, te, bt = self._xw([0, 1, 2])
        y = grouped_matmul(x, w, te, bt, 16)
        assert y.shape == (x.shape[0], w.shape[2])

    def test_traced_tile_expert_skips_check(self):
        """The jitted production path (tile_expert is a tracer) must
        stay check-free — the moe dispatchers construct valid maps by
        construction."""
        from dlrover_tpu.ops.grouped_matmul import grouped_matmul

        x, w, te, bt = self._xw([0, 1, 2])

        @jax.jit
        def f(x, w, te):
            return grouped_matmul(x, w, te, bt, 16)

        assert f(x, w, te).shape == (x.shape[0], w.shape[2])


class TestMoEGroupedEP:
    """The DROPLESS expert-parallel "grouped_ep" dispatch: shard_map +
    two all_to_alls around the grouped Pallas kernel, experts sharded
    over an explicit 8-device "expert" submesh (the CPU-mesh rendering
    of the reference's expert process groups, moe_layer.py:87)."""

    E = 8

    def _mesh(self):
        from jax.sharding import Mesh

        devs = jax.devices()
        assert len(devs) >= 8, "conftest forces an 8-device CPU backend"
        return Mesh(np.array(devs[:8]), ("expert",))

    def _params_x(self, d=32, f=64, b=4, s=16, seed=0):
        rng = np.random.RandomState(seed)
        params = init_moe_params(jax.random.PRNGKey(0), d, f, self.E)
        x = jnp.asarray(rng.randn(b, s, d), jnp.float32)
        return params, x

    def _cfgs(self, top_k=1):
        mesh = self._mesh()
        oracle = MoEConfig(num_experts=self.E, top_k=top_k,
                           capacity_factor=float(self.E),
                           eval_capacity_factor=float(self.E),
                           dispatch="einsum")
        ep = MoEConfig(num_experts=self.E, top_k=top_k,
                       dispatch="grouped_ep", ep_axes=("expert",),
                       mesh=mesh)
        return oracle, ep

    # PR 13 triage: the top_k=1 parametrization is a strict subset of
    # the top_k=2 regime (fewer routing paths) and rides slow; the
    # exact-oracle contract stays tier-1 at top_k=2 here and fwd+bwd
    # in test_grads_match_oracle
    @pytest.mark.parametrize("top_k", [
        pytest.param(1, marks=pytest.mark.slow), 2])
    def test_matches_no_drop_einsum_oracle(self, top_k):
        params, x = self._params_x()
        cfg_o, cfg_ep = self._cfgs(top_k)
        out_o, aux_o, _ = moe_ffn(params, x, cfg_o, train=False)
        out_g, aux_g, m = moe_ffn(params, x, cfg_ep, train=False)
        np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_o),
                                   rtol=1e-4, atol=1e-4)
        # pmean'd routing fractions reproduce the GLOBAL aux exactly
        assert float(aux_g) == pytest.approx(float(aux_o), rel=1e-5)
        assert float(m["dropped_frac"]) == 0.0
        assert m["expert_load"].shape == (self.E,)

    # budget triage (PR 16): the grouped_ep bwd stays pinned tier-1 by
    # test_fp8_matches_qdq_oracle_bitwise_fwd_bwd (bitwise fwd+bwd),
    # the fwd einsum oracle [top_k=2], skewed dropless routing and
    # test_llama_grouped_ep_trains; the heaviest bf16 grads-vs-einsum
    # oracle rides the slow tier with its top_k=1 sibling
    @pytest.mark.slow
    def test_grads_match_oracle(self):
        """The custom VJP composes with the all_to_alls: d(params) and
        d(x) equal the einsum oracle's (top_k=2, the stricter case —
        cross-round queue fill rides the exchanged ranks)."""
        params, x = self._params_x()
        cfg_o, cfg_ep = self._cfgs(top_k=2)

        def loss(p, x, cfg):
            o, a, _ = moe_ffn(p, x, cfg, train=False)
            return (o.astype(jnp.float32) ** 2).sum() + a

        g_o = jax.grad(loss, argnums=(0, 1))(params, x, cfg_o)
        g_e = jax.grad(loss, argnums=(0, 1))(params, x, cfg_ep)
        for lo, le in zip(jax.tree.leaves(g_o), jax.tree.leaves(g_e)):
            np.testing.assert_allclose(np.asarray(le), np.asarray(lo),
                                       rtol=1e-3, atol=1e-4)

    def test_skewed_routing_crosses_shards_dropless(self):
        """Every token routed to ONE expert (one shard owns all the
        compute): the all-to-all carries all rows there and back, and
        nothing is dropped — the capacity paths would drop 7/8 of the
        assignments at factor 1."""
        params, x = self._params_x()
        # positive tokens + a large positive bias column force EVERY
        # argmax to expert 3 (a random-sign x would flip the bias term
        # for negative-sum rows)
        x = jnp.abs(x)
        params["router"]["kernel"] = (
            params["router"]["kernel"].at[:, 3].add(50.0)
        )
        cfg_o, cfg_ep = self._cfgs()
        out_o, _, _ = moe_ffn(params, x, cfg_o, train=False)
        out_g, _, m = moe_ffn(params, x, cfg_ep, train=False)
        np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_o),
                                   rtol=1e-4, atol=1e-4)
        assert float(m["dropped_frac"]) == 0.0
        load = np.asarray(m["expert_load"])
        assert load[3] == pytest.approx(1.0)

    def test_zero_recompiles_across_steps(self):
        """Static shapes survive the count exchange: one compile serves
        arbitrary routing patterns (the elasticity/throughput contract —
        a routing-dependent shape would recompile every step). Also
        pins the explicit ``kernel_interpret=True`` CPU-mesh contract
        riding through the shard_map."""
        params, x0 = self._params_x()
        cfg_ep = MoEConfig(num_experts=self.E, top_k=2,
                           dispatch="grouped_ep", ep_axes=("expert",),
                           mesh=self._mesh(), kernel_interpret=True)

        @jax.jit
        def step(p, x):
            o, a, m = moe_ffn(p, x, cfg_ep, train=False)
            return o.sum() + a, m["dropped_frac"]

        rs = np.random.RandomState(7)
        for i in range(4):
            x = jnp.asarray(rs.randn(*x0.shape), jnp.float32)
            if i == 3:  # adversarial: skew all tokens onto one expert
                p = dict(params)
                p["router"]["kernel"] = (
                    params["router"]["kernel"].at[:, 0].add(50.0)
                )
                step(p, x)
            else:
                step(params, x)
        assert step._cache_size() == 1

    def test_missing_axis_raises(self):
        params, x = self._params_x()
        mesh = self._mesh()
        cfg = MoEConfig(num_experts=self.E, dispatch="grouped_ep",
                        ep_axes=("nonexistent",), mesh=mesh)
        with pytest.raises(ValueError, match="lacks expert submesh"):
            moe_ffn(params, x, cfg, train=False)

    def test_indivisible_experts_raise(self):
        d, f = 16, 32
        params = init_moe_params(jax.random.PRNGKey(0), d, f, 6)
        x = jnp.asarray(np.random.RandomState(0).randn(2, 16, d),
                        jnp.float32)
        cfg = MoEConfig(num_experts=6, dispatch="grouped_ep",
                        ep_axes=("expert",), mesh=self._mesh())
        with pytest.raises(ValueError, match="not divisible"):
            moe_ffn(params, x, cfg, train=False)

    def test_no_mesh_degrades_to_per_shard_grouped(self):
        """No usable expert submesh (no mesh context at all): the same
        dropless math runs per shard — the elastic-shrink contract."""
        params, x = self._params_x()
        cfg_ep = MoEConfig(num_experts=self.E, top_k=2,
                           dispatch="grouped_ep")
        cfg_g = MoEConfig(num_experts=self.E, top_k=2,
                          dispatch="grouped")
        out_e, aux_e, m = moe_ffn(params, x, cfg_ep, train=False)
        out_g, aux_g, _ = moe_ffn(params, x, cfg_g, train=False)
        np.testing.assert_allclose(np.asarray(out_e), np.asarray(out_g))
        assert float(aux_e) == pytest.approx(float(aux_g))
        assert float(m["dropped_frac"]) == 0.0

    def test_llama_grouped_ep_trains(self):
        """moe_dispatch="grouped_ep" + rule_set="moe_ep" flow through
        accelerate into a full train step on the (data x fsdp) expert
        submesh: loss falls, droplessness holds, and the ambient-mesh
        resolution (no mesh frozen into the config) keeps it
        elastic-safe."""
        import optax

        from dlrover_tpu.models import llama
        from dlrover_tpu.parallel.accelerate import accelerate
        from dlrover_tpu.parallel.strategy import Strategy

        cfg = llama.llama_tiny(num_experts=8,
                               moe_dispatch="grouped_ep")
        batch = {
            "input_ids": jax.random.randint(
                jax.random.PRNGKey(0), (8, 16), 0, cfg.vocab_size
            ),
            "labels": jax.random.randint(
                jax.random.PRNGKey(1), (8, 16), 0, cfg.vocab_size
            ),
        }
        strategy = Strategy(mesh=MeshPlan(data=2, fsdp=4),
                            rule_set="moe_ep")
        result = accelerate(
            llama.make_init_fn(cfg), llama.make_loss_fn(cfg),
            optax.adam(1e-2), batch, strategy=strategy,
        )
        state = result.init_fn(jax.random.PRNGKey(0))
        sharded = result.shard_batch(batch)
        losses = []
        for i in range(3):
            state, metrics = result.train_step(
                state, sharded, jax.random.PRNGKey(i)
            )
            losses.append(float(metrics["loss"]))
            assert float(metrics["moe_dropped_frac"]) == 0.0
        assert losses[-1] < losses[0]
