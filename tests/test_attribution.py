"""Performance-attribution plane (ISSUE 8): per-compiled-program
device-time & HBM accounting.

Covers: the shared legacy-jax cost/memory shims and the ONE MFU formula
(utils/prof), the attribution capture + program-cache keyed reuse
(telemetry.attribution / ElasticTrainer.attribution), the derived
MFU / exposed-comm gauges through the real executor (CPU-mesh e2e
smoke, pinned against the fixture-free utils/prof path), the
jax.profiler trace parser against a committed fixture, the runtime
optimizer's memory-feasibility gate (PLAN_REJECTED memory evidence),
G107, the device-memory absent-not-zero guard, the goodput model-FLOPs
column, the `tpurun attribution` CLI, and the ≤5% attribution-overhead
paired gate.
"""

from __future__ import annotations

import gzip
import json
import os
import time

import jax
import jax.numpy as jnp
import optax
import pytest

from dlrover_tpu.common import comm
from dlrover_tpu.common.config import get_context
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.telemetry import names as tm
from dlrover_tpu.telemetry import attribution as attr_mod
from dlrover_tpu.telemetry.events import clear_ring, recent_events
from dlrover_tpu.telemetry.metrics import process_registry
from dlrover_tpu.trainer.conf import Configuration
from dlrover_tpu.trainer.elastic import ElasticTrainer
from dlrover_tpu.trainer.executor import (
    NodeRuntimeReportHook,
    TrainExecutor,
    TrainHook,
)

FIXTURE = os.path.join(os.path.dirname(__file__), "testdata",
                       "attribution_trace.json")

PEAK = 1e9  # deterministic MFU denominator for the CPU mesh


@pytest.fixture(autouse=True)
def _attribution_context():
    """Pin the attribution knobs per test and restore after."""
    ctx = get_context()
    saved = (ctx.telemetry_enabled, ctx.attribution_enabled,
             ctx.device_peak_flops, ctx.device_hbm_budget_bytes)
    ctx.telemetry_enabled = True
    ctx.attribution_enabled = True
    ctx.device_peak_flops = PEAK
    ctx.device_hbm_budget_bytes = 0.0
    yield ctx
    (ctx.telemetry_enabled, ctx.attribution_enabled,
     ctx.device_peak_flops, ctx.device_hbm_budget_bytes) = saved


def _make_trainer(**kwargs):
    def init_fn(rng):
        return {"w": jax.random.normal(rng, (16, 8))}

    def loss_fn(params, batch, rng):
        return jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2), {}

    rngs = jax.random.split(jax.random.PRNGKey(0), 2)
    x = jax.random.normal(rngs[0], (32, 16))
    batch = {"x": x, "y": x @ jax.random.normal(rngs[1], (16, 8))}
    trainer = ElasticTrainer(
        init_fn, loss_fn, optax.sgd(0.05), batch,
        strategy=Strategy(mesh=MeshPlan(data=-1)), **kwargs,
    )
    return trainer, batch


# -- shared shims (satellite: one cost_analysis compatibility helper) --------


class _FakeMem:
    argument_size_in_bytes = 100
    temp_size_in_bytes = 50
    output_size_in_bytes = 30
    alias_size_in_bytes = 20


class _FakeCompiled:
    def __init__(self, cost, mem=_FakeMem()):
        self._cost = cost
        self._mem = mem

    def cost_analysis(self):
        return self._cost

    def memory_analysis(self):
        return self._mem


class TestSharedShims:
    def test_cost_analysis_dict_handles_dict_and_legacy_list(self):
        from dlrover_tpu.utils.prof import cost_analysis_dict

        d = {"flops": 7.0, "bytes accessed": 3.0}
        assert cost_analysis_dict(_FakeCompiled(d)) == d
        assert cost_analysis_dict(_FakeCompiled([d])) == d  # old jax
        assert cost_analysis_dict(_FakeCompiled([])) == {}
        assert cost_analysis_dict(_FakeCompiled(None)) == {}

    def test_cost_analysis_dict_swallows_backend_errors(self):
        from dlrover_tpu.utils.prof import cost_analysis_dict

        class Broken:
            def cost_analysis(self):
                raise NotImplementedError("no backend support")

        assert cost_analysis_dict(Broken()) == {}

    def test_compiled_peak_bytes_accounting(self):
        from dlrover_tpu.utils.prof import compiled_peak_bytes

        # args + temps + outputs - donated aliases
        assert compiled_peak_bytes(_FakeCompiled({})) == 160

        class NoMem:
            def memory_analysis(self):
                return None

        assert compiled_peak_bytes(NoMem()) == 0

    def test_derived_mfu_is_the_one_formula(self):
        from dlrover_tpu.utils.prof import ProfileResult, derived_mfu

        assert derived_mfu(100.0, 0.001, 1e6) == pytest.approx(0.1)
        assert derived_mfu(100.0, 0.0, 1e6) == 0.0
        assert derived_mfu(100.0, 0.001, 0.0) == 0.0
        pr = ProfileResult(
            steps_per_sec=1000.0, step_time_ms=1.0,
            flops_per_step=100.0, achieved_flops_per_sec=100_000.0,
            param_count=1, peak_memory_bytes=0,
        )
        assert pr.mfu(1e6) == pytest.approx(
            derived_mfu(100.0, 0.001, 1e6))


# -- trace parser (satellite: committed fixture, known totals) ---------------


class TestTraceParser:
    def test_fixture_category_totals(self):
        buckets = attr_mod.parse_trace_path(FIXTURE)
        assert buckets["events"] == 6
        assert buckets["compute_s"] == pytest.approx(0.030)
        assert buckets["collective_s"] == pytest.approx(0.015)
        assert buckets["infeed_s"] == pytest.approx(0.002)
        assert buckets["other_s"] == pytest.approx(0.003)
        # busy = the busiest single lane (tid 1: 45 ms; tid 2: 5 ms)
        assert buckets["busy_s"] == pytest.approx(0.045)
        assert buckets["wall_s"] == pytest.approx(0.058)
        assert buckets["idle_s"] == pytest.approx(0.013)
        # comm share of CATEGORIZED device-op time: 15 / (15+30+2)
        assert buckets["measured_comm_frac"] == pytest.approx(
            15 / 47, abs=1e-4)

    def test_host_lanes_cannot_dilute_comm_frac(self):
        # a fully-overlapping host TraceMe lane must not double-count
        # busy time or shrink the measured communication share
        records = [
            {"ph": "X", "pid": 1, "tid": 1, "ts": 0, "dur": 1_000_000,
             "name": "all-reduce.1"},
            {"ph": "X", "pid": 1, "tid": 99, "ts": 0, "dur": 1_000_000,
             "name": "TraceMe host step"},
        ]
        buckets = attr_mod.parse_trace_events(records)
        assert buckets["busy_s"] == pytest.approx(1.0)
        assert buckets["idle_s"] == pytest.approx(0.0)
        assert buckets["measured_comm_frac"] == pytest.approx(1.0)

    def test_gzip_and_directory_discovery(self, tmp_path):
        profile = tmp_path / "plugins" / "profile" / "run1"
        profile.mkdir(parents=True)
        gz = profile / "host.trace.json.gz"
        with gzip.open(gz, "wt") as fh:
            fh.write(open(FIXTURE).read())
        assert attr_mod.find_trace_files(str(tmp_path)) == [str(gz)]
        buckets = attr_mod.parse_trace_path(str(tmp_path))
        assert buckets["collective_s"] == pytest.approx(0.015)
        assert buckets["source_files"] == 1

    def test_categorize_op_collective_wins_over_fusion(self):
        # a fused collective is traffic, not compute
        assert attr_mod.categorize_op("fusion.all-reduce.3") == \
            "collective"
        assert attr_mod.categorize_op("fusion.99") == "compute"
        assert attr_mod.categorize_op("mystery") == "other"

    def test_empty_trace(self):
        buckets = attr_mod.parse_trace_events([])
        assert buckets["busy_s"] == 0.0
        assert buckets["measured_comm_frac"] == 0.0


# -- capture ----------------------------------------------------------------


class TestCapture:
    def test_capture_reads_exact_cost_and_collectives(self):
        trainer, _ = _make_trainer()
        trainer.prepare()
        record = trainer.attribution()
        assert record is not None
        assert record.flops_per_step > 0
        assert record.bytes_accessed_per_step > 0
        assert record.n_devices == len(jax.devices())
        assert record.steps_per_call == 1
        assert record.source == "hlo"
        # a data-parallel mesh must show the gradient all-reduce
        assert record.collective_bytes.get("all-reduce", 0) > 0
        assert record.predicted_comm_total_s == pytest.approx(
            sum(record.predicted_comm_s.values()))
        assert record.peak_flops_per_s == PEAK
        assert record.predicted_compute_s == pytest.approx(
            record.flops_per_step / PEAK)

    def test_record_cached_by_program_key(self):
        trainer, _ = _make_trainer()
        trainer.prepare()
        first = trainer.attribution()
        assert trainer.attribution() is first  # no re-capture

    def test_disabled_returns_none(self, _attribution_context):
        trainer, _ = _make_trainer()
        trainer.prepare()
        _attribution_context.attribution_enabled = False
        assert trainer.attribution() is None

    def test_multi_step_program_normalizes_per_step(self):
        trainer1, _ = _make_trainer()
        trainer1.prepare()
        r1 = trainer1.attribution()
        trainer4, _ = _make_trainer(steps_per_call=4)
        trainer4.prepare()
        r4 = trainer4.attribution()
        assert r4.steps_per_call == 4
        # XLA counts the K-scan body once, and the K-weighted HLO
        # collective bytes are divided back by K: both quantities read
        # PER STEP, so K=4 stays comparable to K=1
        assert r4.flops_per_step == pytest.approx(
            r1.flops_per_step, rel=0.25)
        assert r4.collective_bytes.get("all-reduce", 0) == \
            pytest.approx(r1.collective_bytes.get("all-reduce", 1),
                          rel=0.25)

    def test_planner_source_with_model_spec(self):
        from dlrover_tpu.parallel.planner import ModelSpec

        spec = ModelSpec(param_count=1000, num_layers=2,
                         hidden_size=16, seq_len=8, global_batch=32)
        trainer, batch = _make_trainer()
        trainer.prepare()
        record = attr_mod.capture_attribution(
            trainer.accelerated, example_batch=batch,
            model_spec=spec, emit=False)
        assert record.source == "planner"
        # planner families, not HLO kinds
        assert set(record.predicted_comm_s) <= {
            "tp", "fsdp", "dp", "seq", "pipe", "moe_dispatch"}

    def test_derived_quantities_clamp(self):
        trainer, _ = _make_trainer()
        trainer.prepare()
        record = trainer.attribution()
        assert record.mfu(0.0) == 0.0
        assert 0.0 <= record.exposed_comm_fraction(1e-12) <= 1.0
        assert record.exposed_comm_fraction(1e9) == pytest.approx(
            1.0, abs=1e-6)
        assert record.arithmetic_intensity > 0


# -- executor e2e smoke (satellite: gauges in /metrics, MFU agreement) -------


class TestExecutorSmoke:
    def _run(self, trainer, batch, steps=24, **conf):
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch] * steps,
            conf=Configuration({
                "train_steps": steps, "log_every_steps": 0,
                "train_window": 2, "preemption_grace": False,
                **conf,
            }),
        )
        executor.train_and_evaluate()
        return executor

    def test_gauges_exported_and_agree_with_prof(self):
        process_registry().reset()
        clear_ring()
        trainer, batch = _make_trainer()
        self._run(trainer, batch)
        reg = process_registry()
        mfu_g = reg.get(tm.ATTR_MFU)
        assert mfu_g is not None and mfu_g.value > 0
        assert reg.get(tm.ATTR_EXPOSED_COMM_FRAC) is not None
        assert 0.0 <= reg.get(tm.ATTR_EXPOSED_COMM_FRAC).value <= 1.0
        prom = reg.render_prometheus()
        for name in (tm.ATTR_MFU, tm.ATTR_EXPOSED_COMM_FRAC,
                     tm.ATTR_FLOPS_PER_STEP, tm.ATTR_ARITH_INTENSITY,
                     tm.ATTR_PEAK_HBM_MB, tm.ATTR_COMM_PREDICTED_S):
            assert name in prom
        # the capture event landed with the record attached
        captured = [e for e in recent_events()
                    if e["kind"] == tm.EventKind.ATTRIBUTION_CAPTURED]
        assert captured and captured[-1]["flops_per_step"] > 0

        # MFU agreement with the fixture-free utils/prof path: the
        # FLOPs side is EXACT (same compiled cost analysis through the
        # same shim), and for one shared step time the record's MFU and
        # the profiler's MFU are the SAME number — the one-formula pin
        from dlrover_tpu.utils.prof import DryRunner, analyze_cost

        result = trainer.accelerated
        sharded = result.shard_batch(batch)
        cost = analyze_cost(result.train_step, trainer.prepare(),
                            sharded, jax.random.PRNGKey(0))
        assert reg.get(tm.ATTR_FLOPS_PER_STEP).value == pytest.approx(
            cost.flops)
        profile = DryRunner(warmup=1, steps=3).profile(
            result.train_step, trainer.prepare(), sharded)
        record = trainer.attribution()
        assert record.flops_per_step == pytest.approx(
            profile.flops_per_step)
        assert record.mfu(1.0 / profile.steps_per_sec) == \
            pytest.approx(profile.mfu(PEAK))

    def test_no_fake_zero_before_first_measured_step(self):
        # between capture (train start) and the first materialized
        # step, the STATIC gauges exist but the DERIVED ones must be
        # absent — a scrape during a minutes-long first compile must
        # not read mfu=0 as if the job were measured dead
        process_registry().reset()
        trainer, batch = _make_trainer()
        executor = TrainExecutor(
            trainer, train_iter_fn=lambda: [batch],
            conf=Configuration({"train_steps": 1,
                                "preemption_grace": False}),
        )
        executor.state = trainer.prepare()
        executor._fetch_attribution()
        reg = process_registry()
        assert reg.get(tm.ATTR_FLOPS_PER_STEP) is not None
        assert reg.get(tm.ATTR_MFU) is None
        assert reg.get(tm.ATTR_EXPOSED_COMM_FRAC) is None

    def test_attribution_off_means_absent_not_zero(
            self, _attribution_context):
        process_registry().reset()
        _attribution_context.attribution_enabled = False
        trainer, batch = _make_trainer()
        self._run(trainer, batch, steps=8)
        assert process_registry().get(tm.ATTR_MFU) is None
        assert process_registry().get(tm.ATTR_FLOPS_PER_STEP) is None


# -- memory-feasibility gate --------------------------------------------------


def _big_model_optimizer(hbm_bytes=2e9, budget=0.0):
    from dlrover_tpu.master.monitor.node_series import NodeRuntimeStore
    from dlrover_tpu.master.optimizer import RuntimeOptimizer
    from dlrover_tpu.parallel.planner import DeviceSpec

    get_context().device_hbm_budget_bytes = budget
    opt = RuntimeOptimizer(NodeRuntimeStore(), cooldown_secs=0,
                           device=DeviceSpec(hbm_bytes=hbm_bytes))
    opt.update_model_info(comm.ModelInfo(
        num_params=300_000_000, hidden_size=2048, num_layers=16,
        seq_len=2048))
    opt.update_running_config(comm.TrainerConfigReport(
        node_id=0, world=8, mesh_shape={"fsdp": 8}, train_window=4,
        steps_per_call=1, global_batch=8))
    return opt


class TestMemoryFeasibilityGate:
    def test_oversized_candidates_rejected_with_memory_reason(self):
        clear_ring()
        opt = _big_model_optimizer(hbm_bytes=1e9)  # nothing fits
        decision = opt.replan("straggler:2")
        assert decision is not None
        assert decision.outcome == "rejected"
        assert decision.reason == "memory_infeasible:all"
        assert decision.memory_rejected
        entry = decision.memory_rejected[0]
        assert entry["predicted_hbm_bytes"] > entry["budget_bytes"]
        # the PLAN_REJECTED memory evidence is in the event timeline
        # (what `tpurun plan --events` / `tpurun attribution` read)
        rejected = [e for e in recent_events()
                    if e["kind"] == tm.EventKind.OPTIMIZER_PLAN_REJECTED
                    and str(e.get("reason", "")).startswith("memory")]
        assert rejected
        # the per-pass evidence record carries the worst offender
        evidence = [e for e in rejected if "predicted_hbm_mb" in e]
        assert evidence
        assert evidence[-1]["predicted_hbm_mb"] > \
            evidence[-1]["budget_mb"]
        # and in the queryable trail (tpurun plan / attribution --addr)
        assert opt.memory_rejections()
        trail = opt.to_report()["decisions"][-1]
        assert trail["memory_rejected"]
        # evidence is worst-first: the event's named offender is the
        # true maximum even when the retained list is trimmed
        sizes = [m["predicted_hbm_bytes"]
                 for m in decision.memory_rejected]
        assert sizes == sorted(sizes, reverse=True)
        assert evidence[-1]["predicted_hbm_mb"] == pytest.approx(
            sizes[0] / 1e6, rel=0.01)

    def test_partial_gate_still_prices_feasible_meshes(self):
        # budget between the sharded (fsdp) and replicated (data) cost:
        # the data-heavy meshes die at the gate, the fsdp ones price
        opt = _big_model_optimizer(hbm_bytes=95e9, budget=4.0e9)
        decision = opt.replan("recovered:2")
        assert decision is not None
        assert decision.candidates  # something still priced
        assert decision.memory_rejected  # and something was gated
        gated = {json.dumps(m["mesh"], sort_keys=True)
                 for m in decision.memory_rejected}
        priced = {json.dumps(c["mesh"], sort_keys=True)
                  for c in decision.candidates}
        assert gated.isdisjoint(priced)

    def test_memory_infeasible_error_carries_evidence(self):
        from dlrover_tpu.master.optimizer.calibration import (
            CostCalibrator,
            MemoryInfeasibleError,
        )
        from dlrover_tpu.parallel.planner import DeviceSpec, ModelSpec

        cal = CostCalibrator(
            model=ModelSpec(param_count=300_000_000, num_layers=16,
                            hidden_size=2048, seq_len=2048,
                            global_batch=8),
            device=DeviceSpec(hbm_bytes=1e9),
        )
        with pytest.raises(MemoryInfeasibleError) as err:
            cal.price(MeshPlan(data=8))
        assert err.value.memory_bytes > err.value.budget_bytes
        # the CURRENT config is observably running: never gated
        assert cal.price(MeshPlan(data=8), require_fit=False) > 0


# -- G107 ---------------------------------------------------------------------


class TestG107:
    def test_check_memory_budget_pure(self):
        from dlrover_tpu.analysis.graph_lint import check_memory_budget

        assert check_memory_budget(0, 1e9) == []  # unknown peak
        assert check_memory_budget(1e9, 0) == []  # unknown budget
        assert check_memory_budget(1e9, 2e9) == []  # fits
        findings = check_memory_budget(3e9, 2e9)
        assert len(findings) == 1
        assert findings[0].rule_id == "G107"
        assert "3.00 GB" in findings[0].message

    def test_lint_train_step_fires_on_tiny_budget(self):
        from dlrover_tpu.analysis.graph_lint import lint_train_step

        report = lint_train_step(rules={"G107"}, hbm_budget_bytes=16.0)
        assert [f.rule_id for f in report.findings] == ["G107"]

    def test_g107_in_rule_registry(self):
        from dlrover_tpu.analysis.graph_lint import (
            ALL_GRAPH_RULES,
            GRAPH_RULE_DOCS,
        )

        assert "G107" in ALL_GRAPH_RULES
        assert "G107" in GRAPH_RULE_DOCS


# -- device-memory guard (satellite: absent, never 0) ------------------------


class _NoStatsDevice:
    device_kind = "cpu"


class _StatsDevice:
    device_kind = "TPU v5e"

    @staticmethod
    def memory_stats():
        return {"bytes_in_use": 100 * 1024 * 1024,
                "bytes_limit": 16 * 1024 * 1024 * 1024}


class TestDeviceMemoryGuard:
    def test_no_stats_backend_reports_none(self, monkeypatch):
        hook = NodeRuntimeReportHook(master_client=None, every_steps=1,
                                     min_interval_s=0)
        hook._devices = [_NoStatsDevice()]
        assert hook._device_memory_mb() == (None, None)

    def test_stats_backend_reports_usage_and_headroom(self):
        hook = NodeRuntimeReportHook(master_client=None, every_steps=1,
                                     min_interval_s=0)
        hook._devices = [_StatsDevice(), _StatsDevice()]
        in_use, headroom = hook._device_memory_mb()
        assert in_use == pytest.approx(200.0)
        assert headroom == pytest.approx(2 * 16 * 1024 - 200.0)

    def test_node_series_exports_absent_as_no_gauge(self):
        from dlrover_tpu.master.monitor.node_series import (
            NodeRuntimeStore,
        )

        process_registry().reset()
        store = NodeRuntimeStore()
        report = comm.NodeRuntimeReport(
            node_id=7, step=10, steps_total=10.0,
            bounds=[0.001, 0.01], step_time_counts=[5, 5, 0],
            rss_mb=10.0, device_mem_mb=None, mfu=None,
        )
        sample = store.ingest(report)
        assert sample.device_mem_mb is None and sample.mfu is None
        reg = process_registry()
        assert reg.get(tm.NODE_DEVICE_MEM_MB,
                       labels={"node": "7"}) is None
        assert reg.get(tm.NODE_MFU, labels={"node": "7"}) is None
        # present values DO export
        store.ingest(comm.NodeRuntimeReport(
            node_id=7, step=20, steps_total=20.0,
            bounds=[0.001, 0.01], step_time_counts=[9, 11, 0],
            rss_mb=10.0, device_mem_mb=123.0, mfu=0.5,
            exposed_comm_frac=0.25, hbm_headroom_mb=1000.0))
        assert reg.get(tm.NODE_DEVICE_MEM_MB,
                       labels={"node": "7"}).value == 123.0
        assert reg.get(tm.NODE_MFU, labels={"node": "7"}).value == 0.5
        assert reg.get(tm.NODE_EXPOSED_COMM_FRAC,
                       labels={"node": "7"}).value == 0.25
        # a stat that BECOMES absent (program swap, failed re-capture)
        # RETRACTS its series — the stale 0.5 must not export forever
        store.ingest(comm.NodeRuntimeReport(
            node_id=7, step=30, steps_total=30.0,
            bounds=[0.001, 0.01], step_time_counts=[15, 15, 0],
            rss_mb=10.0, device_mem_mb=None, mfu=None))
        assert reg.get(tm.NODE_MFU, labels={"node": "7"}) is None
        assert reg.get(tm.NODE_DEVICE_MEM_MB,
                       labels={"node": "7"}) is None


# -- straggler verdict gains the comm-vs-compute label -----------------------


class TestStragglerBoundEvidence:
    def test_verdict_labeled_comm_bound(self):
        from dlrover_tpu.master.monitor.node_series import (
            NodeRuntimeStore,
        )
        from dlrover_tpu.master.monitor.straggler import (
            StragglerDetector,
        )

        store = NodeRuntimeStore()
        detector = StragglerDetector(store, ratio=2.0,
                                     confirm_windows=1, hang_secs=0)
        bounds = [0.001, 0.01, 0.1]

        def report(node, counts, **extra):
            store.ingest(comm.NodeRuntimeReport(
                node_id=node, step=10, steps_total=10.0,
                bounds=bounds, step_time_counts=counts, **extra))
            detector.observe(node)

        report(0, [10, 0, 0, 0], exposed_comm_frac=0.2)
        report(1, [10, 0, 0, 0], exposed_comm_frac=0.25)
        report(2, [0, 0, 10, 0], mfu=0.01, exposed_comm_frac=0.8)
        verdicts = detector.verdicts()
        assert verdicts[2]["verdict"] == "straggler"
        evidence = verdicts[2]["evidence"]
        # RELATIVE judgement: 0.8 vs the peers' 0.225 median
        assert evidence["bound"] == "comm-bound"
        assert evidence["exposed_comm_frac"] == pytest.approx(0.8)
        assert evidence["peer_median_comm_frac"] == pytest.approx(
            0.225)
        assert evidence["mfu"] == pytest.approx(0.01)

    def test_verdict_labeled_compute_bound_when_frac_tracks_peers(self):
        from dlrover_tpu.master.monitor.node_series import (
            NodeRuntimeStore,
        )
        from dlrover_tpu.master.monitor.straggler import (
            StragglerDetector,
        )

        store = NodeRuntimeStore()
        detector = StragglerDetector(store, ratio=2.0,
                                     confirm_windows=1, hang_secs=0)
        bounds = [0.001, 0.01, 0.1]

        def report(node, counts, **extra):
            store.ingest(comm.NodeRuntimeReport(
                node_id=node, step=10, steps_total=10.0,
                bounds=bounds, step_time_counts=counts, **extra))
            detector.observe(node)

        # every node (straggler included) shows the same high upper
        # bound — the extra step time is NOT extra communication
        report(0, [10, 0, 0, 0], exposed_comm_frac=0.6)
        report(1, [10, 0, 0, 0], exposed_comm_frac=0.6)
        report(2, [0, 0, 10, 0], exposed_comm_frac=0.65)
        evidence = detector.verdicts()[2]["evidence"]
        assert evidence["bound"] == "compute-bound"


# -- goodput model-FLOPs column ----------------------------------------------


class TestGoodputModelFlops:
    def test_column_derived_from_attribution_record(self):
        from dlrover_tpu.telemetry.goodput import derive_goodput

        events = [
            {"kind": "train_start", "ts": 0.0, "node": "0", "pid": 1,
             "step": 0},
            {"kind": tm.EventKind.ATTRIBUTION_CAPTURED, "ts": 1.0,
             "node": "0", "pid": 1, "flops_per_step": 100.0,
             "n_devices": 4},
            {"kind": "train_end", "ts": 11.0, "node": "0", "pid": 1,
             "step": 50},
        ]
        report = derive_goodput(events)
        col = report["detail"]["model_flops"]
        assert col["flops_per_step"] == pytest.approx(400.0)
        assert col["steps"] == 50
        assert col["total"] == pytest.approx(20000.0)
        assert col["per_productive_second"] > 0

    def test_column_integrates_across_elastic_resizes(self):
        # steps 0-100 on 8 devices, then a resize re-captures at 4
        # devices and the job runs to step 150: each phase is charged
        # at ITS OWN record's rate, not the newest record's
        from dlrover_tpu.telemetry.goodput import derive_goodput

        events = [
            {"kind": "train_start", "ts": 0.0, "node": "0", "pid": 1,
             "step": 0},
            {"kind": tm.EventKind.ATTRIBUTION_CAPTURED, "ts": 1.0,
             "node": "0", "pid": 1, "flops_per_step": 100.0,
             "n_devices": 8},
            {"kind": "train_end", "ts": 50.0, "node": "0", "pid": 1,
             "step": 100},
            {"kind": "train_start", "ts": 60.0, "node": "0", "pid": 1,
             "step": 100},
            {"kind": tm.EventKind.ATTRIBUTION_CAPTURED, "ts": 61.0,
             "node": "0", "pid": 1, "flops_per_step": 100.0,
             "n_devices": 4},
            {"kind": "train_end", "ts": 90.0, "node": "0", "pid": 1,
             "step": 150},
        ]
        col = derive_goodput(events)["detail"]["model_flops"]
        assert col["records"] == 2
        assert col["steps"] == 150
        # 100 steps @ 800 flops + 50 steps @ 400 flops
        assert col["total"] == pytest.approx(100 * 800 + 50 * 400)

    def test_no_record_no_column(self):
        from dlrover_tpu.telemetry.goodput import derive_goodput

        events = [
            {"kind": "train_start", "ts": 0.0, "node": "0", "pid": 1},
            {"kind": "train_end", "ts": 5.0, "node": "0", "pid": 1,
             "step": 9},
        ]
        assert "model_flops" not in derive_goodput(events)["detail"]


# -- CLI ----------------------------------------------------------------------


class TestAttributionCli:
    def test_forensic_events_view(self, tmp_path, capsys):
        from dlrover_tpu.telemetry.cli import main as cli_main

        path = tmp_path / "events.jsonl"
        records = [
            {"kind": tm.EventKind.ATTRIBUTION_CAPTURED, "ts": 1.0,
             "node": "0", "pid": 42, "flops_per_step": 123.0,
             "arithmetic_intensity": 0.5, "peak_hbm_mb": 1.5,
             "predicted_comm_total_s": 0.001, "source": "hlo"},
            {"kind": tm.EventKind.OPTIMIZER_PLAN_REJECTED, "ts": 2.0,
             "node": "0", "pid": 1, "reason": "memory_infeasible",
             "mesh": {"data": 8}, "predicted_hbm_mb": 7000.0,
             "budget_mb": 1600.0},
        ]
        path.write_text(
            "".join(json.dumps(r) + "\n" for r in records))
        rc = cli_main(["attribution", "--events", str(path), "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["records"][0]["flops_per_step"] == 123.0
        assert out["memory_rejected"][0]["reason"] == \
            "memory_infeasible"

    def test_trace_view(self, capsys):
        from dlrover_tpu.telemetry.cli import main as cli_main

        rc = cli_main(["attribution", "--trace", FIXTURE, "--json"])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["measured_comm_frac"] == pytest.approx(15 / 47,
                                                          abs=1e-4)

    def test_tpurun_routes_attribution(self, capsys):
        from dlrover_tpu.trainer.run import main as tpurun_main

        rc = tpurun_main(["attribution", "--trace", FIXTURE, "--json"])
        assert rc == 0
        assert json.loads(
            capsys.readouterr().out)["busy_s"] == pytest.approx(0.045)


# -- overhead gate (satellite: attribution collection stays cheap) -----------


class _TimedRegion(TrainHook):
    def __init__(self, warmup):
        self.warmup = warmup
        self.t0 = None

    def before_step(self, step):
        if step == self.warmup + 1 and self.t0 is None:
            self.t0 = time.perf_counter()


class TestAttributionOverheadGate:
    def test_overhead_within_budget(self, _attribution_context):
        """Attribution must stay observation-only: ≤5% step-loop
        overhead with derivation ON vs OFF, as the median of
        back-to-back paired ratios (run drift on a shared 1-core box
        dwarfs the real cost — two gauge stores per materialization).
        The one-off capture compile lands at TRAIN START (inside the
        COMPILE_FIRST_STEP window), so the timed region sees only the
        per-step cost."""
        steps, warmup = 280, 8
        trainer, batch = _make_trainer()

        def run(enabled):
            _attribution_context.attribution_enabled = enabled
            timer = _TimedRegion(warmup)
            executor = TrainExecutor(
                trainer,
                train_iter_fn=lambda: [batch] * (warmup + steps),
                hooks=[timer],
                conf=Configuration({
                    "train_steps": warmup + steps,
                    "log_every_steps": 0, "train_window": 4,
                    "preemption_grace": False,
                }),
            )
            executor.train_and_evaluate()
            return time.perf_counter() - timer.t0

        run(True)  # prime: capture + program compile out of the pairs

        def leg(enabled, best_of):
            # best_of > 1 takes the MIN over repeats — the floor
            # estimator that filters one-off scheduler stalls (the
            # residual flake on a shared 1-core box)
            return min(run(enabled) for _ in range(best_of))

        def paired_median(pairs=3, best_of=1):
            ratios = []
            for i in range(pairs):
                if i % 2 == 0:
                    dt_off = leg(False, best_of)
                    dt_on = leg(True, best_of)
                else:
                    dt_on = leg(True, best_of)
                    dt_off = leg(False, best_of)
                ratios.append(dt_on / dt_off)
            return sorted(ratios)[len(ratios) // 2]

        # same escalation discipline as the telemetry overhead gate
        # (tests/test_telemetry.py): up to 3 attempts gated on the MIN
        # of attempt medians, retries escalating to best-of-2 legs.
        # The first attempt costs exactly what the old 5-pair gate
        # did; a clean tree stops failing tier-1 on scheduler noise,
        # while the large regressions this gate exists for (≥10%,
        # e.g. capture placement inside the timed loop) fail every
        # attempt.
        medians = [paired_median()]
        while medians[-1] - 1.0 > 0.05 and len(medians) < 3:
            medians.append(paired_median(best_of=2))
        overhead = min(medians) - 1.0
        assert overhead <= 0.05, (
            f"attribution overhead {overhead:.1%} above the 5% budget "
            f"(attempt medians {[round(m, 3) for m in medians]})"
        )
