"""Test environment: force the JAX CPU backend with 8 virtual devices.

Multi-chip semantics (meshes, collectives, shardings) are exercised on a
virtual CPU mesh, mirroring the reference's gloo-on-CPU test strategy
(`atorch/atorch/tests/test_utils.py`). Must run before jax is imported.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# libtpu's init (reached by the deviceless-AOT tests through
# jax.experimental.topologies) probes the GCE metadata server for TPU
# worker hostnames; off-GCE that probe is a ~460 s silent network
# timeout at ~0% CPU — nearly half the tier-1 wall budget. Skip the
# query and point the metadata addresses at a fast-refusing local port
# (setdefault: a real TPU host can still override).
os.environ.setdefault("TPU_SKIP_MDS_QUERY", "true")
os.environ.setdefault("GCE_METADATA_IP", "127.0.0.1:1")
os.environ.setdefault("GCE_METADATA_HOST", "127.0.0.1:1")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# AVX2 ISA cap: silent, portable persistent-cache reloads on CPU (test
# shapes are far too small for AVX512 to matter) — must precede jax
# import; see cap_cpu_isa_for_cache for the full rationale
from dlrover_tpu.utils.compile_cache import cap_cpu_isa_for_cache  # noqa: E402

cap_cpu_isa_for_cache()
os.environ.setdefault("DLROVER_TPU_LOG_LEVEL", "WARNING")

# A SIGKILLed tier-1 run (timeout, OOM-killer) leaves a stale
# /tmp/libtpu_lockfile behind; libtpu's init in LATER runs then waits
# on it silently — the suite looks hung at 0% CPU before a single test
# collects. Remove a leftover at session import — but only after an
# flock probe proves no LIVE process holds it (os.remove succeeds on a
# held flock, so an unconditional unlink would strip a concurrent
# run's lock — the very conflict the file serializes). See
# docs/operations.md "Troubleshooting".
_lock = os.environ.get("LIBTPU_LOCKFILE", "/tmp/libtpu_lockfile")
try:
    if os.path.exists(_lock):
        import fcntl

        with open(_lock) as _fh:
            fcntl.flock(_fh, fcntl.LOCK_EX | fcntl.LOCK_NB)  # probe
            os.remove(_lock)  # stale: nothing holds it
except OSError:
    pass  # held by a live process (or not ours to remove): leave it

# The environment's sitecustomize force-registers an experimental TPU
# platform ('axon') that overrides JAX_PLATFORMS; an explicit config update
# after import is the only reliable way to pin the CPU backend.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def mesh_ctx(mesh):
    """Context establishing ``mesh`` as the ambient mesh for a test:
    ``jax.sharding.set_mesh`` when present; on legacy jax the Mesh
    itself is the (thread-resources) ambient-mesh context manager."""
    set_mesh = getattr(jax.sharding, "set_mesh", None)
    return set_mesh(mesh) if set_mesh is not None else mesh
