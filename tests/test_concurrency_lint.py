"""Concurrency lint (ISSUE 17): inferred lock discipline (DLR010),
the cross-class lock-order graph (DLR011), blocking-calls-under-lock
(DLR009), inline suppressions with mandatory reasons (DLR012), and the
gather-free serving invariant (G110) — plus regression pins for the
runtime races the new pass caught at introduction (sharding client RPC
under lock, hang-detector lost update, torn monitor/PS/router reads).
"""

import json
import textwrap
import threading
import time

import pytest

from dlrover_tpu.analysis.concurrency import (
    analyze_source,
    build_lock_graph,
    lint_source_concurrency,
    lock_order_findings,
)
from dlrover_tpu.analysis.findings import (
    Baseline,
    apply_suppressions,
    scan_suppressions,
)


def _lint(src, rules=None, counters=None):
    return lint_source_concurrency(
        textwrap.dedent(src), "fixture.py", rules=rules,
        counters=counters)


def _ids(findings):
    return [f.rule_id for f in findings]


# -- DLR009: blocking call under a lock --------------------------------------


class TestBlockingUnderLock:
    def test_sleep_under_lock_fires(self):
        fs = _lint("""
            import threading, time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def run(self):
                    with self._lock:
                        time.sleep(1.0)
        """)
        assert _ids(fs) == ["DLR009"]
        assert "time.sleep" in fs[0].message

    def test_sleep_outside_lock_clean(self):
        fs = _lint("""
            import threading, time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def run(self):
                    with self._lock:
                        n = 1
                    time.sleep(1.0)
        """)
        assert fs == []

    def test_rpc_stub_verb_under_lock_fires(self):
        fs = _lint("""
            import threading

            class W:
                def __init__(self, client):
                    self._lock = threading.Lock()
                    self._client = client

                def ask(self):
                    with self._lock:
                        return self._client.get_task("ds")
        """)
        assert _ids(fs) == ["DLR009"]

    def test_queue_get_without_timeout_fires_with_timeout_clean(self):
        src = """
            import queue, threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._queue = queue.Queue()

                def pull(self):
                    with self._lock:
                        return self._queue.get({})
        """
        assert _ids(_lint(src.format(""))) == ["DLR009"]
        assert _lint(src.format("timeout=1.0")) == []
        assert _lint(src.format("False")) == []  # block=False positional

    def test_thread_join_without_timeout_fires(self):
        fs = _lint("""
            import threading

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._thread = threading.Thread(target=print)

                def stop(self):
                    with self._lock:
                        self._thread.join()
        """)
        assert _ids(fs) == ["DLR009"]

    def test_listener_iteration_under_lock_fires(self):
        # the PR 7 deadlock class: callbacks invoked while holding the
        # lock re-enter and deadlock; copying the list doesn't help if
        # the loop body still runs under the lock
        fs = _lint("""
            import threading

            class Bus:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._listeners = []

                def fire(self, ev):
                    with self._lock:
                        for cb in list(self._listeners):
                            cb(ev)
        """)
        assert _ids(fs) == ["DLR009"]

    def test_inferred_held_helper_fires(self):
        # the helper never takes the lock syntactically, but its only
        # call site holds it — the blocking call is still under a lock
        fs = _lint("""
            import threading, time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def _helper(self):
                    time.sleep(0.5)

                def run(self):
                    with self._lock:
                        self._helper()
        """)
        assert _ids(fs) == ["DLR009"]
        assert "every caller" in fs[0].message

    def test_unheld_call_site_vetoes_inference(self):
        fs = _lint("""
            import threading, time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()

                def _helper(self):
                    time.sleep(0.5)

                def run(self):
                    with self._lock:
                        self._helper()

                def bare(self):
                    self._helper()
        """)
        assert fs == []

    def test_lock_passed_as_argument_guards_region(self):
        # an argument lock has no graph identity but the held region
        # is real: blocking inside it still fires
        fs = _lint("""
            import time

            def flush(lock, buf):
                with lock:
                    time.sleep(0.1)
        """)
        assert _ids(fs) == ["DLR009"]


# -- DLR010: mixed-guard attribute access ------------------------------------


class TestMixedGuard:
    FIRING = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0

            def inc(self):
                with self._lock:
                    self._n += 1

            def read(self):
                return self._n
    """

    def test_locked_write_lockfree_read_fires(self):
        fs = _lint(self.FIRING)
        assert _ids(fs) == ["DLR010"]
        assert fs[0].scope == "Counter._n"  # stable baseline key

    def test_locked_everywhere_clean(self):
        fs = _lint("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def inc(self):
                    with self._lock:
                        self._n += 1

                def read(self):
                    with self._lock:
                        return self._n
        """)
        assert fs == []

    def test_init_write_is_exempt(self):
        # __init__ publishes the object before any thread can race;
        # only the lock-free read in a NON-exempt method fires
        fs = _lint("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._v = 0

                def set(self, v):
                    with self._lock:
                        self._v = v
        """)
        assert fs == []

    def test_guarded_by_annotation_exempts(self):
        fs = _lint("""
            import threading

            class Counter:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0  # guarded-by: external serialization

                def inc(self):
                    with self._lock:
                        self._n += 1

                def read(self):
                    return self._n
        """)
        assert fs == []

    def test_same_method_mixing_does_not_fire(self):
        # "written under the lock in one method, touched lock-free in
        # ANOTHER" — a single method mixing with itself is not DLR010
        fs = _lint("""
            import threading

            class C:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._v = 0

                def bump(self):
                    with self._lock:
                        self._v += 1
                    return self._v
        """)
        assert fs == []

    def test_inherited_helper_called_under_subclass_lock(self):
        # base helper writes lock-free but is only ever called from
        # the subclass's locked method: the inheritance-aware
        # inference must not flag it
        fs = _lint("""
            import threading

            class Base:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._state = {}

                def _apply(self, k, v):
                    self._state[k] = v

            class Impl(Base):
                def put(self, k, v):
                    with self._lock:
                        self._apply(k, v)

                def get(self, k):
                    with self._lock:
                        return self._state.get(k)
        """)
        assert fs == []


# -- DLR011: lock-order graph ------------------------------------------------


class TestLockOrderGraph:
    def test_two_lock_inversion_fires(self):
        fs = _lint("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._b:
                        with self._a:
                            pass
        """)
        assert _ids(fs) == ["DLR011"]
        assert "inversion" in fs[0].message

    def test_consistent_order_clean(self):
        fs = _lint("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def three(self):
                    with self._a:
                        with self._b:
                            pass
        """)
        assert fs == []

    def test_three_lock_cycle_fires(self):
        fs = _lint("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._c = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def bc(self):
                    with self._b:
                        with self._c:
                            pass

                def ca(self):
                    with self._c:
                        with self._a:
                            pass
        """)
        assert _ids(fs) == ["DLR011"]
        # the witness names all three locks
        assert fs[0].message.count("->") >= 3

    def test_call_resolved_acquisition(self):
        # outer holds x and calls a helper that takes y: the x->y edge
        # is reached through the method call, one level deep
        fs = _lint("""
            import threading

            class S:
                def __init__(self):
                    self._x = threading.Lock()
                    self._y = threading.Lock()

                def helper(self):
                    with self._y:
                        pass

                def outer(self):
                    with self._x:
                        self.helper()

                def rev(self):
                    with self._y:
                        with self._x:
                            pass
        """)
        assert _ids(fs) == ["DLR011"]

    def test_cross_class_inversion(self):
        # the graph spans classes: A holds its lock and calls into B;
        # B's own method takes the locks in the opposite order through
        # a typed attribute
        fs = _lint("""
            import threading

            class Store:
                def __init__(self):
                    self._lock = threading.Lock()

                def write(self, k):
                    with self._lock:
                        pass

            class Manager:
                def __init__(self, store: Store):
                    self._lock = threading.Lock()
                    self._store = store

                def update(self, k):
                    with self._lock:
                        self._store.write(k)

            class Reporter:
                def __init__(self, mgr: Manager, store: Store):
                    self._mgr = mgr
                    self._store = store

                def snapshot(self):
                    with self._store._lock:
                        with self._mgr._lock:
                            pass
        """)
        assert "DLR011" in _ids(fs)

    def test_with_multi_item_ordering(self):
        # `with a, b:` acquires left-to-right; the reversed pair in
        # another method is an inversion
        fs = _lint("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a, self._b:
                        pass

                def two(self):
                    with self._b, self._a:
                        pass
        """)
        assert _ids(fs) == ["DLR011"]

    def test_nonreentrant_self_reacquire_fires(self):
        fs = _lint("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.Lock()

                def outer(self):
                    with self._lock:
                        with self._lock:
                            pass
        """)
        assert _ids(fs) == ["DLR011"]
        assert "re-acquired" in fs[0].message

    def test_rlock_reentry_clean(self):
        fs = _lint("""
            import threading

            class S:
                def __init__(self):
                    self._lock = threading.RLock()

                def outer(self):
                    with self._lock:
                        with self._lock:
                            pass
        """)
        assert fs == []

    def test_graph_edges_have_witness_sites(self):
        summary = analyze_source(textwrap.dedent("""
            import threading

            class S:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass
        """), "w.py")
        graph = build_lock_graph([summary])
        assert ("S._a", "S._b") in graph.edges
        sites = graph.edges[("S._a", "S._b")]
        assert sites and sites[0].scope.startswith("w.py::")
        assert lock_order_findings(graph, [summary]) == []


# -- DLR012: inline suppressions ---------------------------------------------


class TestSuppressions:
    SRC = """
        import threading, time

        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def run(self):
                with self._lock:
                    time.sleep(1.0)  # dlrlint: disable=DLR009{reason}
    """

    def test_reasoned_disable_suppresses_silently(self):
        counters = {}
        fs = _lint(self.SRC.format(reason=" startup backoff is "
                                          "master-paced"),
                   counters=counters)
        assert fs == []
        assert counters == {"DLR009": 1}

    def test_bare_disable_suppresses_but_is_itself_a_finding(self):
        counters = {}
        fs = _lint(self.SRC.format(reason=""), counters=counters)
        assert _ids(fs) == ["DLR012"]
        assert "reason" in fs[0].message
        assert counters.get("DLR009") == 1

    def test_disable_for_other_rule_does_not_suppress(self):
        fs = _lint(self.SRC.format(reason="").replace(
            "DLR009", "DLR010"))
        assert _ids(fs) == ["DLR009"]

    def test_scan_table_parses_rules_and_reason(self):
        table = scan_suppressions(
            "x = 1  # dlrlint: disable=DLR002,DLR009 known-benign\n")
        assert table == {1: ({"DLR002", "DLR009"}, "known-benign")}

    def test_apply_counts_per_rule(self):
        from dlrover_tpu.analysis.findings import Finding

        fs = [Finding("DLR009", "p.py", 3, "m"),
              Finding("DLR009", "p.py", 3, "m2"),
              Finding("DLR010", "p.py", 9, "m3")]
        counters = {}
        kept = apply_suppressions(
            fs, {3: ({"DLR009"}, "why")}, counters=counters)
        assert [f.rule_id for f in kept] == ["DLR010"]
        assert counters == {"DLR009": 2}


# -- baseline: ratchet + notes -----------------------------------------------


class TestBaselineRatchetForNewRules:
    def test_stale_concurrency_entry_reported(self):
        base = Baseline(entries={"DLR010::gone.py::C._n": 1})
        new, stale = base.filter([])
        assert new == [] and stale == ["DLR010::gone.py::C._n"]

    def test_covered_finding_consumes_budget(self):
        fs = _lint(TestMixedGuard.FIRING)
        base = Baseline.from_findings(fs)
        new, stale = base.filter(fs)
        assert new == [] and stale == []
        # a SECOND violation in the same scope exceeds the budget
        new, _ = base.filter(fs + fs)
        assert len(new) == 1

    def test_notes_round_trip_and_survive_regeneration(self, tmp_path):
        fs = _lint(TestMixedGuard.FIRING)
        base = Baseline.from_findings(fs)
        key = fs[0].baseline_key
        base.notes[key] = "legacy: external serialization via agent"
        p = str(tmp_path / "b.json")
        base.save(p)
        loaded = Baseline.load(p)
        assert loaded.notes == {key: "legacy: external serialization "
                                     "via agent"}
        with open(p) as fh:
            data = json.load(fh)
        assert data["version"] == 1 and key in data["notes"]
        # notes for keys no longer in entries are dropped on save
        base.entries = {}
        base.save(p)
        assert Baseline.load(p).notes == {}


# -- G110: gather-free serving programs --------------------------------------


class TestKVReadGather:
    def test_rank4_gather_fires_rank2_clean(self):
        import jax
        import jax.numpy as jnp

        from dlrover_tpu.analysis import graph_lint

        idx = jax.ShapeDtypeStruct((3,), jnp.int32)
        pool = jax.ShapeDtypeStruct((2, 8, 16, 4, 32), jnp.bfloat16)
        hlo = jax.jit(
            lambda p, i: jnp.take(p, i, axis=1)
        ).lower(pool, idx).compile().as_text()
        fired = graph_lint.check_kv_read_gather(hlo, path="<probe>")
        assert len(fired) == 1 and fired[0].rule_id == "G110"

        emb = jax.ShapeDtypeStruct((1024, 64), jnp.float32)
        hlo2 = jax.jit(
            lambda e, i: jnp.take(e, i, axis=0)
        ).lower(emb, idx).compile().as_text()
        assert graph_lint.check_kv_read_gather(hlo2, path="<p>") == []

    def test_all_gather_collective_not_matched(self):
        from dlrover_tpu.analysis import graph_lint

        hlo = ("  %ag = f32[8,2,3,4,5] all-gather(f32[1,2,3,4,5] %p0)"
               ", dimensions={0}")
        assert graph_lint.check_kv_read_gather(hlo, path="<p>") == []

    def test_serving_programs_gather_free_at_head(self):
        # the five compiled serving programs (decode / prefill / the
        # speculative verify / the two page copies) carry the
        # invariant the slot-major pool exists for: KV reads are
        # contiguous slices, not gathers — and verify's masked
        # multi-token append must not reintroduce one either
        from dlrover_tpu.analysis import graph_lint

        reports = graph_lint.serving_program_audit()
        labels = {r.label for r in reports}
        assert labels == {"serve_decode", "serve_prefill",
                          "serve_verify", "serve_admit_copy",
                          "serve_publish_copy"}
        bad = [f.render() for r in reports for f in r.findings]
        assert bad == [], "\n".join(bad)

    def test_committed_verify_append_fixture_is_gather_free(self):
        # the masked multi-token KV append (speculative verify's
        # scatter: index-redirection + mode="drop", int8 so both the
        # payload and scale scatters are present) compiled in
        # isolation and COMMITTED — the pin survives compiler/version
        # drift because the artifact can't drift, and documents what
        # "G110-clean append" looks like in optimized HLO
        import os

        from dlrover_tpu.analysis import graph_lint

        path = os.path.join(os.path.dirname(__file__), "testdata",
                            "g110_verify_append.hlo")
        with open(path) as fh:
            hlo = fh.read()
        assert "scatter" in hlo  # the append really is in there
        assert graph_lint.check_kv_read_gather(
            hlo, path="g110_verify_append.hlo") == []
        # sanity that the rule still has teeth against this exact
        # module shape: splice in a rank-4 pool gather and it fires
        poisoned = hlo + ("\n  %bad = s8[4,64,2,8] gather("
                          "s8[4,64,2,8]{3,2,1,0} %param.0, "
                          "s32[3]{0} %idx)\n")
        fired = graph_lint.check_kv_read_gather(poisoned, path="<p>")
        assert len(fired) == 1 and fired[0].rule_id == "G110"


# -- regression pins for the races the new pass caught -----------------------


class _ScriptedMaster:
    """Stand-in master client: scripted get_task responses, and an
    assertion hook that observes the sharding client's lock DURING the
    RPC (the DLR009 fix: the RPC must run lock-free)."""

    def __init__(self, tasks):
        self._tasks = list(tasks)
        self.lock_to_watch = None
        self.lock_was_free = []

    def report_dataset_shard_params(self, **kw):
        pass

    def get_task(self, dataset_name):
        if self.lock_to_watch is not None:
            free = self.lock_to_watch.acquire(blocking=False)
            if free:
                self.lock_to_watch.release()
            self.lock_was_free.append(free)
        if not self._tasks:
            return None
        return self._tasks.pop(0)


def _task(task_id, start, end, indices=None):
    from dlrover_tpu.common import comm

    return comm.Task(task_id=task_id,
                     shard=comm.Shard(name="s", start=start, end=end,
                                      record_indices=indices))


class TestShardingClientLockFreeRPC:
    def _client(self, tasks):
        from dlrover_tpu.agent.sharding_client import IndexShardingClient

        master = _ScriptedMaster(tasks)
        c = IndexShardingClient(master, "ds", batch_size=2,
                                dataset_size=8)
        master.lock_to_watch = c._lock
        return c, master

    def test_get_task_rpc_runs_outside_the_lock(self):
        c, master = self._client([_task(0, 0, 4)])
        assert [c.fetch_record_index() for _ in range(4)] == [0, 1, 2, 3]
        assert master.lock_was_free == [True]

    def test_streams_across_shards_and_exhausts(self):
        c, _ = self._client([_task(0, 0, 2), _task(1, 2, 4, [7, 9])])
        assert list(c.record_indices()) == [0, 1, 7, 9]
        assert c.fetch_record_index() is None

    def test_empty_shard_does_not_crash(self):
        # pre-fix code popped from the just-extended (empty) deque and
        # raised IndexError on a zero-record shard
        c, _ = self._client([_task(0, 3, 3), _task(1, 5, 6)])
        assert c.fetch_record_index() == 5


class TestHangDetectorAtomicCheckAndSet:
    def test_hang_fires_once_and_callback_runs_lock_free(self):
        from dlrover_tpu.diagnosis.hang_detector import HangingDetector

        fired = threading.Event()
        seen = {}

        def on_hang(gap):
            # the DLR009 half of the fix: the escalation callback (a
            # report RPC in production) must not run under the lock
            free = det._lock.acquire(blocking=False)
            if free:
                det._lock.release()
            seen["lock_free"] = free
            seen["gap"] = gap
            fired.set()

        det = HangingDetector(timeout_secs=0.05,
                              check_interval_secs=0.01,
                              on_hang=on_hang)
        det.start()
        try:
            assert fired.wait(5.0), "hang never detected"
            assert det.hang_detected
            assert seen["lock_free"] is True
            assert seen["gap"] > 0.05
        finally:
            det.stop()
        det.report_normal()
        assert not det.hang_detected

    def test_report_normal_racing_watch_leaves_no_stale_flag(self):
        # the lost update the lint caught: _watch read the gap, then a
        # report_normal landed, then _watch set hang_detected anyway.
        # With check-and-set under the lock, a post-progress snapshot
        # can never see (fresh progress, hang_detected=True).
        from dlrover_tpu.diagnosis.hang_detector import HangingDetector

        det = HangingDetector(timeout_secs=0.02,
                              check_interval_secs=0.001, monitor=True)
        det.start()
        try:
            deadline = time.time() + 1.0
            while time.time() < deadline:
                det.report_normal()
                with det._lock:
                    stale = (det.hang_detected
                             and time.time() - det._last_normal
                             <= det._timeout)
                assert not stale
        finally:
            det.stop()


class _RecordingLock:
    """Context-manager shim around a real lock that counts entries."""

    def __init__(self):
        self._inner = threading.Lock()
        self.entries = 0

    def __enter__(self):
        self._inner.acquire()
        self.entries += 1
        return self

    def __exit__(self, *exc):
        self._inner.release()

    def acquire(self, *a, **kw):
        return self._inner.acquire(*a, **kw)

    def release(self):
        self._inner.release()


class TestTornReadPins:
    def test_speed_monitor_properties_take_the_lock(self):
        from dlrover_tpu.master.monitor.speed_monitor import SpeedMonitor

        sm = SpeedMonitor()
        sm.collect_global_step(5, timestamp=time.time())
        rec = _RecordingLock()
        sm._lock = rec
        assert sm.completed_global_step == 5
        assert sm.sample_count == 1
        assert rec.entries == 2

    def test_router_dropped_takes_the_lock(self):
        from dlrover_tpu.serving.router import RequestRouter

        r = RequestRouter(lease_timeout_secs=10.0)
        rec = _RecordingLock()
        r._lock = rec
        assert r.dropped() == 0
        assert rec.entries == 1

    def test_ps_reply_version_captured_under_the_lock(self):
        # simulate the race the lint flagged: a push lands the instant
        # the init lock is released. The init reply must carry the
        # version observed INSIDE its critical section, not whatever
        # the racing writer left behind.
        from dlrover_tpu.common import tensor_codec as wire
        from dlrover_tpu.ps.server import PsShardServer

        server = PsShardServer(shard_id=0)

        class BumpOnExit:
            def __init__(self, inner):
                self._inner = inner

            def __enter__(self):
                self._inner.acquire()
                return self

            def __exit__(self, *exc):
                server._version += 1000  # the racing push
                self._inner.release()

        server._lock = BumpOnExit(threading.Lock())
        import numpy as np

        reply = server._do_init({}, {"w": np.zeros(2, np.float32)})
        meta, _ = wire.unpack_frame(reply)
        assert meta["ok"] and meta["version"] == 0


# -- whole-package invariants ------------------------------------------------


class TestPackageLevel:
    def test_concurrency_rules_registered(self):
        from dlrover_tpu.analysis.ast_rules import (
            ALL_AST_RULES,
            RULE_DOCS,
        )

        for rid in ("DLR009", "DLR010", "DLR011", "DLR012"):
            assert rid in ALL_AST_RULES and rid in RULE_DOCS

    def test_rules_subset_runs_only_requested(self):
        fs = _lint("""
            import threading, time

            class W:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0

                def run(self):
                    with self._lock:
                        self._n += 1
                        time.sleep(1.0)

                def read(self):
                    return self._n
        """, rules={"DLR010"})
        assert _ids(fs) == ["DLR010"]

    def test_package_scan_is_fast_and_clean(self):
        import os

        import dlrover_tpu
        from dlrover_tpu.analysis.concurrency import (
            lint_paths_concurrency,
        )

        pkg = os.path.dirname(os.path.abspath(dlrover_tpu.__file__))
        t0 = time.monotonic()
        fs = lint_paths_concurrency([pkg], root=os.path.dirname(pkg))
        dt = time.monotonic() - t0
        assert fs == [], "\n".join(f.render() for f in fs)
        assert dt < 10.0, f"concurrency pass took {dt:.1f}s"
