"""Pluggable state backends: memory, atomic file persistence, registry."""

import pytest

from dlrover_tpu.common.state_store import (
    FileStateBackend,
    MemoryStateBackend,
    StoreManager,
)


class TestMemoryBackend:
    def test_crud(self):
        store = MemoryStateBackend()
        store.set("a/1", {"x": 1})
        store.set("a/2", 2)
        store.set("b/1", 3)
        assert store.get("a/1") == {"x": 1}
        assert store.get("missing", 42) == 42
        assert sorted(store.keys("a/")) == ["a/1", "a/2"]
        assert store.delete("a/1")
        assert not store.delete("a/1")


class TestFileBackend:
    def test_persists_across_instances(self, tmp_path):
        path = str(tmp_path / "state.json")
        store = FileStateBackend(path)
        store.set("rdzv/round", 3)
        store.set("shards", {"todo": [1, 2], "doing": []})
        # a relaunched master re-reads the snapshot
        store2 = FileStateBackend(path)
        assert store2.get("rdzv/round") == 3
        assert store2.get("shards")["todo"] == [1, 2]

    def test_delete_persists(self, tmp_path):
        path = str(tmp_path / "state.json")
        store = FileStateBackend(path)
        store.set("k", 1)
        store.delete("k")
        assert FileStateBackend(path).get("k") is None

    def test_rejects_non_serializable(self, tmp_path):
        store = FileStateBackend(str(tmp_path / "s.json"))
        with pytest.raises(TypeError):
            store.set("bad", object())

    def test_corrupt_file_starts_empty(self, tmp_path):
        path = tmp_path / "state.json"
        path.write_text("{not json")
        store = FileStateBackend(str(path))
        assert store.keys() == []


class TestStoreManager:
    def test_named_stores_and_reuse(self, tmp_path):
        StoreManager.reset()
        a = StoreManager.build_store("job-a")
        assert StoreManager.build_store("job-a") is a
        f = StoreManager.build_store(
            "job-b", backend="file", path=str(tmp_path / "b.json")
        )
        f.set("k", 1)
        assert StoreManager.get_store("job-b").get("k") == 1
        with pytest.raises(ValueError):
            StoreManager.build_store("job-c", backend="redis")
        with pytest.raises(ValueError):
            StoreManager.build_store("job-d", backend="file")
        StoreManager.reset()
