"""Brain cluster watcher: platform -> datastore -> cross-job plans.

Role parity: ``dlrover/go/brain/pkg/platform/k8s/watcher`` (the
``k8smonitor`` role). The point of a CLUSTER-level Brain is that job
B's initial plan improves because of job A's persisted history — here
that chain is driven end-to-end: a (fake) platform is watched, the
rows land in a durable sqlite store, the Brain restarts, and a new
similar job's create-stage optimize returns a plan learned from the
watched job, where an empty cluster yields the cold default.
"""

import pytest

from dlrover_tpu.brain.datastore import MemoryDatastore, SqliteDatastore
from dlrover_tpu.brain.messages import MetricType, OptimizeRequest
from dlrover_tpu.brain.service import BrainService
from dlrover_tpu.brain.watcher import ClusterWatcher, K8sClusterSource
from dlrover_tpu.scheduler.kubernetes import (
    parse_cpu_cores,
    parse_memory_mib,
)
from dlrover_tpu.common.constants import JobStage, NodeType


class FakeSource:
    """Scripted cluster: tests mutate ``jobs``/``nodes`` between polls."""

    def __init__(self):
        self.jobs = []
        self.nodes = {}

    def list_jobs(self):
        return [dict(j) for j in self.jobs]

    def list_job_nodes(self, job_name):
        return self.nodes.get(job_name, {})


def _running_job_a(source, workers=6, used_cpu=5.0):
    source.jobs = [{"name": "nlp-train-1", "uid": "uid-a",
                    "phase": "Running", "node_unit": 1}]
    source.nodes["nlp-train-1"] = {
        NodeType.PS: [{"name": "ps-0", "cpu": 8.0, "used_cpu": used_cpu,
                       "memory": 16384, "used_memory": 9000}],
        NodeType.WORKER: [
            {"name": f"w-{i}", "cpu": 4.0, "used_cpu": 2.0,
             "memory": 8192, "used_memory": 4000}
            for i in range(workers)
        ],
    }


class TestClusterWatcher:
    def test_job_lifecycle_rows(self):
        store = MemoryDatastore()
        source = FakeSource()
        watcher = ClusterWatcher(store, source, interval=999)
        _running_job_a(source)

        assert watcher.poll_once() == 1
        assert watcher.poll_once() == 1
        # META once, RUNTIME per poll, no EXIT while running
        assert len(store.get_job_metrics(
            "uid-a", MetricType.JOB_META)) == 1
        runtime = store.get_job_metrics("uid-a", MetricType.RUNTIME_INFO)
        assert len(runtime) == 2
        assert runtime[-1].payload["workers"] == 6
        ps = runtime[-1].payload["nodes"][NodeType.PS][0]
        assert ps["used_cpu"] == 5.0
        assert not store.get_job_metrics(
            "uid-a", MetricType.JOB_EXIT_REASON)

        source.jobs[0]["phase"] = "Succeeded"
        watcher.poll_once()
        watcher.poll_once()
        exits = store.get_job_metrics("uid-a", MetricType.JOB_EXIT_REASON)
        assert len(exits) == 1 and exits[0].payload["reason"] == "Succeeded"

    def test_restarted_watcher_does_not_duplicate_one_shot_rows(
        self, tmp_path
    ):
        store = SqliteDatastore(str(tmp_path / "brain.db"))
        source = FakeSource()
        _running_job_a(source)
        ClusterWatcher(store, source, interval=999).poll_once()
        source.jobs[0]["phase"] = "Failed"
        ClusterWatcher(store, source, interval=999).poll_once()

        # a THIRD watcher instance over the same durable store
        watcher = ClusterWatcher(store, source, interval=999)
        watcher.poll_once()
        assert len(store.get_job_metrics(
            "uid-a", MetricType.JOB_META)) == 1
        assert len(store.get_job_metrics(
            "uid-a", MetricType.JOB_EXIT_REASON)) == 1

    def test_source_errors_do_not_kill_the_loop(self):
        store = MemoryDatastore()

        class Flaky:
            calls = 0

            def list_jobs(self):
                Flaky.calls += 1
                if Flaky.calls == 1:
                    raise ConnectionError("apiserver away")
                return [{"name": "j", "uid": "u", "phase": "Running"}]

            def list_job_nodes(self, name):
                raise TimeoutError("metrics away")

        watcher = ClusterWatcher(store, Flaky(), interval=999)
        assert watcher.poll_once() == 0
        assert watcher.poll_once() == 1  # meta persisted, runtime skipped
        assert len(store.get_job_metrics("u", MetricType.JOB_META)) == 1
        assert not store.get_job_metrics("u", MetricType.RUNTIME_INFO)


class TestK8sSource:
    def test_adapts_crs_and_pods(self):
        class FakeK8s:
            def list_custom_resources(self, plural):
                assert plural == "elasticjobs"
                return [{
                    "metadata": {"name": "train-2", "uid": "u2",
                                 "labels": {"user": "alice"}},
                    "spec": {"nodeUnit": 4},
                    "status": {"phase": "Running"},
                }]

            def list_pods(self, label_selector=""):
                assert label_selector == "elasticjob-name=train-2"
                return [
                    {"metadata": {"name": "train-2-worker-0",
                                  "labels": {"replica-type": "worker"}},
                     "spec": {"containers": [
                         # sidecar first: effective request is the SUM
                         {"resources": {"requests": {
                             "cpu": "500m", "memory": "512Mi"}}},
                         {"resources": {"requests": {
                             "cpu": "4", "memory": "8Gi"}}},
                     ]}},
                    {"metadata": {"name": "train-2-master-0",
                                  "labels": {"elasticjob-role": "master"}},
                     "spec": {}},
                ]

            def pod_metrics(self, job_name):
                return {"train-2-worker-0": {"cpu": 2.5, "memory": 5000}}

        source = K8sClusterSource(FakeK8s())
        jobs = source.list_jobs()
        assert jobs == [{"name": "train-2", "uid": "u2",
                         "phase": "Running", "user": "alice",
                         "node_unit": 4}]
        nodes = source.list_job_nodes("train-2")
        assert "master" not in nodes
        w = nodes["worker"][0]
        # sidecar (500m, 512Mi) + trainer (4, 8Gi)
        assert w["cpu"] == 4.5 and w["memory"] == 8192 + 512
        assert w["used_cpu"] == 2.5 and w["used_memory"] == 5000

    def test_quantity_parsing(self):
        # k8s quantity grammar: binary/decimal suffixes; PLAIN numbers
        # are bytes (memory) / cores (cpu)
        assert parse_memory_mib("4Gi") == 4096
        assert parse_memory_mib("512Mi") == 512
        assert parse_memory_mib("8G") == 7629  # 8e9 bytes in MiB
        assert parse_memory_mib("8589934592") == 8192
        assert parse_memory_mib(8589934592) == 8192
        assert parse_memory_mib("garbage") == 0
        assert parse_cpu_cores("500m") == 0.5
        assert parse_cpu_cores("4") == 4.0
        assert parse_cpu_cores(2) == 2.0
        assert parse_cpu_cores("oops") == 0.0


class TestCrossJobColdStartE2E:
    @pytest.mark.slow
    def test_job_b_plan_learned_from_watched_job_a(self, tmp_path):
        """The full chain: watcher observes job A -> durable store ->
        Brain RESTART -> job B's create plan reflects A's observed
        scale/usage; an empty cluster gives the cold default."""
        from dlrover_tpu.brain.client import BrainClient

        db = f"sqlite://{tmp_path}/cluster.db"

        # epoch 1: the watcher (k8smonitor role) observes job A's life
        store = SqliteDatastore(str(tmp_path / "cluster.db"))
        source = FakeSource()
        _running_job_a(source, workers=6, used_cpu=5.0)
        watcher = ClusterWatcher(store, source, interval=999)
        for _ in range(3):
            watcher.poll_once()
        source.jobs[0]["phase"] = "Succeeded"
        watcher.poll_once()

        # epoch 2: a fresh Brain over the same durable store
        service = BrainService(port=0, datastore_spec=db)
        service.start()
        try:
            client = BrainClient(f"127.0.0.1:{service.port}")
            plan = client.optimize(OptimizeRequest(
                job_uuid="uid-b", job_name="nlp-train-2",
                algorithm="optimize_job_worker_create_resource",
            ))
            assert plan.success
            # learned from A: 6 workers, not the cold 1
            assert plan.group_resources[NodeType.WORKER].count == 6

            ps_plan = client.optimize(OptimizeRequest(
                job_uuid="uid-b", job_name="nlp-train-2",
                stage=JobStage.CREATE,
            ))
            assert ps_plan.success
            ps = ps_plan.group_resources[NodeType.PS]
            # 1.25x headroom over A's hottest observed PS (5.0 cpu)
            assert ps.cpu == pytest.approx(6.25)
            assert ps.memory >= 9000
            client.close()
        finally:
            service.stop()

        # causality: the SAME requests against an empty cluster store
        # give the cold defaults — the improvement came from A's history
        empty = BrainService(
            port=0, datastore_spec=f"sqlite://{tmp_path}/empty.db"
        )
        empty.start()
        try:
            client = BrainClient(f"127.0.0.1:{empty.port}")
            cold = client.optimize(OptimizeRequest(
                job_uuid="uid-c", job_name="nlp-train-3",
                algorithm="optimize_job_worker_create_resource",
            ))
            assert cold.group_resources[NodeType.WORKER].count == 1
            client.close()
        finally:
            empty.stop()
