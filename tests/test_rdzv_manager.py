"""In-memory tests of the rendezvous managers (reference test model:
``dlrover/python/tests/test_rdzv_manager.py``)."""

from dlrover_tpu.master.elastic_training.rdzv_manager import (
    ElasticTrainingRendezvousManager,
    NetworkCheckRendezvousManager,
)


def make_training_mgr(min_nodes, max_nodes, timeout=60.0, node_unit=1):
    mgr = ElasticTrainingRendezvousManager()
    mgr.update_rdzv_params(min_nodes, max_nodes, timeout, node_unit)
    return mgr


class TestElasticTrainingRendezvous:
    def test_all_nodes_complete_round(self):
        mgr = make_training_mgr(2, 3)
        for rank in range(3):
            r = mgr.join_rendezvous(rank, 4, node_id=rank,
                                    addr=f"10.0.0.{rank}:1234")
            assert r == 0
        rdzv_round, group, world, coord = mgr.get_comm_world(0)
        assert world == {0: 4, 1: 4, 2: 4}
        assert coord == "10.0.0.0:1234"
        assert rdzv_round == 1  # round advanced on completion

    def test_no_completion_below_min(self):
        mgr = make_training_mgr(2, 4, timeout=60.0)
        mgr.join_rendezvous(0, 4)
        _, _, world, _ = mgr.get_comm_world(0)
        assert world == {}

    def test_timeout_completion_with_min_nodes(self):
        mgr = make_training_mgr(2, 4, timeout=0.0)
        mgr.join_rendezvous(0, 4, addr="h0:1")
        mgr.join_rendezvous(1, 4, addr="h1:1")
        mgr.join_rendezvous(2, 4, addr="h2:1")
        _, _, world, coord = mgr.get_comm_world(0)
        assert world == {0: 4, 1: 4, 2: 4}
        assert coord == "h0:1"

    def test_node_unit_rounds_down_to_whole_slices(self):
        # 2 hosts per slice: 5 waiting nodes -> world of 4
        mgr = make_training_mgr(2, 8, timeout=0.0, node_unit=2)
        for rank in range(5):
            mgr.join_rendezvous(rank, 4, addr=f"h{rank}:1")
        _, _, world, _ = mgr.get_comm_world(0)
        assert sorted(world) == [0, 1, 2, 3]
        # the leftover node is still waiting for the next round
        assert mgr.num_nodes_waiting() in (0, 1)

    def test_num_nodes_waiting_restart_semantics(self):
        mgr = make_training_mgr(2, 2, timeout=0.0, node_unit=2)
        mgr.join_rendezvous(0, 4)
        mgr.join_rendezvous(1, 4)
        mgr.get_comm_world(0)
        assert mgr.num_nodes_waiting() == 0
        # a node from the last world re-joins => immediate restart signal
        mgr.join_rendezvous(1, 4)
        assert mgr.num_nodes_waiting() == 1

    def test_remove_alive_node_drops_waiting(self):
        mgr = make_training_mgr(2, 4)
        mgr.join_rendezvous(0, 4, node_id=10)
        mgr.join_rendezvous(1, 4, node_id=11)
        mgr.remove_alive_node(11)
        _, _, world, _ = mgr.get_comm_world(0)
        assert world == {}


class TestNetworkCheckRendezvous:
    def _join_all(self, mgr, n):
        for rank in range(n):
            mgr.join_rendezvous(rank, 4, node_id=rank, addr=f"h{rank}:1")

    def test_pairs_round0(self):
        mgr = NetworkCheckRendezvousManager()
        mgr.update_rdzv_params(4, 4, 10.0, 1)
        self._join_all(mgr, 4)
        _, group0, world0, _ = mgr.get_comm_world(0)
        _, group2, world2, _ = mgr.get_comm_world(2)
        assert world0 == {0: 4, 1: 4}
        assert world2 == {2: 4, 3: 4}
        assert group0 != group2

    def test_fault_localization_two_rounds(self):
        mgr = NetworkCheckRendezvousManager()
        mgr.update_rdzv_params(4, 4, 10.0, 1)
        self._join_all(mgr, 4)
        for rank in range(4):
            mgr.get_comm_world(rank)
        # round 0: pair (0,1) fails (node 1 is bad), pair (2,3) passes
        mgr.report_network_check_result(0, False)
        mgr.report_network_check_result(1, False)
        mgr.report_network_check_result(2, True)
        mgr.report_network_check_result(3, True)
        ok, reason = mgr.network_check_success()
        assert not ok and reason == "node-failure"
        # round 1: suspects (0, 1) each paired with a good node
        self._join_all(mgr, 4)
        _, _, g0, _ = mgr.get_comm_world(0)
        assert 0 in g0 and len(g0) == 2 and 1 not in g0
        # 0 passes when paired with a good node; 1 still fails
        mgr.report_network_check_result(0, True)
        mgr.report_network_check_result(1, False)
        mgr.report_network_check_result(2, True)
        mgr.report_network_check_result(3, True)
        ok, _ = mgr.network_check_success()
        assert not ok
        assert mgr.abnormal_nodes() == [1]

    def test_all_normal_check_succeeds(self):
        mgr = NetworkCheckRendezvousManager()
        mgr.update_rdzv_params(2, 2, 10.0, 1)
        self._join_all(mgr, 2)
        mgr.get_comm_world(0)
        mgr.report_network_check_result(0, True, elapsed=1.0)
        mgr.report_network_check_result(1, True, elapsed=1.1)
        ok, reason = mgr.network_check_success()
        assert ok and reason == ""

    def test_straggler_detection(self):
        mgr = NetworkCheckRendezvousManager()
        mgr.update_rdzv_params(4, 4, 10.0, 1)
        self._join_all(mgr, 4)
        mgr.get_comm_world(0)
        for rank, t in [(0, 1.0), (1, 1.1), (2, 0.9), (3, 9.0)]:
            mgr.report_network_check_result(rank, True, elapsed=t)
        assert mgr.straggler_nodes() == [3]
