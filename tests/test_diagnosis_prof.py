"""Hang detection, error classification, and the XLA-cost profiler."""

import time

import numpy as np

import jax
import jax.numpy as jnp
import optax

from dlrover_tpu.common.constants import NodeExitReason
from dlrover_tpu.diagnosis.error_monitor import ErrorLogMonitor, classify_error
from dlrover_tpu.diagnosis.hang_detector import HangingDetector
from dlrover_tpu.parallel.accelerate import accelerate
from dlrover_tpu.parallel.mesh import MeshPlan
from dlrover_tpu.parallel.strategy import Strategy
from dlrover_tpu.utils.prof import AProfiler, DryRunner, analyze_cost, count_params


class TestHangDetector:
    def test_detects_and_recovers(self):
        hangs = []
        det = HangingDetector(
            timeout_secs=0.2, check_interval_secs=0.05,
            on_hang=lambda gap: hangs.append(gap),
        )
        det.start()
        try:
            time.sleep(0.4)
            assert det.hang_detected
            assert hangs
            det.report_normal()
            assert not det.hang_detected
        finally:
            det.stop()

    def test_no_false_positive_with_heartbeats(self):
        det = HangingDetector(timeout_secs=0.3, check_interval_secs=0.05)
        det.start()
        try:
            for _ in range(6):
                det.report_normal()
                time.sleep(0.05)
            assert not det.hang_detected
        finally:
            det.stop()


class TestErrorClassification:
    def test_signatures(self):
        assert classify_error("RESOURCE_EXHAUSTED: HBM OOM on chip 3") == \
            NodeExitReason.OOM
        assert classify_error("ICI link down on host 2") == \
            NodeExitReason.HARDWARE_ERROR
        assert classify_error("worker preempted by scheduler") == \
            NodeExitReason.PREEMPTED
        assert classify_error("ModuleNotFoundError: no module foo") == \
            NodeExitReason.FATAL_ERROR
        assert classify_error("something else entirely") == \
            NodeExitReason.UNKNOWN_ERROR

    def test_monitor_records_and_counts(self):
        mon = ErrorLogMonitor(max_records=3)
        for i in range(5):
            mon.process_error(i % 2, 0, f"err {i}", "process")
        assert len(mon.records) == 3
        counts = mon.node_error_counts()
        assert sum(counts.values()) == 3


def _mlp_init(rng):
    k1, k2 = jax.random.split(rng)
    return {"w1": jax.random.normal(k1, (16, 32)) * 0.1,
            "w2": jax.random.normal(k2, (32, 8)) * 0.1}


def _mlp_loss(params, batch, rng):
    h = jnp.tanh(batch["x"] @ params["w1"])
    return jnp.mean((h @ params["w2"] - batch["y"]) ** 2), {}


def _batch(n=32):
    rng = np.random.default_rng(0)
    return {"x": rng.normal(size=(n, 16)).astype(np.float32),
            "y": rng.normal(size=(n, 8)).astype(np.float32)}


class TestProfiler:
    def test_cost_analysis_flops(self):
        def matmul(a, b):
            return a @ b

        a = jnp.ones((128, 256))
        b = jnp.ones((256, 64))
        report = analyze_cost(matmul, a, b)
        # 2*M*N*K FLOPs for the matmul; XLA may add small epsilon ops.
        assert report.flops >= 2 * 128 * 256 * 64

    def test_dryrun_profiles_train_step(self):
        res = accelerate(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            strategy=Strategy(mesh=MeshPlan(data=-1)),
        )
        state = res.init_fn(jax.random.PRNGKey(0))
        runner = DryRunner(warmup=1, steps=3)
        prof = runner.profile(
            res.train_step, state, res.shard_batch(_batch()),
            jax.random.PRNGKey(1),
        )
        assert prof.steps_per_sec > 0
        assert prof.param_count == count_params(state.params)
        assert prof.flops_per_step > 0
        assert 0 <= prof.mfu(1e15) < 1

    def test_dryrun_trace_capture(self, tmp_path):
        """trace_dir writes an xprof trace directory the tooling can
        open (SURVEY §5 tracing parity)."""
        import os

        from dlrover_tpu.parallel.auto_tune import dryrun

        res = accelerate(
            _mlp_init, _mlp_loss, optax.adam(1e-2), _batch(),
            strategy=Strategy(mesh=MeshPlan(data=-1)),
        )
        trace_dir = str(tmp_path / "trace")
        report = dryrun(res, _batch(), profile_steps=2,
                        trace_dir=trace_dir)
        assert report.ok, report.error
        found = []
        for root, _dirs, files in os.walk(trace_dir):
            found.extend(files)
        assert found, "no trace files written"

    def test_aprofiler_summary(self):
        params = _mlp_init(jax.random.PRNGKey(0))
        prof = AProfiler(params)
        info = prof.summary(_mlp_loss, _batch(), jax.random.PRNGKey(0))
        assert info["param_count"] == 16 * 32 + 32 * 8
        assert info["forward_flops"] > 0
        assert set(info["subtrees"]) == {"w1", "w2"}
